"""horovod_tpu.tensorflow — the TensorFlow framework shim.

Parity target: horovod/tensorflow/__init__.py (326) + mpi_ops.py (183) +
the C++ binding horovod/tensorflow/mpi_ops.cc (466): differentiable
``allreduce`` / ``allgather`` / ``broadcast`` on ``tf.Tensor``s with the
reference's registered gradients (tensorflow/mpi_ops.py:94-183),
``DistributedOptimizer`` overriding gradient computation
(tensorflow/__init__.py:151-249), ``DistributedGradientTape``
(tensorflow/__init__.py:252-326), ``broadcast_variables`` and a
``BroadcastGlobalVariablesCallback``-style hook.

Where the reference registers a TF ``AsyncOpKernel`` that enqueues into
the MPI coordinator (mpi_ops.cc:281-303), this shim bridges with
``tf.py_function`` into the TPU-native XLA engine: eager tensors cross
zero-copy via DLPack (utils/interop.py; numpy fallback for 64-bit wire);
inside a traced ``tf.function`` the py_function node plays the
AsyncOpKernel's role (a host callback that blocks on the engine handle).
TF stays the autograd engine; the collectives run on the XLA data plane.

Gradient registrations (all three, mirroring tensorflow/mpi_ops.py):
- grad(allreduce(x))  = allreduce(grad)            (94-105)
- grad(allgather(x))  = this rank's slice of the unsummed
                        allreduce of the gathered grad (127-148)
- grad(broadcast(x))  = allreduce(grad), zeroed on non-root (168-183)
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import tensorflow as tf

from .. import ops as _ops
from .. import topology as _topo
from ..compression import Compression
from ..observability import StepTimer as _StepTimer
from ..utils import interop as _interop
from ..topology import (init, shutdown, is_initialized, rank, local_rank,
                        size, local_size, mpi_threads_supported)

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "local_rank", "size",
    "local_size", "mpi_threads_supported", "Compression",
    "allreduce", "grouped_allreduce", "allgather", "broadcast",
    "broadcast_variables", "broadcast_global_variables",
    "DistributedOptimizer", "DistributedGradientTape",
    "BroadcastGlobalVariablesCallback", "BroadcastGlobalVariablesHook",
    "StepMetrics",
]


class StepMetrics(_StepTimer):
    """Per-step telemetry hook for TF training loops (docs/metrics.md):
    ``hvdtpu_step_seconds`` / ``hvdtpu_samples_per_second`` /
    ``hvdtpu_collective_step_share`` (plus the deprecated
    ``hvdtpu_allreduce_step_share`` alias), the per-step
    input/h2d/compute/collective attribution, HBM gauges, and MFU when
    ``flops_per_step`` is supplied — labeled ``framework=tensorflow``.
    Use as a context manager around each train step; the collective
    share comes from the engine's execute-time accounting, so it covers
    the collectives issued through DistributedGradientTape/Optimizer."""

    def __init__(self, batch_size: Optional[int] = None,
                 flops_per_step: Optional[float] = None):
        super().__init__("tensorflow", batch_size=batch_size,
                         flops_per_step=flops_per_step)

# Host-bridge call counter (observability/tests): index 0 counts how many
# py_function/host crossings carried a GROUP of tensors — the fusion-
# restoring path. A tape with 50 gradients must cost 1 bridge, not 50.
_bridge_calls = [0]


# ---------------------------------------------------------------------------
# Host bridge — the AsyncOpKernel analogue
# ---------------------------------------------------------------------------

def _np(x: tf.Tensor) -> np.ndarray:
    arr = x.numpy()
    if arr.dtype == np.float64 or arr.dtype == np.int64:
        # tf defaults many python constants to 64-bit; the engine's wire is
        # 32-bit unless jax_enable_x64 — the result is cast back by Tout.
        import jax
        if not jax.config.jax_enable_x64:
            arr = arr.astype(
                np.float32 if arr.dtype == np.float64 else np.int32)
    return arr


def _ingress(x: tf.Tensor):
    """Eager tensor -> engine payload: DLPack zero-copy (utils/interop)
    when the dtype/device permit, numpy otherwise."""
    a = _interop.try_tf_to_jax(x)
    return a if a is not None else _np(x)


def _egress(out, want_dtype) -> tf.Tensor:
    """Engine result -> tf.Tensor for the py_function return: zero-copy
    DLPack when the buffer exports, else one host copy. py_function does
    NOT cast EagerTensor returns to Tout, so cast here (the 64-bit wire
    narrows to 32-bit in 32-bit JAX mode)."""
    res = _interop.jax_to_tf(out)
    if res.dtype != want_dtype:
        res = tf.cast(res, want_dtype)
    return res


def _hvd_allreduce_host(x: tf.Tensor, average: bool, name: str,
                        compression=None) -> tf.Tensor:
    # ``compression`` only carries a blockwise wire spec down to the
    # engine (cast compressors already transformed the tensor TF-side).
    out = _ops.allreduce(_ingress(x), average=average, name=name or None,
                         compression=compression)
    return _egress(out, x.dtype)


def _py_collective(host_fn, inputs: tf.Tensor, out_dtype, out_shape):
    out = tf.py_function(host_fn, [inputs], Tout=out_dtype)
    if out_shape is not None:
        out.set_shape(out_shape)
    return out


def _grouped_bridge(submit_async, tensors):
    """ONE py_function crossing for a whole tensor group: submit every
    tensor to the engine as a single burst (fused there), wait all
    handles, return outputs with shapes restored. ``submit_async(i, arr)``
    must return an engine Handle. Shared by grouped_allreduce and the
    broadcast hook so bridge counting and singleton normalization live
    in one place."""

    def host(*vs):
        _bridge_calls[0] += 1
        with _ops.engine().burst():
            handles = [submit_async(i, _ingress(v)) for i, v in enumerate(vs)]
        outs = [h.wait() for h in handles]

        def cast(res, dt):
            return tf.cast(res, dt) if res.dtype != dt else res

        # Zero-copy DLPack egress where the buffer exports (gated +
        # counted via interop.try_jax_to_tf); batched device_get for
        # the remainder (one transfer burst per group, not one round
        # trip per tensor — interop.to_host_many).
        results: list = [None] * len(outs)
        rest = []
        for i, out in enumerate(outs):
            res = _interop.try_jax_to_tf(out)
            if res is not None:
                results[i] = cast(res, vs[i].dtype)
                continue
            rest.append(i)
        if rest:
            hosts = _interop.to_host_many([outs[i] for i in rest])
            for i, arr in zip(rest, hosts):
                results[i] = cast(tf.convert_to_tensor(arr),
                                  vs[i].dtype)
        return results

    outs = tf.py_function(host, list(tensors),
                          Tout=[t.dtype.base_dtype if hasattr(t, "dtype")
                                else t.dtype for t in tensors])
    if len(tensors) == 1 and not isinstance(outs, (list, tuple)):
        outs = [outs]
    for t, o in zip(tensors, outs):
        o.set_shape(t.shape)
    return list(outs)


def _wire_tf_dtype(compression):
    """tf.DType the compression transmits on the wire, or None for
    pass-through. Honors ``compression.wire_dtype`` (fp16/bf16/fp8) the
    way keras._tf_graph_allreduce_batch does, instead of assuming fp16.
    A custom compressor that is not Compression.none but declares no
    wire_dtype keeps the historical fp16 wire."""
    if getattr(compression, "wire_spec", None) is not None:
        # Blockwise formats: no TF-side cast — the quantization runs
        # inside the engine's fused XLA program; the spec rides down via
        # the ``compression`` argument of the host bridge.
        return None
    wire = getattr(compression, "wire_dtype", None)
    if wire is None:
        if compression is not Compression.none:
            return tf.float16
        return None
    return tf.as_dtype(np.dtype(wire))


_name_counter = [0]


def _auto_name(prefix: str, name: Optional[str]) -> str:
    if name:
        return name
    _name_counter[0] += 1
    return f"tf.{prefix}.{_name_counter[0]}"


# ---------------------------------------------------------------------------
# Differentiable collectives
# ---------------------------------------------------------------------------

def allreduce(tensor, average: bool = True, name: Optional[str] = None,
              compression=Compression.none):
    """Differentiable allreduce. ``tf.IndexedSlices`` inputs are handled
    as allgather(values)+allgather(indices) — the sparse data-parallel
    path (tensorflow/__init__.py:72-83)."""
    if isinstance(tensor, tf.IndexedSlices):
        values = allgather(tensor.values, name=_auto_name("ar.sv", name))
        indices = allgather(tensor.indices, name=_auto_name("ar.si", name))
        if average:
            values = values / float(_topo.size())
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)

    nm = _auto_name("allreduce", name)

    @tf.custom_gradient
    def _op(x):
        wire = x
        ctx = None
        wire_dt = _wire_tf_dtype(compression)
        if wire_dt is not None and x.dtype.is_floating:
            wire, ctx = tf.cast(x, wire_dt), x.dtype

        blockwise = (compression
                     if getattr(compression, "wire_spec", None) is not None
                     else None)

        def host(v):
            return _hvd_allreduce_host(v, average, nm, blockwise)

        out = _py_collective(host, wire, wire.dtype, wire.shape)
        if ctx is not None:
            out = tf.cast(out, ctx)

        def grad(dy):
            return allreduce(dy, average=average,
                             name=_auto_name("allreduce", None),
                             compression=compression)

        return out, grad

    return _op(tf.convert_to_tensor(tensor))


def grouped_allreduce(tensors, average: bool = True,
                      name: Optional[str] = None,
                      compression=Compression.none):
    """Allreduce a LIST of tensors through ONE host bridge.

    The reference's AsyncOpKernels all enqueue into the coordinator and
    the background cycle fuses them (tensorflow/mpi_ops.cc:276-463 +
    operations.cc:2149-2265); a per-tensor ``tf.py_function`` would
    instead serialize one host round-trip per gradient. This is the
    fusion-restoring path: one ``py_function`` (one bridge) submits the
    whole group to the engine as a single burst — the engine fuses it
    into as few XLA collectives as the threshold allows — and waits all
    handles. Differentiable: the gradient is a grouped allreduce of the
    incoming gradients, matching allreduce's registered gradient
    (tensorflow/mpi_ops.py:94-105).
    """
    tensors = [tf.convert_to_tensor(t) for t in tensors]
    if not tensors:
        return []
    nm = _auto_name("grouped", name)

    @tf.custom_gradient
    def _op(*xs):
        wires = []
        ctxs = []
        wire_dt = _wire_tf_dtype(compression)
        for x in xs:
            if wire_dt is not None and x.dtype.is_floating:
                wires.append(tf.cast(x, wire_dt))
                ctxs.append(x.dtype)
            else:
                wires.append(x)
                ctxs.append(None)

        blockwise = (compression
                     if getattr(compression, "wire_spec", None) is not None
                     else None)
        outs = _grouped_bridge(
            lambda i, arr: _ops.allreduce_async(arr, average=average,
                                                name=f"{nm}.{i}",
                                                compression=blockwise),
            wires)
        res = [tf.cast(o, ctx) if ctx is not None else o
               for o, ctx in zip(outs, ctxs)]

        def grad(*dys):
            return grouped_allreduce(
                list(dys), average=average,
                name=_auto_name("grouped", None), compression=compression)

        return res, grad

    out = _op(*tensors)
    return list(out) if isinstance(out, (list, tuple)) else [out]


def allgather(tensor, name: Optional[str] = None):
    """Differentiable allgather along dim 0 (tensorflow/mpi_ops.py:107-148).
    Backward: sum-allreduce the gathered gradient, slice this rank's
    segment."""
    nm = _auto_name("allgather", name)

    @tf.custom_gradient
    def _op(x):
        dim0 = x.shape[0]

        def host(v):
            return _egress(_ops.allgather(_ingress(v), name=nm), v.dtype)

        out_shape = tf.TensorShape(
            [None if dim0 is None else dim0 * _topo.size()]
            + list(x.shape[1:]))
        out = _py_collective(host, x, x.dtype, out_shape)

        def grad(dy):
            summed = allreduce(dy, average=False,
                               name=_auto_name("allgather.grad", None))
            r = _topo.rank()
            n = tf.shape(summed)[0] // _topo.size()
            return summed[r * n:(r + 1) * n]

        return out, grad

    return _op(tf.convert_to_tensor(tensor))


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None):
    """Differentiable broadcast (tensorflow/mpi_ops.py:150-183).
    Backward: allreduce the gradient; non-root ranks contribute zeros."""
    nm = _auto_name("broadcast", name)

    @tf.custom_gradient
    def _op(x):
        def host(v):
            return _egress(_ops.broadcast(_ingress(v), root_rank, name=nm),
                           v.dtype)

        out = _py_collective(host, x, x.dtype, x.shape)

        def grad(dy):
            g = allreduce(dy, average=False,
                          name=_auto_name("broadcast.grad", None))
            if _topo.rank() != root_rank:
                g = tf.zeros_like(g)
            return g

        return out, grad

    return _op(tf.convert_to_tensor(tensor))


# ---------------------------------------------------------------------------
# Variable sync
# ---------------------------------------------------------------------------

def broadcast_variables(variables, root_rank: int = 0) -> None:
    """Assign every variable the root rank's value
    (tensorflow/__init__.py:95-114)."""
    from ..utils.wire import movement_payload, movement_restore
    handles = []
    for i, v in enumerate(variables):
        arr = np.asarray(v.numpy())  # not ascontiguousarray: it promotes 0-dim to (1,)
        wire, from_bits = movement_payload(arr)
        handles.append((v, arr.dtype, arr.shape, from_bits,
                        _ops.broadcast_async(
                            wire, root_rank, name=f"tf.bcast.{i}.{v.name}")))
    for v, dtype, shape, from_bits, h in handles:
        v.assign(movement_restore(h.wait(), dtype, shape, from_bits))


def broadcast_global_variables(root_rank: int = 0, variables=None) -> None:
    """TF2 has no global-variables collection; pass the variables (e.g.
    ``model.variables``) explicitly."""
    if variables is None:
        raise ValueError(
            "TF2 has no global variable collection; pass variables= "
            "(e.g. model.variables + optimizer.variables)")
    broadcast_variables(variables, root_rank)


try:  # SessionRunHook base: v1 compat surface (removed in some builds)
    _SessionRunHook = tf.compat.v1.train.SessionRunHook
except AttributeError:  # pragma: no cover - ancient/minimal TF builds
    _SessionRunHook = object


class BroadcastGlobalVariablesHook(_SessionRunHook):
    """SessionRunHook that broadcasts all global variables from
    ``root_rank`` once the session is created — the reference's
    estimator/MonitoredTrainingSession integration
    (tensorflow/__init__.py:117-148, examples/tensorflow_mnist.py).

    Graph mode: ``begin()`` builds one grouped assign op over
    ``tf.compat.v1.global_variables()``; ``after_create_session()`` runs
    it. Eager contexts should use
    :class:`BroadcastGlobalVariablesCallback` instead.
    """

    def __init__(self, root_rank: int = 0, device: str = ""):
        self.root_rank = root_rank
        self.device = device  # accepted for API parity; placement is XLA's
        self.bcast_op = None

    def begin(self):
        gvars = tf.compat.v1.global_variables()
        if not gvars:
            self.bcast_op = tf.no_op()
            return
        # ONE bridged group for all variables (like grouped_allreduce):
        # per-variable py_functions would leave fusion to TF's inter-op
        # scheduling racing the engine's drain debounce — hundreds of
        # serialized host round-trips in the worst case.
        nm = _auto_name("hook.bcast", None)
        root = self.root_rank
        outs = _grouped_bridge(
            lambda i, arr: _ops.broadcast_async(arr, root,
                                                name=f"{nm}.{i}"),
            list(gvars))
        self.bcast_op = tf.group(*[
            tf.compat.v1.assign(v, o) for v, o in zip(gvars, outs)])

    def after_create_session(self, session, coord):
        if self.bcast_op is not None:
            session.run(self.bcast_op)


class BroadcastGlobalVariablesCallback:
    """Callable hook: invoke once after the first step (when optimizer
    slots exist) to sync all state from ``root_rank`` — the TF2 analogue
    of the reference's SessionRunHook (tensorflow/__init__.py:117-148)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank
        self._done = False

    def __call__(self, model=None, optimizer=None) -> None:
        if self._done:
            return
        vs = []
        if model is not None:
            vs += list(model.variables)
        if optimizer is not None:
            vs += list(optimizer.variables)
        broadcast_variables(vs, self.root_rank)
        self._done = True


# ---------------------------------------------------------------------------
# DistributedOptimizer / DistributedGradientTape
# ---------------------------------------------------------------------------

def _reduce_grad_list(grads, prefix: str, compression,
                      sparse_as_dense: bool):
    """Average a list of gradients: dense ones in ONE bridged group
    (engine-fused), IndexedSlices through the sparse allgather path."""
    grads = list(grads)
    if sparse_as_dense:
        grads = [tf.convert_to_tensor(g)
                 if isinstance(g, tf.IndexedSlices) else g for g in grads]
    dense_idx = [i for i, g in enumerate(grads)
                 if g is not None and not isinstance(g, tf.IndexedSlices)]
    reduced = grouped_allreduce([grads[i] for i in dense_idx],
                                average=True, name=f"{prefix}.grads",
                                compression=compression)
    for i, rg in zip(dense_idx, reduced):
        grads[i] = rg
    for i, g in enumerate(grads):
        if isinstance(g, tf.IndexedSlices):
            grads[i] = allreduce(g, average=True, name=f"{prefix}.grad.{i}",
                                 compression=compression)
    return grads


def _make_v1_distributed_optimizer(optimizer, name, compression,
                                   sparse_as_dense):
    """The reference's actual shape: a ``tf.compat.v1.train.Optimizer``
    subclass delegating to the wrapped optimizer, with
    ``compute_gradients`` allreduce-averaging every gradient
    (tensorflow/__init__.py:151-249)."""
    v1 = tf.compat.v1.train

    class _DistributedOptimizerV1(v1.Optimizer):
        def __init__(self):
            self._optimizer = optimizer
            self._hvd_prefix = (name or
                                f"Distributed{type(optimizer).__name__}")
            super().__init__(name=self._hvd_prefix, use_locking=False)

        def compute_gradients(self, *args, **kwargs):
            gvs = self._optimizer.compute_gradients(*args, **kwargs)
            grads = _reduce_grad_list([g for g, _ in gvs],
                                      self._hvd_prefix, compression,
                                      sparse_as_dense)
            return [(g, v) for g, (_, v) in zip(grads, gvs)]

        def apply_gradients(self, *args, **kwargs):
            return self._optimizer.apply_gradients(*args, **kwargs)

        def get_slot(self, *args, **kwargs):
            return self._optimizer.get_slot(*args, **kwargs)

        def get_slot_names(self, *args, **kwargs):
            return self._optimizer.get_slot_names(*args, **kwargs)

        def variables(self, *args, **kwargs):
            return self._optimizer.variables(*args, **kwargs)

    return _DistributedOptimizerV1()


def DistributedOptimizer(optimizer, name: Optional[str] = None,
                         compression=Compression.none,
                         sparse_as_dense: bool = False):
    """Wrap an optimizer so gradients are allreduce-averaged before the
    update (tensorflow/__init__.py:151-249). Dispatches on flavor:
    ``tf.compat.v1.train.Optimizer`` gets the reference's delegation
    wrapper overriding ``compute_gradients`` (graph/MonitoredSession
    loops); Keras-style optimizers get a dynamic subclass whose
    ``apply_gradients`` reduces first."""
    try:
        if isinstance(optimizer, tf.compat.v1.train.Optimizer):
            return _make_v1_distributed_optimizer(
                optimizer, name, compression, sparse_as_dense)
    except AttributeError:  # pragma: no cover - minimal TF builds
        pass
    prefix = name or f"Distributed{optimizer.__class__.__name__}"

    class _Wrapped(optimizer.__class__):
        _hvd_wrapped = True

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            gv = list(grads_and_vars)
            reduced = _reduce_grad_list([g for g, _ in gv], prefix,
                                        compression, sparse_as_dense)
            return super().apply_gradients(
                [(g, v) for g, (_, v) in zip(reduced, gv)],
                *args, **kwargs)

    new = _Wrapped.from_config(optimizer.get_config())
    return new


class DistributedGradientTape(tf.GradientTape):
    """``tf.GradientTape`` whose ``gradient()`` returns allreduce-averaged
    gradients (tensorflow/__init__.py:252-326)."""

    def __init__(self, *args, compression=Compression.none,
                 sparse_as_dense: bool = False, **kwargs):
        super().__init__(*args, **kwargs)
        self._hvd_compression = compression
        self._hvd_sparse_as_dense = sparse_as_dense

    def gradient(self, target, sources, *args, **kwargs):
        grads = super().gradient(target, sources, *args, **kwargs)
        # One bridged group for all dense gradients (the reference's
        # fused AsyncOpKernel behavior); sparse stays per-tensor.
        flat = _reduce_grad_list(
            tf.nest.flatten(grads), _auto_name("tape", None),
            self._hvd_compression, self._hvd_sparse_as_dense)
        return tf.nest.pack_sequence_as(grads, flat)
