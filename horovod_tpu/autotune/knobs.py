"""Typed knob registry — the global autotuner's search space.

Every performance knob the framework grew — DCN wire spec, fusion
threshold, torch bucket size, pipeline schedule/microbatch count,
serving spec_tokens — is declared here ONCE, with its domain, the
mechanism that applies it safely to a live job, and a safety class that
tells the driver how disruptive a move is (docs/autotune.md):

``safety`` classes
    ``epoch``      — must flip through the coordinator-stamped
                     wire-epoch mechanism so every rank switches at the
                     same group seq (wire spec, fusion threshold).
    ``boundary``   — applies only at a step boundary while no gradient
                     reductions are in flight (torch bucket size).
    ``rebuild``    — needs a ``build_train_step`` rebuild and is scored
                     per-trial, never flipped under a running program
                     (pipeline schedule, microbatch count).
    ``slot``       — adapts online per serving slot from its own live
                     signal (spec_tokens).
    ``live``       — safe to change between any two engine cycles
                     (cycle time).

``kind`` is ``discrete`` (successive halving owns it) or ``continuous``
(the legacy Bayesian tuner's GP seeds and refines it).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

KINDS = ("discrete", "continuous")
SAFETY_CLASSES = ("live", "epoch", "boundary", "rebuild", "slot")
APPLY_VIAS = ("wire_epoch", "fusion_epoch", "bucket_repartition",
              "train_step_rebuild", "serving_slot", "engine_param")


@dataclasses.dataclass(frozen=True)
class Knob:
    """One tunable: its domain, apply mechanism, and safety class."""

    name: str
    kind: str                    # "discrete" | "continuous"
    domain: Tuple                # values (discrete) or (lo, hi) bounds
    default: Any
    safety: str                  # see SAFETY_CLASSES
    apply_via: str               # see APPLY_VIAS
    description: str = ""

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"knob {self.name!r}: kind {self.kind!r} "
                             f"not in {KINDS}")
        if self.safety not in SAFETY_CLASSES:
            raise ValueError(f"knob {self.name!r}: safety "
                             f"{self.safety!r} not in {SAFETY_CLASSES}")
        if self.apply_via not in APPLY_VIAS:
            raise ValueError(f"knob {self.name!r}: apply_via "
                             f"{self.apply_via!r} not in {APPLY_VIAS}")
        if self.kind == "continuous":
            if len(self.domain) != 2 or self.domain[0] >= self.domain[1]:
                raise ValueError(
                    f"knob {self.name!r}: continuous domain must be "
                    f"(lo, hi) with lo < hi, got {self.domain!r}")
        elif not self.domain:
            raise ValueError(f"knob {self.name!r}: empty domain")
        if self.kind == "discrete" and self.default not in self.domain:
            raise ValueError(f"knob {self.name!r}: default "
                             f"{self.default!r} outside its domain")

    def clamp(self, value):
        """Continuous values clamp to bounds; discrete values must be
        members of the domain."""
        if self.kind == "continuous":
            lo, hi = self.domain
            return min(max(value, lo), hi)
        if value not in self.domain:
            raise ValueError(f"{value!r} is not in knob {self.name!r}'s "
                             f"domain {self.domain!r}")
        return value


class KnobRegistry:
    """Ordered name -> Knob map; the driver iterates it to build the
    joint search space."""

    def __init__(self):
        self._knobs: Dict[str, Knob] = {}

    def register(self, knob: Knob) -> Knob:
        if knob.name in self._knobs:
            raise ValueError(f"knob {knob.name!r} already registered")
        self._knobs[knob.name] = knob
        return knob

    def get(self, name: str) -> Knob:
        return self._knobs[name]

    def __contains__(self, name: str) -> bool:
        return name in self._knobs

    def __iter__(self):
        return iter(self._knobs.values())

    def __len__(self) -> int:
        return len(self._knobs)

    def names(self):
        return list(self._knobs)

    def discrete(self):
        return [k for k in self if k.kind == "discrete"]

    def continuous(self):
        return [k for k in self if k.kind == "continuous"]

    def defaults(self) -> Dict[str, Any]:
        return {k.name: k.default for k in self}


def default_registry(include: Optional[Tuple[str, ...]] = None
                     ) -> KnobRegistry:
    """The stock search space over every subsystem's perf knob. The
    domains are the hand-tuned values the benches sweep
    (BENCH_PIPELINE/BENCH_SHIMS/BENCH_SPEED baselines); ``include``
    filters to a subset by name (the bench tuner scopes to what its
    workload can express)."""
    reg = KnobRegistry()
    all_knobs = (
        Knob("dcn_wire_spec", "discrete",
             ("", "bf16", "int8x256", "fp8x256"), "", "epoch",
             "wire_epoch",
             "Cross-slice gradient wire format (docs/compression.md); "
             "'' is raw fp32. Flips via a coordinator-stamped wire "
             "epoch so every rank requantizes at the same group seq."),
        Knob("fusion_threshold_mb", "discrete", (16, 32, 64, 128), 64,
             "epoch", "fusion_epoch",
             "Fusion-buffer cap (docs/fusion.md). Grouping never "
             "changes numerics, but all ranks must agree per group — "
             "stamped as a fusion epoch in coordinator params."),
        Knob("torch_bucket_mb", "discrete", (8, 16, 32, 64, 128), 64,
             "boundary", "bucket_repartition",
             "torch DistributedOptimizer gradient-bucket cap "
             "(docs/torch.md); re-partitions at a step boundary."),
        Knob("pipeline_schedule", "discrete",
             ("gpipe", "1f1b", "interleaved", "zb-h1"), "1f1b",
             "rebuild", "train_step_rebuild",
             "Pipeline schedule (docs/pipeline.md) — scored per trial "
             "via build_train_step rebuilds; zb-h1 is the zero-bubble "
             "point the search should find at scale."),
        Knob("num_microbatches", "discrete", (4, 8, 16, 32), 8,
             "rebuild", "train_step_rebuild",
             "Pipeline microbatch count; more microbatches shrink the "
             "bubble but pay per-tick overheads."),
        Knob("spec_tokens", "discrete", (1, 2, 3, 4, 6, 8), 4, "slot",
             "serving_slot",
             "Speculative-decode draft length k; adapts per slot from "
             "the live draft-acceptance rate (cold drafter backs off "
             "to k=1)."),
        Knob("cycle_time_ms", "continuous", (1.0, 100.0), 10.0, "live",
             "engine_param",
             "Engine cycle time — the legacy Bayesian tuner's "
             "continuous axis; its GP log seeds this knob."),
    )
    for k in all_knobs:
        if include is None or k.name in include:
            reg.register(k)
    return reg
