"""Global online autotuner (docs/autotune.md).

One search space over every perf knob — wire spec, fusion threshold,
torch bucket size, pipeline schedule/microbatches, serving spec_tokens,
engine cycle time — scored on measured step time from the history
plane, applied through safe per-knob mechanisms, and guarded by the
health plane's step-time regression detector with automatic rollback.

    knobs.py       typed knob registry (domain / apply / safety class)
    search.py      successive halving over the discrete space
    gp.py          numpy GP seeded from the legacy Bayesian tuner's log
    apply.py       the safe online apply plane (injected mechanisms)
    driver.py      the AutoTuner: baseline -> move -> score -> guard
    spec_adapt.py  per-slot adaptive speculative draft length

Enable on a training job with ``--autotune`` on the runner or
``HOROVOD_TPU_AUTOTUNE=1`` (env.autotune_global); the legacy eager-path
tuner keeps its own ``HOROVOD_AUTOTUNE`` switch.
"""

from .apply import ApplyPlane
from .driver import AutoTuner, Move, WindowedStepTime
from .gp import GaussianProcess, seed_gp_for_cycle_time, \
    seed_points_from_legacy_log
from .knobs import APPLY_VIAS, KINDS, SAFETY_CLASSES, Knob, \
    KnobRegistry, default_registry
from .search import Trial, enumerate_configs, rungs_for, \
    successive_halving
from .spec_adapt import SpecTokensController

__all__ = [
    "APPLY_VIAS", "KINDS", "SAFETY_CLASSES",
    "ApplyPlane", "AutoTuner", "GaussianProcess", "Knob",
    "KnobRegistry", "Move", "SpecTokensController", "Trial",
    "WindowedStepTime", "default_registry", "enumerate_configs",
    "rungs_for", "seed_gp_for_cycle_time",
    "seed_points_from_legacy_log", "successive_halving",
]
