"""Tiny Gaussian-process regressor + expected improvement — the
continuous half of the global autotuner's search.

The legacy eager-path Bayesian tuner (reference parameter_manager /
optim, tests/test_autotune.py) runs its GP in the native core and logs
every sampled point to ``HOROVOD_AUTOTUNE_LOG`` as
``fusion_mb,cycle_ms,hier_allreduce,hier_allgather,score`` CSV. This
module is the pure-python counterpart the GLOBAL tuner uses: it can be
seeded from that CSV (:func:`seed_points_from_legacy_log`) so a job
that already ran the legacy tuner starts its continuous knobs from the
legacy posterior instead of cold (docs/autotune.md).

Numpy-only RBF GP with a nugget; no scipy (the container bakes no new
deps). Scores are HIGHER-IS-BETTER (the driver scores negative step
time), matching the legacy log's score column.
"""

from __future__ import annotations

import csv
import math
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np


class GaussianProcess:
    """RBF-kernel GP posterior over a box-bounded input space."""

    def __init__(self, bounds: Sequence[Tuple[float, float]], *,
                 length_scale: float = 0.2, signal: float = 1.0,
                 noise: float = 1e-4):
        self.bounds = [(float(lo), float(hi)) for lo, hi in bounds]
        self.length_scale = float(length_scale)
        self.signal = float(signal)
        self.noise = float(noise)
        self._x: List[np.ndarray] = []
        self._y: List[float] = []
        self._chol: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None

    # ------------------------------------------------------------- data

    def _unit(self, x) -> np.ndarray:
        x = np.asarray(x, dtype=np.float64)
        out = np.empty_like(x)
        for i, (lo, hi) in enumerate(self.bounds):
            out[i] = (x[i] - lo) / (hi - lo) if hi > lo else 0.0
        return out

    def observe(self, x, y: float) -> None:
        self._x.append(self._unit(x))
        self._y.append(float(y))
        self._chol = None

    def __len__(self) -> int:
        return len(self._y)

    # ---------------------------------------------------------- fitting

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return self.signal * np.exp(-0.5 * d2 / self.length_scale ** 2)

    def _fit(self) -> None:
        x = np.stack(self._x)
        y = np.asarray(self._y)
        self._ymean = float(y.mean())
        self._yscale = float(y.std()) or 1.0
        k = self._kernel(x, x) + self.noise * np.eye(len(y))
        self._chol = np.linalg.cholesky(k)
        resid = (y - self._ymean) / self._yscale
        self._alpha = np.linalg.solve(
            self._chol.T, np.linalg.solve(self._chol, resid))

    def predict(self, x) -> Tuple[float, float]:
        """Posterior (mean, std) at one point in ORIGINAL units."""
        if not self._y:
            return 0.0, self.signal
        if self._chol is None:
            self._fit()
        xs = np.stack(self._x)
        q = self._unit(x)[None, :]
        kq = self._kernel(xs, q)[:, 0]
        mean = float(kq @ self._alpha) * self._yscale + self._ymean
        v = np.linalg.solve(self._chol, kq)
        var = max(self.signal - float(v @ v), 1e-12)
        return mean, math.sqrt(var) * self._yscale

    # ------------------------------------------------------ acquisition

    def expected_improvement(self, x) -> float:
        """EI versus the incumbent best (higher-is-better scores)."""
        if not self._y:
            return float("inf")
        mean, std = self.predict(x)
        best = max(self._y)
        if std <= 0:
            return max(mean - best, 0.0)
        z = (mean - best) / std
        # Normal pdf/cdf without scipy.
        pdf = math.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)
        cdf = 0.5 * (1.0 + math.erf(z / math.sqrt(2)))
        return (mean - best) * cdf + std * pdf

    def suggest(self, n_grid: int = 16) -> List[float]:
        """Argmax-EI over a deterministic per-dimension grid — small
        spaces (1-2 continuous knobs) make a grid sweep exact enough,
        and determinism is what the bench reproducibility guard needs."""
        dims = len(self.bounds)
        axes = [np.linspace(lo, hi, n_grid) for lo, hi in self.bounds]
        best_x, best_ei = None, -1.0
        grid = np.meshgrid(*axes, indexing="ij") if dims > 1 else [axes[0]]
        flat = np.stack([g.ravel() for g in grid], axis=-1)
        for row in flat:
            ei = self.expected_improvement(row)
            if ei > best_ei:
                best_ei, best_x = ei, row
        return [float(v) for v in best_x]


def seed_points_from_legacy_log(path: str) -> List[Tuple[dict, float]]:
    """Parse the legacy Bayesian tuner's CSV log into
    ``[({knob: value}, score), ...]`` seed observations.

    The log format is the native core's
    ``fusion_mb,cycle_ms,hier_allreduce,hier_allgather,score``
    (tests/test_autotune.py asserts the header). Missing or torn files
    yield an empty seed list — cold start is always a valid start."""
    if not path or not os.path.exists(path):
        return []
    points: List[Tuple[dict, float]] = []
    try:
        with open(path, newline="") as f:
            reader = csv.reader(f)
            header = next(reader, None)
            if header is None or header[0] != "fusion_mb":
                return []
            for row in reader:
                if len(row) != 5:
                    continue
                try:
                    points.append((
                        {"fusion_mb": float(row[0]),
                         "cycle_time_ms": float(row[1]),
                         "hier_allreduce": bool(float(row[2])),
                         "hier_allgather": bool(float(row[3]))},
                        float(row[4])))
                except ValueError:
                    continue
    except OSError:
        return []
    return points


def seed_gp_for_cycle_time(gp: GaussianProcess, log_path: str) -> int:
    """Feed the legacy log's (cycle_ms, score) samples into a 1-D GP
    over cycle time; returns how many points seeded."""
    pts = seed_points_from_legacy_log(log_path)
    for cfg, score in pts:
        gp.observe([cfg["cycle_time_ms"]], score)
    return len(pts)
