"""Per-slot adaptive speculative-decode draft length.

The ``spec_tokens`` knob is the one knob the global tuner does NOT move
centrally: the right draft length depends on how well the drafter
predicts THIS request's continuation, a signal only the serving engine
sees and only per slot. So the knob's safety class is ``slot`` and this
controller owns it (docs/autotune.md):

  - each slot keeps an EWMA of its draft-acceptance rate (fraction of
    proposed draft tokens the target verified);
  - acceptance below the backoff threshold halves ``k_eff``
    (multiplicative decrease — a cold or mismatched drafter quickly
    lands at k=1, where the engine falls back to the plain decode path
    and stops paying the verify-width tax entirely);
  - acceptance above the raise threshold adds one (additive increase,
    AIMD-style, up to the configured cap);
  - at k=1 the engine calls :meth:`note_plain_step` each plain decode
    step; every ``probe_every`` such steps the controller probes back
    to k=2 so a recovered drafter is re-discovered without a central
    tuner move.

The engine verifies at ``width = max(k_eff)`` over the batch and caps
each slot's accepted run at its own ``k_eff`` — slots never pay for a
neighbour's optimism beyond the shared verify width.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable


@dataclasses.dataclass
class _SlotState:
    k_eff: int
    ewma: float
    plain_steps: int = 0


class SpecTokensController:
    """AIMD controller over per-slot speculative draft length."""

    def __init__(self, k_max: int, *, alpha: float = 0.5,
                 backoff_below: float = 0.25, raise_above: float = 0.6,
                 probe_every: int = 16):
        if k_max < 1:
            raise ValueError("k_max must be >= 1")
        self.k_max = int(k_max)
        self.alpha = float(alpha)
        self.backoff_below = float(backoff_below)
        self.raise_above = float(raise_above)
        self.probe_every = int(probe_every)
        self._slots: Dict[int, _SlotState] = {}
        self._metrics = None

    # ------------------------------------------------------------ state

    def _state(self, slot: int) -> _SlotState:
        st = self._slots.get(slot)
        if st is None:
            # Optimistic start: run at the configured k until the
            # acceptance signal says otherwise.
            st = _SlotState(k_eff=self.k_max, ewma=1.0)
            self._slots[slot] = st
        return st

    def slot_k(self, slot: int) -> int:
        return self._state(slot).k_eff

    def width(self, slots: Iterable[int]) -> int:
        """Verify width for one spec step: the max k over the batch
        (1 when every slot has backed off — the engine then takes the
        plain decode path)."""
        ks = [self._state(s).k_eff for s in slots]
        return max(ks) if ks else self.k_max

    def reset(self, slot: int) -> None:
        self._slots.pop(slot, None)

    # ---------------------------------------------------------- signals

    def observe(self, slot: int, proposed: int, accepted: int) -> int:
        """Feed one spec step's outcome for one slot; returns the
        slot's (possibly adjusted) k_eff."""
        st = self._state(slot)
        if proposed > 0:
            rate = min(max(accepted / proposed, 0.0), 1.0)
            st.ewma = self.alpha * rate + (1.0 - self.alpha) * st.ewma
        old = st.k_eff
        if st.ewma < self.backoff_below:
            st.k_eff = max(1, st.k_eff // 2)
            if st.k_eff != old:
                self._record(slot, st, old, "spec_backoff", "down")
        elif st.ewma > self.raise_above and st.k_eff < self.k_max:
            st.k_eff = st.k_eff + 1
            self._record(slot, st, old, "spec_raise", "up")
        st.plain_steps = 0
        return st.k_eff

    def note_plain_step(self, slot: int) -> int:
        """Tick the probe clock while a slot decodes plainly at k=1;
        after ``probe_every`` plain steps, probe back to k=2 (with a
        half-reset EWMA so one good probe can keep climbing)."""
        st = self._state(slot)
        if st.k_eff > 1:
            return st.k_eff
        st.plain_steps += 1
        if st.plain_steps >= self.probe_every:
            old = st.k_eff
            st.k_eff = min(2, self.k_max)
            st.ewma = max(st.ewma, 0.5)
            st.plain_steps = 0
            if st.k_eff != old:
                self._record(slot, st, old, "spec_probe", "probe")
        return st.k_eff

    # -------------------------------------------------------- telemetry

    def _record(self, slot: int, st: _SlotState, old: int,
                event: str, direction: str) -> None:
        try:
            from ..observability import flight_recorder as _fr
            _fr.recorder().note("autotune", (
                event, "spec_tokens", str(st.k_eff),
                round(st.ewma, 4), float(old), f"slot={slot}"))
        except Exception:  # pragma: no cover
            pass
        try:
            m = self._metrics
            if m is None:
                from ..observability import registry as _obs
                r = _obs.registry()
                m = self._metrics = (
                    r.gauge("hvdtpu_autotune_spec_k",
                            "Adaptive speculative draft length across "
                            "serving slots (stat=min|max)"),
                    r.counter("hvdtpu_autotune_spec_moves_total",
                              "Per-slot spec_tokens adjustments by "
                              "direction (up, down, probe)"))
            gauge, counter = m
            ks = [s.k_eff for s in self._slots.values()]
            gauge.labels(stat="min").set(float(min(ks)))
            gauge.labels(stat="max").set(float(max(ks)))
            counter.labels(direction=direction).inc()
        except Exception:  # pragma: no cover
            pass
