"""Successive halving over the discrete knob space.

The discrete half of the global tuner's search (docs/autotune.md): all
candidate configs get a small measurement budget, the top 1/eta by
score survive to the next rung with the budget multiplied by eta, until
one winner remains — the classic successive-halving bandit, which suits
step-time tuning because a config that is 20% slower reveals itself in
a handful of steps while the final contenders deserve long, low-noise
windows. The MLPerf pod-scaling playbook (arXiv 1909.09756) is the
convergence methodology: measure short, prune hard, re-measure the
survivors at scale.

Everything here is deterministic given the candidate order and the
score function — the bench reproducibility guard regenerates
BENCH_AUTOTUNE.json twice and diffs the deterministic fields.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Callable, Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass
class Trial:
    """One scored measurement of one candidate config at one rung."""

    config: Dict
    rung: int
    budget: int
    score: float


def enumerate_configs(knobs, *, constraint: Optional[Callable] = None
                      ) -> List[Dict]:
    """Cartesian product over the discrete knobs' domains, in domain
    order (deterministic), filtered by ``constraint(config) -> bool``
    (e.g. zb-h1 needs microbatches >= stages)."""
    names = [k.name for k in knobs]
    domains = [k.domain for k in knobs]
    out = []
    for combo in itertools.product(*domains):
        cfg = dict(zip(names, combo))
        if constraint is None or constraint(cfg):
            out.append(cfg)
    return out


def successive_halving(candidates: Sequence[Dict],
                       score_fn: Callable[[Dict, int], float], *,
                       eta: int = 2, base_budget: int = 1,
                       min_survivors: int = 1
                       ) -> Tuple[Dict, List[Trial]]:
    """Run successive halving; returns ``(best_config, trials)``.

    ``score_fn(config, budget)`` measures one candidate with ``budget``
    units of measurement (steps, repeats — the caller's choice) and
    returns a HIGHER-IS-BETTER score (the driver scores negative step
    time). Ties break by candidate order, so equal scores keep the
    earlier candidate — determinism again."""
    if not candidates:
        raise ValueError("successive halving needs at least one "
                         "candidate")
    if eta < 2:
        raise ValueError("eta must be >= 2")
    alive: List[Dict] = list(candidates)
    budget = int(base_budget)
    rung = 0
    trials: List[Trial] = []
    while True:
        scored = []
        for cfg in alive:
            s = float(score_fn(cfg, budget))
            trials.append(Trial(dict(cfg), rung, budget, s))
            scored.append((s, cfg))
        # Stable sort: equal scores keep candidate order.
        scored.sort(key=lambda p: -p[0])
        if len(alive) <= min_survivors:
            return dict(scored[0][1]), trials
        keep = max(min_survivors, len(alive) // eta)
        alive = [cfg for _, cfg in scored[:keep]]
        budget *= eta
        rung += 1


def rungs_for(n_candidates: int, *, eta: int = 2,
              min_survivors: int = 1) -> int:
    """How many rungs successive halving will run (for bench metadata)."""
    rungs = 1
    alive = n_candidates
    while alive > min_survivors:
        alive = max(min_survivors, alive // eta)
        rungs += 1
    return rungs
