"""The safe online apply plane: knob -> live-subsystem mechanism.

The driver never pokes a subsystem directly — it hands a (knob, value)
pair to this plane, which routes by ``knob.apply_via`` to a callable
the integration layer injected (docs/autotune.md):

  ``wire_epoch``         set_wire(spec)       coordinator-stamped wire
                                              epoch (PR 6 mechanism) so
                                              every rank requantizes at
                                              the same group seq.
  ``fusion_epoch``       set_fusion(mb)       coordinator-stamped fusion
                                              epoch — all ranks regroup
                                              at the same seq.
  ``bucket_repartition`` set_bucket_mb(mb)    torch bucket re-partition
                                              at a step boundary.
  ``train_step_rebuild`` rebuild(config)      scored per-trial only —
                                              the plane refuses it as an
                                              ONLINE move.
  ``serving_slot``       (per-slot)           adapts from its own live
                                              signal (spec_adapt.py);
                                              never a driver move.
  ``engine_param``       set_engine_param(name, value)

A mechanism the integration did not inject is simply unsupported: the
driver skips the knob rather than guessing at a side door. That is the
safety contract — every path to a live job goes through exactly one
named, injected hook.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

from .knobs import Knob


@dataclasses.dataclass
class ApplyPlane:
    """Injected mechanism callables, keyed by ``Knob.apply_via``."""

    set_wire: Optional[Callable[[str], Any]] = None
    set_fusion: Optional[Callable[[int], Any]] = None
    set_bucket_mb: Optional[Callable[[int], Any]] = None
    rebuild: Optional[Callable[[dict], Any]] = None
    set_engine_param: Optional[Callable[[str, Any], Any]] = None

    def supports(self, knob: Knob) -> bool:
        """Can this plane flip ``knob`` as an ONLINE move? Rebuild and
        per-slot knobs are never online moves regardless of injection."""
        return self._hook(knob) is not None and knob.apply_via not in (
            "train_step_rebuild", "serving_slot")

    def _hook(self, knob: Knob):
        return {
            "wire_epoch": self.set_wire,
            "fusion_epoch": self.set_fusion,
            "bucket_repartition": self.set_bucket_mb,
            "train_step_rebuild": self.rebuild,
            "engine_param": self.set_engine_param,
        }.get(knob.apply_via)

    def apply(self, knob: Knob, value) -> None:
        if knob.apply_via == "serving_slot":
            raise ValueError(
                f"knob {knob.name!r} adapts per serving slot "
                "(spec_adapt.SpecTokensController), not via the driver")
        if knob.apply_via == "train_step_rebuild":
            raise ValueError(
                f"knob {knob.name!r} needs a train-step rebuild; score "
                "it per-trial via AutoTuner.tune_rebuild, never as an "
                "online move")
        hook = self._hook(knob)
        if hook is None:
            raise ValueError(
                f"no mechanism injected for knob {knob.name!r} "
                f"(apply_via={knob.apply_via!r})")
        if knob.apply_via == "engine_param":
            hook(knob.name, value)
        else:
            hook(value)
