"""The global autotune driver — closes the observe -> tune loop.

One search space over every perf knob (knobs.py), scored on MEASURED
windowed step time read from the ``observability/history`` series, with
a safe online apply plane (apply.py) and a health-plane guard: every
move is recorded in the flight recorder, scored against a pre-move
baseline with the same relative-regression comparison ``tools/health
--baseline`` uses, and automatically rolled back when the step-time
regression detector (observability/health.EwmaDetector) fires or the
post-move window regresses beyond the guard threshold
(docs/autotune.md).

Two operating modes share the scoring machinery:

  - ONLINE (:meth:`AutoTuner.run`): coordinate sweep over the knobs the
    apply plane can flip on a live job (wire spec / fusion threshold
    via coordinator-stamped epochs, torch bucket size at a step
    boundary, cycle time live). Each candidate value is one guarded
    move.
  - OFFLINE / per-trial (:func:`search.successive_halving` via
    :meth:`AutoTuner.tune_rebuild`): the ``rebuild`` safety class
    (pipeline schedule, microbatch count) is scored by rebuilding the
    train step per trial — ``bench_engine.py --autotune`` drives this
    against the bench workload and writes BENCH_AUTOTUNE.json.

Scores are negative mean step seconds — higher is better, matching the
legacy GP log convention so its seeds compose (gp.py).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from .knobs import Knob, KnobRegistry, default_registry
from .search import Trial, enumerate_configs, successive_halving

_log = logging.getLogger("horovod_tpu.autotune")


# --------------------------------------------------------------------------
# Metrics (docs/metrics.md#autotuner)
# --------------------------------------------------------------------------


class _Metrics:
    _instance = None

    def __init__(self):
        from ..observability import registry as _obs
        r = _obs.registry()
        self.trials = r.counter(
            "hvdtpu_autotune_trials_total",
            "Scored autotuner trials, by knob (or 'joint' for the "
            "multi-knob rebuild search)")
        self.moves = r.counter(
            "hvdtpu_autotune_moves_total",
            "Online autotuner moves by knob and outcome: kept (clear "
            "win), reverted (no win), rolled_back (guard fired)")
        self.rollbacks = r.counter(
            "hvdtpu_autotune_rollbacks_total",
            "Guard-triggered rollbacks — the post-move window tripped "
            "the step-time regression detector or the baseline "
            "comparison")
        self.score = r.gauge(
            "hvdtpu_autotune_score",
            "Last trial score per knob (negative mean step seconds — "
            "higher is better)")
        self.best = r.gauge(
            "hvdtpu_autotune_best_score",
            "Best score the tuner has measured so far this run")

    @classmethod
    def get(cls) -> "_Metrics":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance


def _note(event: str, knob: str, value, score, baseline, detail: str = ""):
    try:
        from ..observability import flight_recorder as _fr
        _fr.recorder().note("autotune", (
            event, knob, str(value),
            None if score is None else round(float(score), 6),
            None if baseline is None else round(float(baseline), 6),
            detail))
    except Exception:  # pragma: no cover — telemetry must never break
        pass


# --------------------------------------------------------------------------
# Step-time source: the history plane's series
# --------------------------------------------------------------------------


class WindowedStepTime:
    """Mean step time over the most recent window of the persisted
    ``hvdtpu_step_seconds|mean`` history series (PR 15's on-disk
    time-series) — the measurement the driver scores moves on."""

    FAMILY = "hvdtpu_step_seconds"

    def __init__(self, inputs: Sequence[str], *, window: int = 8):
        self.inputs = list(inputs)
        self.window = int(window)

    def read(self) -> Optional[float]:
        from ..observability import history as _history
        from ..observability.health import split_series_key
        try:
            files = _history.load_history(self.inputs)
        except FileNotFoundError:
            return None
        vals: List[float] = []
        for hf in files:
            for key, pts in hf.series().items():
                family, _, suffix = split_series_key(key)
                if family == self.FAMILY and suffix == "mean":
                    vals.extend(v for _, v in pts[-self.window:])
        if not vals:
            return None
        return sum(vals) / len(vals)


# --------------------------------------------------------------------------
# The driver
# --------------------------------------------------------------------------


@dataclasses.dataclass
class Move:
    """One guarded online move and its verdict."""

    knob: str
    old: Any
    new: Any
    baseline_s: Optional[float]
    after_s: Optional[float]
    outcome: str          # "kept" | "reverted" | "rolled_back"
    detail: str = ""


class AutoTuner:
    """Coordinate-sweep online tuner + per-trial rebuild search.

    Args:
      registry: the knob space (default: :func:`knobs.default_registry`).
      plane: an :class:`apply.ApplyPlane` wiring knobs to live
        subsystems; knobs whose mechanism the plane does not support
        are skipped online.
      measure: ``measure(budget_windows) -> step_seconds`` — blocks
        until ``budget_windows`` fresh measurement windows landed and
        returns their mean step time (see :class:`WindowedStepTime`).
      guard_rel: post-move window worse than baseline by more than this
        fraction => rollback (the ``tools/health --baseline`` regression
        threshold).
      min_rel_gain: keep a move only if it improves step time by at
        least this fraction; anything in between is reverted (no
        free-riding on noise).
      trial_budget: measurement windows per scored candidate.
      seed_log: optional legacy Bayesian tuner CSV
        (``HOROVOD_AUTOTUNE_LOG``) to warm-start continuous knobs.
    """

    def __init__(self, registry: Optional[KnobRegistry] = None, *,
                 plane=None,
                 measure: Optional[Callable[[int], Optional[float]]] = None,
                 guard_rel: float = 0.10, min_rel_gain: float = 0.02,
                 trial_budget: int = 2,
                 seed_log: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic):
        from .apply import ApplyPlane
        from ..observability.health import EwmaDetector
        self.registry = registry or default_registry()
        self.plane = plane or ApplyPlane()
        self.measure = measure or (lambda budget: None)
        self.guard_rel = float(guard_rel)
        self.min_rel_gain = float(min_rel_gain)
        self.trial_budget = int(trial_budget)
        self.clock = clock
        self.current: Dict[str, Any] = self.registry.defaults()
        self.moves: List[Move] = []
        self.best_score: Optional[float] = None
        # The step-time regression detector: same shape the history
        # plane runs on hvdtpu_step_seconds|mean (health.default_specs).
        self._detector = EwmaDetector("up", min_rel=0.15)
        self._gp = self._seed_gp(seed_log)

    def _seed_gp(self, seed_log):
        from .gp import GaussianProcess, seed_gp_for_cycle_time
        cont = self.registry.continuous()
        if not cont:
            return None
        gp = GaussianProcess([k.domain for k in cont])
        if seed_log and len(cont) == 1 and cont[0].name == "cycle_time_ms":
            n = seed_gp_for_cycle_time(gp, seed_log)
            if n:
                _log.info("autotune: seeded cycle-time GP with %d "
                          "legacy-log points", n)
                _note("gp_seed", "cycle_time_ms", n, None, None,
                      seed_log)
        return gp

    # ------------------------------------------------------- measurement

    def _window(self) -> Optional[float]:
        v = self.measure(self.trial_budget)
        if v is not None:
            self._detector.update(self.clock(), float(v))
        return v

    def _score(self, knob_name: str, step_s: Optional[float]) -> float:
        score = float("-inf") if step_s is None else -float(step_s)
        m = _Metrics.get()
        m.trials.labels(knob=knob_name).inc()
        if step_s is not None:
            m.score.labels(knob=knob_name).set(score)
            if self.best_score is None or score > self.best_score:
                self.best_score = score
                m.best.labels().set(score)
        return score

    # ------------------------------------------------------ online moves

    def try_move(self, knob_name: str, value) -> Move:
        """Apply one value through the safe plane, score the post-move
        window against the pre-move baseline, keep / revert / roll
        back. The guard fires on either the detector or the baseline
        comparison — belt and braces, exactly one rollback."""
        knob = self.registry.get(knob_name)
        old = self.current[knob_name]
        value = knob.clamp(value)
        baseline = self._window()
        _note("move", knob_name, value, None, baseline, f"from={old!r}")
        self.plane.apply(knob, value)
        self.current[knob_name] = value
        after = self._window()
        fired = (after is not None and baseline is not None
                 and after > baseline * (1.0 + self.guard_rel))
        det = None
        if after is not None:
            det = self._detector.update(self.clock(), float(after))
        self._score(knob_name, after)
        m = _Metrics.get()
        if fired or det is not None:
            # ROLLBACK: restore the pre-move value through the same
            # mechanism (a wire knob re-stamps an epoch, a bucket knob
            # re-partitions back) and record the guard verdict.
            self.plane.apply(knob, old)
            self.current[knob_name] = old
            detail = ("detector" if det is not None else
                      f"+{(after - baseline) / baseline:.1%}")
            move = Move(knob_name, old, value, baseline, after,
                        "rolled_back", detail)
            m.rollbacks.labels(knob=knob_name).inc()
            m.moves.labels(knob=knob_name, outcome="rolled_back").inc()
            _note("rollback", knob_name, old,
                  None if after is None else -after, baseline, detail)
        elif (after is not None and baseline is not None
              and after <= baseline * (1.0 - self.min_rel_gain)):
            move = Move(knob_name, old, value, baseline, after, "kept")
            m.moves.labels(knob=knob_name, outcome="kept").inc()
            _note("keep", knob_name, value, -after, baseline)
        else:
            self.plane.apply(knob, old)
            self.current[knob_name] = old
            move = Move(knob_name, old, value, baseline, after,
                        "reverted", "no_gain")
            m.moves.labels(knob=knob_name, outcome="reverted").inc()
            _note("revert", knob_name, old,
                  None if after is None else -after, baseline)
        self.moves.append(move)
        return move

    def run(self, knob_names: Optional[Sequence[str]] = None
            ) -> List[Move]:
        """One full online pass: every discrete knob the plane can
        apply, domain values in order, each a guarded move; continuous
        knobs take one GP suggestion each."""
        out: List[Move] = []
        names = (list(knob_names) if knob_names is not None
                 else self.registry.names())
        for name in names:
            knob = self.registry.get(name)
            if not self.plane.supports(knob):
                continue
            if knob.kind == "discrete":
                for v in knob.domain:
                    if v == self.current[name]:
                        continue
                    out.append(self.try_move(name, v))
            elif self._gp is not None:
                cont = [k.name for k in self.registry.continuous()]
                x = self._gp.suggest()
                v = x[cont.index(name)]
                move = self.try_move(name, v)
                if move.after_s is not None:
                    self._gp.observe(x, -move.after_s)
                out.append(move)
        _note("pass_done", "all", len(out), self.best_score, None)
        return out

    # -------------------------------------------------- rebuild knobs

    def tune_rebuild(self, score_fn: Callable[[Dict, int], float], *,
                     knob_names: Sequence[str] = ("pipeline_schedule",
                                                  "num_microbatches"),
                     constraint: Optional[Callable] = None,
                     eta: int = 2):
        """Successive halving over the ``rebuild`` knobs: each
        candidate is scored by rebuilding the train step
        (``score_fn(config, budget) -> score``, higher is better).
        Returns ``(best_config, trials)`` and records every trial."""
        knobs = [self.registry.get(n) for n in knob_names]
        candidates = enumerate_configs(knobs, constraint=constraint)
        m = _Metrics.get()

        def scored(cfg: Dict, budget: int) -> float:
            s = float(score_fn(cfg, budget))
            m.trials.labels(knob="joint").inc()
            m.score.labels(knob="joint").set(s)
            if self.best_score is None or s > self.best_score:
                self.best_score = s
                m.best.labels().set(s)
            _note("trial", "joint", cfg, s, None, f"budget={budget}")
            return s

        best, trials = successive_halving(
            candidates, scored, eta=eta,
            base_budget=max(1, self.trial_budget))
        self.current.update(best)
        _note("converged", "joint", best,
              max(t.score for t in trials), None,
              f"trials={len(trials)}")
        return best, trials
