"""Block-scaled quantization for the collective wire (EQuARX-style).

The cast compressors (compression.py) change the wire dtype but keep the
value range of the input: fp8's ±448 window clips large gradients and
flushes small ones to zero. Block scaling fixes both — the fusion buffer
is cut into fixed-size blocks (default 256 elements), each block is
scaled by its absmax so the full quantizer range is used regardless of
the block's magnitude, and the fp32 per-block scales ride the wire next
to the payload (~1.6% overhead at block 256).

The allreduce itself runs in the quantized domain end-to-end inside the
fused XLA program (EQuARX, arxiv 2506.17615 — "dual quantization"):

  phase 1  quantize the local buffer; all_to_all the wire payload so
           every rank receives each peer's contribution to its own
           shard (a reduce-scatter whose traffic is wire bytes, not
           fp32 bytes); dequantize and accumulate in fp32.
  phase 2  requantize the reduced shard; all_gather payload + scales
           (again wire bytes on the ICI); dequantize.

fp8 payloads cross the collectives bitcast to uint8 — the established
transport idiom for 8-bit float payloads on backends without native
fp8 collective support; the bit pattern is what moves either way.

Everything here is pure jax.numpy, usable eagerly, under jit, and
inside shard_map — the executor's fused programs and the in-jit
``allreduce_gradients`` path share these functions.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Union

import numpy as np

import jax
import jax.numpy as jnp

DEFAULT_BLOCK = 256

# fp32 per-block scale riding the wire next to the payload.
SCALE_BYTES = 4


class WireSpec(NamedTuple):
    """Wire format of a block-scaled quantized collective."""
    kind: str          # "int8_blockwise" | "fp8_blockwise"
    wire_dtype: str    # "int8" | "float8_e4m3fn"
    block_size: int

    @property
    def qmax(self) -> float:
        # int8 uses the symmetric [-127, 127] range; e4m3's largest
        # finite value is 448.
        return 127.0 if self.wire_dtype == "int8" else 448.0

    def encoded(self) -> str:
        tag = "int8" if self.wire_dtype == "int8" else "fp8"
        return f"{tag}x{self.block_size}"


INT8_BLOCKWISE = WireSpec("int8_blockwise", "int8", DEFAULT_BLOCK)
FP8_BLOCKWISE = WireSpec("fp8_blockwise", "float8_e4m3fn", DEFAULT_BLOCK)


def parse(spec: Union[str, WireSpec, None]) -> Optional[WireSpec]:
    """Parse a wire spec string ("int8x256" / "fp8x256") or pass a
    WireSpec through. None stays None (no wire compression)."""
    if spec is None or isinstance(spec, WireSpec):
        return spec
    s = str(spec)
    tag, _, block = s.partition("x")
    try:
        bs = int(block) if block else DEFAULT_BLOCK
    except ValueError:
        raise ValueError(f"malformed wire spec {spec!r}") from None
    if tag == "int8":
        return WireSpec("int8_blockwise", "int8", bs)
    if tag == "fp8":
        return WireSpec("fp8_blockwise", "float8_e4m3fn", bs)
    raise ValueError(
        f"unknown wire spec {spec!r} (expected 'int8xN' or 'fp8xN')")


def padded_size(n: int, multiple: int) -> int:
    return -(-int(n) // multiple) * multiple


def wire_nbytes(spec: Union[str, WireSpec], n_elements: int) -> int:
    """Bytes a tensor of ``n_elements`` occupies on the wire: payload
    padded to whole blocks (1 byte/element for both wire dtypes) plus
    one fp32 scale per block. This is what fusion planning counts
    against the threshold and what the engine's wire-byte accounting
    records."""
    spec = parse(spec)
    blocks = -(-int(n_elements) // spec.block_size)
    return blocks * spec.block_size + blocks * SCALE_BYTES


def quantize_blocks(x, spec: WireSpec):
    """Flat fp32 ``x`` (length a multiple of block_size) -> (payload in
    the wire dtype, fp32 per-block scales)."""
    bs = spec.block_size
    xb = x.reshape(-1, bs)
    absmax = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
    # All-zero blocks (padding, dead gradients) keep scale 1 so the
    # dequantized block is exactly zero instead of 0/0.
    scale = jnp.where(absmax > 0, absmax / spec.qmax, jnp.ones_like(absmax))
    y = xb / scale
    if spec.wire_dtype == "int8":
        q = jnp.clip(jnp.round(y), -spec.qmax, spec.qmax).astype(jnp.int8)
    else:
        q = y.astype(jnp.float8_e4m3fn)
    return q.reshape(-1), scale[:, 0]


def dequantize_blocks(q, scales, spec: WireSpec):
    bs = spec.block_size
    y = q.astype(jnp.float32).reshape(-1, bs) * scales[:, None]
    return y.reshape(-1)


def _to_transport(q, spec: WireSpec):
    """fp8 payloads cross XLA collectives bitcast to uint8; int8 crosses
    natively. Same bytes either way."""
    if spec.wire_dtype == "int8":
        return q
    return jax.lax.bitcast_convert_type(q, jnp.uint8)


def _from_transport(w, spec: WireSpec):
    if spec.wire_dtype == "int8":
        return w
    return jax.lax.bitcast_convert_type(w, jnp.float8_e4m3fn)


def local_roundtrip(x, spec: Union[str, WireSpec]):
    """Quantize-dequantize ``x`` exactly as this rank's phase-1 wire
    contribution would be (flat, per-tensor block boundaries). The
    error-feedback residual is ``x - local_roundtrip(x)`` — what the
    wire dropped this step and the next step must carry."""
    spec = parse(spec)
    dt = x.dtype
    flat = jnp.ravel(x).astype(jnp.float32)
    n = flat.size
    if n == 0:
        return x
    m = padded_size(n, spec.block_size)
    if m != n:
        flat = jnp.concatenate([flat, jnp.zeros((m - n,), jnp.float32)])
    q, s = quantize_blocks(flat, spec)
    out = dequantize_blocks(q, s, spec)[:n]
    return out.reshape(x.shape).astype(dt)


def channel_block(n: int, block: int) -> int:
    """Largest quantization chunk that DIVIDES ``n`` without exceeding
    ``block`` — the wire format's 256-element blocks clamped to a
    channel dimension (a KV head's head_dim is usually 64/128, smaller
    than the default wire block)."""
    qb = min(int(block), int(n))
    while n % qb:
        qb -= 1
    return qb


def quantize_channels(x, spec: Union[str, WireSpec]):
    """Blockwise absmax quantization along the LAST axis of ``x`` —
    the KV-pool variant of :func:`quantize_blocks`: chunks of
    ``channel_block(x.shape[-1], spec.block_size)`` elements, one fp32
    scale each, so a tensor-parallel head shard quantizes exactly as
    the same head does unsharded (blocks never straddle heads).

    Returns ``(payload, scales)`` with payload in the wire dtype and
    ``scales`` shaped ``x.shape[:-1] + (n_chunks,)``."""
    spec = parse(spec)
    n = x.shape[-1]
    qb = channel_block(n, spec.block_size)
    xb = x.astype(jnp.float32).reshape(*x.shape[:-1], n // qb, qb)
    absmax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / spec.qmax,
                      jnp.ones_like(absmax))
    y = xb / scale
    if spec.wire_dtype == "int8":
        q = jnp.clip(jnp.round(y), -spec.qmax, spec.qmax).astype(jnp.int8)
    else:
        q = y.astype(jnp.float8_e4m3fn)
    return q.reshape(x.shape), scale[..., 0]


def dequantize_channels(q, scales, spec: Union[str, WireSpec]):
    """Inverse of :func:`quantize_channels`: fp32 out, same shape as
    the payload."""
    parse(spec)   # validates; the math only needs the shapes
    qb = q.shape[-1] // scales.shape[-1]
    y = (q.astype(jnp.float32)
          .reshape(*scales.shape, qb) * scales[..., None])
    return y.reshape(q.shape)


def allreduce_blocks(buf, axis_name: str, spec: WireSpec,
                     world: Optional[int] = None):
    """Dual block-quantized sum-allreduce of a flat fp32 buffer inside a
    mapped axis. ``buf`` length must be a multiple of
    ``world * block_size`` (use :func:`padded_size`); the result is the
    fp32 sum over the axis, carrying one quantization per phase."""
    if world is None:
        world = axis_world(axis_name)
    n = buf.shape[0]
    bs = spec.block_size
    shard = n // world
    # Phase 1: quantize locally, reduce-scatter in the wire domain.
    q, scales = quantize_blocks(buf, spec)
    qw = _to_transport(q, spec).reshape(world, shard)
    sw = scales.reshape(world, shard // bs)
    qr = jax.lax.all_to_all(qw, axis_name, split_axis=0, concat_axis=0,
                            tiled=True)
    sr = jax.lax.all_to_all(sw, axis_name, split_axis=0, concat_axis=0,
                            tiled=True)
    # fp32 dequant-accumulate of every rank's contribution to my shard.
    contrib = _from_transport(qr, spec).astype(jnp.float32)
    contrib = contrib.reshape(world, shard // bs, bs)
    red = jnp.sum(contrib * sr[:, :, None], axis=0).reshape(shard)
    # Phase 2: requantize the reduced shard, allgather in the wire domain.
    q2, s2 = quantize_blocks(red, spec)
    qg = jax.lax.all_gather(_to_transport(q2, spec), axis_name, axis=0,
                            tiled=True)
    sg = jax.lax.all_gather(s2, axis_name, axis=0, tiled=True)
    return dequantize_blocks(_from_transport(qg, spec), sg, spec)


def axis_world(axis_name: str) -> int:
    """Static size of a bound mapped axis; raises NameError (like
    lax.psum on an unbound axis) so callers' not-under-shard-map
    fallbacks keep working."""
    try:
        return int(jax.lax.axis_size(axis_name))
    except NameError:
        raise
    except Exception as e:
        raise NameError(f"unbound axis name: {axis_name}") from e


def quantized_psum(x, axis_name: str, spec: Union[str, WireSpec]):
    """Sum-allreduce one tensor over ``axis_name`` through the dual
    block-quantized wire — the in-jit (shard_map) counterpart of the
    executor's fused quantized program. Raises NameError when the axis
    is not bound, mirroring lax.psum."""
    spec = parse(spec)
    world = axis_world(axis_name)
    dt = x.dtype
    flat = jnp.ravel(x).astype(jnp.float32)
    n = flat.size
    if n == 0:
        return x
    m = padded_size(n, world * spec.block_size)
    if m != n:
        flat = jnp.concatenate([flat, jnp.zeros((m - n,), jnp.float32)])
    out = allreduce_blocks(flat, axis_name, spec, world)[:n]
    return out.reshape(x.shape).astype(dt)
