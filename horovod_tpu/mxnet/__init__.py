"""horovod_tpu.mxnet — the MXNet framework shim.

Parity target: horovod/mxnet/__init__.py (105 LoC): a
``DistributedOptimizer`` that allreduces gradients inside ``update()`` /
``update_multi_precision()`` before delegating to the wrapped optimizer
(:36-59), and ``broadcast_parameters`` for dicts and gluon
``ParameterDict``s with the deferred-initialization skip (:71-104).

Works against real ``mxnet`` when importable; otherwise against the
NDArray protocol in :mod:`horovod_tpu.mxnet.ndarray` (this image ships
without MXNet). The wrapped optimizer only needs the
``mx.optimizer.Optimizer`` method surface the reference touches.
"""

from __future__ import annotations

import numpy as _np

from .mpi_ops import (init, shutdown, is_initialized, rank, local_rank,
                      size, local_size, mpi_threads_supported,
                      allreduce, allreduce_, allreduce_multi_, allgather,
                      broadcast, broadcast_)
from . import ndarray as nd
from .ndarray import NDArray, DeferredInitializationError

try:  # pragma: no cover - mxnet is not in the image
    import mxnet as _mx
    _OptimizerBase = _mx.optimizer.Optimizer
except ImportError:
    _mx = None
    _OptimizerBase = object

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "local_rank", "size",
    "local_size", "mpi_threads_supported",
    "allreduce", "allreduce_", "allreduce_multi_", "allgather",
    "broadcast", "broadcast_",
    "DistributedOptimizer", "broadcast_parameters", "nd", "NDArray",
]


class DistributedOptimizer(_OptimizerBase):
    """Wraps an MXNet-style optimizer: every ``update`` first averages the
    gradient(s) over all processes (horovod/mxnet/__init__.py:36-59).

    The index-list form enqueues all allreduces before blocking so the
    engine can fuse them into a single XLA program.
    """

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def create_state(self, index, weight):
        return self._optimizer.create_state(index, weight)

    def create_state_multi_precision(self, index, weight):
        return self._optimizer.create_state_multi_precision(index, weight)

    def _do_allreduce(self, index, grad):
        if isinstance(index, (tuple, list)):
            import horovod_tpu.ops as _ops
            handles = [
                _ops.allreduce_async(g.asnumpy(), average=True, name=str(i))
                for i, g in zip(index, grad)]
            for g, h in zip(grad, handles):
                g[:] = _np.asarray(h.wait()).reshape(g.shape)
        else:
            allreduce_(grad, average=True, name=str(index))

    def update(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update_multi_precision(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self._optimizer.set_lr_mult(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self._optimizer.set_wd_mult(args_wd_mult)


def _is_parameter_dict(params) -> bool:
    """True for gluon ``ParameterDict``-likes: items() yields Parameters
    exposing ``.data()`` (horovod/mxnet/__init__.py:87-93)."""
    if _mx is not None and isinstance(
            params, _mx.gluon.parameter.ParameterDict):  # pragma: no cover
        return True
    try:
        items = list(params.items())
    except AttributeError:
        return False
    return bool(items) and all(hasattr(p, "data") and callable(p.data)
                               for _, p in items)


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """Broadcast parameters from ``root_rank`` in place. Accepts a dict of
    NDArrays (``Module.get_params()``) or a ParameterDict
    (``Block.collect_params()``); deferred-init parameters are skipped
    (horovod/mxnet/__init__.py:71-104)."""
    if isinstance(params, dict):
        tensors = [p for _, p in sorted(params.items())]
    elif _is_parameter_dict(params):
        tensors = []
        for _, p in sorted(params.items()):
            try:
                tensors.append(p.data())
            except Exception as e:  # DeferredInitializationError duck-match
                if type(e).__name__ != "DeferredInitializationError":
                    raise
    else:
        raise ValueError("invalid params of type: %s" % type(params))

    for i, tensor in enumerate(tensors):
        broadcast_(tensor, root_rank, str(i))
    for tensor in tensors:
        tensor.wait_to_read()
