"""MXNet-style collectives over the TPU-native engine.

Parity target: horovod/mxnet/mpi_ops.py (214 LoC) + mpi_ops.cc (336 LoC):
``allreduce``/``allreduce_``, ``allgather``, ``broadcast``/``broadcast_``
on NDArray objects, plus re-exported process topology. Where the reference
pushes an async op into the MXNet ``Engine`` with variable dependencies
(mxnet/mpi_ops.cc:204-236) and lets ``wait_to_read()`` block, this shim
enqueues into the TPU-native eager engine (XLA data plane) and completes
the write-back before returning — the engine still fuses concurrently
in-flight requests submitted via the async enqueue API used below.

64-bit data-movement collectives travel as int32 bit pairs so they are
exact even without ``jax_enable_x64`` (same scheme as the torch shim).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from .. import ops as _ops
from ..topology import (init, shutdown, is_initialized, rank, local_rank,
                        size, local_size, mpi_threads_supported)
from . import ndarray as _nd

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "local_rank", "size",
    "local_size", "mpi_threads_supported",
    "allreduce", "allreduce_", "allgather", "broadcast", "broadcast_",
]

_64BIT = (np.int64, np.uint64, np.float64)


def _x64_enabled() -> bool:
    import jax
    return bool(jax.config.jax_enable_x64)


def _payload(arr: np.ndarray) -> Tuple[np.ndarray, bool]:
    """(wire array, from_bits) — 64-bit values become int32 bit pairs for
    data-movement collectives under 32-bit JAX."""
    if arr.dtype.type in _64BIT and not _x64_enabled():
        return np.ascontiguousarray(arr).view(np.int32), True
    return arr, False


def _writeback(tensor, result: np.ndarray, dtype, from_bits: bool):
    """Copy an engine result into an NDArray in place."""
    out = np.asarray(result)
    if from_bits:
        out = np.ascontiguousarray(out).view(dtype)
    tensor[:] = out.reshape(tensor.shape).astype(dtype, copy=False)
    return tensor


def allreduce(tensor, average: bool = True, name: Optional[str] = None):
    """Sum/average over all processes; input unmodified
    (horovod/mxnet/mpi_ops.py:45-80)."""
    output = _nd.zeros(tensor.shape, ctx=getattr(tensor, "context", None),
                       dtype=tensor.dtype)
    handle = _ops.allreduce_async(tensor.asnumpy(), average=average,
                                  name=name)
    return _writeback(output, handle.wait(), np.dtype(tensor.dtype), False)


def allreduce_(tensor, average: bool = True, name: Optional[str] = None):
    """In-place allreduce (horovod/mxnet/mpi_ops.py:83-111)."""
    handle = _ops.allreduce_async(tensor.asnumpy(), average=average,
                                  name=name)
    return _writeback(tensor, handle.wait(), np.dtype(tensor.dtype), False)


def allreduce_multi_(tensors: List, average: bool = True,
                     name_prefix: str = "allreduce") -> List:
    """Enqueue many in-place allreduces before blocking — lets the engine
    fuse them into one XLA program, mirroring the fusion the reference gets
    from its cycle loop when the optimizer submits a grad list
    (horovod/mxnet/__init__.py:46-51 + operations.cc:2149-2265)."""
    arrs = [t.asnumpy() for t in tensors]
    handles = [_ops.allreduce_async(a, average=average,
                                    name=f"{name_prefix}.{i}")
               for i, a in enumerate(arrs)]
    for t, h in zip(tensors, handles):
        _writeback(t, h.wait(), np.dtype(t.dtype), False)
    return tensors


def allgather(tensor, name: Optional[str] = None):
    """Concatenate over ranks along dim 0; first dims may differ
    (horovod/mxnet/mpi_ops.py:114-148)."""
    arr = tensor.asnumpy()
    wire, from_bits = _payload(arr)
    handle = _ops.allgather_async(wire, name=name)
    result = np.asarray(handle.wait())
    if from_bits:
        result = np.ascontiguousarray(result).view(arr.dtype)
    out_shape = (result.shape[0],) + tuple(arr.shape[1:])
    output = _nd.zeros(out_shape, ctx=getattr(tensor, "context", None),
                       dtype=tensor.dtype)
    output[:] = result.reshape(out_shape)
    return output


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    """Out-of-place broadcast from ``root_rank``
    (horovod/mxnet/mpi_ops.py:151-184)."""
    output = _nd.zeros(tensor.shape, ctx=getattr(tensor, "context", None),
                       dtype=tensor.dtype)
    arr = tensor.asnumpy()
    wire, from_bits = _payload(arr)
    handle = _ops.broadcast_async(wire, root_rank, name=name)
    return _writeback(output, handle.wait(), np.dtype(tensor.dtype),
                      from_bits)


def broadcast_(tensor, root_rank: int, name: Optional[str] = None):
    """In-place broadcast (horovod/mxnet/mpi_ops.py:187-214)."""
    arr = tensor.asnumpy()
    wire, from_bits = _payload(arr)
    handle = _ops.broadcast_async(wire, root_rank, name=name)
    return _writeback(tensor, handle.wait(), np.dtype(tensor.dtype),
                      from_bits)
