"""Minimal NDArray compatibility layer for the MXNet shim.

The reference binding operates on ``mx.nd.NDArray`` handles pushed through
the MXNet engine (horovod/mxnet/mpi_ops.py:45-214, mxnet/mpi_ops.cc:204-236).
This image ships without MXNet, so the shim is written against the small
*NDArray protocol* actually used — ``asnumpy()``, ``shape``, ``dtype``,
``context``, ``wait_to_read()`` and slice assignment — and this module
provides a numpy-backed implementation of that protocol used when MXNet is
not importable (and by the test suite). With MXNet installed the same shim
code operates on real ``mx.nd.NDArray`` objects unchanged.
"""

from __future__ import annotations

import numpy as np

try:  # pragma: no cover - mxnet is not in the image
    import mxnet as _mx
except ImportError:
    _mx = None


class DeferredInitializationError(RuntimeError):
    """Raised by ``Parameter.data()`` before shape inference — mirrors
    ``mx.gluon.parameter.DeferredInitializationError``."""


class NDArray:
    """Numpy-backed stand-in for ``mx.nd.NDArray`` (dense, CPU).

    Implements exactly the surface the Horovod MXNet API touches; writes
    through ``arr[:] = value`` mutate the underlying buffer, matching
    MXNet's in-place collective semantics.
    """

    __slots__ = ("_data", "context")

    def __init__(self, data, dtype=None, ctx=None):
        self._data = np.array(data, dtype=dtype)
        self.context = ctx if ctx is not None else "cpu(0)"

    # -- protocol ----------------------------------------------------------
    @property
    def shape(self):
        return self._data.shape

    @property
    def dtype(self):
        return self._data.dtype.type

    @property
    def size(self):
        return self._data.size

    @property
    def ndim(self):
        return self._data.ndim

    def asnumpy(self) -> np.ndarray:
        return self._data.copy()

    def wait_to_read(self):  # engine sync point; shim ops are synchronous
        return None

    def astype(self, dtype):
        return NDArray(self._data.astype(dtype), ctx=self.context)

    def copy(self):
        return NDArray(self._data.copy(), ctx=self.context)

    def reshape(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return NDArray(self._data.reshape(shape), ctx=self.context)

    # -- mutation ----------------------------------------------------------
    def __setitem__(self, key, value):
        if isinstance(value, NDArray):
            value = value._data
        self._data[key] = value

    def __getitem__(self, key):
        out = self._data[key]
        if np.isscalar(out) or out.ndim == 0:
            return out
        return NDArray(out, ctx=self.context)

    # -- arithmetic (what examples/tests use) ------------------------------
    def _coerce(self, other):
        return other._data if isinstance(other, NDArray) else other

    def __add__(self, other):
        return NDArray(self._data + self._coerce(other), ctx=self.context)

    def __sub__(self, other):
        return NDArray(self._data - self._coerce(other), ctx=self.context)

    def __mul__(self, other):
        return NDArray(self._data * self._coerce(other), ctx=self.context)

    __rmul__ = __mul__

    def __truediv__(self, other):
        return NDArray(self._data / self._coerce(other), ctx=self.context)

    def __repr__(self):
        return f"NDArray({self._data!r})"


def zeros(shape, ctx=None, dtype=None):
    """``mx.nd.zeros`` equivalent for output allocation
    (horovod/mxnet/mpi_ops.py:69-70)."""
    if _mx is not None:  # pragma: no cover
        return _mx.nd.zeros(shape=shape, ctx=ctx, dtype=dtype or np.float32)
    return NDArray(np.zeros(shape, dtype=dtype or np.float32), ctx=ctx)


def array(data, ctx=None, dtype=None):
    if _mx is not None:  # pragma: no cover
        return _mx.nd.array(data, ctx=ctx, dtype=dtype)
    return NDArray(np.array(data, dtype=dtype), ctx=ctx)


def is_ndarray(x) -> bool:
    if _mx is not None and isinstance(x, _mx.nd.NDArray):  # pragma: no cover
        return True
    return isinstance(x, NDArray)
