"""horovod_tpu — a TPU-native distributed training framework with the
capabilities of Horovod (reference: lxx719/horovod, v0.15.2).

Built from scratch for TPU: JAX/XLA collectives over a ``jax.sharding.Mesh``
replace MPI/NCCL; a native C++ control-plane runtime (background cycle,
tensor fusion planning, timeline, autotuning) replaces the MPI coordinator;
``jax.distributed`` + the runner replace ``mpirun``.

Five-line usage, mirroring the reference README:

    import horovod_tpu as hvd
    hvd.init()
    opt = hvd.DistributedOptimizer(optax.adam(1e-3 * hvd.size()))
    state = hvd.broadcast_parameters(state, root_rank=0)
    ... standard JAX training loop ...
"""

from .utils import compat as _compat  # noqa: F401  (installs jax shims)
from .topology import (NotInitializedError, generation, hierarchical_mesh,
                       init, is_initialized, local_rank, local_size, mesh,
                       mpi_threads_supported, process_count, process_rank,
                       rank, shutdown, size)
from .topology import topology as get_topology
from .ops import (Handle, HorovodInternalError, allgather, allgather_async,
                  allreduce, allreduce_async, broadcast, broadcast_async,
                  grouped_allreduce, poll, synchronize)
from .compression import Compression
from .optimizer import (DistributedOptimizer, DistributedGradientTransformation,
                        broadcast_parameters, broadcast_optimizer_state,
                        broadcast_object, allreduce_gradients)
from .utils.checkpoint import restore_checkpoint, save_checkpoint
from .checkpoint import CheckpointEngine, CorruptShardError
from .ops.timeline_jit import (step as timeline_jit_step,
                               merge_profiler_trace)
from .elastic import (ElasticState, SlowRankFailure, WorkerFailure,
                      run_elastic)
from .observability import (get_registry, metrics_snapshot,
                            prometheus_text)


def metrics_registry():
    """The process-global metrics registry (docs/metrics.md) — for
    registering application-level counters next to the framework's."""
    return get_registry()


def __getattr__(name):
    # The input-pipeline subsystem (docs/data.md) resolves lazily:
    # `hvd.data.build_loader(...)` works without paying its import on
    # every `import horovod_tpu`.
    if name == "data":
        import importlib
        return importlib.import_module(".data", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__version__ = "0.1.0"

__all__ = [
    "__version__",
    # topology
    "init", "shutdown", "is_initialized", "rank", "local_rank", "size",
    "local_size", "process_rank", "process_count", "mesh",
    "hierarchical_mesh", "get_topology", "mpi_threads_supported",
    "NotInitializedError", "generation",
    # collectives
    "allreduce", "allreduce_async", "allgather", "allgather_async",
    "broadcast", "broadcast_async", "grouped_allreduce", "poll",
    "synchronize", "Handle", "HorovodInternalError",
    "timeline_jit_step", "merge_profiler_trace",
    # training
    "Compression", "DistributedOptimizer",
    "DistributedGradientTransformation", "broadcast_parameters",
    "broadcast_optimizer_state", "broadcast_object", "allreduce_gradients",
    "save_checkpoint", "restore_checkpoint",
    "CheckpointEngine", "CorruptShardError",
    # elastic / adaptation
    "ElasticState", "WorkerFailure", "SlowRankFailure", "run_elastic",
    # observability
    "metrics_snapshot", "metrics_registry", "prometheus_text",
]
