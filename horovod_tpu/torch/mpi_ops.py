"""Torch tensor collectives over the TPU-native engine.

This is the shim the reference implements as a C++ extension
(horovod/torch/mpi_ops_v2.cc + horovod/torch/mpi_ops.py): sync / async /
in-place variants of allreduce / allgather / broadcast on ``torch.Tensor``s,
integer-handle ``poll``/``synchronize`` semantics, and autograd Functions
whose backward passes are themselves collectives (torch/mpi_ops.py:110-121,
236-254, 318-332).

Where the reference operates on the tensor's own memory
(torch/adapter_v2.cc:40-105), this shim hands torch (CPU) tensors to the
JAX collective engine zero-copy via DLPack (utils/interop.py) — bf16
crosses natively — and aliases engine output buffers on the way back.
The numpy fallback path covers what DLPack can't carry exactly: 64-bit
dtypes in 32-bit JAX mode (as int32 bit pairs for movement collectives,
reinterpreted via ml_dtypes for bf16), non-contiguous tensors, and
non-exportable output buffers (real-TPU outputs cross via one D2H copy).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np
import torch

from .. import ops as _ops
from ..ops import HorovodInternalError
from .. import topology as _topo
from ..utils import interop as _interop

try:
    import ml_dtypes as _mld
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    _mld = None


# ---------------------------------------------------------------------------
# torch <-> jax conversion
# ---------------------------------------------------------------------------

_64BIT = (torch.int64, torch.float64)


def _x64_enabled() -> bool:
    import jax
    return bool(jax.config.jax_enable_x64)


def _to_numpy(t: torch.Tensor) -> np.ndarray:
    t = t.detach().cpu().contiguous()
    if t.dtype == torch.bfloat16:
        bits = t.view(torch.uint16).numpy()
        return bits.view(_mld.bfloat16)
    return t.numpy()


def _ingress(t: torch.Tensor):
    """Tensor -> engine payload: DLPack zero-copy when possible, numpy
    otherwise. The payload aliases the tensor's memory either way (for a
    contiguous CPU tensor ``.numpy()`` is also an alias); the engine's
    device_put is the one real transfer."""
    a = _interop.try_torch_to_jax(t)
    return a if a is not None else _to_numpy(t)


def _bits32(t: torch.Tensor) -> np.ndarray:
    """Reinterpret a 64-bit tensor as int32 pairs — exact transport for
    data-movement collectives (broadcast/allgather) under 32-bit JAX."""
    t = t.detach().cpu().contiguous()
    if t.dim() == 0:
        # torch refuses to view a 0-dim tensor as a narrower dtype; the
        # original shape is restored from the handle at synchronize time.
        t = t.reshape(1)
    return t.view(torch.int32).numpy()


def _np_private(arr: np.ndarray) -> np.ndarray:
    """EXACTLY one host copy: a contiguous, writable array that does not
    alias the source. ``np.ascontiguousarray(x).copy()`` paid two copies
    for a non-contiguous source (ascontiguousarray already copies) and
    one avoidable copy chain for bf16; branch instead of stacking."""
    if arr.flags["C_CONTIGUOUS"]:
        # May alias an engine/XLA buffer (np.asarray on a CPU backend
        # array is zero-copy and read-only) — one defensive copy.
        return arr.copy()
    return np.ascontiguousarray(arr)


def _to_torch_host(arr: np.ndarray, dtype: torch.dtype,
                   from_bits: bool = False) -> torch.Tensor:
    """Host numpy array (already transferred) -> torch tensor."""
    if from_bits:
        return torch.from_numpy(_np_private(arr)).view(dtype)
    if dtype == torch.bfloat16:
        return torch.from_numpy(
            _np_private(arr.view(np.uint16))).view(torch.bfloat16)
    return torch.from_numpy(np.array(arr)).to(dtype)


def _to_torch(a, dtype: torch.dtype, from_bits: bool = False) -> torch.Tensor:
    return _to_torch_host(_interop.to_host(a), dtype, from_bits)


# ---------------------------------------------------------------------------
# Handle manager — integer handles like the reference's HandleManager
# (horovod/torch/handle_manager.cc:21-50)
# ---------------------------------------------------------------------------

class _TorchHandle:
    __slots__ = ("inner", "dtype", "shape", "output", "target", "from_bits")

    def __init__(self, inner, dtype, shape, target=None, from_bits=False):
        self.inner = inner          # engine Handle
        self.dtype = dtype          # torch dtype of the result
        self.shape = shape
        self.output = None          # materialized torch result
        self.target = target        # in-place target tensor, if any
        self.from_bits = from_bits  # 64-bit value sent as int32 bit pairs


_lock = threading.Lock()
_next_handle = [0]
_handles: Dict[int, _TorchHandle] = {}


def _register(h: _TorchHandle) -> int:
    with _lock:
        _next_handle[0] += 1
        hid = _next_handle[0]
        _handles[hid] = h
    return hid


def poll(handle: int) -> bool:
    """True iff the collective behind ``handle`` completed
    (mpi_ops_v2.cc:226, torch/mpi_ops.py:406-417)."""
    with _lock:
        th = _handles.get(handle)
    if th is None:
        raise ValueError(f"Unknown handle {handle}")
    return _ops.poll(th.inner)


def synchronize(handle: int) -> torch.Tensor:
    """Block until done; return the output tensor. In-place variants copy
    the result into the submitted tensor (WaitAndClear,
    mpi_ops_v2.cc:228-234 + torch/mpi_ops.py:419-438). One code path
    with the batched variant: this is synchronize_many of one."""
    return synchronize_many([handle])[0]


def synchronize_many(handles) -> list:
    """Synchronize a batch of handles through ONE engine flush and
    BATCHED device-to-host egress. The first ``wait`` hints the engine
    to drain the whole burst; per-handle ``synchronize`` would instead
    pay one readback round trip each — on accelerators behind a
    latency-heavy link that is ~70 ms a transfer (measured through the
    axon tunnel; batching the list is ~2x on a ResNet-50-shaped
    gradient set). Egress is DLPack wherever the backend allows
    (zero-copy alias on the CPU mesh, one batched device→CPU transfer
    on chip — interop.torch_egress_many); only what DLPack cannot carry
    (64-bit bit-pair transport, export refusals) is fetched via
    numpy."""
    handles = list(handles)
    with _lock:
        # Validate BEFORE popping: one bad id must not destroy the
        # other handles in the call (per-handle synchronize never did).
        if len(set(handles)) != len(handles):
            raise ValueError("duplicate handle in synchronize_many")
        missing = [h for h in handles if h not in _handles]
        if missing:
            raise ValueError(f"Unknown handle {missing[0]}")
        ths = [_handles.pop(h) for h in handles]
    outs = [th.inner.wait() for th in ths]
    results: list = [None] * len(ths)
    # DLPack egress for everything but the 64-bit bit-pair transport:
    # zero-copy alias on the CPU mesh, ONE batched device->CPU transfer
    # + alias on accelerator backends (interop.torch_egress_many). The
    # remainder (bits transport, export refusals, kill switch) is
    # batch-fetched through numpy.
    egress_idx = [i for i, th in enumerate(ths) if not th.from_bits]
    exported = _interop.torch_egress_many([outs[i] for i in egress_idx])
    rest = [i for i, th in enumerate(ths) if th.from_bits]
    for i, exp in zip(egress_idx, exported):
        th = ths[i]
        if exp is None or exp[0].dtype != th.dtype:
            rest.append(i)
            continue
        t, private = exp
        if th.target is None and not private:
            # Out-of-place result aliasing an ENGINE-RETAINED buffer
            # (zero-copy CPU-mesh egress): torch has no read-only
            # tensors, and handing the alias out would let ordinary
            # in-place math (result.add_(...)) silently mutate an array
            # the engine still retains. Clone before release. Transfer
            # egress (private=True) and in-place variants (the alias is
            # only a copy_ source) keep the single-copy path.
            t = t.clone()
        results[i] = t
    if rest:
        rest.sort()
        hosts = _interop.to_host_many([outs[i] for i in rest])
        for i, arr in zip(rest, hosts):
            results[i] = _to_torch_host(arr, ths[i].dtype,
                                        ths[i].from_bits)
    final = []
    for th, result in zip(ths, results):
        if th.target is not None:
            with torch.no_grad():
                th.target.copy_(result.reshape(th.target.shape))
            final.append(th.target)
            continue
        if th.shape is not None:
            result = result.reshape(th.shape)
        final.append(result)
    return final


# ---------------------------------------------------------------------------
# Async ops
# ---------------------------------------------------------------------------

def allreduce_async(tensor: torch.Tensor, average: bool = True,
                    name: Optional[str] = None, compression=None) -> int:
    """Returns a handle; result via synchronize() (torch/mpi_ops.py:128-152).

    64-bit reductions without jax_enable_x64 are rejected by the engine's
    narrowing guard (ops/collective.py::_prep) at enqueue time.
    ``compression`` only forwards a blockwise wire spec
    (Compression.int8_blockwise / fp8_blockwise) to the engine — the
    quantization runs inside the fused XLA program."""
    arr = _ingress(tensor)
    inner = _ops.allreduce_async(arr, average=average, name=name,
                                 compression=compression)
    return _register(_TorchHandle(inner, tensor.dtype, tensor.shape))


def allreduce_async_(tensor: torch.Tensor, average: bool = True,
                     name: Optional[str] = None, compression=None) -> int:
    """In-place: the result lands in ``tensor`` (torch/mpi_ops.py:182-207)."""
    arr = _ingress(tensor)
    inner = _ops.allreduce_async(arr, average=average, name=name,
                                 compression=compression)
    return _register(
        _TorchHandle(inner, tensor.dtype, tensor.shape, target=tensor))


def _movement_payload(tensor: torch.Tensor):
    """(engine payload, from_bits) for data-movement collectives: 64-bit
    dtypes travel as exact int32 bit pairs when JAX is in 32-bit mode;
    everything else crosses via DLPack when possible."""
    if tensor.dtype in _64BIT and not _x64_enabled():
        return _bits32(tensor), True
    return _ingress(tensor), False


def allgather_async(tensor: torch.Tensor, name: Optional[str] = None) -> int:
    """Gather along dim 0 from every rank (torch/mpi_ops.py:256-280)."""
    arr, from_bits = _movement_payload(tensor)
    inner = _ops.allgather_async(arr, name=name)
    return _register(
        _TorchHandle(inner, tensor.dtype, None, from_bits=from_bits))


def broadcast_async(tensor: torch.Tensor, root_rank: int,
                    name: Optional[str] = None) -> int:
    arr, from_bits = _movement_payload(tensor)
    inner = _ops.broadcast_async(arr, root_rank, name=name)
    return _register(_TorchHandle(inner, tensor.dtype, tensor.shape,
                                  from_bits=from_bits))


def broadcast_async_(tensor: torch.Tensor, root_rank: int,
                     name: Optional[str] = None) -> int:
    """In-place broadcast (torch/mpi_ops.py:360-392)."""
    arr, from_bits = _movement_payload(tensor)
    inner = _ops.broadcast_async(arr, root_rank, name=name)
    return _register(
        _TorchHandle(inner, tensor.dtype, tensor.shape, target=tensor,
                     from_bits=from_bits))


# ---------------------------------------------------------------------------
# Autograd functions — backward passes are collectives, exactly as the
# reference registers them (torch/mpi_ops.py:110-121, 236-254, 318-332)
# ---------------------------------------------------------------------------

class _HorovodAllreduce(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, average, name, compression=None):
        ctx.average = average
        ctx.compression = compression
        return synchronize(allreduce_async(tensor, average, name,
                                           compression=compression))

    @staticmethod
    def backward(ctx, grad_output):
        # d(allreduce(x))/dx distributes the same allreduce over the grads.
        return (synchronize(allreduce_async(grad_output, ctx.average,
                                            compression=ctx.compression)),
                None, None, None)


class _HorovodAllgather(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, name):
        ctx.dim0 = tensor.shape[0]
        return synchronize(allgather_async(tensor, name))

    @staticmethod
    def backward(ctx, grad_output):
        # Sum-allreduce the full gathered grad, then take this rank's
        # segment (torch/mpi_ops.py:236-254).
        summed = synchronize(allreduce_async(grad_output, average=False))
        r = _topo.rank()
        return summed[r * ctx.dim0:(r + 1) * ctx.dim0], None


class _HorovodBroadcast(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, root_rank, name):
        ctx.root_rank = root_rank
        return synchronize(broadcast_async(tensor, root_rank, name))

    @staticmethod
    def backward(ctx, grad_output):
        grad = synchronize(allreduce_async(grad_output, average=False))
        if _topo.rank() != ctx.root_rank:
            grad = torch.zeros_like(grad)
        return grad, None, None


# ---------------------------------------------------------------------------
# Sync ops
# ---------------------------------------------------------------------------

def allreduce(tensor: torch.Tensor, average: bool = True,
              name: Optional[str] = None, compression=None) -> torch.Tensor:
    """Differentiable synchronous allreduce (torch/mpi_ops.py:110-126)."""
    from .compression import Compression
    compression = compression or Compression.none
    wire, cctx = compression.compress(tensor)
    blockwise = compression \
        if getattr(compression, "wire_spec", None) is not None else None
    out = _HorovodAllreduce.apply(wire, average, name, blockwise)
    return compression.decompress(out, cctx)


def allreduce_(tensor: torch.Tensor, average: bool = True,
               name: Optional[str] = None) -> torch.Tensor:
    """In-place synchronous allreduce (torch/mpi_ops.py:209-233)."""
    return synchronize(allreduce_async_(tensor, average, name))


def allgather(tensor: torch.Tensor,
              name: Optional[str] = None) -> torch.Tensor:
    """Differentiable allgather along dim 0 (torch/mpi_ops.py:282-316)."""
    return _HorovodAllgather.apply(tensor, name)


def broadcast(tensor: torch.Tensor, root_rank: int,
              name: Optional[str] = None) -> torch.Tensor:
    """Differentiable broadcast (torch/mpi_ops.py:318-358)."""
    return _HorovodBroadcast.apply(tensor, root_rank, name)


def broadcast_(tensor: torch.Tensor, root_rank: int,
               name: Optional[str] = None) -> torch.Tensor:
    return synchronize(broadcast_async_(tensor, root_rank, name))
