"""Gradient compression on torch tensors — parity with
horovod/torch/compression.py (identical to tensorflow/compression.py in the
reference). ``Compression.none`` passes through; ``Compression.fp16`` casts
floating tensors to half for the wire and back after; ``Compression.bf16``
is the TPU-native extension (bfloat16 survives the JAX hop losslessly and is
the platform's 16-bit type).
"""

from __future__ import annotations

import torch


class Compressor:
    """Interface (compression.py:23-31)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """No-op (compression.py:33-43)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: torch.dtype = torch.float16

    @classmethod
    def compress(cls, tensor):
        ctx = tensor.dtype
        if tensor.dtype.is_floating_point:
            tensor = tensor.to(cls.wire_dtype)
        return tensor, ctx

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None and ctx.is_floating_point and tensor.dtype != ctx:
            tensor = tensor.to(ctx)
        return tensor


class FP16Compressor(_CastCompressor):
    """fp16 wire format (compression.py:46-61)."""
    wire_dtype = torch.float16


class BF16Compressor(_CastCompressor):
    """bfloat16 wire format — TPU-native extension."""
    wire_dtype = torch.bfloat16


class _BlockwiseCompressor(Compressor):
    """Block-scaled quantized wire format: the torch tensor crosses into
    the engine at its logical dtype and the quantize → reduce-scatter →
    requantize → allgather pipeline runs inside the fused XLA program
    (horovod_tpu.quantization), keyed off ``wire_spec`` — compress and
    decompress are therefore pass-through here."""

    wire_spec = None

    @classmethod
    def compress(cls, tensor):
        return tensor, tensor.dtype

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None and ctx.is_floating_point and tensor.dtype != ctx:
            tensor = tensor.to(ctx)
        return tensor


class Int8BlockwiseCompressor(_BlockwiseCompressor):
    """Absmax-scaled int8 blocks — ~0.25x fp32 wire bytes."""
    wire_spec = "int8x256"


class FP8BlockwiseCompressor(_BlockwiseCompressor):
    """Absmax-scaled e4m3 blocks — same wire bytes, coarser near each
    block's absmax."""
    wire_spec = "fp8x256"


class Compression:
    """Option enum (compression.py:64-75)."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8_blockwise = Int8BlockwiseCompressor
    fp8_blockwise = FP8BlockwiseCompressor
