"""horovod_tpu.torch — the PyTorch framework shim.

Parity target: horovod/torch/__init__.py (348 LoC) + mpi_ops.py (438 LoC):
``DistributedOptimizer`` launching collectives as gradients are
accumulated, ``synchronize()`` flushing before ``step()``,
``backward_passes_per_step`` gradient accumulation,
``broadcast_parameters`` and ``broadcast_optimizer_state``. Torch stays
the autograd/optimizer engine; the collectives run on the TPU-native XLA
data plane (see mpi_ops.py in this package).

Hot path (docs/torch.md): where the reference fires one async allreduce
per parameter and lets its background fusion cycle re-pack them, this
shim packs at the SOURCE — parameters partition into size-targeted
gradient buckets at wrap time, each bucket owns a persistent flat wire
buffer and one persistent compiled allreduce program, hooks memcpy
gradients into the buffer, and the bucket's last hook fires its
collective while backward still runs (backward-overlap). The per-call
dispatch floor is paid per bucket, not per tensor.
"""

from __future__ import annotations

import warnings
from contextlib import contextmanager
from typing import Iterable, List, Optional, Tuple

import torch

from .. import ops as _ops
from ..topology import (init, shutdown, is_initialized, rank, local_rank,
                        size, local_size, mpi_threads_supported)
from ..observability import StepTimer as _StepTimer
from ..observability import numerics as _numerics
from ..observability import registry as _obs
from ..utils import env as _env
from .compression import Compression
from .mpi_ops import (allreduce, allreduce_, allreduce_async,
                      allreduce_async_, allgather, allgather_async,
                      broadcast, broadcast_, broadcast_async,
                      broadcast_async_, poll, synchronize,
                      synchronize_many)

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "local_rank", "size",
    "local_size", "mpi_threads_supported", "Compression",
    "allreduce", "allreduce_", "allreduce_async", "allreduce_async_",
    "allgather", "allgather_async", "broadcast", "broadcast_",
    "broadcast_async", "broadcast_async_", "poll", "synchronize",
    "DistributedOptimizer", "broadcast_parameters",
    "broadcast_optimizer_state", "StepMetrics", "checkpoint_hook",
]


class StepMetrics(_StepTimer):
    """Per-step telemetry hook for the torch training loop
    (docs/metrics.md): records ``hvdtpu_step_seconds``,
    ``hvdtpu_samples_per_second``, ``hvdtpu_collective_step_share``
    (with ``hvdtpu_allreduce_step_share`` as a deprecated alias), the
    input/h2d/compute/collective attribution
    (``hvdtpu_step_phase_seconds``/``_share``), HBM gauges, and — when
    ``flops_per_step`` is supplied — MFU (all labeled
    ``framework=torch``). Use as a context manager around each step::

        metrics = hvd.torch.StepMetrics(batch_size=64)
        for batch in loader:
            with metrics:
                loss = train_step(batch)   # backward + optimizer.step()

    The collective share is computed from the engine's own execute-time
    accounting, so it covers the DistributedOptimizer's async allreduces
    wherever they overlap the step."""

    def __init__(self, batch_size: Optional[int] = None,
                 flops_per_step: Optional[float] = None):
        super().__init__("torch", batch_size=batch_size,
                         flops_per_step=flops_per_step)


class _ShimMetrics:
    """Registry handles for the torch shim's bucket plane, resolved once
    per process (docs/metrics.md) — the same lazy-singleton pattern as
    the engine/executor metric classes."""

    _instance = None

    def __init__(self):
        r = _obs.registry()
        fires = r.counter(
            "hvdtpu_torch_bucket_fires_total",
            "Torch gradient buckets submitted to the engine, by trigger "
            "(hook = last grad hook landed during backward — the "
            "overlap path; flush = synchronize() drained a bucket whose "
            "hooks had not all fired)")
        self.fires = {t: fires.labels(trigger=t) for t in ("hook", "flush")}
        self.bucket_bytes = r.counter(
            "hvdtpu_torch_bucket_bytes_total",
            "Bytes of bucketed gradient payload submitted to the "
            "engine (bucket-buffer bytes at the wire dtype)").labels()
        self.buckets = r.gauge(
            "hvdtpu_torch_buckets",
            "Gradient buckets configured by the most recently "
            "constructed DistributedOptimizer (0 = per-tensor "
            "mode)").labels()
        self.view_rebinds = r.counter(
            "hvdtpu_torch_grad_view_rebinds_total",
            "gradient_as_bucket_view repairs: autograd (or user code) "
            "replaced an aliased p.grad with a fresh tensor — e.g. "
            "zero_grad(set_to_none=True) outside the optimizer — and "
            "the hook copied it back into the bucket buffer and "
            "re-aliased. A steadily climbing count means the zero-copy "
            "pack is silently degrading to the memcpy path "
            "(docs/torch.md)").labels()
        self.view_params = r.gauge(
            "hvdtpu_torch_grad_view_params",
            "Parameters whose .grad is aliased into a bucket buffer by "
            "the most recently constructed DistributedOptimizer").labels()
        self.skipped_steps = r.counter(
            "hvdtpu_torch_skipped_steps_total",
            "Optimizer steps skipped by skip_nonfinite_steps because "
            "the bucket pack observed nonfinite gradient elements "
            "(docs/numerics.md#torch)").labels()

    @classmethod
    def get(cls) -> "_ShimMetrics":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance


class _GradBucket:
    """One fusion bucket of the DistributedOptimizer's backward-overlap
    plane: a fixed flat wire-dtype buffer covering a contiguous span of
    parameters, fired as ONE engine allreduce per step. The buffer shape
    is constant across steps, so the executor's fused-path cache key
    ("ar", ((numel,),), (dtype,), ...) resolves to one persistent jitted
    program per bucket — the reference's fusion-buffer cycle
    (operations.cc:1221-1243) with the memcpy hoisted to hook time."""

    __slots__ = ("index", "params", "offsets", "numel", "buffer", "ready",
                 "name")

    def __init__(self, index: int, params: List[torch.Tensor],
                 dtype: torch.dtype, name: str):
        self.index = index
        self.params = params
        self.offsets = {}
        off = 0
        for p in params:
            n = p.numel()
            self.offsets[id(p)] = (off, n)
            off += n
        self.numel = off
        self.buffer = torch.zeros(off, dtype=dtype)
        self.ready: set = set()
        self.name = name

    def fill(self, p: torch.Tensor) -> None:
        off, n = self.offsets[id(p)]
        with torch.no_grad():
            # copy_ casts param-dtype grads onto the wire dtype (the
            # cast compressor's compress, fused into the pack memcpy).
            self.buffer[off:off + n].copy_(p.grad.detach().reshape(-1))

    def scatter(self, p: torch.Tensor) -> None:
        off, n = self.offsets[id(p)]
        with torch.no_grad():
            # ...and back (decompress): copy_ casts wire -> grad dtype.
            p.grad.copy_(self.buffer[off:off + n].view(p.grad.shape))

    def view_of(self, p: torch.Tensor) -> torch.Tensor:
        """The bucket-buffer span of ``p``'s gradient, shaped like the
        parameter — the tensor installed as ``p.grad`` under
        ``gradient_as_bucket_view`` (only when the wire dtype equals the
        parameter dtype, so no cast hides in the alias)."""
        off, n = self.offsets[id(p)]
        return self.buffer[off:off + n].view(p.shape)


_opt_counter = [0]


def _bucketable(compression) -> bool:
    """Bucketing understands the STOCK compressors (none / fp16 / bf16 /
    blockwise — their transform is a dtype cast or a wire spec, both of
    which fuse into the bucket pack-copy). Anything else — including a
    subclass that may override compress/decompress with arbitrary
    logic — falls back to the per-tensor path, where the compressor is
    invoked verbatim."""
    return compression in (Compression.none, Compression.fp16,
                           Compression.bf16, Compression.int8_blockwise,
                           Compression.fp8_blockwise)


class _DistributedOptimizer(torch.optim.Optimizer):
    """Mixin installed on a dynamic subclass of the wrapped optimizer
    (horovod/torch/__init__.py:42-151).

    Hot path (docs/torch.md): parameters are partitioned at construction
    into size-targeted gradient BUCKETS (``bucket_cap_mb``, default
    HOROVOD_TPU_TORCH_BUCKET_MB = the engine fusion threshold), walked
    in reverse registration order so the earliest-completing gradients
    share the first bucket. Each parameter's post-grad-accumulation hook
    copies its gradient into the bucket's flat wire-dtype buffer; the
    LAST hook of a bucket fires one in-place async allreduce on the
    whole buffer — communication overlaps the remainder of backward,
    the reference's fusion cycle (operations.cc:2149-2265) driven from
    the autograd graph. ``synchronize()`` drains *buckets*, not
    tensors: one engine flush, one batched DLPack egress, then a
    scatter back into each ``p.grad``. ``bucket_cap_mb=0`` (or an
    unrecognized custom compressor) keeps the original per-tensor hook
    path (torch/__init__.py:95-130).
    """

    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step=1, bucket_cap_mb=None,
                 gradient_as_bucket_view=None, skip_nonfinite_steps=None):
        super(self.__class__, self).__init__(params)
        self._compression = compression
        self.backward_passes_per_step = backward_passes_per_step
        self._synchronized = False
        self._should_synchronize = True
        if skip_nonfinite_steps is None:
            skip_nonfinite_steps = _env.torch_skip_nonfinite()
        self._skip_nonfinite = bool(skip_nonfinite_steps)
        self._saw_nonfinite = False

        if named_parameters is not None:
            named_parameters = list(named_parameters)
        else:
            named_parameters = [
                (f"allreduce.noname.{i}.{j}", v)
                for i, group in enumerate(self.param_groups)
                for j, v in enumerate(group["params"])]
        all_params = {id(v) for group in self.param_groups
                      for v in group["params"]}
        named_ids = {id(v) for _, v in named_parameters}
        if len(named_ids) != len(named_parameters):
            raise ValueError(
                "named_parameters contains duplicate parameters")
        if not named_ids.issubset(all_params):
            raise ValueError(
                "named_parameters was not a subset of optimizer.param_groups"
                " parameters (torch/__init__.py:56-66)")
        self._parameter_names = {id(v): k for k, v in named_parameters}
        self._handles = {}
        self._wire_ctx = {}
        self._allreduce_delay = {id(v): backward_passes_per_step
                                 for group in self.param_groups
                                 for v in group["params"]}
        if bucket_cap_mb is None:
            bucket_cap_mb = _env.torch_bucket_mb()
        self._buckets: List[_GradBucket] = []
        self._param_bucket = {}
        self._bucket_residuals = {}
        self._grad_views = {}
        self._metrics = _ShimMetrics.get()
        if bucket_cap_mb > 0 and _bucketable(compression):
            self._build_buckets(float(bucket_cap_mb) * 2 ** 20)
        if gradient_as_bucket_view is None:
            gradient_as_bucket_view = _env.torch_grad_view()
        if gradient_as_bucket_view and self._buckets:
            self._install_grad_views()
        self._metrics.buckets.set(len(self._buckets))
        self._metrics.view_params.set(len(self._grad_views))
        self._register_hooks()

    # ------------------------------------------------------------- buckets

    def _wire_dtype(self, p: torch.Tensor) -> torch.dtype:
        """Bucket-buffer dtype for ``p``'s gradient: the cast
        compressor's wire dtype for floating params, else the param's
        own dtype (blockwise specs quantize inside the fused XLA
        program, so their buffer stays at the logical dtype)."""
        wd = getattr(self._compression, "wire_dtype", None)
        if wd is not None and p.dtype.is_floating_point:
            return wd
        return p.dtype

    def _build_buckets(self, cap_bytes: float) -> None:
        _opt_counter[0] += 1
        prefix = f"hvd.torch.{_opt_counter[0]}.bucket"
        params = [p for group in self.param_groups
                  for p in group["params"] if p.requires_grad]
        # Reverse registration order approximates autograd completion
        # order (backward walks the graph output->input), so the
        # gradients that finish first share the first-fired bucket —
        # the overlap-maximizing assignment the reference gets from its
        # arrival-ordered fusion queue.
        open_spans = {}   # wire dtype -> (param list, bytes)
        spans = []
        for p in reversed(params):
            dt = self._wire_dtype(p)
            nbytes = p.numel() * p.element_size()
            span = open_spans.get(dt)
            if span is None or (span[1] + nbytes > cap_bytes and span[0]):
                span = [[], 0]
                spans.append(span)
                open_spans[dt] = span
            span[0].append(p)
            span[1] += nbytes
        for members, _ in spans:
            b = _GradBucket(len(self._buckets), members,
                            self._wire_dtype(members[0]),
                            f"{prefix}.{len(self._buckets)}")
            self._buckets.append(b)
            for p in members:
                self._param_bucket[id(p)] = b

    def set_bucket_cap_mb(self, bucket_cap_mb: float) -> None:
        """Re-partition the gradient buckets under a new size cap — the
        global autotuner's ``torch_bucket_mb`` knob (docs/autotune.md),
        safety class ``boundary``: legal only at a step boundary, while
        no bucket collective is in flight. The hooks installed at wrap
        time look their bucket up per call (``_param_bucket[id(p)]``),
        so rebuilding the partition re-targets them without touching
        autograd; grad-view aliases are re-established against the new
        flat buffers. Compression error-feedback residuals are bucket-
        shaped and reset (one step of feedback is lost — the same cost
        as a restart, which this move exists to avoid).

        Only positive-cap -> positive-cap moves are supported: a
        bucketless optimizer chose per-parameter hooks at wrap time."""
        if self._handles:
            raise RuntimeError(
                "set_bucket_cap_mb while bucket collectives are in "
                "flight; call synchronize()/step() first — the knob's "
                "safety class is 'boundary' (docs/autotune.md)")
        if not self._buckets or bucket_cap_mb <= 0:
            raise ValueError(
                "set_bucket_cap_mb supports re-partitioning an already "
                "bucketed optimizer to a positive cap (hook shape is "
                "chosen at wrap time)")
        had_views = bool(self._grad_views)
        # Clone aliased grads out of the old flat buffers first: the
        # new partition allocates fresh buffers, and a grad left
        # aliasing retired storage would silently detach from the wire.
        with torch.no_grad():
            for b in self._buckets:
                for p in b.params:
                    if p.grad is not None and id(p) in self._grad_views:
                        p.grad = p.grad.detach().clone()
        old_n = len(self._buckets)
        self._buckets = []
        self._param_bucket = {}
        self._bucket_residuals = {}
        self._grad_views = {}
        self._build_buckets(float(bucket_cap_mb) * 2 ** 20)
        if had_views and self._buckets:
            self._install_grad_views()
        self._metrics.buckets.set(len(self._buckets))
        self._metrics.view_params.set(len(self._grad_views))
        try:
            from ..observability import flight_recorder as _flight
            _flight.recorder().note("autotune", (
                "bucket_repartition", "torch_bucket_mb",
                str(bucket_cap_mb), None, None,
                f"buckets {old_n} -> {len(self._buckets)}"))
        except Exception:
            pass

    def _install_grad_views(self) -> None:
        """gradient_as_bucket_view (docs/torch.md): alias every eligible
        ``p.grad`` into its bucket's flat buffer at wrap time, so
        autograd accumulates STRAIGHT into the fused-collective payload
        — the hook-time pack memcpy and the post-allreduce scatter-back
        both disappear. Eligible = the bucket's wire dtype equals the
        parameter dtype (a cast compressor's pack IS a cast, which an
        alias cannot hide); ineligible parameters keep the copy path
        within the same bucket. A pre-existing gradient is copied in
        before aliasing so wrap-time state is preserved."""
        for b in self._buckets:
            for p in b.params:
                if b.buffer.dtype != p.dtype:
                    continue
                view = b.view_of(p)
                with torch.no_grad():
                    if p.grad is not None:
                        view.copy_(p.grad.detach())
                    else:
                        view.zero_()
                p.grad = view
                self._grad_views[id(p)] = view

    def _grad_is_view(self, p: torch.Tensor) -> bool:
        view = self._grad_views.get(id(p))
        return (view is not None and p.grad is not None
                and p.grad.data_ptr() == view.data_ptr())

    def _fire_bucket(self, b: _GradBucket, trigger: str) -> None:
        blockwise = self._compression if getattr(
            self._compression, "wire_spec", None) is not None else None
        if blockwise is not None and b.buffer.dtype == torch.float32:
            self._apply_error_feedback(b, blockwise.wire_spec)
        if _numerics.enabled() and b.buffer.dtype.is_floating_point:
            # Nonfinite sentinel on the just-packed LOCAL payload — the
            # buffer is hot from the pack memcpy, and post-allreduce the
            # producer is unidentifiable (docs/numerics.md#torch).
            nf = b.numel - int(torch.isfinite(b.buffer).sum().item())
            if nf:
                self._saw_nonfinite = True
                _numerics.note_nonfinite(nf, source="torch_bucket",
                                         detail=b.name)
        self._metrics.fires[trigger].inc()
        self._metrics.bucket_bytes.inc(b.numel * b.buffer.element_size())
        self._handles[b.index] = allreduce_async_(
            b.buffer, average=True, name=b.name, compression=blockwise)

    def _apply_error_feedback(self, b: _GradBucket, spec) -> None:
        """Per-BUCKET error-feedback residual for quantized wire specs:
        the bucket buffer is what the engine quantizes as one flat
        tensor (blocks span the original parameter boundaries), so the
        residual must be keyed and shaped by bucket, not by parameter —
        wire input = grads + residual, new residual = wire input minus
        its local quantize/dequantize roundtrip
        (quantization.local_roundtrip, the phase-1 wire contribution),
        computed on the JAX CPU backend so no extra device dispatch
        rides the hook path."""
        import jax
        import numpy as np
        from .. import quantization as _quant

        res = self._bucket_residuals.get(b.index)
        if res is None:
            res = torch.zeros_like(b.buffer)
            self._bucket_residuals[b.index] = res
        with torch.no_grad():
            b.buffer.add_(res)
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            rt = _quant.local_roundtrip(
                jax.device_put(b.buffer.detach().numpy(), cpu), spec)
        # Write the residual through numpy views — no writable-flag
        # dance, no extra staging copy of a bucket-sized array.
        np.subtract(b.buffer.numpy(), np.asarray(rt), out=res.numpy())
        if _numerics.enabled():
            # Quantization-drift signal: the residual norm is exactly
            # what the wire dropped this step (docs/numerics.md#drift).
            _numerics.note_ef_residual(
                b.name, float(np.linalg.norm(res.numpy())))

    # --------------------------------------------------------------- hooks

    def _register_hooks(self):
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    p.register_post_accumulate_grad_hook(self._make_hook())

    def _make_hook(self):
        if self._buckets:
            def hook(p):
                b = self._param_bucket[id(p)]
                if id(p) in b.ready:
                    raise AssertionError(
                        "Gradient for this parameter was already "
                        "allreduced this step. If you call backward() "
                        "more than once per step, pass "
                        "backward_passes_per_step=<number of backward "
                        "passes> to DistributedOptimizer "
                        "(torch/__init__.py:114-124).")
                self._allreduce_delay[id(p)] -= 1
                if self._allreduce_delay[id(p)] == 0:
                    view = self._grad_views.get(id(p))
                    if view is None:
                        b.fill(p)
                    elif not self._grad_is_view(p):
                        # Someone replaced the aliased grad (e.g.
                        # zero_grad(set_to_none=True) outside this
                        # optimizer): autograd accumulated into a fresh
                        # tensor. Copy it home and re-alias for the
                        # next step.
                        b.fill(p)
                        with torch.no_grad():
                            p.grad = view
                        self._metrics.view_rebinds.inc()
                    # else: autograd already accumulated into the
                    # bucket buffer through the view — zero-copy pack.
                    b.ready.add(id(p))
                    if len(b.ready) == len(b.params):
                        # Backward-overlap: the bucket's last gradient
                        # just landed — fire its collective NOW, while
                        # autograd still works on the rest of the graph.
                        self._fire_bucket(b, trigger="hook")
            return hook

        def hook(p):
            if id(p) in self._handles:
                raise AssertionError(
                    "Gradient for this parameter was already allreduced "
                    "this step. If you call backward() more than once per "
                    "step, pass backward_passes_per_step="
                    "<number of backward passes> to DistributedOptimizer "
                    "(torch/__init__.py:114-124).")
            self._allreduce_delay[id(p)] -= 1
            if self._allreduce_delay[id(p)] == 0:
                self._handles[id(p)] = self._allreduce_grad_async(p)
        return hook

    def _allreduce_grad_async(self, p):
        name = self._parameter_names.get(id(p), f"allreduce.{id(p)}")
        wire, ctx = self._compression.compress(p.grad)
        self._wire_ctx[id(p)] = ctx
        # Blockwise formats pass through compress() unchanged; the wire
        # spec rides the request and the engine quantizes in-program.
        blockwise = self._compression if getattr(
            self._compression, "wire_spec", None) is not None else None
        if wire is p.grad:
            return allreduce_async_(p.grad, average=True, name=name,
                                    compression=blockwise)
        return allreduce_async(wire, average=True, name=name,
                               compression=blockwise)

    def synchronize(self):
        """Flush: enqueue anything whose hook never fired, then block on
        every handle and install the (decompressed) averaged gradients
        (torch/__init__.py:132-147). In bucket mode the unit of flushing
        is the BUCKET: partially-filled buckets (early ``step()``
        mid-accumulation, dynamic graphs) are topped up from whatever
        gradients exist and fired whole — the buffer shape never
        changes, so the same compiled program serves full and partial
        steps — then one batched wait scatters results back into each
        ``p.grad``."""
        if self._buckets:
            self._synchronize_buckets()
            return
        # Every parameter not already in flight gets flushed here — even one
        # mid-accumulation (delay > 0), matching the reference, so that an
        # early step() never applies un-allreduced local gradients
        # (torch/__init__.py:132-140).
        missing = [p for group in self.param_groups
                   for p in group["params"]
                   if p.requires_grad and p.grad is not None
                   and id(p) not in self._handles]
        for p in missing:
            self._handles[id(p)] = self._allreduce_grad_async(p)
        params_by_id = {id(p): p for group in self.param_groups
                        for p in group["params"]}
        # Batched synchronize: one device_get for every non-aliasable
        # result instead of a per-parameter readback round trip
        # (mpi_ops.synchronize_many).
        pids = list(self._handles.keys())
        outs = synchronize_many([self._handles[pid] for pid in pids])
        for pid, out in zip(pids, outs):
            p = params_by_id[pid]
            ctx = self._wire_ctx.pop(pid, None)
            if out is not p.grad:
                p.grad.copy_(self._compression.decompress(out, ctx)
                             .reshape(p.grad.shape))
            self._allreduce_delay[pid] = self.backward_passes_per_step
        self._handles.clear()
        self._synchronized = True

    def _synchronize_buckets(self):
        with _ops.engine().burst():
            for b in self._buckets:
                if b.index in self._handles:
                    continue
                for p in b.params:
                    if p.grad is not None and id(p) not in b.ready:
                        if not self._grad_is_view(p):
                            b.fill(p)
                        b.ready.add(id(p))
                if b.ready:
                    self._fire_bucket(b, trigger="flush")
        fired = sorted(self._handles)
        synchronize_many([self._handles[i] for i in fired])
        for i in fired:
            b = self._buckets[i]
            for p in b.params:
                if id(p) in b.ready and p.grad is not None:
                    # The in-place allreduce landed in the bucket
                    # buffer; aliased gradients already see it — only
                    # copy-path parameters need the scatter-back.
                    if not self._grad_is_view(p):
                        b.scatter(p)
                    self._allreduce_delay[id(p)] = \
                        self.backward_passes_per_step
            b.ready.clear()
        self._handles.clear()
        self._synchronized = True

    @contextmanager
    def skip_synchronize(self):
        """Use after an explicit ``synchronize()`` (e.g. for gradient
        clipping) so ``step()`` does not allreduce a second time
        (torch/__init__.py:149-160)::

            optimizer.synchronize()
            torch.nn.utils.clip_grad_norm_(model.parameters(), 1.0)
            with optimizer.skip_synchronize():
                optimizer.step()
        """
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        if self._should_synchronize:
            if self._synchronized:
                warnings.warn(
                    "optimizer.step() called without skip_synchronize() "
                    "after optimizer.synchronize(); this re-allreduces "
                    "every gradient. Wrap step() in "
                    "optimizer.skip_synchronize() context.")
            self.synchronize()
        self._synchronized = False
        if self._skip_nonfinite and self._saw_nonfinite:
            # Opt-in NaN guard (docs/numerics.md#torch): the collective
            # already ran (every rank stays in lockstep), but the inner
            # update is skipped so the corrupted averaged gradients
            # never touch the weights.
            self._saw_nonfinite = False
            self._metrics.skipped_steps.inc()
            warnings.warn(
                "skip_nonfinite_steps: nonfinite gradient elements "
                "observed this step; optimizer update skipped "
                "(docs/numerics.md#torch)")
            return None
        self._saw_nonfinite = False
        return super(self.__class__, self).step(closure)

    def zero_grad(self, *args, **kwargs):
        if self._handles:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() "
                "but before optimizer.step() or optimizer.synchronize(); "
                "this would discard in-flight allreduced gradients.")
        if self._grad_views and not args and "set_to_none" not in kwargs:
            # gradient_as_bucket_view: the default zero_grad()
            # (set_to_none=True) would drop every alias and force a
            # rebind each step; zero in place instead so the views —
            # and the zero-copy pack — survive. An EXPLICIT
            # set_to_none=True is honored (the hook repairs the alias
            # and counts the rebind).
            kwargs["set_to_none"] = False
        return super(self.__class__, self).zero_grad(*args, **kwargs)


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters: Optional[
                             Iterable[Tuple[str, torch.Tensor]]] = None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         bucket_cap_mb: Optional[float] = None,
                         gradient_as_bucket_view: Optional[bool] = None,
                         skip_nonfinite_steps: Optional[bool] = None):
    """Wrap a torch optimizer so ``step()`` applies allreduce-averaged
    gradients — the reference builds a dynamic subclass of the wrapped
    optimizer's class so isinstance() and LR schedulers keep working
    (torch/__init__.py:154-197).

    ``bucket_cap_mb`` sizes the backward-overlap gradient buckets
    (docs/torch.md): None reads HOROVOD_TPU_TORCH_BUCKET_MB (default =
    the engine fusion threshold, 64 MB), 0 disables bucketing and keeps
    the per-tensor hook path.

    ``gradient_as_bucket_view`` aliases each ``p.grad`` into its
    bucket's flat buffer at wrap time (docs/torch.md) — autograd then
    accumulates directly into the collective payload, dropping the
    hook-time pack memcpy and the scatter-back; bitwise-identical
    results to the copying path. None reads HOROVOD_TPU_TORCH_GRAD_VIEW
    (default off).

    ``skip_nonfinite_steps`` (docs/numerics.md#torch): when the bucket
    pack's nonfinite sentinel (HOROVOD_TPU_NUMERICS=1) counted NaN/Inf
    gradient elements this step, ``step()`` still synchronizes — every
    rank runs the same collectives — but skips the inner optimizer
    update. None reads HOROVOD_TPU_TORCH_SKIP_NONFINITE (default
    off)."""
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step, bucket_cap_mb,
               gradient_as_bucket_view, skip_nonfinite_steps)


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """Broadcast model parameters from ``root_rank`` in place — accepts a
    ``state_dict()`` or ``model.named_parameters()``
    (torch/__init__.py:200-229)."""
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)
    handles = []
    # One fusion burst + one batched synchronize for the whole variable
    # set: a model-sized broadcast is hundreds of tensors, and draining
    # them one synchronize() at a time pays a readback round trip each
    # (the grouped path mpi_ops.synchronize_many exists for).
    with _ops.engine().burst():
        for name, p in items:
            if p is None or not isinstance(p, torch.Tensor):
                continue
            handles.append(
                broadcast_async_(p, root_rank, name=f"bcast.{name}"))
    synchronize_many(handles)


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0) -> None:
    """Broadcast an optimizer's state from ``root_rank`` in place.

    Mirrors torch/__init__.py:232-348: scalar state entries (e.g. Adam's
    ``step`` counts, param-group hyperparameters) are tensorized,
    broadcast, and cast back to their original Python types; tensor state
    (exp_avg, momentum buffers, ...) is broadcast in place. If the
    optimizer has no state yet, it is materialized with zero gradients so
    every rank agrees on the state layout (torch/__init__.py:249-262).
    """
    if isinstance(optimizer, torch.optim.LBFGS):
        raise ValueError(
            "cannot broadcast torch.optim.LBFGS state "
            "(torch/__init__.py:241-244)")
    state_dict = optimizer.state_dict()
    if not state_dict["state"]:
        created = []
        for group in optimizer.param_groups:
            for p in group["params"]:
                if p.requires_grad and p.grad is None:
                    p.grad = torch.zeros_like(p)
                    created.append(p)
        optimizer.step()
        for p in created:
            p.grad = None
        state_dict = optimizer.state_dict()

    callbacks = []
    handles = []
    scalars = {}
    scalar_state_keys = []

    def _tensorize(key, value):
        t = torch.tensor([float(value)], dtype=torch.float64)
        scalars[key] = (t, type(value))
        handles.append(broadcast_async_(t, root_rank, name=f"opt.{key}"))

    with _ops.engine().burst():
        # The whole mixed bag — tensorized scalars, 0-dim views, tensor
        # state — enqueues as ONE fusion burst, then drains through one
        # batched synchronize below (the grouped path).
        for gi, group in enumerate(state_dict["param_groups"]):
            for key, value in group.items():
                if key == "params":
                    continue
                if isinstance(value, (int, float)) and not isinstance(
                        value, bool):
                    skey = f"group.{gi}.{key}"
                    _tensorize(skey, value)

                    def make_cb(gi=gi, key=key, skey=skey):
                        def cb():
                            t, typ = scalars[skey]
                            optimizer.param_groups[gi][key] = typ(t.item())
                        return cb
                    callbacks.append(make_cb())
        for pid, pstate in state_dict["state"].items():
            for key, value in pstate.items():
                if isinstance(value, torch.Tensor):
                    if value.ndim == 0:
                        # 0-dim tensors (modern torch 'step') broadcast
                        # via a 1-element view-alike then copy back.
                        flat = value.reshape(1).clone()
                        handles.append(broadcast_async_(
                            flat, root_rank, name=f"opt.state.{pid}.{key}"))

                        def make_cb0(value=value, flat=flat):
                            def cb():
                                value.copy_(flat[0])
                            return cb
                        callbacks.append(make_cb0())
                    else:
                        handles.append(broadcast_async_(
                            value, root_rank, name=f"opt.state.{pid}.{key}"))
                elif isinstance(value, (int, float)) and not isinstance(
                        value, bool):
                    skey = f"state.{pid}.{key}"
                    _tensorize(skey, value)
                    scalar_state_keys.append((pid, key, skey))
    # Same grouped path as broadcast_parameters: every tensorized scalar
    # and state tensor rides one burst + one batched synchronize; the
    # per-key callbacks then re-cast from the landed buffers.
    synchronize_many(handles)
    for cb in callbacks:
        cb()
    if scalar_state_keys:
        # One state_dict round trip for ALL scalar state entries (not one
        # per entry): load_state_dict re-casts every tensor, so per-entry
        # reloads would be O(P^2) in tensor traffic.
        sd = optimizer.state_dict()
        for pid, key, skey in scalar_state_keys:
            t, typ = scalars[skey]
            sd["state"][pid][key] = typ(t.item())
        optimizer.load_state_dict(sd)


def checkpoint_hook(directory=None, *, engine=None, model=None,
                    optimizer=None, every: int = 100, extra=None):
    """Async save hook for the torch training loop on the sharded
    checkpoint engine (docs/checkpoint.md).

    Returns ``save(step)``: call it once per step; every ``every`` steps
    it snapshots ``model.state_dict()`` / ``optimizer.state_dict()``
    tensors to host numpy (a replicated tree — rank 0 writes under the
    engine's layout rules) and hands them to the engine, which
    serializes and commits atomically in the background. The returned
    callable exposes ``save.engine`` (e.g. for ``engine.wait()`` at
    train end) and forces a blocking commit with ``save(step,
    block=True)``. Restore via ``engine.restore()`` — the tree is plain
    nested dicts, so no template is needed — then
    ``model.load_state_dict``/``optimizer.load_state_dict`` with
    re-tensorized leaves.

    ``extra`` is a JSON-able dict recorded in every commit's manifest —
    pass ``serving.transformer_extra(cfg)`` (plus matching state-dict
    keys, docs/serving.md#torch) to make the checkpoint directly
    servable by ``python -m horovod_tpu.serving --framework torch``.
    """
    if (directory is None) == (engine is None):
        raise ValueError("pass exactly one of directory= or engine=")
    if engine is None:
        from ..checkpoint import CheckpointEngine
        engine = CheckpointEngine(directory)

    def _host_tree(sd):
        out = {}
        for key, value in sd.items():
            if isinstance(value, torch.Tensor):
                out[key] = value.detach().cpu().numpy()
            elif isinstance(value, dict):
                out[key] = _host_tree(value)
            elif isinstance(value, (list, tuple)):
                out[key] = [_host_tree(v) if isinstance(v, dict)
                            else (v.detach().cpu().numpy()
                                  if isinstance(v, torch.Tensor) else v)
                            for v in value]
            else:
                out[key] = value
        return out

    def save(step: int, block: bool = False):
        if step % every:
            return None
        tree = {}
        if model is not None:
            tree["model"] = _host_tree(model.state_dict())
        if optimizer is not None:
            tree["optimizer"] = _host_tree(optimizer.state_dict())
        if not tree:
            raise ValueError("checkpoint_hook needs model= and/or "
                             "optimizer=")
        return engine.save(tree, step=step, block=block, extra=extra)

    save.engine = engine
    return save
