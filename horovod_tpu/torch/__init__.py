"""horovod_tpu.torch — the PyTorch framework shim.

Parity target: horovod/torch/__init__.py (348 LoC) + mpi_ops.py (438 LoC):
``DistributedOptimizer`` firing an async allreduce per parameter as its
gradient is accumulated, ``synchronize()`` flushing handles before
``step()``, ``backward_passes_per_step`` gradient accumulation,
``broadcast_parameters`` and ``broadcast_optimizer_state``. Torch stays the
autograd/optimizer engine; the collectives run on the TPU-native XLA data
plane (see mpi_ops.py in this package).
"""

from __future__ import annotations

import warnings
from contextlib import contextmanager
from typing import Iterable, Optional, Tuple

import torch

from ..topology import (init, shutdown, is_initialized, rank, local_rank,
                        size, local_size, mpi_threads_supported)
from ..observability import StepTimer as _StepTimer
from .compression import Compression
from .mpi_ops import (allreduce, allreduce_, allreduce_async,
                      allreduce_async_, allgather, allgather_async,
                      broadcast, broadcast_, broadcast_async,
                      broadcast_async_, poll, synchronize,
                      synchronize_many)

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "local_rank", "size",
    "local_size", "mpi_threads_supported", "Compression",
    "allreduce", "allreduce_", "allreduce_async", "allreduce_async_",
    "allgather", "allgather_async", "broadcast", "broadcast_",
    "broadcast_async", "broadcast_async_", "poll", "synchronize",
    "DistributedOptimizer", "broadcast_parameters",
    "broadcast_optimizer_state", "StepMetrics", "checkpoint_hook",
]


class StepMetrics(_StepTimer):
    """Per-step telemetry hook for the torch training loop
    (docs/metrics.md): records ``hvdtpu_step_seconds``,
    ``hvdtpu_samples_per_second``, ``hvdtpu_collective_step_share``
    (with ``hvdtpu_allreduce_step_share`` as a deprecated alias), the
    input/h2d/compute/collective attribution
    (``hvdtpu_step_phase_seconds``/``_share``), HBM gauges, and — when
    ``flops_per_step`` is supplied — MFU (all labeled
    ``framework=torch``). Use as a context manager around each step::

        metrics = hvd.torch.StepMetrics(batch_size=64)
        for batch in loader:
            with metrics:
                loss = train_step(batch)   # backward + optimizer.step()

    The collective share is computed from the engine's own execute-time
    accounting, so it covers the DistributedOptimizer's async allreduces
    wherever they overlap the step."""

    def __init__(self, batch_size: Optional[int] = None,
                 flops_per_step: Optional[float] = None):
        super().__init__("torch", batch_size=batch_size,
                         flops_per_step=flops_per_step)


class _DistributedOptimizer(torch.optim.Optimizer):
    """Mixin installed on a dynamic subclass of the wrapped optimizer
    (horovod/torch/__init__.py:42-151).

    Each parameter gets a post-grad-accumulation hook that launches an
    async in-place allreduce as soon as its gradient is ready (the
    reference registers hooks on the grad accumulator nodes,
    torch/__init__.py:95-130); ``step()`` synchronizes all outstanding
    handles first (torch/__init__.py:149-151).
    """

    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step=1):
        super(self.__class__, self).__init__(params)
        self._compression = compression
        self.backward_passes_per_step = backward_passes_per_step
        self._synchronized = False
        self._should_synchronize = True

        if named_parameters is not None:
            named_parameters = list(named_parameters)
        else:
            named_parameters = [
                (f"allreduce.noname.{i}.{j}", v)
                for i, group in enumerate(self.param_groups)
                for j, v in enumerate(group["params"])]
        all_params = {id(v) for group in self.param_groups
                      for v in group["params"]}
        named_ids = {id(v) for _, v in named_parameters}
        if len(named_ids) != len(named_parameters):
            raise ValueError(
                "named_parameters contains duplicate parameters")
        if not named_ids.issubset(all_params):
            raise ValueError(
                "named_parameters was not a subset of optimizer.param_groups"
                " parameters (torch/__init__.py:56-66)")
        self._parameter_names = {id(v): k for k, v in named_parameters}
        self._handles = {}
        self._wire_ctx = {}
        self._allreduce_delay = {id(v): backward_passes_per_step
                                 for group in self.param_groups
                                 for v in group["params"]}
        self._register_hooks()

    def _register_hooks(self):
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    p.register_post_accumulate_grad_hook(self._make_hook())

    def _make_hook(self):
        def hook(p):
            if id(p) in self._handles:
                raise AssertionError(
                    "Gradient for this parameter was already allreduced "
                    "this step. If you call backward() more than once per "
                    "step, pass backward_passes_per_step="
                    "<number of backward passes> to DistributedOptimizer "
                    "(torch/__init__.py:114-124).")
            self._allreduce_delay[id(p)] -= 1
            if self._allreduce_delay[id(p)] == 0:
                self._handles[id(p)] = self._allreduce_grad_async(p)
        return hook

    def _allreduce_grad_async(self, p):
        name = self._parameter_names.get(id(p), f"allreduce.{id(p)}")
        wire, ctx = self._compression.compress(p.grad)
        self._wire_ctx[id(p)] = ctx
        # Blockwise formats pass through compress() unchanged; the wire
        # spec rides the request and the engine quantizes in-program.
        blockwise = self._compression if getattr(
            self._compression, "wire_spec", None) is not None else None
        if wire is p.grad:
            return allreduce_async_(p.grad, average=True, name=name,
                                    compression=blockwise)
        return allreduce_async(wire, average=True, name=name,
                               compression=blockwise)

    def synchronize(self):
        """Flush: enqueue any parameter whose hook never fired, then block
        on every handle and install the (decompressed) averaged gradients
        (torch/__init__.py:132-147)."""
        # Every parameter not already in flight gets flushed here — even one
        # mid-accumulation (delay > 0), matching the reference, so that an
        # early step() never applies un-allreduced local gradients
        # (torch/__init__.py:132-140).
        missing = [p for group in self.param_groups
                   for p in group["params"]
                   if p.requires_grad and p.grad is not None
                   and id(p) not in self._handles]
        for p in missing:
            self._handles[id(p)] = self._allreduce_grad_async(p)
        params_by_id = {id(p): p for group in self.param_groups
                        for p in group["params"]}
        # Batched synchronize: one device_get for every non-aliasable
        # result instead of a per-parameter readback round trip
        # (mpi_ops.synchronize_many).
        pids = list(self._handles.keys())
        outs = synchronize_many([self._handles[pid] for pid in pids])
        for pid, out in zip(pids, outs):
            p = params_by_id[pid]
            ctx = self._wire_ctx.pop(pid, None)
            if out is not p.grad:
                p.grad.copy_(self._compression.decompress(out, ctx)
                             .reshape(p.grad.shape))
            self._allreduce_delay[pid] = self.backward_passes_per_step
        self._handles.clear()
        self._synchronized = True

    @contextmanager
    def skip_synchronize(self):
        """Use after an explicit ``synchronize()`` (e.g. for gradient
        clipping) so ``step()`` does not allreduce a second time
        (torch/__init__.py:149-160)::

            optimizer.synchronize()
            torch.nn.utils.clip_grad_norm_(model.parameters(), 1.0)
            with optimizer.skip_synchronize():
                optimizer.step()
        """
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        if self._should_synchronize:
            if self._synchronized:
                warnings.warn(
                    "optimizer.step() called without skip_synchronize() "
                    "after optimizer.synchronize(); this re-allreduces "
                    "every gradient. Wrap step() in "
                    "optimizer.skip_synchronize() context.")
            self.synchronize()
        self._synchronized = False
        return super(self.__class__, self).step(closure)

    def zero_grad(self, *args, **kwargs):
        if self._handles:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() "
                "but before optimizer.step() or optimizer.synchronize(); "
                "this would discard in-flight allreduced gradients.")
        return super(self.__class__, self).zero_grad(*args, **kwargs)


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters: Optional[
                             Iterable[Tuple[str, torch.Tensor]]] = None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1):
    """Wrap a torch optimizer so ``step()`` applies allreduce-averaged
    gradients — the reference builds a dynamic subclass of the wrapped
    optimizer's class so isinstance() and LR schedulers keep working
    (torch/__init__.py:154-197)."""
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step)


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """Broadcast model parameters from ``root_rank`` in place — accepts a
    ``state_dict()`` or ``model.named_parameters()``
    (torch/__init__.py:200-229)."""
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)
    handles = []
    for name, p in items:
        if p is None or not isinstance(p, torch.Tensor):
            continue
        handles.append(broadcast_async_(p, root_rank, name=f"bcast.{name}"))
    for h in handles:
        synchronize(h)


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0) -> None:
    """Broadcast an optimizer's state from ``root_rank`` in place.

    Mirrors torch/__init__.py:232-348: scalar state entries (e.g. Adam's
    ``step`` counts, param-group hyperparameters) are tensorized,
    broadcast, and cast back to their original Python types; tensor state
    (exp_avg, momentum buffers, ...) is broadcast in place. If the
    optimizer has no state yet, it is materialized with zero gradients so
    every rank agrees on the state layout (torch/__init__.py:249-262).
    """
    if isinstance(optimizer, torch.optim.LBFGS):
        raise ValueError(
            "cannot broadcast torch.optim.LBFGS state "
            "(torch/__init__.py:241-244)")
    state_dict = optimizer.state_dict()
    if not state_dict["state"]:
        created = []
        for group in optimizer.param_groups:
            for p in group["params"]:
                if p.requires_grad and p.grad is None:
                    p.grad = torch.zeros_like(p)
                    created.append(p)
        optimizer.step()
        for p in created:
            p.grad = None
        state_dict = optimizer.state_dict()

    callbacks = []
    handles = []
    scalars = {}
    scalar_state_keys = []

    def _tensorize(key, value):
        t = torch.tensor([float(value)], dtype=torch.float64)
        scalars[key] = (t, type(value))
        handles.append(broadcast_async_(t, root_rank, name=f"opt.{key}"))

    for gi, group in enumerate(state_dict["param_groups"]):
        for key, value in group.items():
            if key == "params":
                continue
            if isinstance(value, (int, float)) and not isinstance(
                    value, bool):
                skey = f"group.{gi}.{key}"
                _tensorize(skey, value)

                def make_cb(gi=gi, key=key, skey=skey):
                    def cb():
                        t, typ = scalars[skey]
                        optimizer.param_groups[gi][key] = typ(t.item())
                    return cb
                callbacks.append(make_cb())
    for pid, pstate in state_dict["state"].items():
        for key, value in pstate.items():
            if isinstance(value, torch.Tensor):
                if value.ndim == 0:
                    # 0-dim tensors (modern torch 'step') broadcast via a
                    # 1-element view-alike then copy back.
                    flat = value.reshape(1).clone()
                    handles.append(broadcast_async_(
                        flat, root_rank, name=f"opt.state.{pid}.{key}"))

                    def make_cb0(value=value, flat=flat):
                        def cb():
                            value.copy_(flat[0])
                        return cb
                    callbacks.append(make_cb0())
                else:
                    handles.append(broadcast_async_(
                        value, root_rank, name=f"opt.state.{pid}.{key}"))
            elif isinstance(value, (int, float)) and not isinstance(
                    value, bool):
                skey = f"state.{pid}.{key}"
                _tensorize(skey, value)
                scalar_state_keys.append((pid, key, skey))
    for h in handles:
        synchronize(h)
    for cb in callbacks:
        cb()
    if scalar_state_keys:
        # One state_dict round trip for ALL scalar state entries (not one
        # per entry): load_state_dict re-casts every tensor, so per-entry
        # reloads would be O(P^2) in tensor traffic.
        sd = optimizer.state_dict()
        for pid, key, skey in scalar_state_keys:
            t, typ = scalars[skey]
            sd["state"][pid][key] = typ(t.item())
        optimizer.load_state_dict(sd)


def checkpoint_hook(directory=None, *, engine=None, model=None,
                    optimizer=None, every: int = 100):
    """Async save hook for the torch training loop on the sharded
    checkpoint engine (docs/checkpoint.md).

    Returns ``save(step)``: call it once per step; every ``every`` steps
    it snapshots ``model.state_dict()`` / ``optimizer.state_dict()``
    tensors to host numpy (a replicated tree — rank 0 writes under the
    engine's layout rules) and hands them to the engine, which
    serializes and commits atomically in the background. The returned
    callable exposes ``save.engine`` (e.g. for ``engine.wait()`` at
    train end) and forces a blocking commit with ``save(step,
    block=True)``. Restore via ``engine.restore()`` — the tree is plain
    nested dicts, so no template is needed — then
    ``model.load_state_dict``/``optimizer.load_state_dict`` with
    re-tensorized leaves.
    """
    if (directory is None) == (engine is None):
        raise ValueError("pass exactly one of directory= or engine=")
    if engine is None:
        from ..checkpoint import CheckpointEngine
        engine = CheckpointEngine(directory)

    def _host_tree(sd):
        out = {}
        for key, value in sd.items():
            if isinstance(value, torch.Tensor):
                out[key] = value.detach().cpu().numpy()
            elif isinstance(value, dict):
                out[key] = _host_tree(value)
            elif isinstance(value, (list, tuple)):
                out[key] = [_host_tree(v) if isinstance(v, dict)
                            else (v.detach().cpu().numpy()
                                  if isinstance(v, torch.Tensor) else v)
                            for v in value]
            else:
                out[key] = value
        return out

    def save(step: int, block: bool = False):
        if step % every:
            return None
        tree = {}
        if model is not None:
            tree["model"] = _host_tree(model.state_dict())
        if optimizer is not None:
            tree["optimizer"] = _host_tree(optimizer.state_dict())
        if not tree:
            raise ValueError("checkpoint_hook needs model= and/or "
                             "optimizer=")
        return engine.save(tree, step=step, block=block)

    save.engine = engine
    return save
