"""Python Chrome-trace timeline — the fallback/multi-process writer.

The native runtime owns the timeline on the single-process path
(runtime/src/timeline.cc, the reference's lock-free writer design,
timeline.h:66-68). Two paths cannot use it: the Python control-plane
fallback (no toolchain) and multi-process mode (where the native core's
local negotiation is bypassed for the TCP coordinator). This module
gives those paths the same artifact: catapult JSON with one "process"
per tensor (pid = interned tensor index, timeline.cc:70-90) and the
NEGOTIATE_* / op / activity phases the reference writes
(operations.h:29-50), so ``chrome://tracing`` renders identically.

Writer thread + queue mirror the native design at Python scale: events
append to a deque; a daemon thread drains it so the enqueue path never
blocks on file IO.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import Optional

class PyTimeline:
    """Chrome-trace writer with the reference's phase vocabulary."""

    def __init__(self, path: str):
        self._path = path
        self._f = open(path, "w")
        self._f.write("[\n")
        self._start = time.monotonic()
        self._pids = {}
        self._queue = collections.deque()
        self._wake = threading.Event()
        self._stop = False
        self._first = True
        self._thread = threading.Thread(target=self._drain,
                                        name="hvd-tpu-timeline",
                                        daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- events

    def _ts(self) -> int:
        return int((time.monotonic() - self._start) * 1e6)

    def _pid(self, tensor: str) -> int:
        pid = self._pids.get(tensor)
        if pid is None:
            pid = len(self._pids)
            self._pids[tensor] = pid
            self._queue.append({"name": "process_name", "ph": "M",
                                "pid": pid,
                                "args": {"name": tensor}})
        return pid

    def _emit(self, tensor: str, ph: str, name: Optional[str] = None,
              args: Optional[dict] = None, scope: Optional[str] = None):
        ev = {"ph": ph, "ts": self._ts(), "pid": self._pid(tensor),
              "tid": 0}
        if name is not None:
            ev["name"] = name
        if args:
            ev["args"] = args
        if scope is not None:
            ev["s"] = scope
        self._queue.append(ev)
        self._wake.set()

    # Phase API — mirrors the native Timeline's surface used by the engine.

    def negotiate_start(self, tensor: str, op_name: str):
        self._emit(tensor, "B", f"NEGOTIATE_{op_name.upper()}")

    def negotiate_end(self, tensor: str):
        self._emit(tensor, "E")

    def start(self, tensor: str, op_name: str):
        self._emit(tensor, "B", op_name)

    def activity_start_all(self, tensors, activity: str):
        for t in tensors:
            self._emit(t, "B", activity)

    def activity_end_all(self, tensors):
        for t in tensors:
            self._emit(t, "E")

    def end(self, tensor: str, shape=None):
        args = {"shape": list(shape)} if shape is not None else None
        self._emit(tensor, "E", args=args)

    def mark_cycle(self):
        # Instant events need an explicit scope: without "s" Perfetto
        # and Chrome render a thread-scoped tick on tid 0 only; "g"
        # (global) draws the cycle marker across the whole trace, which
        # is what a background-cycle boundary means (Trace Event Format
        # §Instant Events).
        self._emit("_cycles", "i", "CYCLE_START", scope="g")

    # ------------------------------------------------------------- writer

    def _drain(self):
        while True:
            self._wake.wait(timeout=0.2)
            self._wake.clear()
            wrote = False
            while self._queue:
                ev = self._queue.popleft()
                prefix = "" if self._first else ",\n"
                self._first = False
                self._f.write(prefix + json.dumps(ev))
                wrote = True
            if wrote:
                self._f.flush()
            if self._stop and not self._queue:
                return

    def close(self):
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            # Drain thread stuck in a slow write (NFS, huge backlog):
            # closing underneath it would interleave the footer with its
            # writes and crash it on the closed handle. Leave the file
            # open — a missing ']' is tolerated by trace viewers.
            return
        try:
            self._f.write("\n]\n")
            self._f.close()
        except ValueError:
            pass  # already closed
