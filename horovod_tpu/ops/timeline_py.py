"""Python Chrome-trace timeline — the fallback/multi-process writer.

The native runtime owns the timeline on the single-process path
(runtime/src/timeline.cc, the reference's lock-free writer design,
timeline.h:66-68). Two paths cannot use it: the Python control-plane
fallback (no toolchain) and multi-process mode (where the native core's
local negotiation is bypassed for the TCP coordinator). This module
gives those paths the same artifact: catapult JSON with one "process"
per tensor (pid = interned tensor index, timeline.cc:70-90) and the
NEGOTIATE_* / op / activity phases the reference writes
(operations.h:29-50), so ``chrome://tracing`` renders identically.

Writer thread + queue mirror the native design at Python scale: events
append to a deque; a daemon thread drains it so the enqueue path never
blocks on file IO.

Cross-rank additions (docs/tracing.md): every rank may write its own
trace (``HOROVOD_TPU_TIMELINE`` with a ``{rank}`` placeholder), so each
file carries clock metadata — the writer's monotonic start and the
rank's estimated offset to rank 0 from the control-plane handshake
(``set_clock_meta``) — letting ``python -m horovod_tpu.tools.trace``
realign N per-rank files onto one clock. ``negotiate_end`` records the
coordinator's group sequence number so the merger can attribute
per-fused-group critical paths across ranks without guessing from
timestamps.
"""

from __future__ import annotations

import atexit
import collections
import json
import threading
import time
from typing import Optional

# Trace-metadata event name shared with the merge tool
# (horovod_tpu/tools/trace.py) and the sidecar writer (ops/collective.py).
TRACE_META_EVENT = "horovod_tpu_trace_meta"


def clock_sidecar_path(trace_path: str) -> str:
    """Path of the clock-metadata sidecar written next to a per-rank
    trace. The sidecar exists because the NATIVE timeline writer
    (runtime/src/timeline.cc) owns its file in C++ and cannot carry the
    Python-measured clock offset in-band; the Python writer embeds the
    same fields as a metadata event AND gets the sidecar, so the merge
    tool reads whichever is present."""
    return trace_path + ".clock.json"


def write_clock_sidecar(trace_path: str, meta: dict) -> None:
    with open(clock_sidecar_path(trace_path), "w") as f:
        json.dump(meta, f, indent=1, sort_keys=True)
        f.write("\n")


class PyTimeline:
    """Chrome-trace writer with the reference's phase vocabulary."""

    def __init__(self, path: str, rank: int = 0, world: int = 1,
                 proc: Optional[str] = None):
        self._path = path
        self._f = open(path, "w")
        self._f.write("[\n")
        self._start = time.monotonic()
        self.rank = rank
        self.world = world
        # Human-readable process identity for non-rank writers (the
        # serving request-trace plane names its files "router" /
        # "replica1" — the merge tool displays this instead of
        # "rank N" when present).
        self.proc = proc
        self._pids = {}
        self._name_json = {}   # event name -> pre-escaped JSON string
        self._neg_cache = {}   # op name -> "NEGOTIATE_<OP>"
        self._queue = collections.deque()
        self._wake = threading.Event()
        self._stop = False
        self._first = True
        self._thread = threading.Thread(target=self._drain,
                                        name="hvd-tpu-timeline",
                                        daemon=True)
        self._thread.start()
        # Flush-on-exit (crash/SIGTERM paths of the elastic driver reach
        # interpreter exit without engine.shutdown()): close() drains the
        # deque and terminates the JSON array so buffered events are not
        # lost. close() is idempotent — a later explicit shutdown() is a
        # no-op on the already-closed file.
        atexit.register(self.close)
        # Clock metadata header: written immediately with offset unknown;
        # set_clock_meta() re-emits it once the control-plane handshake
        # measured the offset to rank 0.
        self._emit_clock_meta(offset_us=0.0, rtt_us=0.0, synced=False)

    @property
    def start_monotonic_us(self) -> int:
        """This trace's epoch on the local monotonic clock — event ts are
        microseconds since this instant."""
        return int(self._start * 1e6)

    def _emit_clock_meta(self, offset_us: float, rtt_us: float,
                         synced: bool) -> None:
        args = {"rank": self.rank, "world": self.world,
                "start_mono_us": self.start_monotonic_us,
                "offset_to_rank0_us": float(offset_us),
                "rtt_us": float(rtt_us),
                "clock_synced": bool(synced)}
        if self.proc is not None:
            args["proc"] = self.proc
        self._queue.append({
            "name": TRACE_META_EVENT, "ph": "M", "pid": 0, "tid": 0,
            "args": args})
        self._wake.set()

    def set_clock_meta(self, offset_s: float, rtt_s: float) -> None:
        """Record the measured offset-to-rank-0 (seconds; positive means
        rank 0's monotonic clock reads ahead of ours) from the NTP-style
        control-plane handshake. The merge tool uses the LAST meta event
        in the file, so re-emitting supersedes the unsynced header."""
        self._emit_clock_meta(offset_us=offset_s * 1e6,
                              rtt_us=rtt_s * 1e6, synced=True)

    # ------------------------------------------------------------- events

    def _ts(self) -> int:
        return int((time.monotonic() - self._start) * 1e6)

    def _pid(self, tensor: str) -> int:
        pid = self._pids.get(tensor)
        if pid is None:
            pid = len(self._pids)
            self._pids[tensor] = pid
            self._queue.append({"name": "process_name", "ph": "M",
                                "pid": pid,
                                "args": {"name": tensor}})
        return pid

    def _emit(self, tensor: str, ph: str, name: Optional[str] = None,
              args: Optional[dict] = None, scope: Optional[str] = None):
        # The emitting thread is the engine's dispatch thread — this
        # call sits between group delivery and handle fulfillment, i.e.
        # on the step's critical path. Append the raw fields only; the
        # drain thread builds the dict/JSON (BENCH_TRACE holds the
        # step-time cost of all-ranks tracing under 3%).
        self._queue.append((ph, self._ts(), self._pid(tensor), name,
                            args, scope))
        # Deliberately NO wake: the drain thread polls DRAIN_POLL_S.
        # Waking per event made every enqueue a context-switch
        # invitation — on a saturated host the writer preempted the step
        # loop it was observing (measured ~38% step overhead on a 1-core
        # box). Worst case DRAIN_POLL_S of events sit buffered; close()
        # still drains everything.

    # Phase API — mirrors the native Timeline's surface used by the
    # engine. These sit on the enqueue/dispatch threads' critical path,
    # so they append raw tuples directly (no _emit indirection, cached
    # phase-name strings); the drain thread does all formatting.

    def negotiate_start(self, tensor: str, op_name: str):
        nm = self._neg_cache.get(op_name)
        if nm is None:
            nm = self._neg_cache[op_name] = "NEGOTIATE_" + op_name.upper()
        self._queue.append(("B", self._ts(), self._pid(tensor), nm,
                            None, None))

    def negotiate_end(self, tensor: str, group: Optional[int] = None):
        # The group sequence number (coordinator-agreed in MP mode, a
        # local counter otherwise) keys cross-rank critical-path
        # attribution in the merge tool: the same group seq names the
        # same fused collective on every rank. Shipped as a raw tagged
        # value — the drain thread formats it; no dict on this path.
        self._queue.append(
            ("E", self._ts(), self._pid(tensor), None,
             ("group", int(group)) if group is not None else None, None))

    def start(self, tensor: str, op_name: str):
        self._queue.append(("B", self._ts(), self._pid(tensor), op_name,
                            None, None))

    def activity_start_all(self, tensors, activity: str):
        for t in tensors:
            self._queue.append(("B", self._ts(), self._pid(t), activity,
                                None, None))

    def activity_end_all(self, tensors):
        for t in tensors:
            self._queue.append(("E", self._ts(), self._pid(t), None,
                                None, None))

    def end(self, tensor: str, shape=None):
        args = (("shape", tuple(int(d) for d in shape))
                if shape is not None else None)
        self._queue.append(("E", self._ts(), self._pid(tensor), None,
                            args, None))

    # Complete-span fast path ("X" events): the engine's dispatch loop
    # already holds both endpoints of every phase (enqueued_at,
    # delivery, execute start/end on its own monotonic clock), so one
    # event carries what a B/E pair would — half the event volume, and
    # nothing emitted from the user's enqueue thread at all. Trade-off
    # vs. the native writer's live B/E stream: a tensor stuck IN a phase
    # has no open span in the file; the stall detector and the
    # coordinator's lateness metrics cover that case (docs/tracing.md).

    def negotiate_span(self, tensor: str, op_name: str, t0: float,
                       t1: float, group: Optional[int] = None):
        """One NEGOTIATE_<OP> complete span from monotonic seconds
        ``t0`` (enqueue) to ``t1`` (group delivery)."""
        nm = self._neg_cache.get(op_name)
        if nm is None:
            nm = self._neg_cache[op_name] = "NEGOTIATE_" + op_name.upper()
        self._queue.append(
            ("X", int((t0 - self._start) * 1e6), self._pid(tensor), nm,
             ("group", int(group)) if group is not None else None,
             max(0, int((t1 - t0) * 1e6))))

    def execute_span(self, tensor: str, activity: str, t0: float,
                     t1: float, shape=None):
        """One XLA_* complete span over the fused program execution."""
        args = (("shape", tuple(int(d) for d in shape))
                if shape is not None else None)
        self._queue.append(
            ("X", int((t0 - self._start) * 1e6), self._pid(tensor),
             activity, args, max(0, int((t1 - t0) * 1e6))))

    def request_span(self, row: str, name: str, t0: float, t1: float,
                     args: Optional[dict] = None):
        """One complete span on a NAMED row — the serving request-trace
        plane's emitter (serving/reqtrace.py): ``row`` is the request's
        trace id (each request renders as its own process row, exactly
        like tensors do in the training capture), ``t0``/``t1`` are
        monotonic seconds, ``args`` an optional small dict (formatted on
        the drain thread, never here)."""
        self._queue.append(
            ("X", int((t0 - self._start) * 1e6), self._pid(row), name,
             args, max(0, int((t1 - t0) * 1e6))))

    def mark_cycle(self):
        # Instant events need an explicit scope: without "s" Perfetto
        # and Chrome render a thread-scoped tick on tid 0 only; "g"
        # (global) draws the cycle marker across the whole trace, which
        # is what a background-cycle boundary means (Trace Event Format
        # §Instant Events).
        self._emit("_cycles", "i", "CYCLE_START", scope="g")

    # ------------------------------------------------------------- writer

    # Drain cadence: long enough to batch hundreds of events per write
    # (one json+IO burst instead of a wakeup per event), short enough
    # that a SIGKILL loses at most a blink of trace.
    DRAIN_POLL_S = 0.05

    def _drain(self):
        # Event records are serialized HERE, not at emit time, with the
        # few variable pieces (event names, args) going through cached /
        # per-occurrence json.dumps for correct escaping; ph and scope
        # are single-character constants from this module. One write +
        # flush per poll turns ~DRAIN_POLL_S of events into a single IO
        # burst.
        dumps = json.dumps
        name_json = self._name_json
        while True:
            self._wake.wait(timeout=self.DRAIN_POLL_S)
            self._wake.clear()
            parts = []
            while self._queue:
                item = self._queue.popleft()
                if isinstance(item, dict):   # metadata events
                    parts.append(dumps(item))
                    continue
                # extra = dur for "X" complete events, scope for "i"
                # instants, None otherwise.
                ph, ts, pid, name, args, extra = item
                s = f'{{"ph":"{ph}","ts":{ts},"pid":{pid},"tid":0'
                if name is not None:
                    e = name_json.get(name)
                    if e is None:
                        e = name_json[name] = dumps(name)
                    s += f',"name":{e}'
                if args is not None:
                    # ("group", int) / ("shape", (ints,)) fast paths —
                    # integer-only payloads need no escaping; anything
                    # else goes through json.dumps.
                    if type(args) is tuple:
                        k, v = args
                        if k == "shape":
                            v = f'[{",".join(map(str, v))}]'
                        s += f',"args":{{"{k}":{v}}}'
                    else:
                        s += f',"args":{dumps(args)}'
                if extra is not None:
                    if ph == "X":
                        s += f',"dur":{extra}'
                    else:
                        s += f',"s":"{extra}"'
                parts.append(s + "}")
            if parts:
                prefix = "" if self._first else ",\n"
                self._first = False
                self._f.write(prefix + ",\n".join(parts))
                self._f.flush()
            if self._stop and not self._queue:
                return

    def close(self):
        try:
            atexit.unregister(self.close)
        except Exception:  # pragma: no cover - interpreter teardown
            pass
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            # Drain thread stuck in a slow write (NFS, huge backlog):
            # closing underneath it would interleave the footer with its
            # writes and crash it on the closed handle. Leave the file
            # open — a missing ']' is tolerated by trace viewers.
            return
        try:
            self._f.write("\n]\n")
            self._f.close()
        except ValueError:
            pass  # already closed
