"""Eager collective ops — enqueue API, async handles, background cycle.

This is the TPU-native equivalent of the reference's L1 enqueue API and
background-thread runtime (horovod/common/operations.cc):

  - ``EnqueueTensorAllreduce/Allgather/Broadcast`` (operations.cc:2472-2591)
    → :func:`allreduce_async` / :func:`allgather_async` /
    :func:`broadcast_async`, returning integer handles like the torch binding
    (torch/mpi_ops_v2.cc:52-76, torch/handle_manager.cc:21-50).
  - The background thread + cycle (operations.cc:1921-1923, 2030-2380)
    → a dispatcher thread that wakes every ``cycle_time`` ms, drains the
    request queue, asks the native control plane (or the Python fallback)
    for a *fusion plan* — groups of same-op/same-dtype requests whose summed
    bytes fit the fusion threshold, with look-ahead over skipped requests
    (operations.cc:2149-2265) — and executes each group as ONE fused XLA
    program via :mod:`horovod_tpu.executor`.
  - Duplicate in-flight names are rejected with the reference's wording
    (DUPLICATE_NAME_ERROR, operations.cc:270-273).
  - ``poll``/``synchronize`` (torch/mpi_ops_v2.cc:228-234,
    torch/mpi_ops.py:406-438).

Negotiation: the reference's rank-0 coordinator gathers per-rank request
lists and only fuses tensors every rank has submitted (operations.cc:
2088-2134). Under JAX's single-controller model every *process* submits for
all its local virtual ranks at once, so intra-host negotiation is trivially
satisfied; the multi-host control plane (TCP coordinator in the native
runtime) mirrors the gather/bcast protocol across processes.
"""

from __future__ import annotations

import atexit
import contextlib
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from . import wire_format as _wire_flags
from .. import quantization as _quant
from .. import topology as _topo
from ..executor import (ALLGATHER, ALLREDUCE, BROADCAST, CollectiveExecutor,
                        default_executor)
from ..observability import flight_recorder as _flight
from ..observability import registry as _obs
from ..utils import env as _env
from ..utils.logging import get_logger

_log = get_logger("ops")


class _EngineMetrics:
    """Registry handles for the engine's hot paths, resolved ONCE at
    engine construction (docs/metrics.md): the per-op/per-phase child
    lookup must never sit inside the enqueue or dispatch loop. All
    counters are process-global registry state — they deliberately
    survive ``reset_engine()`` (the satellite fix: telemetry must not
    vanish with the instance that recorded it)."""

    _OPS = (ALLREDUCE, ALLGATHER, BROADCAST)

    def __init__(self):
        r = _obs.registry()
        phase = r.histogram(
            "hvdtpu_op_phase_seconds",
            "Per-collective latency by lifecycle phase (negotiate = "
            "enqueue until the group is agreed/delivered; queue = "
            "delivery until XLA dispatch; execute = fused program wall "
            "time)", buckets=_obs.LATENCY_BUCKETS)
        ops = r.counter("hvdtpu_ops_total", "Collective requests enqueued")
        exec_total = r.counter(
            "hvdtpu_op_execute_seconds_total",
            "Cumulative wall seconds executing fused collective groups")
        self.phase = {
            (op, ph): phase.labels(op=_op_name(op), phase=ph)
            for op in self._OPS
            for ph in ("negotiate", "queue", "execute")}
        self.ops = {op: ops.labels(op=_op_name(op)) for op in self._OPS}
        self.exec_total = {op: exec_total.labels(op=_op_name(op))
                           for op in self._OPS}
        self.group_size = r.histogram(
            "hvdtpu_fused_group_size",
            "Tensors per executed fusion group",
            buckets=_obs.SIZE_BUCKETS).labels()
        self.group_bytes = r.histogram(
            "hvdtpu_fused_group_bytes",
            "Wire bytes per executed fusion group",
            buckets=_obs.BYTE_BUCKETS).labels()
        self._wire = r.counter(
            "hvdtpu_wire_bytes_enqueued_total",
            "Bytes-on-wire enqueued, by compression wire spec ('raw' = "
            "the tensor's own dtype); matches _Request accounting")
        self._wire_children = {None: self._wire.labels(spec="raw")}
        self.cycles = r.counter(
            "hvdtpu_cycles_total",
            "Background dispatcher cycles (Python fallback loop)").labels()
        self.cycle_busy = r.counter(
            "hvdtpu_cycle_busy_seconds_total",
            "Dispatcher seconds spent draining/planning/executing").labels()
        self.cycle_idle = r.counter(
            "hvdtpu_cycle_idle_seconds_total",
            "Dispatcher seconds spent waiting for work").labels()
        self.stalled_count = r.gauge(
            "hvdtpu_engine_stalled_tensors",
            "In-flight collectives currently past the stall warning "
            "window (engine view)").labels()
        self.stalled_info = r.gauge(
            "hvdtpu_engine_stalled_tensor_seconds",
            "Seconds each stalled tensor has waited, labeled with the "
            "coordinator's missing-ranks report when available")
        self._adapted = r.counter(
            "hvdtpu_adaptation_applied_groups_total",
            "Fused allreduce groups executed under a policy wire "
            "override, by spec (docs/adaptation.md)")
        self._adapted_children: Dict[str, object] = {}

    def adapted_group(self, spec: str) -> None:
        child = self._adapted_children.get(spec)
        if child is None:
            child = self._adapted.labels(spec=spec)
            self._adapted_children[spec] = child
        child.inc()

    def wire_bytes(self, spec, nbytes: int) -> None:
        child = self._wire_children.get(spec)
        if child is None:
            child = self._wire.labels(spec=spec)
            self._wire_children[spec] = child
        child.inc(nbytes)

    def group_delivered(self, op: int, reqs, t_deliver: float) -> None:
        """Close the negotiate phase for every request in a delivered
        group and record the group's shape."""
        ph = self.phase.get((op, "negotiate"))
        if ph is None:
            return
        for r in reqs:
            ph.observe(t_deliver - r.enqueued_at)
        self.group_size.observe(len(reqs))
        self.group_bytes.observe(sum(r.nbytes for r in reqs))

    def group_executed(self, op: int, n: int, t_deliver: float,
                       t_start: float, t_end: float) -> None:
        key = (op, "queue")
        if key not in self.phase:
            return
        self.phase[key].observe(t_start - t_deliver)
        self.phase[(op, "execute")].observe(t_end - t_start)
        self.exec_total[op].inc(t_end - t_start)

    def set_stalls(self, entries) -> None:
        """Replace the stalled-tensor gauges with the current episode:
        ``entries`` is [(tensor, age_s, missing_ranks_str)]. Clearing
        first keeps resolved stalls from lingering in the export."""
        self.stalled_info.clear()
        self.stalled_count.set(len(entries))
        for tensor, age, missing in entries:
            self.stalled_info.labels(
                tensor=tensor, missing_ranks=missing).set(age)

DUPLICATE_NAME_ERROR = (
    "Requested to {op} a tensor with the same name as another tensor that is "
    "currently being processed. If you want to request another tensor, use a "
    "different tensor name.")

SHUT_DOWN_ERROR = (
    "Horovod has been shut down. This was caused by an exception on one of "
    "the ranks or an attempt to {op} a tensor after one of the ranks "
    "finished execution.")

# Enqueue-burst debounce for the fallback dispatcher (mirrors core.cc
# kDrainDebounceNs/kDrainMaxDeferNs): defer draining while a burst is
# still arriving so one step's requests always fuse into the same groups
# — stable compositions are what make the fused-program jit cache hit.
_DRAIN_DEBOUNCE_S = 0.002
_DRAIN_MAX_DEFER_S = 0.020
# Explicit burst scopes (engine.burst()) get a much larger valve: the
# scope's exit IS the drain boundary, and a 50-leaf enqueue loop alone
# can exceed 20 ms of wall time on an oversubscribed host. The valve only
# guards against a submitter hanging inside an open scope (mirrors
# core.cc kBurstMaxDeferNs).
_BURST_MAX_DEFER_S = 1.0


class HorovodInternalError(RuntimeError):
    pass


# Process-global launch lock for HOROVOD_TPU_ORDERED_LAUNCH=1: the engine
# takes it around each fused-collective enqueue, and producer streams take
# it via launch_lock() around their mesh-wide jit calls, making the host's
# launch order total WITHOUT waiting for producer completion (the fence's
# cost). Measured caveat (experiments/ordered_launch_ab.py): on the CPU
# backend PJRT fans executions out to per-device queues AFTER the Python
# call returns, so this ordering does NOT close the rendezvous-inversion
# window there — the completion fence stays the default.
_LAUNCH_LOCK = threading.RLock()


@contextlib.contextmanager
def launch_lock():
    """Order a producer launch against the engine's collective launches
    (ordered-launch mode). Wrap mesh-wide jit calls whose outputs feed
    eager collectives:

        with hvd.ops.launch_lock():
            grads = train_grads(params, batch)   # mesh-wide jit
        handles = [hvd.allreduce_async(g) for g in grads]

    A no-op contract note: taking the lock is only required when
    HOROVOD_TPU_ORDERED_LAUNCH=1; under the default fence policy it is
    harmless but unnecessary."""
    with _LAUNCH_LOCK:
        yield


class Handle:
    """Async operation handle (torch/handle_manager.{h,cc} equivalent)."""

    __slots__ = ("_event", "_result", "_error", "id", "name")

    def __init__(self, hid: int, name: str):
        self.id = hid
        self.name = name
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    def _fulfill(self, result=None, error: Optional[BaseException] = None):
        self._result = result
        self._error = error
        self._event.set()

    def poll(self) -> bool:
        """Non-blocking completion check (mpi_ops_v2.cc ``PollHandle``)."""
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None):
        """Block until done; raise the op's error if any
        (``WaitAndClear`` semantics, torch/mpi_ops_v2.cc:228-234).

        About to block == the submitter's burst is fully enqueued (an
        async caller waits only after enqueueing everything), so hint the
        engine to drain immediately instead of waiting out the burst
        debounce."""
        if not self._event.is_set():
            _flush_hint()
            if not self._event.wait(timeout):
                raise TimeoutError(
                    f"collective '{self.name}' did not complete "
                    f"within {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result


def _plan_dtype(dtype) -> np.dtype:
    """Size-equivalent numpy dtype for fusion planning (bfloat16 and fp8
    have no stable numpy identity across paths; only itemsize and
    same-key grouping matter here — execution dispatches on the real jax
    dtype)."""
    s = str(dtype)
    if s == "bfloat16":
        return np.dtype(np.float16)
    if s.startswith("float8"):
        return np.dtype(np.uint8)
    return np.dtype(dtype)


def _semantics_fingerprint(req) -> int:
    """Execution-semantic fingerprint carried in the wire's ``device``
    field (the reference records per-rank devices in each request and the
    coordinator rejects inconsistent groups, operations.cc:480-497; on
    the TPU path there is no per-op GPU id, so the slot carries the
    attributes that DO affect the execution program here). Processes
    passing different (average, prescale, postscale, sharded) for one
    tensor would silently compute different programs; fingerprinting
    them into the validated device slot turns that into the
    coordinator's Mismatched error instead (VERDICT r2 #5). Also keys
    coordinator-side fusion: tensors with different semantics land in
    different groups on every process identically."""
    import zlib
    key = (f"{int(req.average)}|{req.prescale!r}|{req.postscale!r}|"
           f"{int(req.sharded)}|{int(req.per_rank is None)}|"
           f"{req.wire or ''}")
    return zlib.crc32(key.encode()) & 0x7FFFFFFF


class _Request:
    __slots__ = ("name", "op", "tensor", "per_rank", "root_rank", "average",
                 "prescale", "postscale", "handle", "nbytes", "dtype",
                 "enqueued_at", "sharded", "wire")

    def __init__(self, name, op, tensor, handle, *, per_rank=None,
                 root_rank=0, average=False, prescale=1.0, postscale=1.0,
                 sharded=False, wire=None):
        self.name = name
        self.op = op
        self.tensor = tensor
        self.per_rank = per_rank
        self.root_rank = root_rank
        self.average = average
        self.prescale = prescale
        self.postscale = postscale
        self.handle = handle
        self.sharded = sharded
        # Wire-format spec ("int8x256" / "fp8x256") for block-scaled
        # quantized allreduce; None = the tensor's own dtype is the wire.
        self.wire = wire
        if tensor is not None:
            self.dtype = _plan_dtype(tensor.dtype)
            n_elements = int(np.prod(tensor.shape))
            if wire is not None:
                # What fusion planning (and the engine's wire-byte
                # accounting) must count is bytes ON THE WIRE: quantized
                # payload + per-block scales, not the logical fp32 bytes.
                self.nbytes = _quant.wire_nbytes(wire, n_elements)
            else:
                self.nbytes = n_elements * self.dtype.itemsize
        else:
            self.dtype = _plan_dtype(per_rank[0].dtype)
            self.nbytes = sum(int(np.prod(t.shape)) for t in per_rank) * \
                self.dtype.itemsize
        self.enqueued_at = time.monotonic()


class CollectiveEngine:
    """Background dispatcher: queue → fusion plan → fused XLA programs.

    One instance per process, lazily started on first enqueue — mirroring
    ``InitializeHorovodOnce`` spawning the background thread
    (operations.cc:2384-2402).
    """

    def __init__(self, executor: Optional[CollectiveExecutor] = None):
        self._executor = executor
        self._lock = threading.Lock()
        self._queue: List[_Request] = []
        self._in_flight: Dict[str, _Request] = {}
        self._handle_counter = 0
        self._name_counter = 0
        self._thread: Optional[threading.Thread] = None
        self._shutdown = False
        self._wake = threading.Event()
        self._last_enqueue_t = 0.0
        self._oldest_enqueue_t = 0.0
        self._last_seen_qlen = 0
        # Flush hint (see flush_hint): a submitter about to block on a
        # handle declared the burst fully enqueued — drain NOW.
        self._flush = False
        # Explicit burst scope depth (see burst()): while > 0 the drain
        # defers regardless of queue growth. Owner threads (ident ->
        # open-scope count) are tracked so a FOREIGN waiter's flush hint
        # (a thread with no open scope blocking on a handle) cuts the
        # scope instead of being consumed by it — otherwise that wait
        # stalls until the 1 s burst valve fires.
        self._burst_depth = 0
        self._burst_owners: Dict[int, int] = {}
        self._foreign_flush = False
        # Producer-fence decision cache (see _fence_producers): resolved
        # once on first use — read-once env-knob semantics like every
        # other engine knob, and no environ/device lookups on the
        # per-group launch hot path.
        self._fence_decision: Optional[bool] = None
        self._ordered_decision: Optional[bool] = None
        self.mp_params: Dict = {}
        # name -> (latest coordinator missing-ranks stall line, wall time)
        # in MP mode; entries expire after 2x the warning window.
        self._coord_stall_lines: Dict[str, tuple] = {}
        # Knobs — reference defaults: 64 MiB fusion, 5 ms cycle
        # (operations.cc:1838,1846). We default the cycle to 1 ms: there is
        # no MPI round-trip to amortize on the single-controller path.
        self.fusion_threshold = _env.fusion_threshold_bytes()
        self.cycle_time_s = _env.cycle_time_ms() / 1000.0
        # Cumulative bytes-on-wire of every enqueued request (wire bytes,
        # i.e. quantized payload + scales for blockwise formats) — the
        # accounting the compression bench and acceptance tests read.
        # DEPRECATION ALIAS: the canonical series is the registry's
        # hvdtpu_wire_bytes_enqueued_total (labeled by wire spec, and it
        # survives reset_engine()); this attribute stays for existing
        # delta-based callers.
        self.wire_bytes_enqueued = 0
        # Registry handles (docs/metrics.md), resolved once — the
        # registry itself is process-global, so totals accumulate across
        # engine instances.
        self._metrics = _EngineMetrics()
        self.timeline = None          # Python-mode timeline (fallback path)
        self._timeline_tried = False  # decide once, off the hot path
        self._mark_cycles = _env.timeline_mark_cycles()
        # Cross-rank trace clock state (docs/tracing.md): the resolved
        # per-rank trace path, its monotonic epoch, and whether the
        # clock-alignment handshake still has to run (nonzero MP ranks
        # sync on their first control-plane cycle).
        self._trace_path: Optional[str] = None
        self._trace_start_mono_us = 0
        self._trace_clock_pending = False
        # Local fused-group counter for the single-process dispatch path
        # (MP groups carry the coordinator's seq instead) — keys the
        # merge tool's per-group attribution.
        self._local_group_seq = 0
        self.stall_warning_s = _env.stall_warning_secs()
        self._last_stall_check = time.monotonic()
        # Failure escalation window (elastic recovery): > 0 turns stalls
        # past the window — and coordinator-reported failure events —
        # into a typed WorkerFailure on the pending handles instead of
        # the warn-then-hang path. 0 (default) = seed behavior.
        self.failure_timeout_s = _env.failure_timeout_secs()
        # Env-forced hierarchical modes; the SP tuner's flags OR on top
        # (_on_native_execute).
        self._env_hier_allreduce = _env.hierarchical_allreduce()
        self._env_hier_allgather = _env.hierarchical_allgather()
        # Native control plane (C++ core, runtime/src/core.cc). When it
        # loads, the background cycle / tensor table / fusion planning /
        # timeline / stall check / autotune all run natively and this class
        # only executes the planned groups as XLA programs.
        self._native_core = None
        self._native_tried = False
        self._native_pending: Dict[int, _Request] = {}
        # Multi-process control plane (ops/control_plane.py): when more
        # than one host process participates, fusion groups must be agreed
        # across processes (SPMD programs over the global mesh), so the
        # rank-0 TCP coordinator replaces local planning.
        self._mp = None               # tri-state: None=unknown
        self._mp_client = None
        self._mp_service = None
        self._announced: set = set()
        # Fault harness (docs/adaptation.md): resolved once on first
        # enqueue; None (the default, no HOROVOD_TPU_FAULT_SPEC) keeps
        # the hot path at a single attribute check.
        self._faults = None
        self._faults_tried = False
        # Policy wire-override epochs from the coordinator's params
        # side-channel: [(from_seq, spec)] — groups with seq >= from_seq
        # execute with spec ('' = raw). Seq-keyed so every process flips
        # at the same group boundary (docs/adaptation.md).
        self._wire_epochs: List = []
        # Fusion-threshold epochs from the same side-channel:
        # [(from_seq, threshold_bytes)] stamped by the coordinator's
        # wire-epoch arbiter when the global autotuner re-caps the
        # fusion buffer (docs/autotune.md). The coordinator's planner is
        # the authority on grouping; this mirror exists so every
        # process's flight recorder shows the same seq-stamped move.
        self._fusion_epochs: List = []
        # Delivered-group counter for the native MP path (group
        # callbacks arrive in coordinator-seq order but carry no seq on
        # the wire) — mirrors the fallback path's group['seq'].
        self._mp_group_seq = 0

    # ------------------------------------------------------------- lifecycle

    @property
    def executor(self) -> CollectiveExecutor:
        if self._executor is None:
            self._executor = default_executor()
        return self._executor

    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._shutdown = False
                self._thread = threading.Thread(
                    target=self._loop, name="horovod_tpu_background",
                    daemon=True)
                self._thread.start()

    def _ensure_native(self):
        """Load + initialize the native control plane once (equivalent of
        InitializeHorovodOnce spawning the C++ background thread,
        operations.cc:2384-2402). Falls back to the Python control plane
        when the toolchain is unavailable or it is disabled via
        HOROVOD_TPU_DISABLE_NATIVE=1.

        In multi-process mode the native core IS the control plane too:
        its background cycle serializes this process's request batch
        (message.cc codec), hands it to :meth:`_native_transport` for the
        TCP announce/long-poll-fetch legs, parses the coordinator-agreed
        ResponseList, and delivers each group to :meth:`_on_native_group`
        for XLA execution — the worker half of the reference's
        RunLoopOnce (operations.cc:2323-2377) running in C++."""
        with self._lock:
            if self._native_tried:
                return self._native_core
            # Resolve under the lock: a concurrent first-enqueue must not
            # observe _native_tried=True with the core still loading (it
            # would silently split the control plane between the native and
            # Python paths).
            try:
                if os.environ.get("HOROVOD_TPU_DISABLE_NATIVE") == "1":
                    return None
                from ..runtime import native as _native_mod
                core = _native_mod.load()
                if core is None:
                    return None
                topo = _topo._get()
                # Per-rank trace capture (docs/tracing.md): the native
                # timeline reads HOROVOD_TPU_TIMELINE in C++ at init, so
                # expand the {rank} placeholder here — and drop the env
                # for nonzero ranks when there is NO placeholder, or
                # every process's native writer would open (and
                # truncate) the one shared file.
                tl_raw = _env.timeline_path()
                tl_resolved = (_env.resolved_timeline_path(
                    topo.process_index) if tl_raw else None)
                if tl_raw and tl_resolved is None:
                    os.environ.pop("HOROVOD_TPU_TIMELINE", None)
                    os.environ.pop("HOROVOD_TIMELINE", None)
                elif tl_resolved is not None and tl_resolved != tl_raw:
                    os.environ["HOROVOD_TPU_TIMELINE"] = tl_resolved
                t_before = time.monotonic()
                core.init(topo.process_index, topo.process_count,
                          topo.local_size, topo.size)
                if tl_resolved is not None and core.timeline_enabled():
                    # The native writer's epoch is steady_clock at its
                    # Initialize, somewhere inside core.init — the
                    # bracket midpoint approximates it to well under the
                    # init duration (same CLOCK_MONOTONIC domain).
                    self._trace_path = tl_resolved
                    self._trace_start_mono_us = int(
                        (t_before + time.monotonic()) / 2.0 * 1e6)
                    if topo.process_index == 0 or topo.process_count == 1:
                        self._write_clock_meta(0.0, 0.0, synced=True)
                    else:
                        self._trace_clock_pending = True
                else:
                    self._arm_blackbox_clock()
                core.set_execute_callback(self._on_native_execute)
                if topo.process_count > 1:
                    core.set_group_callback(self._on_native_group)
                    core.set_transport_callback(self._native_transport)
                self._native_core = core
            except Exception as e:  # pragma: no cover - degraded path
                _log.warning("native control plane init failed: %s", e)
                self._native_core = None
            finally:
                self._native_tried = True
        return self._native_core

    def _ensure_timeline(self):
        """Create the Python timeline writer for paths the native core
        does not cover (Python fallback, multi-process). Without a
        ``{rank}`` placeholder in the path, rank 0 writes like the
        reference (operations.cc:1824-1829) and an undeterminable rank
        does NOT write (a second writer would truncate rank 0's file);
        WITH the placeholder every rank writes its own file — the
        cross-rank capture mode (docs/tracing.md). Decision is made
        once; the monotonic flag makes the unlocked fast-path read
        safe."""
        if self._timeline_tried:
            return self.timeline
        with self._lock:
            if self._timeline_tried:
                return self.timeline
            self._timeline_tried = True
            if not _env.timeline_path() or self._shutdown:
                self._arm_blackbox_clock()
                return None
            try:
                topo = _topo._get()
                rank, world = topo.process_index, topo.process_count
            except Exception:
                return None
            path = _env.resolved_timeline_path(rank)
            if not path:
                self._arm_blackbox_clock()
                return None
            try:
                from .timeline_py import PyTimeline
                self.timeline = PyTimeline(path, rank=rank, world=world)
            except OSError as e:
                # Unwritable path disables the timeline, as the native
                # writer does (runtime/src/timeline.cc) — never fail the
                # user's collective over tracing.
                _log.warning("timeline disabled: cannot open %s: %s",
                             path, e)
                return None
            self._trace_path = path
            self._trace_start_mono_us = self.timeline.start_monotonic_us
            # Rank 0 (and single-process jobs) ARE the reference clock:
            # offset 0 by definition, sidecar written now. Other ranks
            # sync against the coordinator on their first MP cycle
            # (_maybe_sync_trace_clock).
            if rank == 0 or world == 1:
                self._write_clock_meta(0.0, 0.0, synced=True)
            else:
                self._trace_clock_pending = True
            return self.timeline

    def _arm_blackbox_clock(self) -> None:
        """With a blackbox dir configured but NO per-rank trace, the
        clock handshake must still run once so postmortem dumps align
        onto rank 0's clock: nonzero MP ranks mark the sync pending
        (the next control-plane cycle runs it); rank 0 and
        single-process jobs ARE the reference clock."""
        if not _env.blackbox_dir():
            return
        try:
            topo = _topo._get()
            rank, world = topo.process_index, topo.process_count
        except Exception:
            return
        if rank == 0 or world == 1:
            _flight.recorder().set_clock_meta(0.0, 0.0, True)
        else:
            self._trace_clock_pending = True

    def _write_clock_meta(self, offset_s: float, rtt_s: float,
                          synced: bool) -> None:
        """Record this rank's trace clock header: in-band metadata when
        the Python writer owns the file, plus the sidecar either way
        (the native writer's file is owned by C++ — the sidecar is the
        only channel there). ``offset_s`` is the estimated rank-0
        monotonic clock minus ours."""
        # The flight recorder's dump header carries the same clock
        # fields, so the postmortem tool aligns per-rank dumps exactly
        # like the trace merger aligns per-rank timelines
        # (docs/postmortem.md).
        _flight.recorder().set_clock_meta(offset_s, rtt_s, synced)
        path = self._trace_path
        if not path:
            return
        try:
            topo = _topo._get()
            rank, world = topo.process_index, topo.process_count
        except Exception:
            rank, world = 0, 1
        if self.timeline is not None and synced:
            self.timeline.set_clock_meta(offset_s, rtt_s)
        from . import timeline_py as _tlpy
        try:
            _tlpy.write_clock_sidecar(path, {
                "rank": rank, "world": world,
                "start_mono_us": self._trace_start_mono_us,
                "offset_to_rank0_us": offset_s * 1e6,
                "rtt_us": rtt_s * 1e6,
                "clock_synced": bool(synced)})
        except OSError as e:
            _log.warning("trace clock sidecar write failed: %s", e)

    def _maybe_sync_trace_clock(self, client) -> None:
        """Run the clock-alignment handshake once (nonzero MP ranks
        only; rank 0 is the reference clock): K NTP-style pings over the
        coordinator channel, min-RTT sample wins
        (CoordinatorClient.clock_sync), result recorded in the trace
        clock header. Runs on the background cycle thread right after
        the control plane comes up — a one-time cost of K tiny RPCs,
        never on the enqueue path."""
        if not self._trace_clock_pending:
            return
        self._trace_clock_pending = False
        probes = _env.trace_clock_probes()
        if probes <= 0:
            self._write_clock_meta(0.0, 0.0, synced=False)
            return
        try:
            res = client.clock_sync(probes=probes)
        except Exception as e:
            _log.warning("trace clock sync failed; offset recorded as "
                         "unsynced: %s", e)
            self._write_clock_meta(0.0, 0.0, synced=False)
            return
        self._write_clock_meta(res["offset_s"], res["rtt_s"], synced=True)

    def _is_multiprocess(self) -> bool:
        if self._mp is None:
            try:
                self._mp = _topo._get().process_count > 1
            except Exception:
                return False
        return self._mp

    def _ensure_mp(self):
        """Bring up the cross-process control plane once: process 0 hosts
        the coordinator (the rank-0 role, operations.cc:2061-2067), every
        process connects a client."""
        from . import control_plane as _cp
        with self._lock:
            if self._mp_client is not None:
                return self._mp_client
            topo = _topo._get()
            if topo.process_index == 0:
                self._mp_service = _cp.start_coordinator(
                    topo.process_count, self.fusion_threshold,
                    virtual_size=topo.size)
                self._mp_client = _cp.CoordinatorClient(
                    [("127.0.0.1", self._mp_service.port)],
                    self._mp_service.key, topo.process_index)
                return self._mp_client
            else:
                ep = _cp.control_endpoint()
                if ep is None:
                    raise HorovodInternalError(
                        "Multi-process eager collectives need the "
                        "coordinator address in HOROVOD_TPU_CONTROL "
                        "(exported by the horovod_tpu runner); launch "
                        "workers with `python -m horovod_tpu.runner` or "
                        "export it manually.")
                addr = [ep]
            self._mp_client = _cp.CoordinatorClient(
                addr, _cp.control_key(), topo.process_index)
            return self._mp_client

    def shutdown(self):
        """Drain and stop; outstanding handles get SHUT_DOWN_ERROR
        (operations.cc:1942-1998)."""
        if self._mp_client is not None:
            # Tell the controller first so peers' fetches see the flag;
            # the client reference stays until the native core is down
            # (its background thread may be mid-transport).
            self._mp_client.announce_shutdown()
        core = self._native_core
        if core is not None:
            # Native path: the C++ shutdown drains its queue (the execute
            # callback keeps firing until empty), then joins the background
            # thread and flushes the timeline.
            core.shutdown()
            self._native_core = None  # _native_tried stays True: terminal
            with self._lock:
                native_pending = list(self._native_pending.values())
                self._native_pending.clear()
            for req in native_pending:
                req.handle._fulfill(error=HorovodInternalError(
                    SHUT_DOWN_ERROR.format(op=_op_name(req.op))))
        self._mp_client = None
        with self._lock:
            self._shutdown = True
            pending = list(self._queue) + list(self._in_flight.values())
            self._queue.clear()
            self._in_flight.clear()
        self._wake.set()
        for req in pending:
            req.handle._fulfill(error=HorovodInternalError(
                SHUT_DOWN_ERROR.format(op=_op_name(req.op))))
        t = self._thread
        if t is not None and t.is_alive() and t is not threading.current_thread():
            t.join(timeout=5.0)
        self._thread = None
        if self._mp_service is not None:
            self._mp_service.shutdown()
            self._mp_service = None
        if self.timeline is not None:
            self.timeline.close()
            self.timeline = None
        from . import shm_transport as _shm
        _shm.reset()  # unmap + unlink this process's data-plane segments

    # --------------------------------------------------------------- enqueue

    def _next_name(self, prefix: str) -> str:
        with self._lock:
            self._name_counter += 1
            return f"{prefix}.noname.{self._name_counter}"

    def enqueue(self, req: _Request) -> Handle:
        if self._shutdown:
            # Terminal for this engine instance (operations.cc:2374-2377);
            # tests use reset_engine() to get a fresh one.
            raise HorovodInternalError(
                SHUT_DOWN_ERROR.format(op=_op_name(req.op)))
        if not self._faults_tried:
            # Fault harness (docs/adaptation.md), resolved once: with no
            # HOROVOD_TPU_FAULT_SPEC the enqueue path keeps exactly one
            # attribute check.
            self._faults_tried = True
            from ..adaptation import faults as _faults_mod
            self._faults = _faults_mod.injector()
        if self._faults is not None:
            poisoned = self._faults.on_enqueue(tensor=req.tensor)
            if poisoned is not None:
                # nan_at clause fired: the engine carries the poisoned
                # payload from here on, exactly as if the producer had
                # computed a NaN — detection happens downstream in the
                # numerics sentinel, not here (docs/numerics.md).
                req.tensor = poisoned
        self.wire_bytes_enqueued += req.nbytes
        self._metrics.wire_bytes(req.wire, req.nbytes)
        self._metrics.ops[req.op].inc()
        core = self._ensure_native()
        if core is not None:
            return self._enqueue_native(core, req)
        self._ensure_timeline()
        with self._lock:
            if self._shutdown:
                raise HorovodInternalError(
                    SHUT_DOWN_ERROR.format(op=_op_name(req.op)))
            if req.name in self._in_flight:
                raise ValueError(DUPLICATE_NAME_ERROR.format(
                    op=_op_name(req.op)))
            self._in_flight[req.name] = req
            if not self._queue:
                self._oldest_enqueue_t = time.monotonic()
            self._queue.append(req)
            self._last_enqueue_t = time.monotonic()
            # No timeline event here: the NEGOTIATE span is emitted as
            # one complete "X" event at group delivery, anchored at
            # req.enqueued_at — nothing on the user's enqueue path
            # (PyTimeline.negotiate_span).
        self._ensure_thread()
        self._wake.set()
        return req.handle

    # ----------------------------------------------------- native delegation

    def _enqueue_native(self, core, req: _Request) -> Handle:
        """EnqueueTensor* through the C++ tensor table
        (operations.cc:2472-2591)."""
        t = req.tensor if req.tensor is not None else req.per_rank[0]
        shape = list(t.shape)
        dtype = str(t.dtype)
        # Hold the engine lock across enqueue + registration: the native
        # cycle can fire the execute callback for this id before we return,
        # and the callback takes the same lock to pop the request — so it
        # blocks until registration is visible rather than dropping the op.
        with self._lock:
            native_id = core.enqueue(req.op, req.name, dtype, shape,
                                     root_rank=req.root_rank,
                                     device=_semantics_fingerprint(req),
                                     nbytes=req.nbytes)
            if native_id == -1:
                raise ValueError(DUPLICATE_NAME_ERROR.format(
                    op=_op_name(req.op)))
            if native_id == -2:
                raise HorovodInternalError(
                    SHUT_DOWN_ERROR.format(op=_op_name(req.op)))
            self._native_pending[native_id] = req
        return req.handle

    def _on_native_execute(self, op: int, native_ids: List[int], err: str):
        """Execute callback from the native background thread: the group was
        negotiated + fusion-planned in C++ (the PerformOperation dispatch
        point, operations.cc:768-791); run it as XLA programs."""
        core = self._native_core
        t_deliver = time.monotonic()
        with self._lock:
            pairs = [(i, self._native_pending.pop(i))
                     for i in native_ids if i in self._native_pending]
        if not pairs:
            return
        self._metrics.group_delivered(op, [r for _, r in pairs], t_deliver)
        # Flight-recorder group lifecycle (docs/postmortem.md): the
        # native SP wire carries no seq, so a local counter keys the
        # events (mirrors the timeline's _local_group_seq role).
        seq = self._local_group_seq
        self._local_group_seq += 1
        _flight.recorder().group_deliver(seq, _op_name(op), len(pairs))
        if err:
            _flight.recorder().group_error(seq, _op_name(op), len(pairs),
                                           err)
            core.complete([i for i, _ in pairs], 2, err)
            for i, r in pairs:
                core.release(i)
                r.handle._fulfill(error=HorovodInternalError(err))
            return
        # The native planner fuses on (op, dtype, bytes); execution-semantic
        # knobs the planner doesn't track (sharded-ness, averaging, scaling,
        # ragged gathers) subdivide the group here.
        subgroups: Dict[tuple, List] = {}
        for i, r in pairs:
            k = (r.sharded, r.average, r.prescale, r.postscale,
                 r.per_rank is None, r.root_rank, r.wire)
            subgroups.setdefault(k, []).append((i, r))
        ex = self.executor
        # Apply the SP tuner's execution-mode flags (hvdtpu_current_flags;
        # MP groups get theirs from the plan instead): env knobs force a
        # mode, the tuner explores on top — without this the tuned
        # hierarchical decision would never reach execution.
        flags = core.current_flags()
        ex.hierarchical_allreduce = (self._env_hier_allreduce or bool(
            flags & _wire_flags.FLAG_HIERARCHICAL_ALLREDUCE))
        ex.hierarchical_allgather = (self._env_hier_allgather or bool(
            flags & _wire_flags.FLAG_HIERARCHICAL_ALLGATHER))
        tl = core.timeline_enabled()
        for sub in subgroups.values():
            ids = [i for i, _ in sub]
            reqs = [r for _, r in sub]
            if tl:
                for r in reqs:
                    core.timeline_activity_end(r.name)       # close QUEUE
                    core.timeline_activity_start(r.name, _xla_activity(op))
            t_start = time.monotonic()
            try:
                results = self._execute_group(ex, reqs)
            except BaseException as e:
                msg = str(e)
                _flight.recorder().group_error(seq, _op_name(op),
                                               len(reqs), msg)
                core.complete(ids, 2, msg)
                for (i, r) in sub:
                    core.release(i)
                    r.handle._fulfill(error=_as_error(e))
                continue
            t_end = time.monotonic()
            self._metrics.group_executed(op, len(reqs), t_deliver,
                                         t_start, t_end)
            _flight.recorder().group_done(seq, _op_name(op), len(reqs),
                                          t_deliver, t_start, t_end)
            core.complete(ids, 0, "")
            for (i, r), out in zip(sub, results):
                core.release(i)
                r.handle._fulfill(result=out)

    # ------------------------------------- native multi-process bridge

    def _apply_fetch_side_channel(self, resp) -> None:
        """Coordinator side-channel shared by the native and fallback MP
        paths: log the authoritative missing-ranks stall report, and apply
        tuned SCALAR knobs (SyncParams, parameter_manager.cc:213-246) —
        cycle time paces this engine's announce cadence; program-affecting
        flags arrive per group instead (SPMD lockstep)."""
        for name, line in resp.stall:
            _log.warning("stalled tensor (coordinator report): %s", line)
            # Keep the authoritative missing-ranks line per tensor so the
            # engine's own stall warning can name the missing processes
            # (CheckForStalledTensors, operations.cc:1644-1668). The name
            # arrives as structured data in the (name, line) pair — never
            # parsed out of the display text. Stamped so stale lines
            # (tensor completed, name reused later) are never reported
            # and the cache cannot grow unboundedly.
            if name:
                self._coord_stall_lines[name] = (line, time.monotonic())
        failures = getattr(resp, "failures", None)
        if failures:
            for f in failures:
                _flight.recorder().note("failure", (
                    int(f.get("rank", -1)), str(f.get("kind", "")),
                    str(f.get("detail", ""))[:300]))
            # The coordinator escalated (heartbeat loss / stall past the
            # failure timeout): pending quorums can never complete, so
            # fail every in-flight handle with the TYPED event — the
            # elastic driver (or any caller) dispatches on
            # WorkerFailure.rank/host/kind instead of parsing log text.
            from ..elastic.failure import failure_from_event
            f = dict(failures[0])
            f["detail"] = "; ".join(
                str(x.get("detail", "")) for x in failures)
            # Typed construction: a slow_rank event becomes a
            # SlowRankFailure so the elastic driver can apply the
            # slow-rank blacklist window instead of the crash one.
            err = failure_from_event(f)
            _log.error("coordinator escalated worker failure: %s", err)
            self._fail_native_pending(err)
            self._fail_all(err)
        params = resp.params
        if params:
            we = params.get("wire_epochs")
            if we:
                # Policy wire-override epochs (docs/adaptation.md):
                # replace wholesale — the coordinator ships the full
                # (small) list every fetch, so a late joiner catches up
                # in one response.
                epochs = [(int(s), str(sp)) for s, sp in we]
                if epochs != self._wire_epochs:
                    _flight.recorder().note(
                        "wire_epoch", (";".join(
                            f"{s}:{sp or 'raw'}" for s, sp in epochs),))
                self._wire_epochs = epochs
            cyc = params.get("cycle_time_ms")
            if cyc and abs(cyc - self.cycle_time_s * 1000.0) > 1e-9:
                self.cycle_time_s = cyc / 1000.0
                core = self._native_core
                if core is not None:
                    core.cycle_time_ms = cyc
            fe = params.get("fusion_epochs")
            if fe:
                fepochs = [(int(s), int(t)) for s, t in fe]
                if fepochs != self._fusion_epochs:
                    _flight.recorder().note("autotune", (
                        "fusion_epoch", "fusion_threshold_mb",
                        str(fepochs[-1][1] >> 20), None, None,
                        ";".join(f"{s}:{t >> 20}" for s, t in fepochs)))
                self._fusion_epochs = fepochs
            ft = params.get("fusion_threshold")
            if ft:
                self.fusion_threshold = int(ft)
            # Last coordinator-served params (autotune_active/done etc.)
            # for tests and observability.
            self.mp_params = dict(params)

    def _fail_native_pending(self, err: BaseException) -> None:
        """Fail every native-tracked in-flight request loudly — the MP
        engine's _fail_all: clears the C++ tensor table (so names can be
        reused after the error) and fulfills the Python handles."""
        core = self._native_core
        with self._lock:
            pending = list(self._native_pending.items())
            self._native_pending.clear()
        for i, r in pending:
            if core is not None:
                core.complete([i], 2, str(err))
                core.release(i)
            r.handle._fulfill(error=_as_error(err))

    def _native_transport(self, req_bytes: bytes, nreq: int, complete: int,
                          pending: int) -> bytes:
        """The announce/fetch legs of the MP cycle, called from the native
        background thread (core.cc TransportCallback): ship this process's
        serialized RequestList to the rank-0 controller and long-poll the
        agreed ResponseList in ONE combined RPC, returning its bytes for
        the C++ parser. ``nreq == 0`` with a non-empty batch means
        retry-after-overflow (native.py caches the payload), so only
        announce fresh batches. ``complete`` marks the batch a complete
        enqueue burst — the coordinator plans eagerly on the last rank's
        complete announce, so long-poll for the imminent group; an
        INCOMPLETE (max-defer) announce short-polls to get back to
        announcing the burst remainder quickly.

        A transport failure (coordinator unreachable past the client's
        retries) is FATAL for the in-flight ops: the batch was already
        drained from the native queue and will never be re-announced, so
        peers would wait on quorum forever — fail the handles loudly
        instead of hanging the fleet."""
        try:
            client = self._ensure_mp()
            self._maybe_sync_trace_clock(client)
            if pending <= 0 and nreq <= 0:
                return b""
            wait = (self.cycle_time_s if (nreq > 0 and not complete)
                    else max(self.cycle_time_s, 0.05))
            if pending <= 0:
                wait = 0.0
            if nreq > 0:
                resp = client.announce_fetch(payload=req_bytes,
                                             complete=bool(complete),
                                             wait_s=wait)
            else:
                resp = client.fetch(wait_s=wait)
        except BaseException as e:
            _log.error("multi-process control plane failed: %s", e)
            self._fail_native_pending(_as_error(e))
            return b""
        self._apply_fetch_side_channel(resp)
        return resp.payload or b""

    def _on_native_group(self, op: int, native_ids: List[int], nnames: int,
                         sizes: List[int], flags: int, err: str):
        """Execute one coordinator-agreed group (core.cc GroupCallback) —
        the MP analogue of :meth:`_on_native_execute`, with group metadata
        (ragged allgather sizes, hierarchical flags) from the wire."""
        core = self._native_core
        if core is None:
            return
        t_deliver = time.monotonic()
        # Coordinator seq of this group: callbacks fire in seq order and
        # exactly once per group, so a local counter mirrors it (the
        # native wire carries no seq field) — keys the policy's
        # wire-override epochs identically to the fallback path.
        group_seq = self._mp_group_seq
        self._mp_group_seq += 1
        with self._lock:
            pairs = [(i, self._native_pending.pop(i))
                     for i in native_ids if i in self._native_pending]
        if len(native_ids) != nnames or len(pairs) != nnames:
            # Local/coordinator desync: peers will enter this group's SPMD
            # program; skipping it here would deadlock them. Fail loudly
            # (ADVICE r1) — every local in-flight op dies with a
            # diagnostic instead of the job hanging.
            desync = HorovodInternalError(
                f"coordinator/local state desync: group of {nnames} "
                f"tensors matched {len(pairs)} local handles; failing the "
                "engine rather than skipping a collective the other ranks "
                "will enter")
            _log.error("%s", desync)
            with self._lock:
                extra = list(self._native_pending.items())
                self._native_pending.clear()
            for i, r in pairs + extra:
                core.complete([i], 2, str(desync))
                core.release(i)
                r.handle._fulfill(error=desync)
            return
        self._metrics.group_delivered(op, [r for _, r in pairs], t_deliver)
        _flight.recorder().group_deliver(group_seq, _op_name(op),
                                         len(pairs))
        if err:
            _flight.recorder().group_error(group_seq, _op_name(op),
                                           len(pairs), err)
            ids = [i for i, _ in pairs]
            core.complete(ids, 2, err)
            for i, r in pairs:
                core.release(i)
                r.handle._fulfill(error=HorovodInternalError(err))
            return
        topo = _topo._get()
        nproc = topo.process_count
        # Per-process first dims in tensor_names (== handles) order.
        sizes_of = {}
        if op == ALLGATHER and len(sizes) == nnames * nproc:
            for j, (_, r) in enumerate(pairs):
                sizes_of[r.name] = sizes[j * nproc:(j + 1) * nproc]
        meta = {"sizes": sizes_of, "seq": group_seq}
        ex = self.executor
        # Plan-time flags rule execution for THIS group on every process —
        # the engine thread is the only executor user, so the flip is safe.
        ex.hierarchical_allreduce = bool(
            flags & _wire_flags.FLAG_HIERARCHICAL_ALLREDUCE)
        ex.hierarchical_allgather = bool(
            flags & _wire_flags.FLAG_HIERARCHICAL_ALLGATHER)
        subgroups: Dict[tuple, List] = {}
        for i, r in pairs:
            k = (r.sharded, r.average, r.prescale, r.postscale,
                 r.root_rank, r.wire)
            subgroups.setdefault(k, []).append((i, r))
        tl = core.timeline_enabled()
        for sub in subgroups.values():
            ids = [i for i, _ in sub]
            reqs = [r for _, r in sub]
            if tl:
                for r in reqs:
                    core.timeline_activity_end(r.name)       # close QUEUE
                    core.timeline_activity_start(r.name, _xla_activity(op))
            t_start = time.monotonic()
            try:
                results = self._execute_group_mp(ex, reqs, meta, topo, op)
            except BaseException as e:
                msg = str(e)
                _flight.recorder().group_error(group_seq, _op_name(op),
                                               len(reqs), msg)
                core.complete(ids, 2, msg)
                for (i, r) in sub:
                    core.release(i)
                    r.handle._fulfill(error=_as_error(e))
                continue
            t_end = time.monotonic()
            self._metrics.group_executed(op, len(reqs), t_deliver,
                                         t_start, t_end)
            _flight.recorder().group_done(group_seq, _op_name(op),
                                          len(reqs), t_deliver, t_start,
                                          t_end)
            core.complete(ids, 0, "")
            for (i, r), out in zip(sub, results):
                core.release(i)
                r.handle._fulfill(result=out)

    def make_handle(self, name: str) -> Handle:
        with self._lock:
            self._handle_counter += 1
            return Handle(self._handle_counter, name)

    def flush_hint(self) -> None:
        """Submitter hint that the current enqueue burst is complete (a
        handle is about to block): drain + announce NOW instead of
        waiting out the drain debounce — in tight synchronous training
        loops this collapses 1-3 ms of per-step control latency (the
        debounce window plus up to one cycle of pacing sleep)."""
        core = self._native_core
        if core is not None:
            core.flush()
        with self._lock:
            if threading.get_ident() not in self._burst_owners:
                # Foreign waiter: must not be consumed by an open burst
                # scope (see _loop's burst branch). Marked regardless of
                # CURRENT depth — a hint landing just before another
                # thread's burst() would otherwise be consumed by that
                # scope (the loop may not run in between); a stale mark
                # with no scope open is cleared by the loop. Scope exits
                # flush via _flush_now, never through here.
                self._foreign_flush = True
            self._flush = True
        self._wake.set()

    def _flush_now(self) -> None:
        """Scope-exit flush for the Python fallback dispatcher: drain
        immediately without the foreign-waiter marking (the exit IS the
        burst boundary, not a cut of it). The native path never comes
        here — hvdtpu_burst_end sets its flush hint in C++."""
        with self._lock:
            self._flush = True
        self._wake.set()

    @contextlib.contextmanager
    def burst(self):
        """Explicit burst scope for a multi-tensor submission: the cycle
        will not drain until the scope closes (bounded by the max-defer
        valve), so the whole group always lands as ONE fusion burst.
        Without it the drain debounce infers burst boundaries from queue
        growth, which misfires when the enqueueing thread is descheduled
        mid-burst on a busy host — a partial drain is a NEW fusion
        composition, and every distinct composition is a distinct
        compiled XLA program (measured: an unstable 53-leaf ResNet burst
        recompiled ~1 s/step on the CPU mesh; stable compositions hit
        the jit cache). Exiting the outermost scope flushes.

        Scope-owner threads are tracked: a blocking ``Handle.wait`` from
        a thread with NO open scope (a foreign waiter) cuts the scope
        and drains immediately instead of stalling until the 1 s
        max-defer valve — only the owner's own flush hints are
        superseded by the scope."""
        core = self._ensure_native()
        tid = threading.get_ident()
        if core is not None:
            core.burst_begin()
        else:
            with self._lock:
                self._burst_depth += 1
                self._burst_owners[tid] = self._burst_owners.get(tid, 0) + 1
        try:
            yield
        finally:
            if core is not None:
                core.burst_end()
            else:
                with self._lock:
                    self._burst_depth -= 1
                    outermost = self._burst_depth == 0
                    if self._burst_owners.get(tid, 0) <= 1:
                        self._burst_owners.pop(tid, None)
                    else:
                        self._burst_owners[tid] -= 1
                if outermost:
                    self._flush_now()

    # ------------------------------------------------------------ background

    def _loop(self):
        """``RunLoopOnce`` (operations.cc:2030-2380): sleep to cycle time,
        drain queue, plan fusion, execute. In multi-process mode the plan
        comes from the rank-0 coordinator instead of local fusion."""
        mp = self._is_multiprocess()
        m = self._metrics
        prev_cycle_end = time.monotonic()
        while not self._shutdown:
            self._wake.wait(timeout=self.cycle_time_s)
            self._wake.clear()
            if self._shutdown:
                return
            # Cycle utilization (docs/metrics.md): busy = this
            # iteration's drain/plan/execute work, idle = the wait
            # above. utilization = busy / (busy + idle).
            t_wake = time.monotonic()
            m.cycles.inc()
            m.cycle_idle.inc(t_wake - prev_cycle_end)
            if self._mark_cycles and self.timeline is not None:
                self.timeline.mark_cycle()  # HOROVOD_TIMELINE_MARK_CYCLES
            with self._lock:
                # Burst debounce (mirrors core.cc DrainShouldDefer):
                # draining mid-burst cuts timing-dependent fusion groups,
                # and every distinct composition is a distinct compiled
                # program. Bounded so a continuous stream cannot starve
                # dispatch, and overridden by a flush hint (a submitter
                # about to block declared the burst fully enqueued).
                now = time.monotonic()
                qlen = len(self._queue)
                grew = qlen > self._last_seen_qlen
                self._last_seen_qlen = qlen
                complete = True
                if qlen > 0 and self._burst_depth > 0:
                    # Explicit burst scope open: defer regardless of
                    # growth (the growth heuristic misfires when the
                    # enqueuer is descheduled on a busy host), bounded
                    # by the burst valve. The scope OWNER's flush hint
                    # is consumed — the scope supersedes it (its own
                    # exit will flush). A FOREIGN waiter's hint cuts
                    # the scope: stalling that wait until the 1 s valve
                    # is worse than one timing-dependent composition.
                    # Mirrors DrainShouldDefer.
                    self._flush = False
                    if self._foreign_flush:
                        self._foreign_flush = False
                        defer = False
                        complete = False  # mid-scope cut
                    elif (now - self._oldest_enqueue_t
                            >= _BURST_MAX_DEFER_S):
                        defer = False
                        complete = False  # valve cut a mid-scope burst
                    else:
                        defer = True
                else:
                    self._foreign_flush = False
                    flush = self._flush
                    # Defer only while the burst is still GROWING — a
                    # lone blocking caller's single request must not pay
                    # the debounce (its submitter is stuck on the
                    # handle).
                    defer = (qlen > 0 and grew and not flush
                             and now - self._last_enqueue_t
                             < _DRAIN_DEBOUNCE_S
                             and now - self._oldest_enqueue_t
                             < _DRAIN_MAX_DEFER_S)
                    if not defer:
                        # Complete unless the max-defer valve cut a
                        # still-growing burst.
                        complete = flush or not (
                            grew
                            and now - self._oldest_enqueue_t
                            >= _DRAIN_MAX_DEFER_S)
                if defer:
                    batch = []
                else:
                    batch = self._queue
                    self._queue = []
                    self._last_seen_qlen = 0
                    self._flush = False
            if defer:
                # Also skip the MP fetch: a long-poll here would hold the
                # rest of the burst back past the coordinator's quiet
                # window.
                prev_cycle_end = time.monotonic()
                m.cycle_busy.inc(prev_cycle_end - t_wake)
                continue
            if mp:
                try:
                    self._mp_cycle(batch, complete)
                except BaseException as e:   # pragma: no cover - safety net
                    _log.error("multi-process cycle failed: %s", e)
                    self._fail_all(_as_error(e))
            elif batch:
                try:
                    self._dispatch(batch)
                except BaseException as e:   # pragma: no cover - safety net
                    _log.error("background dispatch failed: %s", e)
            self._maybe_check_stalls()
            prev_cycle_end = time.monotonic()
            m.cycle_busy.inc(prev_cycle_end - t_wake)

    def _fail_all(self, err: BaseException):
        with self._lock:
            pending = list(self._in_flight.values())
            self._in_flight.clear()
        for r in pending:
            r.handle._fulfill(error=err)

    # ------------------------------------------- multi-process cycle

    def _mp_cycle(self, batch: List[_Request], complete: bool = True):
        """The worker half of RunLoopOnce (operations.cc:2323-2377):
        announce newly-ready requests (the Gatherv) and fetch the agreed
        ordered group list (the Bcast) in ONE combined RPC, then execute
        each group. A complete-burst announce long-polls (the coordinator
        plans eagerly on the last rank's complete announce); an
        incomplete one short-polls to announce the remainder quickly."""
        client = self._ensure_mp()
        self._maybe_sync_trace_clock(client)
        requests = [{
            "name": r.name, "op": r.op,
            "dtype": str((r.tensor if r.tensor is not None
                          else r.per_rank[0]).dtype),
            "shape": tuple((r.tensor if r.tensor is not None
                            else r.per_rank[0]).shape),
            "root_rank": r.root_rank, "nbytes": r.nbytes,
            "device": _semantics_fingerprint(r),
        } for r in batch]
        with self._lock:
            waiting = bool(self._in_flight)
        if not waiting and not requests:
            return
        wait = (self.cycle_time_s if (batch and not complete)
                else max(self.cycle_time_s, 0.05))
        if not waiting:
            wait = 0.0
        resp = client.announce_fetch(requests=requests or None,
                                     complete=complete, wait_s=wait)
        self._apply_fetch_side_channel(resp)
        if resp.shutdown:
            # A peer announced shutdown — possibly from its teardown path,
            # in which case it will never enter the still-pending SPMD
            # programs; executing them would hang the surviving ranks in
            # XLA collectives. Fail everything with SHUT_DOWN_ERROR
            # instead, matching the reference's drain of queued tensors on
            # shutdown (operations.cc:1942-1998).
            self._fail_all(HorovodInternalError(
                SHUT_DOWN_ERROR.format(op="run")))
            return
        for group in resp.groups:
            self._execute_mp_group(group)

    def _execute_mp_group(self, group: dict):
        """Execute one coordinator-agreed group. All names were announced
        by this process (a group forms only when every process announced),
        so the requests MUST be in our in-flight table — a missing name
        means local/coordinator desync (e.g. _fail_all cleared in-flight
        after a cycle exception while announcements remained registered).
        Skipping the collective while peers execute it would deadlock the
        SPMD program, so desync is fatal for the engine instead."""
        t_deliver = time.monotonic()
        with self._lock:
            reqs = [self._in_flight.pop(n) for n in group["names"]
                    if n in self._in_flight]
        if len(reqs) != len(group["names"]):
            have = {r.name for r in reqs}
            missing = [n for n in group["names"] if n not in have]
            err = HorovodInternalError(
                "coordinator/local state desync: coordinator group "
                f"{group['names']} includes tensors this process no longer "
                f"has in flight ({missing}); failing the engine rather than "
                "skipping a collective the other ranks will enter")
            _log.error("%s", err)
            for r in reqs:
                r.handle._fulfill(error=err)
            # Propagate: _loop's guard fails every remaining in-flight
            # request, so the job dies with a diagnostic instead of
            # hanging all ranks.
            raise err
        if reqs:
            self._metrics.group_delivered(reqs[0].op, reqs, t_deliver)
            _flight.recorder().group_deliver(
                group.get("seq"), _op_name(reqs[0].op), len(reqs))
        tl = self.timeline
        if tl is not None:
            for r in reqs:
                # One complete NEGOTIATE span per tensor, anchored at
                # its true enqueue tick, carrying the coordinator seq —
                # identical on every rank for this group, the merge
                # tool's cross-rank group key (docs/tracing.md).
                tl.negotiate_span(r.name, _op_name(r.op), r.enqueued_at,
                                  t_deliver, group=group.get("seq"))
        if group["error"]:
            if reqs:
                _flight.recorder().group_error(
                    group.get("seq"), _op_name(reqs[0].op), len(reqs),
                    group["error"])
            for r in reqs:
                r.handle._fulfill(error=HorovodInternalError(group["error"]))
            return
        ex = self.executor
        # Plan-time flags rule execution for this group on every process
        # (SPMD lockstep; the engine thread is the executor's only user).
        flags = int(group.get("flags", 0))
        ex.hierarchical_allreduce = bool(
            flags & _wire_flags.FLAG_HIERARCHICAL_ALLREDUCE)
        ex.hierarchical_allgather = bool(
            flags & _wire_flags.FLAG_HIERARCHICAL_ALLGATHER)
        # Execution-semantic attributes the coordinator doesn't track
        # subdivide the group — deterministically, since SPMD call sites
        # pass identical attributes on every process.
        subgroups: Dict[tuple, List[_Request]] = {}
        for r in reqs:
            k = (r.sharded, r.average, r.prescale, r.postscale,
                 r.root_rank, r.wire)
            subgroups.setdefault(k, []).append(r)
        topo = _topo._get()
        for sub in subgroups.values():
            t_start = time.monotonic()
            try:
                results = self._execute_group_mp(ex, sub, group, topo)
            except BaseException as e:
                if tl is not None:
                    t_end = time.monotonic()
                    for r in sub:
                        tl.execute_span(r.name, _xla_activity(sub[0].op),
                                        t_start, t_end)
                _flight.recorder().group_error(
                    group.get("seq"), _op_name(sub[0].op), len(sub),
                    str(e))
                err = _as_error(e)
                for r in sub:
                    r.handle._fulfill(error=err)
                continue
            t_end = time.monotonic()
            self._metrics.group_executed(sub[0].op, len(sub), t_deliver,
                                         t_start, t_end)
            _flight.recorder().group_done(
                group.get("seq"), _op_name(sub[0].op), len(sub),
                t_deliver, t_start, t_end)
            for r, out in zip(sub, results):
                if tl is not None:
                    # One complete XLA span per tensor, shape riding
                    # along (the reference's activity + shape-on-end).
                    tl.execute_span(r.name, _xla_activity(sub[0].op),
                                    t_start, t_end,
                                    getattr(out, "shape", None))
                r.handle._fulfill(result=out)

    def _execute_group_mp(self, ex: CollectiveExecutor,
                          group: List[_Request], meta: dict, topo,
                          op: Optional[int] = None) -> List:
        """One coordinator-agreed subgroup as XLA programs — shared by the
        native (_on_native_group) and fallback (_execute_mp_group) MP
        paths; ``meta['sizes']`` carries the per-process allgather dims."""
        if op is None:
            op = group[0].op
        if op == ALLREDUCE:
            if group[0].sharded:
                return [ex.allreduce_sharded(
                    r.tensor, average=r.average, prescale=r.prescale,
                    postscale=r.postscale) for r in group]
            post = group[0].postscale
            if group[0].average:
                post = post / ex.world_size
            wire = group[0].wire
            tensors = [r.tensor for r in group]
            restore = None
            if wire is None:
                # Policy wire override (docs/adaptation.md), keyed on
                # the coordinator seq so every process flips at the
                # same group boundary. 'bf16' is a cast transport (the
                # fused program moves bf16); the blockwise specs ride
                # the executor's quantized wire path.
                ov = self._wire_override_for(meta.get("seq"), group)
                if ov == "bf16":
                    restore = [t.dtype for t in tensors]
                    tensors = [t.astype(jnp.bfloat16) for t in tensors]
                elif ov:
                    wire = ov
            outs = ex.allreduce_fused_mp(
                tensors, prescale=group[0].prescale,
                postscale=post, wire=wire)
            if restore is not None:
                outs = [o.astype(dt) for o, dt in zip(outs, restore)]
            return outs
        if op == BROADCAST:
            if group[0].sharded:
                return [ex.broadcast_sharded(r.tensor, r.root_rank)
                        for r in group]
            # Root from the request (validated identical across ranks by
            # the coordinator); the native wire carries no root field.
            return ex.broadcast_fused_mp([r.tensor for r in group],
                                         group[0].root_rank)
        if op == ALLGATHER:
            outs: List = []
            for r in group:
                if r.sharded:
                    # Already a global dp-sharded array: re-gather in
                    # place (cannot be pulled host-side across processes).
                    outs.append(ex.allgather_sharded_mp(r.tensor))
                    continue
                proc_dims = meta["sizes"].get(r.name)
                if proc_dims is None:
                    proc_dims = [int(r.tensor.shape[0])] * topo.process_count
                # One segment per virtual rank: expand the per-process
                # first dims by each process's device count (homogeneous
                # topology, checked at init like operations.cc:1772-1790).
                dev_dims = [d for d in proc_dims
                            for _ in range(topo.local_size)]
                if len(set(dev_dims)) == 1:
                    outs.append(ex.allgather_fused_mp([r.tensor])[0])
                else:
                    outs.append(ex.allgather_ragged_mp(r.tensor, dev_dims))
            return outs
        raise ValueError(f"unknown op {op}")

    def _wire_override_for(self, seq, group) -> Optional[str]:
        """Wire spec the policy's epoch list imposes on this fused
        allreduce group, or None. Epochs are [(from_seq, spec)] in
        ascending from_seq order; the last epoch at or below ``seq``
        wins ('' = back to raw). Only clean floating full-precision
        groups are eligible — an explicit user wire spec, sharded
        arrays, and non-float dtypes are left untouched."""
        epochs = self._wire_epochs
        if not epochs or seq is None:
            return None
        spec = None
        for fs, sp in epochs:
            if seq >= fs:
                spec = sp
            else:
                break
        if not spec:
            return None
        for r in group:
            t = r.tensor
            if (t is None or r.sharded or r.wire is not None
                    or not jnp.issubdtype(t.dtype, jnp.floating)):
                return None
        self._metrics.adapted_group(spec)
        return spec

    def _maybe_check_stalls(self):
        """Stall detector (CheckForStalledTensors, operations.cc:1625-1672):
        warn about requests stuck in flight past the warning time, with
        the reference report's per-tensor diagnostic quality — op type,
        wait duration, and (multi-process) WHICH ranks are missing, taken
        from the coordinator's authoritative table. In single-process
        mode every virtual rank is driven by this process, so no rank can
        be 'missing' — a stall there means the dispatcher is wedged or an
        async handle was never awaited, and the report says so."""
        if self.stall_warning_s <= 0:
            return
        now = time.monotonic()
        if now - self._last_stall_check < self.stall_warning_s:
            return
        self._last_stall_check = now
        with self._lock:
            stalled = [(r.name, _op_name(r.op), now - r.enqueued_at)
                       for r in self._in_flight.values()
                       if now - r.enqueued_at > self.stall_warning_s]
        if not stalled:
            # The previous episode resolved: zero the gauges so the
            # export stops naming tensors that completed.
            self._metrics.set_stalls([])
            return
        mp = self._is_multiprocess()
        # Expire coordinator lines from a PREVIOUS stall episode: a line
        # older than two warning windows describes ranks that were
        # missing then, not now (names are commonly reused per step).
        cutoff = now - 2.0 * self.stall_warning_s
        self._coord_stall_lines = {
            n: (ln, ts) for n, (ln, ts) in self._coord_stall_lines.items()
            if ts >= cutoff}
        lines = []
        gauge_entries = []
        for name, op, age in sorted(stalled):
            coord = self._coord_stall_lines.get(name)
            if coord is not None:
                lines.append(f"{coord[0]} [{op}, waiting {int(age)}s]")
                gauge_entries.append(
                    (name, age, _missing_ranks_of(coord[0])))
            elif mp:
                lines.append(
                    f"{name} [{op}, waiting {int(age)}s; announced, "
                    "awaiting coordinator grouping — see coordinator "
                    "report for missing ranks]")
                gauge_entries.append((name, age, "unknown"))
            else:
                lines.append(
                    f"{name} [{op}, waiting {int(age)}s; single-process: "
                    "all virtual ranks are local, so no rank is missing — "
                    "likely a wedged dispatcher or an unawaited handle]")
                gauge_entries.append((name, age, "none(single-process)"))
        self._metrics.set_stalls(gauge_entries)
        _log.warning(
            "One or more tensors were submitted to be reduced, gathered "
            "or broadcasted by subset of ranks and are waiting for "
            "remainder of ranks for more than %d seconds. This may "
            "indicate that different ranks are trying to submit "
            "different tensors or that only subset of ranks is "
            "submitting tensors, which will cause deadlock.\n"
            "Stalled ops:\n%s",
            int(self.stall_warning_s), "\n".join(lines))
        self._maybe_escalate_stalls(now)

    def _maybe_escalate_stalls(self, now: float) -> None:
        """Escalation past the failure timeout (elastic recovery): a
        request stuck longer than ``failure_timeout_s`` will never
        complete — some rank is gone — so fail its handle with a typed
        WorkerFailure instead of warning forever. The blocked submitter
        unblocks with an event the elastic driver can act on. Off by
        default (``failure_timeout_s == 0`` keeps warn-only parity with
        the reference's stall report)."""
        if self.failure_timeout_s <= 0:
            return
        with self._lock:
            overdue = [r for r in self._in_flight.values()
                       if now - r.enqueued_at > self.failure_timeout_s]
            for r in overdue:
                self._in_flight.pop(r.name, None)
                if r in self._queue:
                    self._queue.remove(r)
        if not overdue:
            return
        from ..elastic.failure import WorkerFailure
        names = ", ".join(sorted(r.name for r in overdue))
        for r in overdue:
            coord = self._coord_stall_lines.get(r.name)
            err = WorkerFailure(
                kind="stall",
                detail=(f"collective '{r.name}' ({_op_name(r.op)}) "
                        f"incomplete after "
                        f"{now - r.enqueued_at:.1f}s "
                        f"(> failure timeout {self.failure_timeout_s:.1f}s)"
                        + (f"; coordinator report: {coord[0]}"
                           if coord else "")))
            r.handle._fulfill(error=err)
        _log.error("escalated %d stalled collectives to WorkerFailure "
                   "after %.1fs: %s", len(overdue),
                   self.failure_timeout_s, names)
        # Stall escalation is a death sentence for the pending work —
        # capture the evidence NOW, while the engine still remembers the
        # episode (the submitter may hang instead of exiting cleanly).
        _flight.recorder().note(
            "stall", (names, round(max(now - r.enqueued_at
                                       for r in overdue), 3)))
        _flight.dump_on("stall_escalation")

    # ------------------------------------------------------------- execution

    @staticmethod
    def _fusion_key(req: _Request) -> tuple:
        """Attributes that must agree for two requests to share one fused
        program: op, planning dtype, WIRE format (wire bytes are what the
        threshold counts, and a quantized program is a different program),
        sharded-ness, root, and the execution-scaling knobs."""
        return (req.op, str(req.dtype), req.wire, req.sharded,
                req.root_rank, req.average, req.prescale, req.postscale)

    def _plan_fusion(self, batch: List[_Request]) -> List[List[_Request]]:
        """Greedy fusion with look-ahead (operations.cc:2149-2265).

        Requests fuse when they share a fusion key and the group's wire
        bytes stay under the threshold. Single pass over the batch:
        requests bucket by fusion key, and within a key a request joins
        the FIRST open group with room (first-fit) or opens a new group
        at its submission position. This reproduces the reference's
        round-based look-ahead exactly — in round r a request joins
        group r iff it didn't fit groups 1..r-1, which is first-fit in
        group-creation order — without the old O(n²) full rescan per
        group. Groups come out ordered by their first member's
        submission position. Per-rank (ragged allgather) requests never
        fuse and form singleton groups in place.
        """
        groups: List[List[_Request]] = []
        open_groups: Dict[tuple, List[List]] = {}  # key -> [group, total]s
        for req in batch:
            if req.per_rank is not None:
                groups.append([req])
                continue
            buckets = open_groups.setdefault(self._fusion_key(req), [])
            for entry in buckets:
                if entry[1] + req.nbytes <= self.fusion_threshold:
                    entry[0].append(req)
                    entry[1] += req.nbytes
                    break
            else:
                group = [req]
                groups.append(group)
                buckets.append([group, req.nbytes])
        return groups

    def _dispatch(self, batch: List[_Request]):
        ex = self.executor
        tl = self.timeline
        t_drain = time.monotonic()
        for group in self._plan_fusion(batch):
            names = [r.name for r in group]
            op = group[0].op
            self._metrics.group_delivered(op, group, t_drain)
            seq = self._local_group_seq
            self._local_group_seq += 1
            _flight.recorder().group_deliver(seq, _op_name(op), len(group))
            if tl is not None:
                for r in group:
                    # Same span diet as the MP path: one complete
                    # NEGOTIATE span anchored at the enqueue tick, one
                    # XLA span after execution.
                    tl.negotiate_span(r.name, _op_name(op),
                                      r.enqueued_at, t_drain, group=seq)
            t_start = time.monotonic()
            try:
                results = self._execute_group(ex, group)
            except BaseException as e:
                with self._lock:
                    for r in group:
                        self._in_flight.pop(r.name, None)
                if tl is not None:
                    t_end = time.monotonic()
                    for n in names:
                        tl.execute_span(n, _xla_activity(op), t_start,
                                        t_end)
                _flight.recorder().group_error(seq, _op_name(op),
                                               len(group), str(e))
                for r in group:
                    r.handle._fulfill(error=_as_error(e))
                continue
            t_end = time.monotonic()
            self._metrics.group_executed(op, len(group), t_drain,
                                         t_start, t_end)
            _flight.recorder().group_done(seq, _op_name(op), len(group),
                                          t_drain, t_start, t_end)
            with self._lock:
                for r in group:
                    self._in_flight.pop(r.name, None)
            for r, out in zip(group, results):
                if tl is not None:
                    # One complete XLA span per tensor, shape attached.
                    tl.execute_span(r.name, _xla_activity(op), t_start,
                                    t_end, getattr(out, "shape", None))
                r.handle._fulfill(result=out)

    def _fence_producers(self) -> bool:
        """Whether collective launches must wait for input producers.

        The hazard (VERDICT r2, observed 4-of-8 on the CPU mesh): this
        engine thread launching a mesh-wide program while a user
        thread's mesh-wide program dispatch is still fanning out across
        the per-device queues leaves no global enqueue order — two
        all-device programs queued in opposite orders on different
        devices deadlock in XLA's collective rendezvous. The inversion
        NEEDS more than one addressable device: with one device per
        process (the real-pod shape, and the single-chip bench) every
        launch lands in one FIFO queue and ordering is total, so the
        fence is skipped and the collective enqueues behind the
        still-running producer — restoring the compute/collective
        overlap the reference gets from ready-events + NCCL streams
        (operations.cc:816-840, 1117-1191). HOROVOD_TPU_PRODUCER_FENCE
        forces either way.

        Contract (measured, test_engine_overlap.py): the fence covers
        PRODUCER-feeding flows — mesh programs whose outputs are the
        collective's inputs. An unrelated mesh-wide jit stream from
        another thread concurrent with eager collectives deadlocks on
        a multi-device process regardless (no fence can order two
        threads' unrelated launches); that pattern must use the jit
        optimizer path."""
        if self._fence_decision is None:
            forced = _env.producer_fence()
            self._fence_decision = (forced if forced is not None
                                    else jax.local_device_count() > 1)
        return self._fence_decision

    def _ordered_launch(self) -> bool:
        """HOROVOD_TPU_ORDERED_LAUNCH=1 (read once, like every engine
        knob): replace the completion fence with enqueue-ordering under
        _LAUNCH_LOCK. Prototype for platforms whose per-device enqueue
        is host-call-ordered; see utils/env.ordered_launch for the
        measured CPU-backend caveat."""
        if self._ordered_decision is None:
            self._ordered_decision = _env.ordered_launch()
        return self._ordered_decision

    def _execute_group(self, ex: CollectiveExecutor,
                       group: List[_Request]) -> List:
        if self._ordered_launch():
            # Enqueue-ordered launch: no producer completion wait; the
            # lock only serializes the enqueue against producer streams
            # that take launch_lock(). The XLA dispatch below returns
            # futures, so the lock hold time is the enqueue, not the
            # collective.
            with _LAUNCH_LOCK:
                return self._execute_group_ops(ex, group)
        if self._fence_producers():
            # Multi-device process: retire producers first (see
            # _fence_producers). Tensors that are already on device and
            # committed (is_ready) — or host arrays — skip the block,
            # so an async submitter whose grads landed early pays
            # nothing.
            pending = []
            for r in group:
                ts = r.per_rank if r.per_rank is not None else (r.tensor,)
                for t in ts:
                    if t is None:
                        continue
                    ready = getattr(t, "is_ready", None)
                    if ready is not None and not ready():
                        pending.append(t)
            if pending:
                jax.block_until_ready(pending)
        return self._execute_group_ops(ex, group)

    def _execute_group_ops(self, ex: CollectiveExecutor,
                           group: List[_Request]) -> List:
        op = group[0].op
        if op == ALLREDUCE:
            if group[0].sharded:
                return [ex.allreduce_sharded(
                    r.tensor, average=r.average, prescale=r.prescale,
                    postscale=r.postscale) for r in group]
            n = ex.world_size
            pre = group[0].prescale
            post = group[0].postscale
            if group[0].average:
                post = post / n
            outs = ex.allreduce_fused([r.tensor for r in group],
                                      prescale=pre, postscale=post,
                                      wire=group[0].wire)
            return outs
        if op == BROADCAST:
            if group[0].sharded:
                return [ex.broadcast_sharded(r.tensor, r.root_rank)
                        for r in group]
            return ex.broadcast_fused([r.tensor for r in group],
                                      group[0].root_rank)
        if op == ALLGATHER:
            outs: List = [None] * len(group)
            fused_idx = [i for i, r in enumerate(group)
                         if r.per_rank is None and not r.sharded]
            if fused_idx:
                fused_out = ex.allgather_fused(
                    [group[i].tensor for i in fused_idx])
                for i, o in zip(fused_idx, fused_out):
                    outs[i] = o
            for i, r in enumerate(group):
                if r.per_rank is not None:
                    outs[i] = ex.allgather_ragged(r.per_rank)
                elif r.sharded:
                    outs[i] = ex.allgather_ragged(list(r.tensor))
            return outs
        raise ValueError(f"unknown op {op}")


def _op_name(op: int) -> str:
    return {ALLREDUCE: "allreduce", ALLGATHER: "allgather",
            BROADCAST: "broadcast"}[op]


def _missing_ranks_of(display_line: str) -> str:
    """Best-effort extraction of the missing-rank list from a
    coordinator stall display line ("name [missing ranks: 1, 3]") for
    the gauge label. The structured source is the coordinator's own
    metrics (control_plane.check_stalls); this is the worker-side echo,
    parsed from OUR controller's stable wording — worst case the label
    degrades to 'unknown', never to a wrong rank."""
    marker = "missing ranks:"
    i = display_line.find(marker)
    if i < 0:
        return "unknown"
    tail = display_line[i + len(marker):]
    ranks = []
    for tok in tail.replace("]", " ").split(","):
        tok = tok.strip()
        if tok.isdigit():
            ranks.append(tok)
        elif ranks:
            break
    return ",".join(ranks) if ranks else "unknown"


def _xla_activity(op: int) -> str:
    # Timeline activity names; the reference's are NCCL_ALLREDUCE /
    # MPI_ALLREDUCE etc. (operations.h:29-50).
    return {ALLREDUCE: "XLA_ALLREDUCE", ALLGATHER: "XLA_ALLGATHER",
            BROADCAST: "XLA_BROADCAST"}[op]


def _as_error(e: BaseException) -> BaseException:
    if isinstance(e, (ValueError, TypeError, HorovodInternalError)):
        return e
    from .control_plane import CoordinatorUnreachableError
    if isinstance(e, CoordinatorUnreachableError):
        # Typed for the elastic plane: a dead rank-0 process is a
        # recoverable worker loss (the driver re-rendezvouses), not an
        # anonymous internal error.
        from ..elastic.failure import WorkerFailure
        return WorkerFailure(rank=0, kind="coordinator_unreachable",
                             detail=str(e))
    return HorovodInternalError(str(e))


_engine: Optional[CollectiveEngine] = None
_engine_lock = threading.Lock()


def _flush_hint() -> None:
    """Forward a Handle.wait flush hint to the live engine (no-op when no
    engine is up — e.g. a handle fulfilled synchronously)."""
    eng = _engine
    if eng is not None and not eng._shutdown:
        try:
            eng.flush_hint()
        except Exception:  # pragma: no cover - teardown race
            pass


def engine() -> CollectiveEngine:
    global _engine
    with _engine_lock:
        if _engine is None:
            _engine = CollectiveEngine()
            atexit.register(_shutdown_atexit)
        return _engine


def _shutdown_atexit():
    global _engine
    if _engine is not None:
        _engine.shutdown()
        _engine = None


def reset_engine():
    """Test hook: drop the engine and the default executor (and with it the
    jitted-program cache keyed on the old mesh)."""
    from .. import executor as _exec
    global _engine
    with _engine_lock:
        if _engine is not None:
            _engine.shutdown()
        _engine = None
    _exec.reset_default_executor()


# ---------------------------------------------------------------------------
# Public eager API — mirrors horovod/torch/mpi_ops.py + tensorflow/mpi_ops.py
# ---------------------------------------------------------------------------

def _prep(tensor):
    """Accept numpy / python / jax inputs; detect per-rank leading-axis
    sharding.

    The per-rank convention is: a jax.Array whose *leading* axis is sharded
    over the mesh axis ('dp') and whose other axes are unsharded represents
    one tensor per virtual rank. Any other non-replicated layout is
    ambiguous for eager Horovod semantics and is rejected with guidance
    (rather than silently reinterpreted).
    """
    if isinstance(tensor, jax.Array):
        sh = tensor.sharding
        if sh.is_fully_replicated or len(sh.device_set) <= 1:
            return tensor, False
        spec = getattr(sh, "spec", None)
        if spec is not None:
            leading = spec[0] if len(spec) > 0 else None
            rest = [s for s in spec[1:] if s is not None]
            if leading in ("dp", ("dp",)) and not rest:
                return tensor, True
        raise ValueError(
            "Eager collectives accept replicated arrays (every rank "
            "contributes a copy) or arrays sharded over the mesh 'dp' axis "
            f"on the LEADING dimension only (per-rank values); got sharding "
            f"{sh}. For other layouts use the in-jit collectives "
            "(horovod_tpu.allreduce_gradients inside shard_map) instead.")
    src_dtype = getattr(tensor, "dtype", None)
    arr = jnp.asarray(tensor)
    if (src_dtype is not None
            and np.dtype(src_dtype).itemsize > arr.dtype.itemsize):
        # jnp.asarray silently narrowed a 64-bit input (jax_enable_x64 is
        # off) — refuse rather than corrupt values; the reference reduces
        # int64/float64 natively over MPI (mpi_message.h:26-37).
        raise ValueError(
            f"collective on {src_dtype} requires 64-bit JAX mode; enable "
            "it with jax.config.update('jax_enable_x64', True) before "
            "hvd.init(), or cast to a 32-bit dtype first")
    return arr, False


def _wire_for(tensor, sharded: bool, compression) -> Optional[str]:
    """Wire-format spec a blockwise compression selects for this request,
    or None (cast compressors transform the tensor before enqueue; the
    wire IS the tensor dtype then). Sharded per-rank arrays keep the
    full-precision path — their reduce is per-request, not fused."""
    spec = getattr(compression, "wire_spec", None)
    if spec is None or sharded:
        return None
    if not jnp.issubdtype(tensor.dtype, jnp.floating):
        return None
    return _quant.parse(spec).encoded()


def allreduce_async(tensor, average: bool = True, name: Optional[str] = None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    compression=None) -> Handle:
    """Asynchronous allreduce; returns a :class:`Handle`.

    Parity: ``hvd.allreduce_async`` (torch/mpi_ops.py:110-180). ``average``
    divides by ``size()`` after summation, as the torch binding does in its
    completion callback (torch/mpi_ops_v2.cc:62-69).

    ``compression`` here only selects a blockwise WIRE format
    (``Compression.int8_blockwise`` / ``fp8_blockwise``): the tensor is
    submitted at its logical dtype and the quantize → reduce-scatter →
    requantize → allgather pipeline runs inside the fused XLA program.
    Cast compressors transform the tensor before enqueue (see
    :func:`allreduce`) and are ignored here.
    """
    _topo._get()
    eng = engine()
    t, sharded = _prep(tensor)
    nm = name or eng._next_name("allreduce")
    h = eng.make_handle(nm)
    req = _Request(nm, ALLREDUCE, t, h, average=average,
                   prescale=prescale_factor, postscale=postscale_factor,
                   sharded=sharded, wire=_wire_for(t, sharded, compression))
    return eng.enqueue(req)


def allreduce(tensor, average: bool = True, name: Optional[str] = None,
              compression=None, prescale_factor: float = 1.0,
              postscale_factor: float = 1.0):
    """Synchronous allreduce (sum / average over all virtual ranks).

    ``compression`` mirrors ``hvd.Compression`` usage in
    tensorflow/__init__.py:46-92: a cast compressor transforms the tensor
    before the collective and restores it after; a blockwise compressor
    (``Compression.int8_blockwise`` / ``fp8_blockwise``) instead selects
    the quantized wire format executed inside the fused program.
    """
    if compression is not None:
        t, ctx = compression.compress(jnp.asarray(tensor))
        out = allreduce_async(t, average=average, name=name,
                              prescale_factor=prescale_factor,
                              postscale_factor=postscale_factor,
                              compression=compression).wait()
        return compression.decompress(out, ctx)
    return allreduce_async(tensor, average=average, name=name,
                           prescale_factor=prescale_factor,
                           postscale_factor=postscale_factor).wait()


def grouped_allreduce(tensors: Sequence, average: bool = True,
                      name: Optional[str] = None) -> List:
    """Allreduce a list of tensors as one fused submission."""
    with engine().burst():
        handles = [allreduce_async(t, average=average,
                                   name=(f"{name}.{i}" if name else None))
                   for i, t in enumerate(tensors)]
    return [h.wait() for h in handles]


def allgather_async(tensor, name: Optional[str] = None) -> Handle:
    """Asynchronous allgather along dim 0 (torch/mpi_ops.py:236-290).

    Accepts a replicated tensor (every rank contributes a copy), a jax.Array
    sharded over 'dp' (per-rank rows), or a list of per-rank tensors with
    varying first dims (the MPI_Allgatherv case, operations.cc:843-1113).
    """
    _topo._get()
    eng = engine()
    if isinstance(tensor, (list, tuple)):
        if eng._is_multiprocess():
            raise ValueError(
                "per-virtual-rank tensor lists are a single-process "
                "convenience; in multi-process mode pass this process's "
                "tensor (first dims may differ across processes — the "
                "MPI_Allgatherv case, operations.cc:843-1113)")
        per_rank = [jnp.asarray(t) for t in tensor]
        nm = name or eng._next_name("allgather")
        h = eng.make_handle(nm)
        req = _Request(nm, ALLGATHER, None, h, per_rank=per_rank)
        return eng.enqueue(req)
    t, sharded = _prep(tensor)
    nm = name or eng._next_name("allgather")
    h = eng.make_handle(nm)
    req = _Request(nm, ALLGATHER, t, h, sharded=sharded)
    return eng.enqueue(req)


def allgather(tensor, name: Optional[str] = None):
    return allgather_async(tensor, name=name).wait()


def broadcast_async(tensor, root_rank: int, name: Optional[str] = None
                    ) -> Handle:
    """Asynchronous broadcast from ``root_rank`` (torch/mpi_ops.py:318-392)."""
    topo = _topo._get()
    if not (0 <= root_rank < topo.size):
        # ConstructMPIResponse rejects invalid root ranks
        # (operations.cc:472-478) instead of silently deadlocking.
        raise ValueError(
            f"Invalid root_rank {root_rank}: root rank must be in "
            f"[0, {topo.size})")
    eng = engine()
    t, sharded = _prep(tensor)
    nm = name or eng._next_name("broadcast")
    h = eng.make_handle(nm)
    req = _Request(nm, BROADCAST, t, h, root_rank=root_rank, sharded=sharded)
    return eng.enqueue(req)


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    return broadcast_async(tensor, root_rank, name=name).wait()


def poll(handle: Handle) -> bool:
    """True iff the op behind ``handle`` finished (torch/mpi_ops.py:406-417)."""
    return handle.poll()


def synchronize(handle: Handle, timeout: Optional[float] = None):
    """Wait for ``handle`` and return its output (torch/mpi_ops.py:419-438)."""
    return handle.wait(timeout)
