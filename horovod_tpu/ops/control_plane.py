"""Cross-process control plane — the rank-0 coordinator protocol over TCP.

This is the TPU-native equivalent of the reference's coordinator half of
``RunLoopOnce`` (horovod/common/operations.cc:2030-2380): there, every
cycle each rank MPI_Gathers its serialized request list to rank 0, rank 0
counts announcements per tensor (``IncrementTensorCount``,
operations.cc:287-313), validates cross-rank consistency
(``ConstructMPIResponse``, operations.cc:321-523), fuses ready tensors
into response groups with look-ahead (operations.cc:2149-2265), and
MPI_Bcasts the ordered response list so *every rank executes the same
fused collectives in the same order*.

Here the transport is the launcher's HMAC-authenticated TCP RPC
(runner/network.py) instead of MPI, and the executed collective is a
jitted XLA program over the global device mesh — which is exactly why the
agreement matters: a multi-host XLA program is SPMD, so every process
must enter the *same* program in the *same* order or the job deadlocks.
The coordinator's ordered group sequence provides that guarantee; cycle
timing differences between processes can no longer diverge the fusion
plan.

The PLANNER is the native runtime (runtime/src/controller.cc wrapping
coordinator.cc's MessageTable/ConstructResponse/FuseResponses, with
message.cc's codec as the payload format — one planner, one wire); this
module is the TCP transport around it plus a pure-Python fallback planner
for hosts without the toolchain. Both planners speak the same wire format
(ops/wire_format.py mirrors the native codec byte-for-byte) and are
asserted to produce identical fusion plans in tests/test_native.py.

Endpoint discovery: the launcher exports ``HOROVOD_TPU_CONTROL``
(host:port, bound by process 0) and ``HOROVOD_TPU_SECRET_KEY``; workers
poll with ``FetchGroups`` (the Bcast analogue) after announcing requests
(the Gather analogue).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import wire_format as _wire
from ..observability import registry as _obs
from ..runner.network import BasicClient, BasicService
from ..runner.secret import SECRET_ENV, decode_key, make_secret_key
from ..utils.logging import get_logger

_log = get_logger("control_plane")

# Quiet window before the coordinator cuts fusion groups: plan only once no
# announce has arrived for this long (and no tensor is partially
# announced), so one training step's burst of announces — which worker
# cycles deliver in several chunks — always fuses into the same group
# composition. Every distinct composition is a distinct fused XLA program;
# determinism here is what makes the executor's jit cache hit across
# steps. Must match controller.cc's Controller::plan_debounce_s.
PLAN_DEBOUNCE_S = 0.002

# Bounded-defer valve (native: Controller::kMaxDeferFactor): under
# continuously overlapping announce bursts the quiet window never opens,
# so plan unconditionally once the oldest ready tensor has waited this
# many debounce windows — mirroring the client-side kDrainMaxDeferNs cap.
PLAN_MAX_DEFER_FACTOR = 10.0

CONTROL_ENV = "HOROVOD_TPU_CONTROL"


class CoordinatorUnreachableError(ConnectionError):
    """The rank-0 coordinator could not be reached after the bounded
    retry/backoff schedule. Subclasses ConnectionError so existing
    transport-failure handlers keep working, while giving callers (the
    engine, the elastic plane) a typed event to dispatch on instead of
    a generic socket error — a worker polling a dead or restarting
    coordinator surfaces this in seconds with an actionable message,
    rather than hanging or dying with a bare ECONNREFUSED."""

# Wire op enums shared with the engine (executor.ALLREDUCE etc.).
_OP_NAMES = {0: "allreduce", 1: "allgather", 2: "broadcast"}


# --------------------------------------------------------------------------
# Wire messages
# --------------------------------------------------------------------------

class AnnounceRequest:
    """One process's newly-ready request metadata for this cycle — the
    serialized MPIRequestList of the reference (mpi_message.h:88-105).

    ``announce_id`` is a per-rank monotonically increasing sequence number
    making announces idempotent end-to-end: BasicClient retries a request
    whose response was lost, and if the first delivery completed a quorum
    (entry deleted), a blind re-apply would resurrect a stale one-rank
    entry with last step's shape metadata. The coordinator drops ids it
    has already processed instead."""

    def __init__(self, rank: int, requests: List[dict], shutdown: bool = False,
                 announce_id: int = 0, payload: Optional[bytes] = None,
                 complete: bool = False):
        self.rank = rank
        self.requests = requests  # {name, op, dtype, shape, root_rank, nbytes}
        self.shutdown = shutdown
        self.announce_id = announce_id
        # Native-engine processes announce pre-serialized RequestList bytes
        # (message.cc codec) instead of dicts; `requests` is then empty.
        self.payload = payload
        # True when this announce carries a COMPLETE enqueue burst (the
        # worker drained after debounce-quiet or a flush hint, not via the
        # max-defer valve): once every rank's complete announce has landed
        # and no tensor is partial, the coordinator plans IMMEDIATELY —
        # the quiet window exists only to guard against mid-burst
        # chunking, which the marker rules out.
        self.complete = complete


class AnnounceFetchRequest:
    """Combined announce + long-poll fetch — ONE control-plane round trip
    per worker cycle instead of two. The reference pays one MPI_Gatherv +
    one MPI_Bcast per cycle (operations.cc:2088-2287); over TCP each leg
    is a full RPC, and on a busy host the second round trip is pure added
    step latency, so the worker ships both legs in one request."""

    def __init__(self, announce: Optional[AnnounceRequest],
                 fetch: FetchRequest):
        self.announce = announce  # None for fetch-only cycles
        self.fetch = fetch


class AnnounceResponse:
    def __init__(self, ok: bool = True):
        self.ok = ok


class ClockProbeRequest:
    """One ping of the clock-alignment handshake (docs/tracing.md): the
    worker samples its monotonic clock around the round trip and the
    coordinator answers with its own monotonic reading. NTP-style
    round-trip halving — offset = t_coord + rtt/2 - t_recv — repeated K
    times with the minimum-RTT sample winning gives each rank its
    estimated offset to rank 0's clock, recorded in the per-rank trace
    header so the offline merger can realign N traces onto one clock."""

    def __init__(self, rank: int):
        self.rank = rank


class ClockProbeResponse:
    def __init__(self, t_mono_us: int):
        self.t_mono_us = t_mono_us


class AlertNoteRequest:
    """One health alert forwarded to the rank-0 coordinator as an
    adaptation-ladder input (docs/health.md#adaptation): a remote
    rank's detector saw a step-time regression or an HBM leak that the
    coordinator's own lateness signal may not reflect (a leak is not
    late until it OOMs). Best-effort, fire-and-forget — alerting must
    never stall a worker."""

    def __init__(self, rank: int, kind: str, severity: str = "warning",
                 value: float = 0.0):
        self.rank = rank
        self.kind = kind
        self.severity = severity
        self.value = value


class FingerprintRequest:
    """One rank's param-tree fingerprint digests for one training step
    (docs/numerics.md#fingerprints): per-leaf ``[norm, crc, n]`` from
    ``observability.numerics.fingerprint_tree``. The rank-0 coordinator
    collects a step's set and majority-compares it — a mismatch fires
    the typed ``rank_divergence`` alert naming the first divergent leaf
    and rank. Best-effort like AlertNoteRequest: a dropped probe means
    a skipped compare, never a stalled worker."""

    def __init__(self, rank: int, step: int, digests: dict):
        self.rank = rank
        self.step = step
        self.digests = digests


class TunerMoveRequest:
    """One global-autotuner move proposal (docs/autotune.md): the tuner
    asks the rank-0 coordinator to stamp a knob change — a wire spec or
    fusion threshold as an epoch ``(from_seq, value)``, a cycle time
    live. The coordinator's :class:`WireEpochArbiter` serializes these
    against the adaptation ladder's own epochs so two planes can never
    stamp conflicting values for the same group seq; the response says
    whether the move landed and from which seq it takes effect."""

    def __init__(self, rank: int, knob: str, value):
        self.rank = rank
        self.knob = knob
        self.value = value


class TunerMoveResponse:
    def __init__(self, accepted: bool, from_seq: int = -1,
                 reason: str = ""):
        self.accepted = accepted
        self.from_seq = from_seq
        self.reason = reason


class FetchRequest:
    """Long-poll for response groups after ``after_seq`` — the response
    list Bcast of the reference (operations.cc:2282-2287)."""

    def __init__(self, rank: int, after_seq: int, wait_s: float = 0.0):
        self.rank = rank
        self.after_seq = after_seq
        self.wait_s = wait_s


class FetchResponse:
    def __init__(self, groups: List[dict], shutdown: bool,
                 payload: Optional[bytes] = None,
                 params: Optional[dict] = None,
                 stall: Optional[List[Tuple[str, str]]] = None,
                 failures: Optional[List[dict]] = None):
        self.groups = groups      # [{seq, op, names, error, flags,
        #                            sizes: {name: [dim0 per process]}}]
        self.shutdown = shutdown
        # Serialized ResponseList (message.cc codec) for native engines —
        # the exact bytes the native core parses (the Bcast payload).
        self.payload = payload
        # Coordinator-tuned scalar knobs (SyncParams equivalent,
        # parameter_manager.cc:213-246): fusion_threshold, cycle_time_ms,
        # flags, autotune_active, autotune_done.
        self.params = params or {}
        # Coordinator stall report as (tensor_name, display_line) pairs
        # (missing-ranks diagnostics, operations.cc:1625-1672), logged by
        # every process; keyed by name so no one re-parses display text.
        self.stall = stall or []
        # Escalated failure events ({rank, kind, detail} dicts) — present
        # only when HOROVOD_TPU_FAILURE_TIMEOUT > 0 (elastic runs):
        # receiving engines fail their pending handles with a typed
        # WorkerFailure instead of waiting on a quorum that can never
        # complete.
        self.failures = failures or []


class _Entry:
    __slots__ = ("op_by_rank", "dtype_by_rank", "shape_by_rank",
                 "root_by_rank", "device_by_rank", "nbytes", "ranks",
                 "order", "first_seen")

    def __init__(self, order: int):
        self.op_by_rank: Dict[int, int] = {}
        self.dtype_by_rank: Dict[int, str] = {}
        self.shape_by_rank: Dict[int, Tuple[int, ...]] = {}
        self.root_by_rank: Dict[int, int] = {}
        # Execution-semantics fingerprint per rank (the wire's device
        # slot — collective._semantics_fingerprint).
        self.device_by_rank: Dict[int, int] = {}
        self.nbytes = 0
        self.ranks = set()
        self.order = order
        self.first_seen = time.monotonic()

    @property
    def op(self) -> int:
        return next(iter(self.op_by_rank.values()))

    @property
    def dtype(self) -> str:
        return next(iter(self.dtype_by_rank.values()))


class _SkewTracker:
    """Per-rank negotiate-lateness accounting from the coordinator's
    announce ticks (the live half of the cross-rank tracing subsystem,
    docs/tracing.md): the coordinator is the one place that sees WHEN
    each rank announced each tensor, so it can quantify skew without any
    trace files. For every tensor that reaches quorum, each rank's
    lateness is its announce tick minus the first rank's; the per-rank
    distribution goes to ``hvdtpu_negotiate_lateness_seconds{rank=}``
    and an exponentially-decayed accumulator elects the current
    straggler (``hvdtpu_straggler_rank``). The MLPerf pod study (arxiv
    1909.09756) attributes most scaling loss to exactly this skew; this
    makes it a scrapeable number instead of a "ranks N,M not ready"
    log line."""

    # Decay per completed tensor: ~0.99^400 ≈ 0.02, so the straggler
    # election follows the last few hundred collectives (a few training
    # steps), not the whole job history.
    DECAY = 0.99

    def __init__(self, nproc: int):
        self._nproc = nproc
        self._pending: Dict[str, Dict[int, float]] = {}
        r = _obs.registry()
        self._m_lateness = r.histogram(
            "hvdtpu_negotiate_lateness_seconds",
            "Per-rank announce lateness behind the first-announcing rank, "
            "per fully-announced tensor (rank-0 coordinator view)",
            buckets=_obs.LATENCY_BUCKETS)
        self._m_lateness_total = r.counter(
            "hvdtpu_negotiate_lateness_seconds_total",
            "Cumulative announce-lateness seconds by rank")
        # Re-key on (re-)rendezvous: these families are labeled by rank
        # under the CURRENT world size. A new tracker means a new world
        # (elastic shrink/grow, or a reset coordinator in tests) — an
        # evicted rank's per-rank children lingering in the export would
        # keep naming it as the straggler forever, so the rank-keyed
        # series are dropped and rebuilt rather than accumulated across
        # worlds (unlike the world-agnostic totals elsewhere).
        self._m_lateness.clear()
        self._m_lateness_total.clear()
        self._m_straggler = r.gauge(
            "hvdtpu_straggler_rank",
            "Rank with the highest recent negotiate lateness "
            "(exponentially decayed; -1 until any skew is observed)"
        ).labels()
        self._m_straggler_lateness = r.gauge(
            "hvdtpu_straggler_lateness_seconds",
            "Decay-weighted mean negotiate lateness of the current "
            "straggler rank").labels()
        self._m_straggler_lateness.set(0.0)
        self._hist_children = {
            rk: self._m_lateness.labels(rank=str(rk))
            for rk in range(nproc)}
        self._total_children = {
            rk: self._m_lateness_total.labels(rank=str(rk))
            for rk in range(nproc)}
        self._acc = [0.0] * nproc
        self._weight = [0.0] * nproc
        self._m_straggler.set(-1)

    def note(self, rank: int, names, now: float) -> None:
        """Record ``rank``'s announce tick for each tensor name; on
        quorum, fold the per-rank lateness into the metrics."""
        for name in names:
            entry = self._pending.setdefault(name, {})
            if rank in entry:
                continue  # duplicate announce (client retry)
            entry[rank] = now
            if len(entry) < self._nproc:
                continue
            del self._pending[name]
            t0 = min(entry.values())
            for rk, t in entry.items():
                late = t - t0
                self._hist_children[rk].observe(late)
                self._total_children[rk].inc(late)
                self._acc[rk] = self._acc[rk] * self.DECAY + late
                self._weight[rk] = self._weight[rk] * self.DECAY + 1.0
            worst = max(range(self._nproc), key=lambda rk: self._acc[rk])
            if self._acc[worst] > 0.0:
                self._m_straggler.set(worst)
                self._m_straggler_lateness.set(
                    self._acc[worst] / self._weight[worst])

    def recent_lateness_by_rank(self) -> Dict[int, float]:
        """Decay-weighted mean lateness per rank — the quantitative tail
        for the stall warning."""
        return {rk: self._acc[rk] / self._weight[rk]
                for rk in range(self._nproc) if self._weight[rk] > 0.0}

    def prune(self, older_than: float) -> None:
        """Drop partially-announced entries whose newest tick is older
        than ``older_than`` (monotonic seconds): tensors stuck past the
        stall window are the stall detector's story; keeping their ticks
        forever would grow coordinator memory on misbehaving jobs."""
        stale = [n for n, e in self._pending.items()
                 if max(e.values()) < older_than]
        for n in stale:
            del self._pending[n]


class WireEpochArbiter:
    """The single serialization point for epoch-stamped knob changes.

    Two planes retune the collective wire: the adaptation ladder
    (``adaptation.policy``, reacting to health alerts) and the global
    autotuner (``autotune.driver``, searching for speed). Both express
    a change the same way — an epoch ``(from_seq, value)`` declaring
    that groups planned from ``from_seq`` on use the new value. If each
    appended to the epoch list independently, both could stamp the SAME
    from_seq with different values inside one planning gap, and ranks
    would disagree on the program for that seq (the exact hazard the
    wire-epoch mechanism exists to prevent). Every producer therefore
    proposes through this arbiter, which holds the coordinator's
    planning lock while it reads the next seq and appends, with
    deterministic precedence when the two planes collide in one gap:

      - one producer re-stamping the same pending from_seq appends
        (every fetch ships the whole list; later entries win, so a
        ladder escalating through its tiers — or a tuner rolling back
        its own move — stays deterministic on every rank);
      - the ladder REPLACES a pending tuner move at the same from_seq
        (a health reaction outranks an optimization);
      - a tuner move against a pending ladder epoch is REJECTED.
    """

    def __init__(self, mu, next_seq):
        self._mu = mu                # the coordinator's planning lock
        self._next_seq = next_seq    # () -> first not-yet-planned seq
        self.wire_epochs: List[Tuple[int, str]] = []
        self.fusion_epochs: List[Tuple[int, int]] = []
        self._wire_src: List[str] = []
        self._fusion_src: List[str] = []

    def _propose(self, epochs, srcs, source: str, value, initial):
        seq = int(self._next_seq())
        current = epochs[-1][1] if epochs else initial
        if value == current:
            return {"accepted": False, "from_seq": seq, "reason": "noop"}
        if epochs and epochs[-1][0] == seq:
            pending = {s for (fs, _), s in zip(epochs, srcs) if fs == seq}
            if source == "tuner" and "ladder" in pending:
                return {"accepted": False, "from_seq": seq,
                        "reason": "conflict_with_ladder"}
            if source == "ladder" and "tuner" in pending:
                # No group at from_seq has been planned yet (we hold
                # the planning lock), so no rank has seen the tuner's
                # entries — drop them and stamp the ladder's value.
                kept = [(e, s) for e, s in zip(epochs, srcs)
                        if not (e[0] == seq and s == "tuner")]
                epochs[:] = [e for e, _ in kept]
                srcs[:] = [s for _, s in kept]
                epochs.append((seq, value))
                srcs.append(source)
                return {"accepted": True, "from_seq": seq,
                        "reason": "replaced_tuner"}
        epochs.append((seq, value))
        srcs.append(source)
        return {"accepted": True, "from_seq": seq, "reason": "ok"}

    def propose_wire(self, source: str, spec: Optional[str]) -> dict:
        with self._mu:
            return self._propose(self.wire_epochs, self._wire_src,
                                 source, spec or "", "")

    def propose_fusion(self, source: str, threshold_bytes: int) -> dict:
        with self._mu:
            return self._propose(self.fusion_epochs, self._fusion_src,
                                 source, int(threshold_bytes), None)


class CoordinatorService(BasicService):
    """Rank-0 coordinator: counts announcements, validates, plans fusion,
    serves the ordered group sequence.

    The planner is the native controller (runtime/src/controller.cc) when
    the toolchain is available — the reference's C++ coordinator running
    the real cross-process negotiation — with this class as TCP transport.
    ``native=False`` forces the pure-Python fallback planner (used on
    hosts without g++, and by the plan-equivalence tests)."""

    def __init__(self, nproc: int, key: bytes,
                 fusion_threshold: int = 64 * 1024 * 1024,
                 port: int = 0, native: object = "auto",
                 virtual_size: int = 0,
                 stall_warning_s: Optional[float] = None):
        # NOTE: the TCP service (super().__init__) is brought up at the
        # very END of this constructor. Workers connect-poll the
        # launcher-published control port, so the instant it binds,
        # announce RPCs arrive — binding first (the old order) let a
        # handler thread read half-initialized coordinator state and die
        # with an AttributeError, stranding that rank's announce
        # (observed as a "missing ranks" stall on an otherwise healthy
        # job).
        self.key = key
        self._nproc = nproc
        self.fusion_threshold = fusion_threshold
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._table: Dict[str, _Entry] = {}
        self._ready: List[Tuple[str, _Entry]] = []
        # Executed-group history is pruned up to the lowest sequence every
        # process has acknowledged (via FetchRequest.after_seq) — a
        # days-long job must not grow coordinator memory linearly.
        self._groups: List[dict] = []
        self._base_seq = 0
        self._acked: Dict[int, int] = {}
        self._order = 0
        self._shutdown = False
        # Highest announce_id processed per rank — replay protection for
        # client retries (a retried announce must be a no-op, or it can
        # resurrect a quorum-deleted entry with stale shape metadata).
        self._last_announce: Dict[int, int] = {}
        # Wall time of the last announce — the quiescence-planner clock
        # (_maybe_plan_locked).
        self._last_announce_t = time.monotonic()
        # When the oldest currently-ready tensor became ready — the
        # bounded-defer clock (PLAN_MAX_DEFER_FACTOR).
        self._oldest_ready_t: Optional[float] = None
        # Stall reporting (CheckForStalledTensors, operations.cc:1625-1672):
        # the coordinator alone knows WHICH ranks are missing per tensor.
        # Window from env (HOROVOD_TPU_STALL_CHECK_DISABLE honored), the
        # same knob source the engine uses (collective.py).
        from ..utils import env as _envmod
        self.stall_warning_s = (stall_warning_s if stall_warning_s is not None
                                else _envmod.stall_warning_secs())
        self._last_stall_check = time.monotonic()
        # Failure escalation (elastic): the fetch long-poll every worker
        # issues each cycle doubles as its control-plane heartbeat. With
        # HOROVOD_TPU_FAILURE_TIMEOUT > 0, a rank silent past the window
        # — or a tensor stuck partially announced past it — becomes a
        # typed failure event shipped to every surviving rank through
        # the fetch response (check_failures); 0 keeps the seed's
        # warn-only behavior.
        self.failure_timeout_s = _envmod.failure_timeout_secs()
        self._last_seen: Dict[int, float] = {}
        # Plan-affecting env knobs, stamped into every group so all
        # processes execute the same program shape (Response::Flags).
        self._flags = ((_wire.FLAG_HIERARCHICAL_ALLREDUCE
                        if _envmod.hierarchical_allreduce() else 0)
                       | (_wire.FLAG_HIERARCHICAL_ALLGATHER
                          if _envmod.hierarchical_allgather() else 0))
        self.cycle_time_ms = _envmod.cycle_time_ms()
        # Registry metrics (docs/metrics.md): the coordinator is the ONE
        # place that knows which ranks are missing per stalled tensor,
        # so its stall report is exported as gauges here — closing the
        # gap where multi-process stalls were visible only as log lines.
        r = _obs.registry()
        self._m_stalled_count = r.gauge(
            "hvdtpu_coordinator_stalled_tensors",
            "Tensors announced by only a subset of ranks past the stall "
            "warning window (rank-0 coordinator view)").labels()
        self._m_stalled_info = r.gauge(
            "hvdtpu_coordinator_stalled_tensor_seconds",
            "Per stalled tensor: seconds since first announce, labeled "
            "with the ranks that have not announced it")
        self._m_failures = r.counter(
            "hvdtpu_coordinator_failure_events_total",
            "Escalated worker-failure events, by kind")
        self._m_groups = r.counter(
            "hvdtpu_coordinator_groups_planned_total",
            "Fusion groups cut by the coordinator planner").labels()
        self._m_announces = r.counter(
            "hvdtpu_coordinator_announces_total",
            "Announce RPCs processed").labels()
        self._m_alert_notes = r.counter(
            "hvdtpu_coordinator_alert_notes_total",
            "Health alerts forwarded by remote ranks as adaptation "
            "ladder inputs, by alert kind (docs/health.md#adaptation)")
        self._groups_seen = 0
        self._failures_reported: set = set()
        # Live skew telemetry (docs/tracing.md): per-rank announce
        # lateness histograms + straggler election from the announce
        # ticks this service already observes.
        self._skew = _SkewTracker(nproc)
        # Stall→failure blame ledger (docs/adaptation.md): ranks named
        # missing by CONSECUTIVE stall reports, with the tick they were
        # first blamed — past failure_timeout_s the repeat offender is
        # escalated to a typed failure event instead of warned forever.
        # Works for BOTH planners (the fallback's table-based escalation
        # in check_failures never covered the native controller).
        self._stall_blame: Dict[int, float] = {}
        self._escalated_stalls: Dict[int, str] = {}
        # Closed-loop adaptation (docs/adaptation.md): the rank-0 policy
        # ladder over the skew tracker's signal. Off unless
        # HOROVOD_TPU_ADAPTATION=1; eviction additionally requires the
        # elastic failure plane (failure_timeout_s > 0) — on a fixed
        # world an eviction is just a job kill.
        self._base_fusion_threshold = fusion_threshold
        self._policy = None
        self._policy_failures: List[dict] = []
        # Wire-override epochs: [(from_seq, spec)] — groups with seq >=
        # from_seq execute with `spec` ("" = back to raw). Published
        # under _mu BEFORE any group at from_seq can be planned, and
        # shipped whole in every fetch's params, so every process maps
        # seq → spec identically (the agreement that makes a mid-run
        # wire switch safe: a group quantized on one rank and raw on
        # another would be two different SPMD programs). Both producers
        # — the adaptation ladder and the global autotuner — stamp
        # epochs through ONE arbiter so they can never disagree on the
        # value for a seq (docs/autotune.md#arbitration).
        self._arbiter = WireEpochArbiter(self._mu, self._next_plan_seq)
        # Cycle-time override from a tuner move (None until one lands);
        # overlaid on params so it reaches engines on both planner paths.
        self._tuner_cycle_ms: Optional[float] = None
        self._m_tuner_moves = r.counter(
            "hvdtpu_autotune_coord_moves_total",
            "Global-autotuner move proposals arbitrated by the "
            "coordinator, by knob and verdict (docs/autotune.md)")
        if _envmod.adaptation_enabled():
            from ..adaptation.policy import (AdaptationConfig,
                                             AdaptationPolicy)
            self._policy = AdaptationPolicy(
                AdaptationConfig.from_env(),
                allow_evict=self.failure_timeout_s > 0)
            self._last_policy_tick = time.monotonic()
        self._ctl = None
        if native is not False:
            try:
                from ..runtime import native as _native_mod
                core = _native_mod.load(required=(native is True))
                if core is not None:
                    self._ctl = _native_mod.NativeController(
                        core, nproc,
                        virtual_size if virtual_size > 0 else (1 << 30),
                        fusion_threshold, self.cycle_time_ms,
                        self.stall_warning_s,
                        _envmod.hierarchical_allreduce(),
                        _envmod.hierarchical_allgather(),
                        _envmod.autotune(),
                        _envmod.autotune_log() or "")
            except Exception as e:
                if native is True:
                    raise
                _log.warning("native controller unavailable, using Python "
                             "fallback planner: %s", e)
        # Fully initialized — NOW answer the phone (see the note at the
        # top of this constructor).
        super().__init__("horovod-tpu-coordinator", key, port=port)

    @property
    def native_active(self) -> bool:
        return self._ctl is not None

    def history_len(self) -> int:
        """Groups retained in the (pruned) history — observability/tests."""
        if self._ctl is not None:
            return self._ctl.group_count() - self._ctl.base_seq()
        with self._mu:
            return len(self._groups)

    def base_seq(self) -> int:
        """First un-pruned sequence number."""
        if self._ctl is not None:
            return self._ctl.base_seq()
        with self._mu:
            return self._base_seq

    # ------------------------------------------------------------- protocol

    def _handle(self, req, client_address):
        if isinstance(req, AnnounceFetchRequest):
            if req.announce is not None:
                self._announce(req.announce)
            return self._fetch(req.fetch)
        if isinstance(req, AnnounceRequest):
            return self._announce(req)
        if isinstance(req, FetchRequest):
            return self._fetch(req)
        if isinstance(req, ClockProbeRequest):
            # Answer with the coordinator's monotonic clock, sampled as
            # close to the reply as possible — the worker halves the
            # round trip around this reading (min-RTT sample wins).
            return ClockProbeResponse(int(time.monotonic() * 1e6))
        if isinstance(req, AlertNoteRequest):
            # Remote detector alert → ladder pressure on the policy
            # (docs/health.md#adaptation). Accepted (and counted) even
            # without a policy so the sender's path stays uniform.
            self._m_alert_notes.labels(kind=str(req.kind)).inc()
            if self._policy is not None:
                self._policy.note_alert(req.kind, req.rank,
                                        time.monotonic())
            return AnnounceResponse()
        if isinstance(req, TunerMoveRequest):
            return self._tuner_move(req)
        if isinstance(req, FingerprintRequest):
            # Divergence probe (docs/numerics.md#fingerprints): stash
            # this rank's digests; the numerics plane compares once the
            # step's set is complete and fires rank_divergence itself.
            try:
                from ..observability import numerics as _numerics
                _numerics.record_fingerprint(
                    int(req.rank), int(req.step), dict(req.digests),
                    self._nproc)
            except Exception as e:  # telemetry never breaks the plane
                _log.warning("fingerprint compare failed: %s", e)
            return AnnounceResponse()
        return super()._handle(req, client_address)

    def _announce(self, req: AnnounceRequest) -> AnnounceResponse:
        self._m_announces.inc()
        with self._cv:
            self._last_seen[req.rank] = time.monotonic()
            if req.announce_id:
                if req.announce_id <= self._last_announce.get(req.rank, 0):
                    return AnnounceResponse()  # duplicate delivery (retry)
                self._last_announce[req.rank] = req.announce_id
            if req.shutdown:
                # Any rank announcing shutdown stops the world — the
                # reference ORs the shutdown flag into the response list
                # (operations.cc:2125-2128).
                self._shutdown = True
                if self._ctl is not None:
                    self._ctl.announce(_wire.encode_request_list(
                        req.rank, [], shutdown=True))
                self._cv.notify_all()
                return AnnounceResponse()
            if self._ctl is not None:
                # Native planner: feed message.cc-codec bytes (encoding
                # dict announces from fallback-mode workers on the way in).
                payload = req.payload
                if payload is None:
                    payload = _wire.encode_request_list(req.rank,
                                                        req.requests)
                if _obs.enabled():
                    # Skew telemetry needs the tensor names; native-engine
                    # announces carry them only in the codec bytes, so
                    # decode (pure-python struct parse) — gated on the
                    # metrics flag to keep the disabled path free.
                    if req.requests:
                        names = [r["name"] for r in req.requests]
                    else:
                        try:
                            names = [r["name"] for r in
                                     _wire.decode_request_list(payload)[0]]
                        except Exception:
                            names = []
                    self._skew.note(req.rank, names, time.monotonic())
                self._ctl.announce(payload)
                if req.complete:
                    # Burst-complete announce: plan NOW if no tensor is
                    # left partial — the last completing rank cuts the
                    # groups, skipping the quiet window entirely.
                    self._ctl.plan_ready()
                self._last_announce_t = time.monotonic()
                self._cv.notify_all()  # waiters recheck group_count
                return AnnounceResponse()
            requests = req.requests
            if req.payload is not None:
                decoded, sd = _wire.decode_request_list(req.payload)
                if sd:
                    self._shutdown = True
                    self._cv.notify_all()
                    return AnnounceResponse()
                requests = decoded
            self._skew.note(req.rank, [r["name"] for r in requests],
                            time.monotonic())
            for r in requests:
                e = self._table.get(r["name"])
                if e is None:
                    e = _Entry(self._order)
                    self._order += 1
                    self._table[r["name"]] = e
                if req.rank in e.ranks:
                    continue  # duplicate announce (client retry)
                e.ranks.add(req.rank)
                e.op_by_rank[req.rank] = int(r["op"])
                e.dtype_by_rank[req.rank] = str(r["dtype"])
                e.shape_by_rank[req.rank] = tuple(r["shape"])
                e.root_by_rank[req.rank] = int(r.get("root_rank", -1))
                e.device_by_rank[req.rank] = int(r.get("device", -1))
                # Payload bytes from shape × dtype, exactly as the native
                # planner derives them from the wire Request — both
                # planners must fuse identically.
                nbytes = _wire.dtype_size(_wire.dtype_enum(str(r["dtype"])))
                for d in r["shape"]:
                    nbytes *= int(d)
                e.nbytes = max(e.nbytes, nbytes)
                # Mismatched op/dtype is detected in _validate once every
                # rank has announced — SPMD code enqueues the same name on
                # all ranks, so a colliding name still reaches quorum and
                # becomes an error group (operations.cc:321-395) rather
                # than a divergent program.
                if len(e.ranks) == self._nproc:
                    if not self._ready and self._oldest_ready_t is None:
                        self._oldest_ready_t = time.monotonic()
                    self._ready.append((r["name"], e))
                    del self._table[r["name"]]
            # Plan ONLY on a burst-complete announce with no partial
            # tensor left (the last completing rank cuts the groups);
            # otherwise groups are cut by _maybe_plan_locked once the
            # announce stream is quiescent (mirrors the native
            # controller). Cutting groups at announce-chunk boundaries
            # would make group composition timing-dependent, and every
            # distinct composition is a distinct fused XLA program — a
            # recompile per step instead of a cache hit.
            if req.complete and not self._table and self._ready:
                self._plan_locked()
            self._last_announce_t = time.monotonic()
            self._cv.notify_all()
        return AnnounceResponse()

    def _maybe_plan_locked(self) -> None:
        """Quiescence planner (native: hvdtpu_ctl_maybe_plan): plan once
        no tensor is partially announced and no announce has arrived for
        PLAN_DEBOUNCE_S — i.e. every rank's cycle-chunked announces of one
        burst have landed, so the group composition is the full burst,
        deterministic across steps."""
        if not self._ready:
            return
        now = time.monotonic()
        quiet = (not self._table
                 and now - self._last_announce_t >= PLAN_DEBOUNCE_S)
        overdue = (self._oldest_ready_t is not None
                   and now - self._oldest_ready_t
                   >= PLAN_DEBOUNCE_S * PLAN_MAX_DEFER_FACTOR)
        if quiet or overdue:
            self._plan_locked()

    def check_stalls(self) -> List[Tuple[str, str]]:
        """Warn about tensors announced by only a subset of ranks past the
        stall window, naming the missing ranks — the reference
        coordinator's report (operations.cc:1644-1668). Returns
        (tensor_name, display_line) pairs (also logged, and shipped to
        every worker through the fetch response) so consumers key on the
        structured name instead of re-parsing the display text."""
        now = time.monotonic()
        lines: List[Tuple[str, str]] = []
        entries: List[Tuple[str, float, str]] = []
        with self._mu:
            if (self.stall_warning_s <= 0
                    or now - self._last_stall_check < self.stall_warning_s):
                return lines
            self._last_stall_check = now
            # Ticks of tensors stuck partially announced are the stall
            # detector's story from here on; cap tracker memory.
            self._skew.prune(now - 2.0 * self.stall_warning_s)
            if self._ctl is not None:
                lines = self._ctl.stalled()
                from .collective import _missing_ranks_of
                # The native wire carries no age; the stall window is a
                # guaranteed lower bound (a tensor only appears once it
                # has waited at least that long).
                entries = [(name, self.stall_warning_s,
                            _missing_ranks_of(line))
                           for name, line in lines]
            else:
                for name, e in sorted(self._table.items()):
                    if now - e.first_seen > self.stall_warning_s:
                        missing = sorted(set(range(self._nproc)) - e.ranks)
                        lines.append(
                            (name,
                             f"{name} [missing ranks: "
                             f"{', '.join(map(str, missing))}]"))
                        entries.append(
                            (name, now - e.first_seen,
                             ",".join(map(str, missing))))
        # Repeat-offender escalation (docs/adaptation.md): a rank named
        # missing by stall reports spanning more than failure_timeout_s
        # becomes a typed failure event (check_failures) instead of a
        # warning loop — the drop_announce fault is exactly this shape
        # (its fetch heartbeat stays alive, so only the stall report
        # ever names it). Blame entries for ranks a report no longer
        # names are cleared: the episode resolved.
        if self.failure_timeout_s > 0:
            named: set = set()
            for name, age, missing in entries:
                for tok in missing.split(","):
                    tok = tok.strip()
                    if tok.isdigit():
                        named.add(int(tok))
            for rk in named:
                first = self._stall_blame.setdefault(rk, now)
                if now - first > self.failure_timeout_s \
                        and rk not in self._escalated_stalls:
                    self._escalated_stalls[rk] = (
                        f"rank {rk} named missing by stall reports for "
                        f"{now - first:.1f}s (> failure timeout "
                        f"{self.failure_timeout_s:.1f}s)")
            for rk in list(self._stall_blame):
                if rk not in named:
                    del self._stall_blame[rk]
        # Gauge export of the authoritative report: cleared and re-set
        # each completed check, so a resolved episode zeroes out instead
        # of naming completed tensors forever.
        self._m_stalled_info.clear()
        self._m_stalled_count.set(len(entries))
        for name, age, missing in entries:
            self._m_stalled_info.labels(
                tensor=name, missing_ranks=missing).set(age)
        if lines:
            # Quantitative tail (docs/tracing.md): the warning names the
            # missing ranks; the skew tracker says HOW LATE those ranks
            # have recently been, so a straggler is diagnosable from the
            # log alone — no trace collection required.
            report = "\n".join(line for _, line in lines)
            late = self._skew.recent_lateness_by_rank()
            if late:
                report += (
                    "\nRecent negotiate lateness by rank "
                    "(decay-weighted mean): "
                    + ", ".join(f"rank {rk}: {v * 1e3:.1f} ms"
                                for rk, v in sorted(late.items())))
            _log.warning(
                "One or more tensors were submitted to be reduced, "
                "gathered or broadcasted by subset of ranks and are "
                "waiting for the remainder of ranks for more than %d "
                "seconds. This may indicate that different ranks are "
                "trying to submit different tensors or that only subset "
                "of ranks is submitting tensors, which will cause "
                "deadlock.\nStalled ops:\n%s",
                int(self.stall_warning_s), report)
        return lines

    def check_failures(self) -> List[dict]:
        """Escalated failure events (elastic recovery): ranks whose
        control-plane heartbeat (announce/fetch) went silent past
        ``failure_timeout_s``, and — on the fallback planner, which owns
        the Python tensor table — tensors stuck partially announced past
        it, attributed to their missing ranks. Empty when escalation is
        off (the default) or nothing is overdue. Ranks that have never
        contacted the coordinator are NOT flagged: initial rendezvous
        may legitimately take longer than the failure window."""
        if self.failure_timeout_s <= 0:
            return []
        now = time.monotonic()
        failures: List[dict] = []
        # Policy evictions (docs/adaptation.md) persist until the world
        # re-forms: a rank idling in user code at escalation time must
        # still receive its obituary on its NEXT fetch, or it would hang
        # in a quorum its evicted peer can never complete.
        failures.extend(self._policy_failures)
        for rank, detail in sorted(self._escalated_stalls.items()):
            failures.append({"rank": rank, "kind": "stall",
                             "detail": detail})
        for rank, t in sorted(self._last_seen.items()):
            if now - t > self.failure_timeout_s:
                failures.append({
                    "rank": rank, "kind": "heartbeat_timeout",
                    "detail": (f"rank {rank} last contacted the "
                               f"coordinator {now - t:.1f}s ago "
                               f"(failure timeout "
                               f"{self.failure_timeout_s:.1f}s)")})
        if self._ctl is None:
            with self._mu:
                for name, e in sorted(self._table.items()):
                    age = now - e.first_seen
                    if age > self.failure_timeout_s:
                        missing = sorted(set(range(self._nproc)) - e.ranks)
                        failures.append({
                            "rank": missing[0] if missing else -1,
                            "kind": "stall",
                            "detail": (f"tensor {name} waited {age:.1f}s "
                                       f"(> failure timeout) for ranks "
                                       f"{missing}")})
        for f in failures:
            # check_failures recomputes on every fetch; count each
            # distinct (rank, kind) event once.
            key = (f["rank"], f["kind"])
            if key not in self._failures_reported:
                self._failures_reported.add(key)
                self._m_failures.labels(kind=f["kind"]).inc()
        return failures

    # ----------------------------------------------------------- adaptation

    def _next_plan_seq(self) -> int:
        """First not-yet-planned group seq (caller holds ``_mu``)."""
        if self._ctl is not None:
            return self._ctl.group_count()
        return len(self._groups) + self._base_seq

    @property
    def _wire_epochs(self) -> List[Tuple[int, str]]:
        return self._arbiter.wire_epochs

    @property
    def _fusion_epochs(self) -> List[Tuple[int, int]]:
        return self._arbiter.fusion_epochs

    def _publish_wire_epoch(self, spec: Optional[str],
                            source: str = "ladder") -> dict:
        """Record that groups planned from NOW on use ``spec`` ("" =
        raw). The arbiter takes ``_mu`` so the epoch boundary is ordered
        against planning: any group with seq >= from_seq is planned
        after the epoch exists, hence every fetch serving it also
        carries the epoch in params — all processes agree. Returns the
        arbiter verdict ({"accepted", "from_seq", "reason"})."""
        return self._arbiter.propose_wire(source, spec)

    def _tuner_move(self, req: TunerMoveRequest) -> TunerMoveResponse:
        """Arbitrate one global-autotuner move (docs/autotune.md): wire
        and fusion knobs stamp epochs through the same arbiter the
        adaptation ladder uses; cycle time applies live. Anything the
        arbiter rejects (ladder already owns the pending seq, no-op,
        unknown knob) reports as a rejected move — the driver treats
        that as "knob unavailable", never as an error."""
        knob, value = str(req.knob), req.value
        if knob == "dcn_wire_spec":
            res = self._arbiter.propose_wire("tuner", str(value or ""))
        elif knob == "fusion_threshold_mb":
            nbytes = int(float(value) * (1 << 20))
            res = self._arbiter.propose_fusion("tuner", nbytes)
            if res["accepted"]:
                # The planner cuts future groups with the tuned cap;
                # the ladder's shrink (a safety reaction) still scales
                # whatever base the tuner picked.
                self._base_fusion_threshold = nbytes
                shrink = (self._policy is not None
                          and self._policy.shrink_active())
                self.fusion_threshold = (
                    nbytes // self._policy.config.shrink_factor
                    if shrink else nbytes)
                if self._ctl is not None:
                    self._ctl.set_fusion_threshold(self.fusion_threshold)
        elif knob == "cycle_time_ms":
            self._tuner_cycle_ms = float(value)
            self.cycle_time_ms = float(value)
            res = {"accepted": True, "from_seq": -1, "reason": "live"}
        else:
            res = {"accepted": False, "from_seq": -1,
                   "reason": "unknown_knob"}
        self._m_tuner_moves.labels(
            knob=knob, verdict=("accepted" if res["accepted"]
                                else res["reason"])).inc()
        try:
            from ..observability import flight_recorder as _flight
            _flight.recorder().note("autotune", (
                "coord_move", knob, str(value), None, None,
                f"{res['reason']} from_seq={res['from_seq']}"))
        except Exception:
            pass
        return TunerMoveResponse(res["accepted"], res["from_seq"],
                                 res["reason"])

    def _maybe_adapt(self) -> None:
        """One policy evaluation (time-gated to interval_s), applied to
        the coordinator's authoritative knobs: the fusion threshold the
        planner cuts groups with, the wire-override epoch list, and —
        at the top of the ladder — a ``slow_rank`` failure event for
        the elastic driver."""
        if self._policy is None:
            return
        now = time.monotonic()
        if now - self._last_policy_tick < self._policy.config.interval_s:
            return
        self._last_policy_tick = now
        # Health alerts fired in THIS process (rank 0's own detector
        # plane) feed the ladder directly; remote ranks arrive via
        # AlertNoteRequest (docs/health.md#adaptation).
        try:
            from ..observability import health as _health
            for a in _health.drain_policy_alerts():
                self._policy.note_alert(a["kind"], a["rank"], now)
        except Exception:  # never fail planning over telemetry
            pass
        prev_wire = self._policy.wire_spec()
        events = self._policy.observe(
            self._skew.recent_lateness_by_rank(), now)
        if not events:
            return
        shrink = self._policy.shrink_active()
        self.fusion_threshold = (
            self._base_fusion_threshold // self._policy.config.shrink_factor
            if shrink else self._base_fusion_threshold)
        if self._ctl is not None:
            self._ctl.set_fusion_threshold(self.fusion_threshold)
        wire = self._policy.wire_spec()
        if wire != prev_wire:
            self._publish_wire_epoch(wire)
        for ev in events:
            if ev["name"] == "evict" and ev["action"] == "escalate":
                self._policy_failures.append({
                    "rank": ev["rank"], "kind": "slow_rank",
                    "detail": (
                        f"rank {ev['rank']} evicted by the adaptation "
                        f"policy: negotiate lateness "
                        f"{ev['lateness_s'] * 1e3:.1f} ms sustained above "
                        f"{self._policy.config.threshold_s * 1e3:.1f} ms "
                        "through every degradation tier "
                        f"({', '.join(self._policy.config.tiers[:-1])})")})

    def _adapted_params(self, params: dict) -> dict:
        """Overlay the policy's knobs on a params dict (either planner's):
        the shrunk fusion threshold and the wire-epoch list every engine
        needs to map group seq → wire spec."""
        if (self._policy is None and not self._wire_epochs
                and not self._fusion_epochs
                and self._tuner_cycle_ms is None):
            return params
        params = dict(params)
        params["fusion_threshold"] = self.fusion_threshold
        if self._tuner_cycle_ms is not None:
            params["cycle_time_ms"] = self._tuner_cycle_ms
        if self._wire_epochs:
            # No lock (the fallback fetch path already holds _mu via its
            # condition when building params): list appends are atomic,
            # and any epoch relevant to a served group was fully
            # appended — under _mu — before that group was planned.
            params["wire_epochs"] = [list(e) for e in self._wire_epochs]
        if self._fusion_epochs:
            params["fusion_epochs"] = [list(e) for e in
                                       self._fusion_epochs]
        return params

    def _fetch(self, req: FetchRequest) -> FetchResponse:
        stall = self.check_stalls()
        self._maybe_adapt()
        # Refresh the fetching rank's heartbeat BEFORE checking: a rank
        # returning after a long idle gap must not be handed its own
        # obituary.
        self._last_seen[req.rank] = time.monotonic()
        failures = self.check_failures()
        deadline = time.monotonic() + max(0.0, req.wait_s)
        if self._ctl is not None:
            # Autotune cadence: rank 0's fetch marks one coordinator-side
            # engine cycle (the reference samples once per RunLoopOnce,
            # parameter_manager.cc:144-170).
            if req.rank == 0:
                self._ctl.tick()
            with self._cv:
                while (self._ctl.group_count() <= req.after_seq
                       and not self._ctl.shutdown_flag()
                       and time.monotonic() < deadline):
                    # Sliced wait: each slice polls the quiescence
                    # planner so groups are cut PLAN_DEBOUNCE_S after the
                    # announce stream goes quiet.
                    self._cv.wait(timeout=max(0.0, min(
                        PLAN_DEBOUNCE_S,
                        deadline - time.monotonic())))
                    if self._ctl.maybe_plan() > req.after_seq:
                        self._cv.notify_all()
                        break
                if (self._ctl.group_count() <= req.after_seq
                        and not self._ctl.shutdown_flag()
                        and time.monotonic() - self._last_announce_t
                        >= PLAN_DEBOUNCE_S):
                    # Timed out with nothing new AND the announce stream
                    # is quiet: fire the planning valve so fully-announced
                    # tensors are not stalled behind a lingering partial
                    # announce. The quiet guard keeps a short-wait fetch
                    # (issued mid-burst) from force-cutting a partial
                    # burst into a timing-dependent group.
                    if self._ctl.plan() > req.after_seq:
                        self._cv.notify_all()
                total = self._ctl.group_count()
                if total > self._groups_seen:
                    self._m_groups.inc(total - self._groups_seen)
                    self._groups_seen = total
                payload = self._ctl.fetch(req.rank, req.after_seq)
                groups, shutdown = _wire.decode_response_list(payload,
                                                              self._nproc)
                for i, g in enumerate(groups):
                    g["seq"] = req.after_seq + i
                return FetchResponse(
                    groups, shutdown, payload=payload,
                    params=self._adapted_params(self._ctl.params()),
                    stall=stall, failures=failures)
        with self._cv:
            self._acked[req.rank] = max(self._acked.get(req.rank, 0),
                                        req.after_seq)
            if len(self._acked) == self._nproc:
                floor = min(self._acked.values())
                if floor > self._base_seq:
                    del self._groups[: floor - self._base_seq]
                    self._base_seq = floor
            next_seq = len(self._groups) + self._base_seq
            while (next_seq <= req.after_seq and not self._shutdown
                   and time.monotonic() < deadline):
                # Sliced wait polling the quiescence planner (see the
                # native branch above).
                self._cv.wait(timeout=max(0.0, min(
                    PLAN_DEBOUNCE_S, deadline - time.monotonic())))
                self._maybe_plan_locked()
                next_seq = len(self._groups) + self._base_seq
                if next_seq > req.after_seq:
                    self._cv.notify_all()
            if (next_seq <= req.after_seq and not self._shutdown
                    and time.monotonic() - self._last_announce_t
                    >= PLAN_DEBOUNCE_S):
                # Timed out AND quiet: planning valve (see the native
                # branch) — serve fully-announced work past a lingering
                # partial without cutting an in-progress burst.
                self._plan_locked()
                if len(self._groups) + self._base_seq > next_seq:
                    self._cv.notify_all()
            start = max(0, req.after_seq - self._base_seq)
            groups = self._groups[start:]
            params = self._adapted_params(
                {"fusion_threshold": self.fusion_threshold,
                 "cycle_time_ms": self.cycle_time_ms,
                 "flags": self._flags, "autotune_active": False,
                 "autotune_done": False})
            return FetchResponse(
                groups, self._shutdown,
                payload=_wire.encode_response_list(groups, self._shutdown,
                                                   self._nproc),
                params=params, stall=stall, failures=failures)

    # ------------------------------------------------------------- planning

    def _validate(self, name: str, e: _Entry) -> str:
        """ConstructMPIResponse's cross-rank checks (operations.cc:321-523)."""
        if len(set(e.op_by_rank.values())) > 1:
            ops = sorted({_OP_NAMES.get(o, str(o))
                          for o in e.op_by_rank.values()})
            return (f"Mismatched collective operations for tensor {name}: "
                    f"ranks requested {ops} (operations.cc:354-360)")
        if len(set(e.dtype_by_rank.values())) > 1:
            return (f"Mismatched data types for tensor {name}: "
                    f"{sorted(set(e.dtype_by_rank.values()))} "
                    "(operations.cc:341-352)")
        shapes = list(e.shape_by_rank.values())
        op_name = _OP_NAMES.get(e.op, str(e.op))
        if e.op in (0, 2):  # allreduce / broadcast: identical shapes
            if any(s != shapes[0] for s in shapes):
                return (f"Mismatched {op_name} tensor shapes: tensor {name} "
                        f"has different shapes on different ranks: "
                        f"{sorted(set(shapes))}")
        if e.op == 1:  # allgather: dims beyond the first must agree
            rests = {s[1:] for s in shapes}
            if len(rests) > 1 or any(len(s) == 0 for s in shapes):
                return (f"Mismatched allgather tensor shapes: tensor {name} "
                        "must agree on every dimension except the first "
                        f"across ranks; got {sorted(set(shapes))}")
        if e.op == 2:  # broadcast: same root everywhere
            roots = sorted(set(e.root_by_rank.values()))
            if len(roots) > 1:
                # Same wording as ConstructResponse (coordinator.cc) /
                # the reference (operations.cc:448-478).
                return (f"Mismatched root ranks: One rank specified root "
                        f"rank {roots[0]}, but another rank specified "
                        f"root rank {roots[1]}.")
        # Execution-semantics fingerprint (the wire device slot — the
        # reference's device-consistency role, operations.cc:480-497):
        # ranks passing different average/prescale/postscale/sharded
        # would execute DIFFERENT programs for one agreed group.
        devs = set(e.device_by_rank.values())
        if len(devs) > 1:
            return (f"Mismatched execution attributes for tensor {name}: "
                    "ranks passed different average/prescale/postscale/"
                    "sharded arguments (fingerprints "
                    f"{sorted(devs)}).")
        return ""

    def _plan_locked(self):
        """Greedy fusion with look-ahead over the ready list
        (operations.cc:2149-2265): same (op, dtype, root) under the byte
        threshold fuse into one group; error entries become singleton
        error groups."""
        remaining = self._ready
        self._ready = []
        self._oldest_ready_t = None
        n_before = len(self._groups)
        while remaining:
            name, e = remaining.pop(0)
            err = self._validate(name, e)
            if err:
                # op 3 == Response::ERROR — same verdict encoding as the
                # native planner (message.h) so plans stay identical.
                self._groups.append({
                    "seq": len(self._groups) + self._base_seq, "op": 3,
                    "names": [name], "error": err, "root_rank": -1,
                    "sizes": {}, "flags": self._flags})
                continue
            group_names = [name]
            sizes = {}
            if e.op == 1:
                sizes[name] = [e.shape_by_rank[r][0]
                               for r in range(self._nproc)]
            total = e.nbytes
            keep = []
            for name2, e2 in remaining:
                if (e2.op == e.op and e2.dtype == e.dtype
                        and not self._validate(name2, e2)
                        and e2.root_by_rank == e.root_by_rank
                        and e2.device_by_rank == e.device_by_rank
                        and total + e2.nbytes <= self.fusion_threshold):
                    group_names.append(name2)
                    total += e2.nbytes
                    if e2.op == 1:
                        sizes[name2] = [e2.shape_by_rank[r][0]
                                        for r in range(self._nproc)]
                else:
                    keep.append((name2, e2))
            remaining = keep
            self._groups.append({
                "seq": len(self._groups) + self._base_seq, "op": e.op,
                "names": group_names, "error": "",
                "root_rank": next(iter(e.root_by_rank.values()), -1),
                "sizes": sizes, "flags": self._flags})
        self._m_groups.inc(len(self._groups) - n_before)


    def shutdown(self) -> None:
        # The native controller handle is deliberately NOT destroyed:
        # socketserver handler threads can still be mid-request after
        # shutdown() returns, and a freed controller under a live call is
        # a use-after-free. The reference keeps its global state for the
        # process lifetime for the same reason (operations.cc comment at
        # hvdtpu_shutdown); a controller is a few KB.
        super().shutdown()


class CoordinatorClient:
    """Per-process client — the worker half of RunLoopOnce
    (operations.cc:2323-2377).

    Post-rendezvous RPC failures are retried with BOUNDED exponential
    backoff plus deterministic per-rank jitter (every worker polls the
    coordinator each cycle; on a coordinator restart, synchronized
    retries would stampede the fresh socket — decorrelating them is the
    standard thundering-herd fix), then surface as a typed
    :class:`CoordinatorUnreachableError` naming the endpoint and budget
    — previously a worker polling a dead/restarting coordinator hung in
    the transport or died with an uninformative socket error."""

    def __init__(self, addresses: List[Tuple[str, int]], key: bytes,
                 rank: int, retries: Optional[int] = None,
                 backoff_s: Optional[float] = None):
        # Patient FIRST connection only: rank 0 binds the coordinator
        # lazily on its first collective, which may come seconds after
        # the other ranks' (e.g. rank 0 reads a checkpoint first) — the
        # reference's workers block in MPI_Gather until rank 0 arrives.
        # After rendezvous this layer owns the retry schedule, so the
        # inner client attempts each request once.
        from ..utils import env as _envmod
        self._client = BasicClient(addresses, key, attempts=1,
                                   connect_attempts=300)
        self._addresses = list(self._client._addresses)
        self._rank = rank
        self._retries = (retries if retries is not None
                         else _envmod.coord_retries())
        self._backoff_s = (backoff_s if backoff_s is not None
                           else _envmod.coord_backoff_s())
        self._backoff_max_s = 2.0
        # Deterministic per-rank jitter stream: reproducible runs, and
        # distinct ranks decorrelate without sharing a seed.
        import random
        self._jitter = random.Random(0x9E3779B1 * (rank + 1))
        self._ever_ok = False
        self.last_seq = 0
        self._announce_seq = 0
        # Fault harness (docs/adaptation.md): the drop_announce fault
        # suppresses this client's announce legs. Resolved once —
        # without a spec this is a None attribute check per announce.
        from ..adaptation import faults as _faults
        self._faults = _faults.injector()

    def _rpc(self, req):
        """One coordinator RPC with the bounded retry/backoff/jitter
        schedule; raises CoordinatorUnreachableError when the budget is
        spent (or immediately when rendezvous itself — which has its own
        patience window inside BasicClient — never succeeded)."""
        delay = self._backoff_s
        last: Optional[Exception] = None
        for attempt in range(max(1, self._retries)):
            try:
                resp = self._client.request(req)
                self._ever_ok = True
                return resp
            except (ConnectionError, OSError) as e:
                last = e
                if not self._ever_ok or attempt >= self._retries - 1:
                    break
                time.sleep(delay * (0.5 + self._jitter.random()))
                delay = min(delay * 2.0, self._backoff_max_s)
        # Flight-recorder evidence (docs/postmortem.md): a worker whose
        # control plane died records WHY before the typed error unwinds
        # the engine — the postmortem tool reads this as "coordinator
        # (rank 0) was unreachable from rank N at t".
        from ..observability import flight_recorder as _flight
        _flight.recorder().note("coord_error", (
            f"coordinator at {self._addresses} unreachable after "
            f"{self._retries} attempts: {last}",))
        raise CoordinatorUnreachableError(
            f"rank {self._rank}: coordinator at {self._addresses} "
            f"unreachable after {self._retries} attempts with "
            f"exponential backoff (base {self._backoff_s:.2f}s): {last}. "
            "The rank-0 process is dead or partitioned; in elastic runs "
            "the driver will re-rendezvous the surviving world."
        ) from last

    def _drop_announce(self) -> bool:
        return (self._faults is not None
                and self._faults.drop_announce_active())

    def announce(self, requests: List[dict],
                 complete: bool = False) -> None:
        if self._drop_announce():
            return
        self._announce_seq += 1
        self._rpc(AnnounceRequest(self._rank, requests,
                                  announce_id=self._announce_seq,
                                  complete=complete))

    def announce_bytes(self, payload: bytes,
                       complete: bool = False) -> None:
        """Announce a pre-serialized RequestList (message.cc codec) — the
        native engine's path: the bytes the C++ core serialized travel
        verbatim to the controller's C++ parser."""
        if self._drop_announce():
            return
        self._announce_seq += 1
        self._rpc(AnnounceRequest(
            self._rank, [], announce_id=self._announce_seq,
            payload=payload, complete=complete))

    def fetch(self, wait_s: float = 0.0) -> FetchResponse:
        resp = self._rpc(
            FetchRequest(self._rank, self.last_seq, wait_s))
        if resp.groups:
            self.last_seq = resp.groups[-1]["seq"] + 1
        return resp

    def announce_fetch(self, requests: Optional[List[dict]] = None,
                       payload: Optional[bytes] = None,
                       complete: bool = False,
                       wait_s: float = 0.0) -> FetchResponse:
        """Both cycle legs in ONE round trip (AnnounceFetchRequest):
        announce newly-ready requests (dicts or pre-serialized bytes),
        then long-poll the agreed group sequence."""
        ann = None
        if (requests or payload is not None) and not self._drop_announce():
            self._announce_seq += 1
            ann = AnnounceRequest(self._rank, requests or [],
                                  announce_id=self._announce_seq,
                                  payload=payload, complete=complete)
        resp = self._rpc(AnnounceFetchRequest(
            ann, FetchRequest(self._rank, self.last_seq, wait_s)))
        if resp.groups:
            self.last_seq = resp.groups[-1]["seq"] + 1
        return resp

    def clock_sync(self, probes: int = 8) -> dict:
        """NTP-style clock-alignment handshake against the rank-0
        coordinator (docs/tracing.md): ``probes`` round trips, each
        estimating ``offset = t_coord + rtt/2 - t_recv`` (the coordinator
        clock's lead over ours, assuming symmetric paths); the
        minimum-RTT sample wins — it bounds the asymmetry error by
        rtt/2, so the cleanest round trip gives the tightest estimate.

        Returns ``{"offset_s", "rtt_s", "probes"}`` where ``offset_s``
        is the estimated rank-0-monotonic minus local-monotonic, for the
        per-rank trace clock header."""
        best_rtt = None
        best_offset = 0.0
        for _ in range(max(1, probes)):
            t0 = time.monotonic()
            resp = self._rpc(ClockProbeRequest(self._rank))
            t1 = time.monotonic()
            rtt = t1 - t0
            offset = resp.t_mono_us / 1e6 + rtt / 2.0 - t1
            if best_rtt is None or rtt < best_rtt:
                best_rtt, best_offset = rtt, offset
        return {"offset_s": best_offset, "rtt_s": best_rtt,
                "probes": int(probes)}

    def note_alert(self, kind: str, rank: Optional[int] = None,
                   severity: str = "warning", value: float = 0.0) -> None:
        """Forward one health alert to the coordinator as an adaptation
        ladder input (docs/health.md#adaptation). ONE attempt, errors
        swallowed — alerting is advisory; the retry/backoff machinery
        exists for the collective path, not telemetry."""
        try:
            self._client.request(AlertNoteRequest(
                self._rank if rank is None else int(rank), str(kind),
                str(severity), float(value)))
        except Exception:
            pass

    def note_fingerprint(self, step: int, digests: dict) -> None:
        """Ship this rank's param fingerprints for ``step`` to the
        rank-0 collector (docs/numerics.md#fingerprints). ONE attempt,
        errors swallowed — a dropped probe is a skipped compare."""
        try:
            self._client.request(FingerprintRequest(
                self._rank, int(step), dict(digests)))
        except Exception:
            pass

    def tuner_move(self, knob: str, value) -> dict:
        """Propose one global-autotuner move to the coordinator-side
        arbiter (docs/autotune.md). Returns the verdict dict
        ``{"accepted", "from_seq", "reason"}``; an unreachable
        coordinator reports as a rejected move — the tuner skips the
        knob rather than stalling the job over an optimization."""
        try:
            resp = self._rpc(TunerMoveRequest(self._rank, str(knob),
                                              value))
            return {"accepted": bool(resp.accepted),
                    "from_seq": int(resp.from_seq),
                    "reason": str(resp.reason)}
        except Exception:
            return {"accepted": False, "from_seq": -1,
                    "reason": "unreachable"}

    def announce_shutdown(self) -> None:
        try:
            self._client.request(
                AnnounceRequest(self._rank, [], shutdown=True))
        except Exception:
            pass  # coordinator may already be gone at teardown


# --------------------------------------------------------------------------
# Process wiring
# --------------------------------------------------------------------------

def control_key() -> bytes:
    """HMAC key for the control plane, from the launcher-provided env.

    There is deliberately NO fallback derived from the control address:
    the service unpickles authenticated frames, so a guessable key would
    hand code execution to anyone who can reach the port. Processes that
    did not receive a key must fail loudly (the reference likewise
    requires ``_HOROVOD_SECRET_KEY`` for its RPC plane,
    spark/util/secret.py:21-36)."""
    v = os.environ.get(SECRET_ENV)
    if v:
        return decode_key(v)
    raise RuntimeError(
        f"{SECRET_ENV} is not set. Multi-process eager collectives "
        "authenticate their control plane with a shared secret; launch "
        "workers with `python -m horovod_tpu.runner` (which mints one), "
        "or export the same random key on every process.")


def control_endpoint() -> Optional[Tuple[str, int]]:
    v = os.environ.get(CONTROL_ENV)
    if not v:
        return None
    host, port = v.rsplit(":", 1)
    return host, int(port)


def start_coordinator(nproc: int, fusion_threshold: int,
                      virtual_size: int = 0) -> CoordinatorService:
    """Start the rank-0 coordinator, binding the launcher-published port
    from HOROVOD_TPU_CONTROL. Without a published endpoint (single-host
    tests talking to it in-process) an ephemeral port and a random key
    are used — nothing off this host can authenticate."""
    ep = control_endpoint()
    key = control_key() if (ep or os.environ.get(SECRET_ENV)) \
        else make_secret_key()
    return CoordinatorService(nproc, key,
                              fusion_threshold=fusion_threshold,
                              port=ep[1] if ep else 0,
                              virtual_size=virtual_size)
