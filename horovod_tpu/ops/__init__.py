"""Collective ops package.

- :mod:`collective` — eager enqueue API, async handles, fusion cycle
  (reference: horovod/common/operations.cc enqueue + torch/mpi_ops.py).
- :mod:`injit` — collectives for use *inside* jitted SPMD programs
  (psum/all_gather/ppermute over mesh axes) — the path XLA fuses itself.
"""

from .collective import (Handle, allgather, allgather_async, allreduce,
                         allreduce_async, broadcast, broadcast_async,
                         engine, grouped_allreduce, launch_lock, poll,
                         reset_engine, synchronize, HorovodInternalError)

__all__ = [
    "Handle", "allreduce", "allreduce_async", "allgather", "allgather_async",
    "broadcast", "broadcast_async", "grouped_allreduce", "launch_lock",
    "poll", "synchronize", "engine", "reset_engine", "HorovodInternalError",
]
