"""Pallas TPU flash attention — the hot-op kernel for the flagship model.

No reference equivalent (the reference ships no model/attention code at
all — SURVEY.md §5.7); this is the TPU-native kernel for the attention
the transformer (models/transformer.py) runs, written per the Pallas TPU
playbook: blockwise online softmax so the [S, S] score matrix never
materializes in HBM, fp32 accumulation on the MXU, static shapes, grid
iterated sequentially so the running (m, l, acc) statistics live in VMEM
scratch across k-blocks (FlashAttention-2 schedule).

Forward saves the per-row logsumexp; backward recomputes block scores
(the rematerialization trade: O(S) memory instead of O(S^2), extra FLOPs
the MXU has to spare) in two passes — one accumulating dK/dV per
key-block, one accumulating dQ per query-block.

Layout matches the rest of the stack: [batch, seq, heads, head_dim],
internally reshaped to [batch*heads, seq, head_dim]. ``interpret=True``
runs the same kernels through the Pallas interpreter — used by the CPU
test mesh; on TPU they compile to Mosaic.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30  # large-negative instead of -inf: keeps exp() exact zero
#                   without nan from (-inf) - (-inf) in masked-out rows


def _row_ok(start_block: int, block: int, limit: int):
    """[block, 1] validity mask for rows of a cdiv-padded block. Padded
    rows read uninitialized (NaN in interpret mode) memory; every load is
    masked with where() because 0 * NaN still poisons matmul accumulations."""
    rows = start_block * block + jax.lax.broadcasted_iota(
        jnp.int32, (block, 1), 0)
    return rows < limit


def _scores(q, k):
    """q k^T block scores (q pre-scaled), fp32 accumulation.

    The ONE score convention, shared by the masked and unmasked paths
    of the forward and both backward kernels so a convention change
    (bias term, different scaling, ...) cannot desynchronize them."""
    return jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)               # [BQ, BK]


def _masked_scores(q, k, qi, kj, *, causal, block_q, block_k,
                   seq_q, seq_k):
    """_scores with the bounds+causal mask applied.

    Shared by the forward and both backward kernels so a mask change
    (sliding window, segment ids, ...) cannot desynchronize them.
    Returns (scores, valid)."""
    s = _scores(q, k)
    q_pos = (qi * block_q
             + jax.lax.broadcasted_iota(jnp.int32,
                                        (block_q, block_k), 0))
    k_pos = (kj * block_k
             + jax.lax.broadcasted_iota(jnp.int32,
                                        (block_q, block_k), 1))
    # Bounds mask handles block-padded tails (grid is cdiv-rounded);
    # the causal mask stacks on top.
    valid = (q_pos < seq_q) & (k_pos < seq_k)
    if causal:
        valid = valid & (q_pos >= k_pos)
    return jnp.where(valid, s, _NEG_INF), valid


def _block_dispatch(update, *, qi, kj, causal, block_q, block_k,
                    n_q, n_k, seq_q, seq_k):
    """Run ``update(masked)`` with per-block mask specialization.

    Mask construction (two [BQ, BK] iotas + compares + wheres) costs
    several VPU passes over the score block — comparable to the block's
    MXU time — yet only blocks straddling the causal diagonal or a
    cdiv-padded tail need any of it. Interior blocks (the vast majority
    at long sequence: all-but-one block per row for causal 8k/512) take
    the unmasked path. Both specializations are compiled; pl.when on
    the (scalar) block coordinates picks one per grid step."""
    tail = None
    if seq_q % block_q != 0:
        tail = qi == n_q - 1
    if seq_k % block_k != 0:
        t2 = kj == n_k - 1
        tail = t2 if tail is None else (tail | t2)
    if causal:
        # active: block reaches at or below the diagonal.
        active = kj * block_k <= (qi + 1) * block_q - 1
        # edge: block straddles the diagonal (its top-right corner is
        # strictly above it) — the only active blocks with invalid pairs.
        edge = (kj + 1) * block_k - 1 > qi * block_q
        if tail is not None:
            edge = edge | tail

        @pl.when(active & edge)
        def _():
            update(True)

        @pl.when(active & jnp.logical_not(edge))
        def _():
            update(False)
    elif tail is not None:
        @pl.when(tail)
        def _():
            update(True)

        @pl.when(jnp.logical_not(tail))
        def _():
            update(False)
    else:
        update(False)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, l_sc, acc_sc,
                *, scale: float, causal: bool, block_q: int, block_k: int,
                n_q: int, n_k: int, seq_q: int, seq_k: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    def _update(masked):
        # scale folded into q ([BQ, D] pass) instead of into the
        # [BQ, BK] score block.
        if masked:
            q_ok = _row_ok(qi, block_q, seq_q)
            k_ok = _row_ok(kj, block_k, seq_k)
            q = jnp.where(q_ok, q_ref[0], 0) * scale   # [BQ, D]
            k = jnp.where(k_ok, k_ref[0], 0)           # [BK, D]
            v = jnp.where(k_ok, v_ref[0], 0)
            s, valid = _masked_scores(
                q, k, qi, kj, causal=causal, block_q=block_q,
                block_k=block_k, seq_q=seq_q, seq_k=seq_k)
        else:
            q = q_ref[0] * scale
            k = k_ref[0]
            v = v_ref[0]
            s = _scores(q, k)

        m_prev = m_sc[:, 0]                                # [BQ]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])                    # [BQ, BK]
        if masked:
            p = jnp.where(valid, p, 0.0)
        l_sc[:, 0] = l_sc[:, 0] * corr + p.sum(axis=-1)
        acc_sc[:] = (acc_sc[:] * corr[:, None]
                     + jax.lax.dot_general(
                         p.astype(v.dtype), v,
                         (((1,), (0,)), ((), ())),
                         preferred_element_type=jnp.float32))
        m_sc[:, 0] = m_new

    _block_dispatch(_update, qi=qi, kj=kj, causal=causal,
                    block_q=block_q, block_k=block_k, n_q=n_q, n_k=n_k,
                    seq_q=seq_q, seq_k=seq_k)

    @pl.when(kj == n_k - 1)
    def _finalize():
        l = jnp.maximum(l_sc[:, 0], 1e-30)
        o_ref[0] = (acc_sc[:] / l[:, None]).astype(o_ref.dtype)
        lse_ref[0] = (m_sc[:, 0] + jnp.log(l))[:, None]


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_sc, dv_sc,
                *, scale: float, causal: bool, block_q: int, block_k: int,
                n_q: int, n_k: int, seq_q: int, seq_k: int):
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    def _update(masked):
        # All matmul operands stay bf16 (fp32 accumulation via
        # preferred_element_type) — fp32 operands run the MXU at a
        # fraction of its bf16 rate and were the round-4 profile's
        # single largest flash-kernel cost. q is pre-scaled, which
        # also absorbs dK's trailing `* scale` (dK = dS^T (scale Q)).
        if masked:
            q_ok = _row_ok(qi, block_q, seq_q)
            k_ok = _row_ok(kj, block_k, seq_k)
            q = jnp.where(q_ok, q_ref[0], 0) * scale   # [BQ, D]
            k = jnp.where(k_ok, k_ref[0], 0)           # [BK, D]
            v = jnp.where(k_ok, v_ref[0], 0)
            do = jnp.where(q_ok, do_ref[0], 0)
            lse = jnp.where(q_ok, lse_ref[0], 0)
            delta = jnp.where(q_ok, delta_ref[0], 0)
            s, valid = _masked_scores(
                q, k, qi, kj, causal=causal, block_q=block_q,
                block_k=block_k, seq_q=seq_q, seq_k=seq_k)
        else:
            q = q_ref[0] * scale
            k = k_ref[0]
            v = v_ref[0]
            do = do_ref[0]
            lse = lse_ref[0]
            delta = delta_ref[0]
            s = _scores(q, k)
        p = jnp.exp(s - lse)                              # [BQ, BK]
        if masked:
            p = jnp.where(valid, p, 0.0)
        p_lo = p.astype(do.dtype)
        # dV += P^T dO
        dv_sc[:] += jax.lax.dot_general(
            p_lo, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dS = P * (dO V^T - delta)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [BQ, BK]
        ds = p * (dp - delta)
        if masked:
            ds = jnp.where(valid, ds, 0.0)
        # dK += dS^T (scale Q)
        dk_sc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _block_dispatch(_update, qi=qi, kj=kj, causal=causal,
                    block_q=block_q, block_k=block_k, n_q=n_q, n_k=n_k,
                    seq_q=seq_q, seq_k=seq_k)

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_sc, *, scale: float, causal: bool, block_q: int,
               block_k: int, n_q: int, n_k: int, seq_q: int, seq_k: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    def _update(masked):
        # bf16 matmul operands, fp32 accumulation (see _dkv_kernel).
        # The constant `* scale` on dQ moves to _finalize: one [BQ, D]
        # pass per q-block instead of one per (q, k) block pair.
        if masked:
            q_ok = _row_ok(qi, block_q, seq_q)
            k_ok = _row_ok(kj, block_k, seq_k)
            q = jnp.where(q_ok, q_ref[0], 0) * scale
            k = jnp.where(k_ok, k_ref[0], 0)
            v = jnp.where(k_ok, v_ref[0], 0)
            do = jnp.where(q_ok, do_ref[0], 0)
            lse = jnp.where(q_ok, lse_ref[0], 0)
            delta = jnp.where(q_ok, delta_ref[0], 0)
            s, valid = _masked_scores(
                q, k, qi, kj, causal=causal, block_q=block_q,
                block_k=block_k, seq_q=seq_q, seq_k=seq_k)
        else:
            q = q_ref[0] * scale
            k = k_ref[0]
            v = v_ref[0]
            do = do_ref[0]
            lse = lse_ref[0]
            delta = delta_ref[0]
            s = _scores(q, k)
        p = jnp.exp(s - lse)
        if masked:
            p = jnp.where(valid, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        if masked:
            ds = jnp.where(valid, ds, 0.0)
        dq_sc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _block_dispatch(_update, qi=qi, kj=kj, causal=causal,
                    block_q=block_q, block_k=block_k, n_q=n_q, n_k=n_k,
                    seq_q=seq_q, seq_k=seq_k)

    @pl.when(kj == n_k - 1)
    def _finalize():
        dq_ref[0] = (dq_sc[:] * scale).astype(dq_ref.dtype)


def _default_block(block, interpret: bool, head_dim: int = 128,
                   cap: int = 1024) -> int:
    """Default tile size. Compiled Mosaic kernels want LARGE blocks —
    the kernels are bound by re-streaming K/V (fwd, dq) and Q/dO (dkv)
    from HBM once per opposing block row, so doubling the block halves
    that traffic. Measured on v5e at S=8192, head_dim 128 (calibrated
    against the per-call tunnel overhead, experiments/flash_block_sweep
    .py): fwd 29.2% MFU at 512x512 -> 49.9% at 1024x1024; the backward
    kernels each cap the dimension they do NOT stream over at 512
    (dkv 512x1024, dq 1024x512 — see _flash_bwd_rule) because
    1024x1024 intermittently fails to compile (scoped-vmem) — hence
    the per-kernel ``cap``. The VMEM
    footprint scales with block*head_dim, so the compiled default
    SHRINKS for larger head dims, rounded DOWN to a multiple of 128 for
    the TPU lane/sublane tiling and floored at 128 (so a huge head_dim
    still gets a legal — if over-budget — block; pass explicit sizes
    there). The interpreter keeps 128 so CPU tests stay fast. Blocks
    are clamped to the sequence length either way."""
    if block is not None:
        return block
    if interpret:
        return 128
    b = cap * 128 // max(head_dim, 1)
    return max(128, min(cap, b // 128 * 128))


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
               out_dtype=None):
    # out_dtype: ring attention requests fp32 per-block outputs so its
    # streaming merge accumulates without an n-fold bf16 rounding.
    bh, s, d = q.shape
    sk = k.shape[1]
    block_q = min(_default_block(block_q, interpret, d), s)
    block_k = min(_default_block(block_k, interpret, d), sk)
    n_q = pl.cdiv(s, block_q)
    n_k = pl.cdiv(sk, block_k)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, n_q=n_q, n_k=n_k, seq_q=s, seq_k=sk)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), out_dtype or q.dtype),
            jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max m
            pltpu.VMEM((block_q, 1), jnp.float32),   # running norm l
            pltpu.VMEM((block_q, d), jnp.float32),   # output accumulator
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: bool = False):
    """Flash attention over [batch, seq, heads, head_dim] inputs.

    Exact (up to fp) vs full attention; O(seq) memory. ``interpret``
    routes through the Pallas interpreter (CPU tests); on TPU leave
    False for the compiled Mosaic kernel. Compiled block sizes default
    per kernel — forward 1024x1024, dK/dV 512x1024, dQ 1024x512 (each
    kernel's streaming-vs-scoped-vmem optimum) — measured fastest on
    v5e at head_dim 128 (see _default_block and _flash_bwd_rule);
    explicit ``block_q``/``block_k`` override ALL kernels; interpreted
    defaults stay 128.
    """
    out, _ = _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k,
                             interpret)
    return out


def _prep(q, scale):
    b, s, h, d = q.shape
    return (scale if scale is not None else d ** -0.5)


def _to_bh(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _from_bh(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k, interpret):
    b, s, h, d = q.shape
    sc = _prep(q, scale)
    qb, kb, vb = _to_bh(q), _to_bh(k), _to_bh(v)
    out, lse = _flash_fwd(qb, kb, vb, sc, causal, block_q, block_k,
                          interpret)
    out4 = _from_bh(out, b, h)
    return out4, (q, k, v, out4, lse)


def _flash_bwd_rule(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    b, s, h, d = q.shape
    sc = _prep(q, scale)
    qb, kb, vb = _to_bh(q), _to_bh(k), _to_bh(v)
    ob, gb = _to_bh(out), _to_bh(g)

    # delta = rowsum(dO * O) — the softmax-jacobian diagonal term.
    delta = jnp.sum(gb.astype(jnp.float32) * ob.astype(jnp.float32),
                    axis=-1, keepdims=True)                   # [bh, s, 1]

    dq, dk, dv = _flash_bwd(qb, kb, vb, gb, lse, delta, sc, causal,
                            block_q, block_k, interpret)
    return (_from_bh(dq, b, h), _from_bh(dk, b, h), _from_bh(dv, b, h))


def _flash_bwd(qb, kb, vb, gb, lse, delta, sc, causal, block_q, block_k,
               interpret, out_dtype=None):
    """Per-block backward passes on [bh, s, d] operands.

    ``lse``/``delta`` are the GLOBAL per-query-row logsumexp and
    softmax-jacobian diagonal — which is what makes these kernels
    directly reusable by ring attention: each (q-shard, kv-block) pair's
    gradient contribution only needs the block operands plus these two
    global row statistics (p = exp(s - lse) is the true global softmax
    restricted to the block)."""
    bh, s, d = qb.shape
    sk = kb.shape[1]
    # The two backward kernels get opposite geometries: dkv re-streams
    # Q/dO once per K-block row (wants LARGE block_k), dq re-streams
    # K/V once per Q-block row (wants LARGE block_q). Both cap the
    # other dimension at 512 — the [block_q, block_k] fp32
    # intermediates at 1024x1024 blow the scoped-vmem budget.
    # Explicit block_q/block_k override both kernels.
    bq = min(_default_block(block_q, interpret, d, cap=512), s)
    bk = min(_default_block(block_k, interpret, d), sk)
    n_q = pl.cdiv(s, bq)
    n_k = pl.cdiv(sk, bk)

    dkv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=sc, causal=causal,
                          block_q=bq, block_k=bk, n_q=n_q, n_k=n_k,
                          seq_q=s, seq_k=sk),
        grid=(bh, n_k, n_q),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), out_dtype or kb.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), out_dtype or vb.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(qb, kb, vb, gb, lse, delta)
    dk, dv = dkv

    bq2 = min(_default_block(block_q, interpret, d), s)
    bk2 = min(_default_block(block_k, interpret, d, cap=512), sk)
    n_q2 = pl.cdiv(s, bq2)
    n_k2 = pl.cdiv(sk, bk2)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=sc, causal=causal,
                          block_q=bq2, block_k=bk2, n_q=n_q2, n_k=n_k2,
                          seq_q=s, seq_k=sk),
        grid=(bh, n_q2, n_k2),
        in_specs=[
            pl.BlockSpec((1, bq2, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk2, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk2, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bq2, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq2, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq2, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq2, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), out_dtype or qb.dtype),
        scratch_shapes=[pltpu.VMEM((bq2, d), jnp.float32)],
        interpret=interpret,
    )(qb, kb, vb, gb, lse, delta)

    return dq, dk, dv


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
