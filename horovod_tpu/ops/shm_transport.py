"""Shared-memory data plane for same-host eager collectives.

The reference reduces CPU tensors through MPI, which uses a shared-memory
BTL for ranks on one host (the path behind HOROVOD_CPU_OPERATIONS and the
hierarchical local stage, operations.cc:1284-1436) — same-host gradient
bytes never touch a socket. The TPU-native eager engine stages its fused
buffer host-side (executor._run_fused_buffers), so the analogous fast
path is direct shared memory: every process maps the same /dev/shm
segments, writes its buffer, reduces its 1/N slice in place, and reads
the peers' reduced slices — ~4 memcpy passes over the buffer in total,
against a TCP-loopback ring's 2(N-1) socket stages (measured on the
8-process CPU mesh: a 33 MB fused allreduce drops from ~1.45 s through
the gloo ring to the memcpy cost).

Used only when every process of the job is on ONE host (the launcher is
the placement authority and exports HOROVOD_TPU_ALL_LOCAL); multi-host
jobs keep the XLA collective data plane. All processes of a job must
gate identically (the launcher env guarantees it) or the fleet would
split between two data planes and deadlock.

Synchronization is flag-based: a per-(bucket, rank) sequence number is
written AFTER the payload; peers spin (sched_yield) until the flag
reaches the expected sequence. Engines execute coordinator-agreed groups
in one global order, so per-bucket sequence counters advance identically
on every process. x86-TSO store ordering makes the flag-after-payload
protocol safe without explicit fences; the spin deadline turns a dead
peer into a loud HorovodInternalError instead of a silent hang.
"""

from __future__ import annotations

import hashlib
import mmap
import os
import platform
import time
from typing import Dict, Optional, Tuple

import numpy as np

from ..utils.logging import get_logger

_log = get_logger("shm")

_HEADER_BYTES = 16  # [in_seq int64][out_seq int64]
_SPIN_DEADLINE_S = 120.0
_DIR = "/dev/shm"


class ShmTimeout(RuntimeError):
    pass


def job_tag() -> Optional[str]:
    """Job-unique segment namespace from the launch secret (unique per
    launch, shared by all ranks) — stale segments of a crashed previous
    job can never alias a live one. Returns None when no launch secret
    exists: without a shared per-run nonce, two runs would share a tag
    and a peer could map a crashed run's stale segment whose sequence
    flags are already past the expected value — silently reducing dead
    bytes. No secret -> no shm plane (the XLA path takes over)."""
    from ..runner.secret import SECRET_ENV
    secret = os.environ.get(SECRET_ENV, "")
    if not secret:
        return None
    return hashlib.sha256(secret.encode()).hexdigest()[:12]


class _Segment:
    """One mapped /dev/shm file: header + input area + output area.

    Plain mmap on a /dev/shm file instead of multiprocessing.shared_memory
    — the stdlib's resource tracker unlinks attached segments on process
    exit (it cannot tell owner from peer), which would tear the data plane
    down under the surviving ranks.
    """

    def __init__(self, path: str, size: int, create: bool):
        self.path = path
        flags = os.O_RDWR | (os.O_CREAT if create else 0)
        fd = os.open(path, flags, 0o600)
        try:
            if create:
                os.ftruncate(fd, size)
            self.mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self.header = np.frombuffer(self.mm, np.int64, count=2)
        self.size = size

    def body(self, dtype, count: int, offset: int) -> np.ndarray:
        return np.frombuffer(self.mm, dtype, count=count,
                             offset=_HEADER_BYTES + offset)

    def close(self, unlink: bool = False) -> None:
        self.header = None
        try:
            self.mm.close()
        except BufferError:  # pragma: no cover - outstanding views
            pass
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass


def _spin(predicate, what: str) -> None:
    """Wait for a peer's flag. A few sched_yields for the fast path, then
    sleep with backoff: on an oversubscribed host a hard spin burns
    exactly the core the working peer needs (measured: pure sched_yield
    spinning roughly doubles the 8-process fused-allreduce time)."""
    deadline = time.monotonic() + _SPIN_DEADLINE_S
    pause = 0.0002
    for _ in range(20):
        if predicate():
            return
        os.sched_yield()
    while not predicate():
        if time.monotonic() > deadline:
            raise ShmTimeout(
                f"shared-memory data plane timed out waiting for {what} "
                f"after {_SPIN_DEADLINE_S:.0f}s — a peer process died or "
                "is wedged")
        time.sleep(pause)
        pause = min(pause * 1.5, 0.004)


class ShmTransport:
    """Fused-buffer allreduce/broadcast over /dev/shm for one-host jobs.

    Per (bucket=padded byte size) each process owns one segment:
    ``{dir}/hvdtpu_{tag}_{bucket}_{rank}`` with layout
    ``[in_seq][out_seq][input bucket bytes][output bucket bytes]``.
    Reduction is slice-parallel: process r sums slice r over all input
    areas into its own output area (deterministic rank order — same
    float-sum order on every process), then reads peers' reduced slices.
    """

    def __init__(self, rank: int, nproc: int, tag: Optional[str] = None):
        self.rank = rank
        self.nproc = nproc
        self.tag = tag or job_tag()
        self._own: Dict[int, _Segment] = {}
        self._peers: Dict[Tuple[int, int], _Segment] = {}
        self._seq: Dict[int, int] = {}
        self._closed = False

    # ------------------------------------------------------------- plumbing

    def _path(self, bucket: int, rank: int) -> str:
        return os.path.join(_DIR, f"hvdtpu_{self.tag}_{bucket}_{rank}")

    def _segment_size(self, bucket: int) -> int:
        return _HEADER_BYTES + 2 * bucket

    def _own_segment(self, bucket: int) -> _Segment:
        seg = self._own.get(bucket)
        if seg is None:
            path = self._path(bucket, self.rank)
            try:
                os.unlink(path)  # stale file from a dead same-tag run
            except OSError:
                pass
            seg = _Segment(path, self._segment_size(bucket), create=True)
            seg.header[0] = 0
            seg.header[1] = 0
            self._own[bucket] = seg
        return seg

    def _peer_segment(self, bucket: int, rank: int) -> _Segment:
        if rank == self.rank:
            return self._own_segment(bucket)
        seg = self._peers.get((bucket, rank))
        if seg is None:
            path = self._path(bucket, rank)
            size = self._segment_size(bucket)

            def ready():
                try:
                    return os.path.getsize(path) >= size
                except OSError:
                    return False

            _spin(ready, f"rank {rank}'s segment {path}")
            seg = _Segment(path, size, create=False)
            self._peers[(bucket, rank)] = seg
        return seg

    def _slice(self, n: int, r: int) -> Tuple[int, int]:
        q = n // self.nproc
        lo = r * q
        hi = n if r == self.nproc - 1 else lo + q
        return lo, hi

    # ------------------------------------------------------------------ ops

    def allreduce(self, buf: np.ndarray) -> np.ndarray:
        """Sum-allreduce a flat fused buffer across all processes. The
        buffer size must be identical on every process (the engine's
        size-quantized fusion buffer guarantees it)."""
        n = int(buf.size)
        bucket = int(buf.nbytes)
        seq = self._seq[bucket] = self._seq.get(bucket, 0) + 1
        own = self._own_segment(bucket)
        segs = [self._peer_segment(bucket, r) for r in range(self.nproc)]
        item = buf.dtype.itemsize

        own.body(buf.dtype, n, 0)[:] = buf.ravel()
        own.header[0] = seq  # payload visible before the flag (x86 TSO)

        for r, seg in enumerate(segs):
            if r != self.rank:
                _spin(lambda s=seg: s.header[0] >= seq,
                      f"rank {r}'s input (seq {seq})")

        lo, hi = self._slice(n, self.rank)
        if hi > lo:
            acc = own.body(buf.dtype, hi - lo, bucket + lo * item)
            np.copyto(acc, segs[0].body(buf.dtype, hi - lo, lo * item))
            for seg in segs[1:]:
                acc += seg.body(buf.dtype, hi - lo, lo * item)
        own.header[1] = seq

        for r, seg in enumerate(segs):
            if r != self.rank:
                _spin(lambda s=seg: s.header[1] >= seq,
                      f"rank {r}'s reduced slice (seq {seq})")

        out = np.empty((n,), buf.dtype)
        for r, seg in enumerate(segs):
            lo, hi = self._slice(n, r)
            if hi > lo:
                out[lo:hi] = seg.body(buf.dtype, hi - lo, bucket + lo * item)
        return out

    def broadcast(self, buf: np.ndarray, root_process: int) -> np.ndarray:
        """Broadcast the root process's flat buffer to every process."""
        n = int(buf.size)
        bucket = int(buf.nbytes)
        seq = self._seq[bucket] = self._seq.get(bucket, 0) + 1
        own = self._own_segment(bucket)
        root = self._peer_segment(bucket, root_process)
        if root_process == self.rank:
            own.body(buf.dtype, n, 0)[:] = buf.ravel()
            own.header[0] = seq
            # Wait for every reader's ack (out_seq) before the next use of
            # this bucket may overwrite the payload.
            for r in range(self.nproc):
                if r != self.rank:
                    _spin(lambda s=self._peer_segment(bucket, r):
                          s.header[1] >= seq, f"rank {r}'s bcast ack")
            own.header[1] = seq
            return np.array(buf.ravel(), copy=True)
        _spin(lambda: root.header[0] >= seq,
              f"root {root_process}'s bcast payload (seq {seq})")
        out = np.array(root.body(buf.dtype, n, 0), copy=True)
        own.header[1] = seq  # ack
        return out

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for seg in self._peers.values():
            seg.close(unlink=False)
        for seg in self._own.values():
            seg.close(unlink=True)
        self._peers.clear()
        self._own.clear()


_transport: Optional[ShmTransport] = None
_failed = False


def get(rank: int, nproc: int) -> Optional[ShmTransport]:
    """Process-wide transport, or None when unavailable (non-Linux, no
    /dev/shm). Callers gate on the ALL_LOCAL/SHM env before asking."""
    global _transport, _failed
    if _failed:
        return None
    if _transport is None:
        try:
            if platform.machine() not in ("x86_64", "AMD64"):
                # The flag-after-payload protocol relies on x86-TSO store
                # ordering (module docstring); on weaker memory models
                # (aarch64) the un-fenced numpy stores can be observed
                # reordered — torn or stale payloads, silently reduced.
                raise OSError(
                    f"flag-sequenced protocol requires x86-TSO ordering "
                    f"(machine is {platform.machine()})")
            if not os.path.isdir(_DIR):
                raise OSError(f"{_DIR} not present")
            tag = job_tag()
            if tag is None:
                raise OSError(
                    "no launch secret for a job-unique segment namespace")
            _transport = ShmTransport(rank, nproc, tag=tag)
        except Exception as e:  # pragma: no cover - platform fallback
            _failed = True
            _log.warning("shared-memory data plane unavailable (%s); "
                         "using XLA collectives", e)
            return None
    return _transport


def reset() -> None:
    """Test hook / engine shutdown: drop the transport and its segments."""
    global _transport, _failed
    if _transport is not None:
        _transport.close()
    _transport = None
    _failed = False
