"""Fused batch-norm(+residual+ReLU) with Pallas TPU kernels.

This is the measured test of docs/benchmarks.md's round-3 hypothesis
that a fused BN-backward kernel would lift ResNet-50 training toward a
~3000 img/s v5e ceiling. The verdict (v5e, [256,56,56,256] bf16, all
in-process A/B — experiments/bn_bwd_probe.py, pallas_shape_probe.py,
resnet_ab.py): the hypothesis is FALSE. Once the ~100 ms per-call axon
tunnel overhead is amortized out (k=100 chained steps), XLA's own BN
fusion already runs at the arithmetic minimum pass count (fwd ~2.8
passes vs optimum 3, bwd ~5.7 vs optimum 5 at the ~570 GB/s effective
HBM rate), while Mosaic/Pallas streams HBM at only ~310 GB/s on this
chip generation — so these kernels lose to XLA at equal pass counts,
and in the full model (where XLA fuses across op boundaries the custom
VJP makes opaque) the flax path wins outright: 2312 img/s flax vs 1586
hand-structured jnp VJP vs 1002 Pallas. The kernels and the custom-VJP
structure are kept as selectable impls and as the regression record of
that measurement; models default to the flax path.

The pass structure (the arithmetic minimum, with the bf16->fp32 cast
done in-register):

  forward:  stats kernel   reads x          -> channel sums(x, x^2)
            norm kernel    reads x, writes y = relu(x_hat*gamma+beta [+r])
  backward: reduce kernel  reads x, da      -> s1 = sum(dy),
                                               s2 = sum(dy * x_hat)
            dx kernel      reads x, da, writes dx (+ dr = dy)

where dy = da * relu_mask and the relu mask is RECOMPUTED in-register
from x (mask = pre-relu z > 0, z = x_hat*gamma+beta [+ r]) — the relu
backward costs zero extra HBM traffic, where the unfused graph reads a
saved mask or the forward output.

The backward closed form (per channel, m = reduction size):
  dx = (gamma * rstd) * (dy - s1/m - x_hat * s2/m);  dgamma = s2;
  dbeta = s1;  and for the residual variant dr = dy.

No reference counterpart: the reference ships no model/kernel code (its
ResNet comes from Keras applications, examples/tensorflow_synthetic_
benchmark.py:24-42); this is the TPU-native hot-op under the benchmark
the reference's docs/benchmarks.md headlines. Statistics follow flax
(`flax.linen.normalization._compute_stats`): fp32 mean of x and of x^2,
biased variance, so the module below is checkpoint-compatible with
`nn.BatchNorm`.

Channels: lanes want multiples of 128, so C < 128 folds row-pairs into
lanes ([M, C] -> [M/k, k*C], k = 128//C) — per-channel sums then fold
back with a [k, C] reshape-sum, and the per-channel vectors are tiled k
times. C not dividing 128 (or an M with no power-of-two factor >= 8)
falls back to a jnp implementation of the SAME 2+3-pass structure via
the same custom VJP, so CPU/odd shapes share one numerical definition.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_MAX_BM = 1024
# Per-block byte budget (bf16 elements): the widest kernel holds ~5
# blocks (x, da, r, dx, dr) double-buffered plus fp32 temporaries in
# 16 MB of scoped VMEM; 256 KB bf16 blocks keep the worst case < 6 MB
# (measured: 1024x1024 blocks OOM'd scoped vmem at 17.8 MB on v5e).
_BLOCK_ELEMS = 128 * 1024


def _pow2_div(n: int, cap: int = _MAX_BM) -> int:
    d = n & (-n)  # largest power-of-two divisor
    return min(d, cap)


def _block_rows(m2: int, c2: int) -> int:
    cap = max(8, _BLOCK_ELEMS // c2)
    # Floor the cap to a power of two: _pow2_div returns a power-of-two
    # divisor of m2, and min() against a non-power-of-two cap (e.g.
    # C=384 -> cap 341) would yield a block that does not divide m2 —
    # a truncated grid that silently skips the trailing rows.
    cap = 1 << (cap.bit_length() - 1)
    return _pow2_div(m2, cap)


def _fold(c: int) -> int:
    return 128 // c if (c < 128 and 128 % c == 0) else 1


def _can_pallas(m: int, c: int) -> bool:
    k = _fold(c)
    c2 = c * k
    return c2 % 128 == 0 and m % k == 0 and _pow2_div(m // k) >= 8


# ------------------------------------------------------------------ kernels


def _stats_kernel(x_ref, s1_ref, s2_ref):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        s1_ref[:] = jnp.zeros_like(s1_ref)
        s2_ref[:] = jnp.zeros_like(s2_ref)

    xf = x_ref[:].astype(jnp.float32)
    s1_ref[:] += jnp.sum(xf, axis=0, keepdims=True)
    s2_ref[:] += jnp.sum(xf * xf, axis=0, keepdims=True)


def _norm_kernel(x_ref, r_ref, sc_ref, sh_ref, y_ref, *, relu, residual):
    z = x_ref[:].astype(jnp.float32) * sc_ref[:] + sh_ref[:]
    if residual:
        z = z + r_ref[:].astype(jnp.float32)
    if relu:
        z = jnp.maximum(z, 0.0)
    y_ref[:] = z.astype(y_ref.dtype)


def _bwd_reduce_kernel(x_ref, da_ref, r_ref, mu_ref, rs_ref, sc_ref,
                       sh_ref, s1_ref, s2_ref, *, relu, residual):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        s1_ref[:] = jnp.zeros_like(s1_ref)
        s2_ref[:] = jnp.zeros_like(s2_ref)

    xf = x_ref[:].astype(jnp.float32)
    daf = da_ref[:].astype(jnp.float32)
    xhat = (xf - mu_ref[:]) * rs_ref[:]
    if relu:
        z = xf * sc_ref[:] + sh_ref[:]
        if residual:
            z = z + r_ref[:].astype(jnp.float32)
        daf = jnp.where(z > 0, daf, 0.0)
    s1_ref[:] += jnp.sum(daf, axis=0, keepdims=True)
    s2_ref[:] += jnp.sum(daf * xhat, axis=0, keepdims=True)


def _bwd_dx_kernel(x_ref, da_ref, r_ref, mu_ref, rs_ref, sc_ref, sh_ref,
                   g1_ref, g2_ref, dx_ref, dr_ref, *, relu, residual,
                   inv_m):
    xf = x_ref[:].astype(jnp.float32)
    daf = da_ref[:].astype(jnp.float32)
    xhat = (xf - mu_ref[:]) * rs_ref[:]
    if relu:
        z = xf * sc_ref[:] + sh_ref[:]
        if residual:
            z = z + r_ref[:].astype(jnp.float32)
        daf = jnp.where(z > 0, daf, 0.0)
    if residual:
        dr_ref[:] = daf.astype(dr_ref.dtype)
    dx = sc_ref[:] * (daf - g1_ref[:] * inv_m - xhat * (g2_ref[:] * inv_m))
    dx_ref[:] = dx.astype(dx_ref.dtype)


def _vec(v, k):
    """Per-channel fp32 row vector [1, k*C] for lane broadcast."""
    v = jnp.asarray(v, jnp.float32)
    if k > 1:
        v = jnp.tile(v, k)
    return v[None, :]


def _row_spec(bm, c2):
    return pl.BlockSpec((bm, c2), lambda i: (i, 0))


def _vec_spec(c2):
    return pl.BlockSpec((1, c2), lambda i: (0, 0))


def _stats_pallas(x2, interpret):
    m2, c2 = x2.shape
    bm = _block_rows(m2, c2)
    s1, s2 = pl.pallas_call(
        _stats_kernel,
        grid=(m2 // bm,),
        in_specs=[_row_spec(bm, c2)],
        out_specs=[_vec_spec(c2), _vec_spec(c2)],
        out_shape=[jax.ShapeDtypeStruct((1, c2), jnp.float32)] * 2,
        interpret=interpret,
    )(x2)
    return s1[0], s2[0]


def _norm_pallas(x2, r2, scale, shift, relu, out_dtype, interpret):
    m2, c2 = x2.shape
    bm = _block_rows(m2, c2)
    residual = r2 is not None
    kernel = functools.partial(_norm_kernel, relu=relu, residual=residual)
    return pl.pallas_call(
        kernel,
        grid=(m2 // bm,),
        in_specs=[_row_spec(bm, c2),
                  _row_spec(bm, c2) if residual else _vec_spec(c2),
                  _vec_spec(c2), _vec_spec(c2)],
        out_specs=_row_spec(bm, c2),
        out_shape=jax.ShapeDtypeStruct((m2, c2), out_dtype),
        interpret=interpret,
    )(x2, r2 if residual else scale, scale, shift)


def _bwd_reduce_pallas(x2, da2, r2, mean, rstd, scale, shift, relu,
                       interpret):
    m2, c2 = x2.shape
    bm = _block_rows(m2, c2)
    residual = r2 is not None
    rfill = r2 if residual else mean  # unused slot when no residual
    red = functools.partial(_bwd_reduce_kernel, relu=relu,
                            residual=residual)
    s1, s2 = pl.pallas_call(
        red,
        grid=(m2 // bm,),
        in_specs=[_row_spec(bm, c2), _row_spec(bm, c2),
                  _row_spec(bm, c2) if residual else _vec_spec(c2),
                  _vec_spec(c2), _vec_spec(c2), _vec_spec(c2),
                  _vec_spec(c2)],
        out_specs=[_vec_spec(c2), _vec_spec(c2)],
        out_shape=[jax.ShapeDtypeStruct((1, c2), jnp.float32)] * 2,
        interpret=interpret,
    )(x2, da2, rfill, mean, rstd, scale, shift)
    return s1[0], s2[0]


def _bwd_dx_pallas(x2, da2, r2, mean, rstd, scale, shift, g1, g2, inv_m,
                   relu, interpret):
    m2, c2 = x2.shape
    bm = _block_rows(m2, c2)
    residual = r2 is not None
    rfill = r2 if residual else mean
    dxk = functools.partial(_bwd_dx_kernel, relu=relu, residual=residual,
                            inv_m=inv_m)
    out_specs = [_row_spec(bm, c2)]
    out_shape = [jax.ShapeDtypeStruct((m2, c2), x2.dtype)]
    if residual:
        out_specs.append(_row_spec(bm, c2))
        out_shape.append(jax.ShapeDtypeStruct((m2, c2), r2.dtype))
    else:
        out_specs.append(_vec_spec(c2))
        out_shape.append(jax.ShapeDtypeStruct((1, c2), jnp.float32))
    outs = pl.pallas_call(
        dxk,
        grid=(m2 // bm,),
        in_specs=[_row_spec(bm, c2), _row_spec(bm, c2),
                  _row_spec(bm, c2) if residual else _vec_spec(c2),
                  _vec_spec(c2), _vec_spec(c2), _vec_spec(c2),
                  _vec_spec(c2), _vec_spec(c2), _vec_spec(c2)],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(x2, da2, rfill, mean, rstd, scale, shift, g1, g2)
    dx2 = outs[0]
    dr2 = outs[1] if residual else None
    return dx2, dr2


# ---------------------------------------------------------------- jnp path


def _jnp_stats(x2):
    xf = x2.astype(jnp.float32)
    return (jnp.sum(xf, axis=0), jnp.sum(jnp.square(xf), axis=0))


def _jnp_norm(x2, r2, scale, shift, relu, out_dtype):
    z = x2.astype(jnp.float32) * scale + shift
    if r2 is not None:
        z = z + r2.astype(jnp.float32)
    if relu:
        z = jnp.maximum(z, 0.0)
    return z.astype(out_dtype)


def _jnp_bwd_reduce(x2, da2, r2, mean, rstd, scale, shift, relu):
    xf = x2.astype(jnp.float32)
    daf = da2.astype(jnp.float32)
    xhat = (xf - mean) * rstd
    if relu:
        z = xf * scale + shift
        if r2 is not None:
            z = z + r2.astype(jnp.float32)
        daf = jnp.where(z > 0, daf, 0.0)
    return jnp.sum(daf, axis=0), jnp.sum(daf * xhat, axis=0)


def _jnp_bwd_dx(x2, da2, r2, mean, rstd, scale, shift, g1, g2, inv_m,
                relu):
    xf = x2.astype(jnp.float32)
    daf = da2.astype(jnp.float32)
    xhat = (xf - mean) * rstd
    if relu:
        z = xf * scale + shift
        if r2 is not None:
            z = z + r2.astype(jnp.float32)
        daf = jnp.where(z > 0, daf, 0.0)
    dx = scale * (daf - g1 * inv_m - xhat * (g2 * inv_m))
    dr2 = daf.astype(r2.dtype) if r2 is not None else None
    return dx.astype(x2.dtype), dr2


# ------------------------------------------------------------- public vjp


def _use_pallas(m: int, c: int, impl: str) -> Tuple[bool, bool]:
    """Resolve ``impl`` to (use pallas kernels?, interpreter flag).

    'jnp'       — the same 2+3-pass structure in plain jnp, fused by XLA.
    'pallas'    — compiled Pallas kernels (falls back to jnp when the
                  layout can't fold to 128 lanes).
    'interpret' — Pallas through the interpreter (CPU tests).
    'auto'      — 'jnp' everywhere: measured on v5e, XLA compiles each
                  jnp pass at ~570 GB/s effective while Mosaic streams
                  at ~310 GB/s, so the pass-optimal structure is fastest
                  when XLA does the streaming (experiments/
                  pallas_shape_probe.py; docs/benchmarks.md).
    """
    if not _can_pallas(m, c):
        return False, False
    if impl == "pallas":
        return True, False
    if impl == "interpret":
        return True, True
    return False, False


def _prep(x, r, gamma, beta):
    c = x.shape[-1]
    m = x.size // c
    k = _fold(c)
    x2 = x.reshape(m // k, k * c) if k > 1 else x.reshape(m, c)
    r2 = None
    if r is not None:
        r2 = r.reshape(x2.shape)
    return x2, r2, m, c, k


def _bn_act_fwd(x, r, gamma, beta, eps, relu, has_residual, impl):
    r_in = r if has_residual else None
    x2, r2, m, c, k = _prep(x, r_in, gamma, beta)
    pallas, interp = _use_pallas(m, c, impl)
    if pallas:
        s1, s2 = _stats_pallas(x2, interp)
    else:
        s1, s2 = _jnp_stats(x2)
    if k > 1:
        s1 = s1.reshape(k, c).sum(0)
        s2 = s2.reshape(k, c).sum(0)
    mean = s1 / m
    var = s2 / m - jnp.square(mean)
    rstd = jax.lax.rsqrt(var + eps)
    gf = jnp.asarray(gamma, jnp.float32)
    bf = jnp.asarray(beta, jnp.float32)
    scale = gf * rstd
    shift = bf - mean * scale
    scale_v, shift_v = _vec(scale, k), _vec(shift, k)
    if pallas:
        y2 = _norm_pallas(x2, r2, scale_v, shift_v, relu, x.dtype, interp)
    else:
        y2 = _jnp_norm(x2, r2, scale_v, shift_v, relu, x.dtype)
    y = y2.reshape(x.shape)
    return (y, mean, var), (x, r_in, mean, rstd, gf, bf)


def _bn_act_bwd(eps, relu, has_residual, impl, res, ct):
    day, _, _ = ct  # cotangents of (y, mean, var); stats feed only the
    #                 stop-gradient running-average update, so their
    #                 cotangents are structurally zero (flax BatchNorm
    #                 has the same property).
    x, r_in, mean, rstd, gf, bf = res
    x2, r2, m, c, k = _prep(x, r_in, gf, bf)
    da2 = day.reshape(x2.shape)
    pallas, interp = _use_pallas(m, c, impl)
    scale = gf * rstd
    shift = bf - mean * scale
    mean_v, rstd_v = _vec(mean, k), _vec(rstd, k)
    scale_v, shift_v = _vec(scale, k), _vec(shift, k)
    if pallas:
        s1, s2 = _bwd_reduce_pallas(x2, da2, r2, mean_v, rstd_v,
                                    scale_v, shift_v, relu, interp)
    else:
        s1, s2 = _jnp_bwd_reduce(x2, da2, r2, mean_v, rstd_v,
                                 scale_v, shift_v, relu)
    if k > 1:
        # Combine the per-lane partial sums of each real channel BEFORE
        # the dx pass: in the folded layout lane c and lane c + j*C each
        # hold 1/k of channel c's sum, but dx needs the full channel sum
        # over the true reduction size m.
        s1 = s1.reshape(k, c).sum(0)
        s2 = s2.reshape(k, c).sum(0)
    inv_m = 1.0 / float(m)
    g1_v, g2_v = _vec(s1, k), _vec(s2, k)
    if pallas:
        dx2, dr2 = _bwd_dx_pallas(x2, da2, r2, mean_v, rstd_v, scale_v,
                                  shift_v, g1_v, g2_v, inv_m, relu,
                                  interp)
    else:
        dx2, dr2 = _jnp_bwd_dx(x2, da2, r2, mean_v, rstd_v, scale_v,
                               shift_v, g1_v, g2_v, inv_m, relu)
    dx = dx2.reshape(x.shape)
    dr = dr2.reshape(x.shape) if dr2 is not None else None
    dgamma = s2.astype(jnp.float32)
    dbeta = s1.astype(jnp.float32)
    if not has_residual:
        dr = jnp.zeros((), x.dtype)  # placeholder cotangent, unused
    return dx, dr, dgamma, dbeta


# custom_vjp functions must return the primal output only; re-define the
# primal to return the full (y, mean, var) triple.
def _bn_act_primal(x, r, gamma, beta, eps, relu, has_residual, impl):
    out, _ = _bn_act_fwd(x, r, gamma, beta, eps, relu, has_residual,
                         impl)
    return out


_bn_act_core = jax.custom_vjp(_bn_act_primal, nondiff_argnums=(4, 5, 6, 7))
_bn_act_core.defvjp(_bn_act_fwd, _bn_act_bwd)


def bn_act(x, gamma, beta, *, residual=None, eps: float = 1e-5,
           relu: bool = True, impl: str = "auto"):
    """Train-mode fused batch-norm(+residual)(+ReLU).

    Returns ``(y, batch_mean, batch_var)``; the stats are fp32 biased
    moments for the caller's running-average update (use them under
    stop_gradient — their cotangents are treated as zero). ``residual``
    is added AFTER normalization, before the ReLU (the ResNet v1.5
    bottleneck join). Gradients: x, residual, gamma, beta.

    ``impl``: 'auto' (jnp passes, XLA-fused — fastest measured),
    'jnp', 'pallas' (compiled kernels), 'interpret' (Pallas interpreter,
    CPU tests). See _use_pallas for the measured rationale.
    """
    if impl not in ("auto", "jnp", "pallas", "interpret"):
        # A typo'd impl silently measuring the wrong implementation is
        # worse than an error — this repo's benchmark verdicts hang on
        # knowing which path actually ran.
        raise ValueError(f"unknown bn_act impl {impl!r}; expected "
                         "'auto', 'jnp', 'pallas' or 'interpret'")
    has_residual = residual is not None
    r = residual if has_residual else jnp.zeros((), x.dtype)
    return _bn_act_core(x, r, gamma, beta, float(eps), bool(relu),
                        has_residual, str(impl))


def bn_act_inference(x, gamma, beta, running_mean, running_var, *,
                     residual=None, eps: float = 1e-5, relu: bool = True):
    """Eval-mode normalize with running stats — plain jnp (a single
    elementwise chain XLA fuses on its own; no reduction pass exists)."""
    rstd = jax.lax.rsqrt(running_var.astype(jnp.float32) + eps)
    scale = gamma.astype(jnp.float32) * rstd
    shift = beta.astype(jnp.float32) - running_mean.astype(jnp.float32) * scale
    z = x.astype(jnp.float32) * scale + shift
    if residual is not None:
        z = z + residual.astype(jnp.float32)
    if relu:
        z = jnp.maximum(z, 0.0)
    return z.astype(x.dtype)
