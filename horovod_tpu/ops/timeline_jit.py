"""Jit-path timeline observability (VERDICT r3 #3).

The reference's timeline instruments every collective it executes with
negotiation + activity phases (timeline.h:33-121, operations.cc:728-740)
— possible because its collectives are discrete library calls. On the
TPU-native jit path the collectives live INSIDE compiled XLA programs
(`DistributedOptimizer`'s in-jit psum route, everything in `parallel/`),
where Python cannot emit per-op events. This module closes that
observability gap with the two pieces that are possible from outside a
compiled program, writing into the SAME Chrome trace the engine's
negotiation phases land in:

1. ``step(name)`` — brackets each compiled-step execution as an
   ``XLA_STEP`` span on the Horovod timeline (native writer when the C++
   core owns the timeline, the Python writer otherwise), so the trace
   shows exactly when the jit path was on device.
2. ``merge_profiler_trace(...)`` — merges a ``jax.profiler.trace``
   capture (its ``*.trace.json.gz`` is already Chrome-trace JSON, with
   per-device lanes carrying the compiled programs' device time) into
   the Horovod timeline file: pids are re-interned after the engine's,
   and timestamps are shifted so the capture aligns with the first
   ``XLA_STEP`` bracket (clock bases differ; alignment is anchored, not
   clock-exact — the device lanes' durations and internal structure are
   the payload).

Usage (also docs/timeline.md):

    with jax.profiler.trace(logdir):
        for _ in range(steps):
            with hvd.timeline_jit_step("train"):
                state = train_step(state, batch)
    hvd.shutdown()   # close the timeline file
    hvd.merge_profiler_trace(timeline_path, logdir)

CLI: ``python -m horovod_tpu.ops.timeline_jit TIMELINE LOGDIR [-o OUT]``.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
from typing import List, Optional

_PID_GAP = 10000  # profiler pids re-based above the engine's interned pids


@contextlib.contextmanager
def step(name: str = "step"):
    """Bracket a compiled-step execution on the Horovod timeline as an
    ``XLA_STEP`` span under process ``jit::<name>``. When no timeline
    path is configured this is a no-op that never touches the engine —
    the always-on usage (bracketing every training step) must not add
    lock traffic to the hot path."""
    from ..utils import env as _env
    if not _env.timeline_path():
        yield
        return
    from . import collective as _c
    eng = _c.engine()
    tensor = f"jit::{name}"
    core = eng._ensure_native()
    if core is not None and core.timeline_enabled():
        core.timeline_activity_start(tensor, "XLA_STEP")
        try:
            yield
        finally:
            core.timeline_activity_end(tensor)
        return
    tl = eng._ensure_timeline()
    if tl is not None:
        tl.start(tensor, "XLA_STEP")
        try:
            yield
        finally:
            tl.end(tensor)
        return
    yield


def _load_timeline(path: str) -> List[dict]:
    """Read a (possibly unterminated — see PyTimeline.close) Chrome
    trace array."""
    txt = open(path).read().strip()
    if txt.endswith(","):
        txt = txt[:-1]
    if not txt.endswith("]"):
        txt += "\n]"
    return json.loads(txt)


def _newest_capture(profile_dir: str) -> str:
    paths = sorted(glob.glob(os.path.join(
        profile_dir, "**", "*.trace.json.gz"), recursive=True),
        key=os.path.getmtime)
    if not paths:
        raise FileNotFoundError(
            f"no *.trace.json.gz under {profile_dir} — did "
            "jax.profiler.trace() run?")
    return paths[-1]


def merge_profiler_trace(timeline_path: str, profile_dir: str,
                         out_path: Optional[str] = None) -> str:
    """Merge the newest ``jax.profiler`` capture under ``profile_dir``
    into the Horovod timeline at ``timeline_path``.

    Returns the merged file's path (``out_path`` or
    ``<timeline>.merged.json``). Call after the timeline file is closed
    (``hvd.shutdown()``) — merging a live file would race its writer.
    """
    base = _load_timeline(timeline_path)
    capture = json.loads(gzip.open(_newest_capture(profile_dir)).read())
    prof = capture.get("traceEvents", [])

    max_pid = max((e.get("pid", 0) for e in base), default=0)
    pid_off = max_pid + _PID_GAP

    # Anchor: align the capture's earliest timestamp with the first
    # XLA_STEP bracket (the step the user profiled); fall back to the
    # timeline's own start.
    anchor_ts = None
    jit_pids = {e["pid"] for e in base
                if e.get("name") == "process_name"
                and str(e.get("args", {}).get("name", "")).startswith("jit::")}
    for e in base:
        if e.get("ph") == "B" and e.get("pid") in jit_pids:
            anchor_ts = e.get("ts", 0)
            break
    if anchor_ts is None:
        anchor_ts = min((e.get("ts", 0) for e in base
                         if e.get("ph") != "M"), default=0)
    prof_ts = [e["ts"] for e in prof
               if e.get("ph") not in (None, "M") and "ts" in e]
    ts_off = anchor_ts - (min(prof_ts) if prof_ts else 0)

    merged = list(base)
    for e in prof:
        e = dict(e)
        if "pid" in e:
            e["pid"] = e["pid"] + pid_off
        if "ts" in e and e.get("ph") != "M":
            e["ts"] = e["ts"] + ts_off
        merged.append(e)

    out = out_path or timeline_path + ".merged.json"
    with open(out, "w") as f:
        json.dump(merged, f)
    return out


def _main(argv=None):  # pragma: no cover - thin CLI
    import argparse
    ap = argparse.ArgumentParser(
        description="Merge a jax.profiler capture into a Horovod "
                    "timeline (Chrome trace)")
    ap.add_argument("timeline")
    ap.add_argument("profile_dir")
    ap.add_argument("-o", "--out", default=None)
    args = ap.parse_args(argv)
    print(merge_profiler_trace(args.timeline, args.profile_dir, args.out))


if __name__ == "__main__":  # pragma: no cover
    _main()
