"""Pure-Python mirror of the native control-plane codec (N2).

The cross-process control plane's wire format is defined by the native
runtime (runtime/src/message.{h,cc} — the TPU-native equivalent of the
reference's FlatBuffers wire, horovod/common/mpi_message.cc:134-230):
little-endian fixed-width ints and length-prefixed strings. The rank-0
controller parses announce payloads and serializes response lists in C++;
this module is the byte-exact Python mirror used by

  - processes whose native toolchain is unavailable (degraded mode — they
    still speak the same wire format, so mixed fleets interoperate), and
  - the Python fallback planner and tests.

``tests/test_native.py`` asserts byte-for-byte round-trips against the
native codec; any format change must land in both files.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple

_I32 = struct.Struct("<i")
_I64 = struct.Struct("<q")

# Wire op enums (message.h Request::Type / Response::Type).
ALLREDUCE, ALLGATHER, BROADCAST, ERROR = 0, 1, 2, 3

# Response flags (message.h Response::Flags) — plan-time execution-mode
# bits every process applies for the group (SPMD lockstep).
FLAG_HIERARCHICAL_ALLREDUCE = 1 << 0
FLAG_HIERARCHICAL_ALLGATHER = 1 << 1

# Dtype enum (runtime/src/common.h DataType; reference mpi_message.h:26-37
# plus bfloat16). fp8 dtypes plan under the 1-byte uint8 slot, matching
# runtime/native.py's enqueue convention.
_DTYPE_TO_ENUM = {
    "uint8": 0, "int8": 1, "uint16": 2, "int16": 3, "int32": 4,
    "int64": 5, "float16": 6, "float32": 7, "float64": 8, "bool": 9,
    "bfloat16": 10, "uint32": 11, "uint64": 12,
}
_ENUM_TO_DTYPE = {v: k for k, v in _DTYPE_TO_ENUM.items()}
_DTYPE_SIZE = {0: 1, 1: 1, 2: 2, 3: 2, 4: 4, 5: 8, 6: 2, 7: 4, 8: 8,
               9: 1, 10: 2, 11: 4, 12: 8}


def dtype_enum(name: str) -> int:
    if name.startswith("float8"):
        return _DTYPE_TO_ENUM["uint8"]
    try:
        return _DTYPE_TO_ENUM[name]
    except KeyError:
        raise ValueError(
            f"dtype {name!r} is not supported on the collective wire "
            f"(supported: {sorted(_DTYPE_TO_ENUM)})") from None


def dtype_name(enum: int) -> str:
    return _ENUM_TO_DTYPE.get(enum, "unknown")


def dtype_size(enum: int) -> int:
    return _DTYPE_SIZE.get(enum, 0)


class _Writer:
    def __init__(self):
        self.parts: List[bytes] = []

    def i32(self, v: int):
        self.parts.append(_I32.pack(v))

    def i64(self, v: int):
        self.parts.append(_I64.pack(v))

    def s(self, v: str):
        b = v.encode()
        self.i32(len(b))
        self.parts.append(b)

    def bytes(self) -> bytes:
        return b"".join(self.parts)


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.off = 0

    def i32(self) -> int:
        v = _I32.unpack_from(self.data, self.off)[0]
        self.off += 4
        return v

    def i64(self) -> int:
        v = _I64.unpack_from(self.data, self.off)[0]
        self.off += 8
        return v

    def s(self) -> str:
        n = self.i32()
        v = self.data[self.off:self.off + n].decode()
        self.off += n
        return v


# --------------------------------------------------------------- requests

def encode_request(w: _Writer, rank: int, op: int, dtype: str, name: str,
                   root_rank: int, device: int,
                   shape: Sequence[int]) -> None:
    w.i32(rank)
    w.i32(op)
    w.i32(dtype_enum(dtype))
    w.s(name)
    w.i32(root_rank)
    w.i32(device)
    w.i32(len(shape))
    for d in shape:
        w.i64(int(d))


def encode_request_list(rank: int, requests: List[dict],
                        shutdown: bool = False) -> bytes:
    """Serialize one process's announce — requests are the engine's dicts
    {name, op, dtype, shape, root_rank}. Mirrors RequestList::SerializeTo."""
    w = _Writer()
    w.i32(1 if shutdown else 0)
    w.i32(len(requests))
    for r in requests:
        encode_request(w, rank, int(r["op"]), str(r["dtype"]),
                       str(r["name"]), int(r.get("root_rank", -1)),
                       int(r.get("device", -1)), tuple(r["shape"]))
    return w.bytes()


def decode_request_list(data: bytes) -> Tuple[List[dict], bool]:
    """Parse a RequestList into planner dicts. Mirrors
    RequestList::ParseFrom."""
    r = _Reader(data)
    shutdown = r.i32() != 0
    n = r.i32()
    out: List[dict] = []
    for _ in range(n):
        rank = r.i32()
        op = r.i32()
        dt = r.i32()
        name = r.s()
        root = r.i32()
        device = r.i32()
        ndims = r.i32()
        shape = tuple(r.i64() for _ in range(ndims))
        nbytes = dtype_size(dt)
        for d in shape:
            nbytes *= d
        out.append({"rank": rank, "op": op, "dtype": dtype_name(dt),
                    "name": name, "root_rank": root, "device": device,
                    "shape": shape, "nbytes": nbytes})
    return out, shutdown


# -------------------------------------------------------------- responses

def encode_response_list(groups: List[dict], shutdown: bool = False,
                         nproc: int = 1) -> bytes:
    """Serialize planner group dicts ({op, names, error, sizes, flags}) as
    a ResponseList. ``sizes`` maps name -> per-process first dims; the wire
    flattens them in tensor_names order (mpi_message.h:147-152)."""
    w = _Writer()
    w.i32(1 if shutdown else 0)
    w.i32(len(groups))
    for g in groups:
        op = ERROR if g.get("error") else int(g["op"])
        w.i32(op)
        names = list(g["names"])
        w.i32(len(names))
        for n in names:
            w.s(n)
        w.s(g.get("error", "") or "")
        w.i32(0)  # devices (CPU_DEVICE_ID implied; not used on TPU path)
        sizes = g.get("sizes") or {}
        flat: List[int] = []
        if sizes and not g.get("error"):
            for n in names:
                flat.extend(int(x) for x in sizes.get(n, ()))
        w.i32(len(flat))
        for v in flat:
            w.i64(v)
        w.i32(int(g.get("flags", 0)))
    return w.bytes()


def decode_response_list(data: bytes, nproc: int) -> Tuple[List[dict], bool]:
    """Parse a ResponseList into engine group dicts. Per-tensor allgather
    sizes are re-grouped from the flat wire layout (nproc entries per
    tensor, tensor_names order)."""
    r = _Reader(data)
    shutdown = r.i32() != 0
    count = r.i32()
    groups: List[dict] = []
    for _ in range(count):
        op = r.i32()
        n_names = r.i32()
        names = [r.s() for _ in range(n_names)]
        error = r.s()
        n_dev = r.i32()
        for _ in range(n_dev):
            r.i32()
        n_sizes = r.i32()
        flat = [r.i64() for _ in range(n_sizes)]
        flags = r.i32()
        sizes: Dict[str, List[int]] = {}
        if flat and nproc > 0 and len(flat) == len(names) * nproc:
            for i, nm in enumerate(names):
                sizes[nm] = flat[i * nproc:(i + 1) * nproc]
        groups.append({"op": op, "names": names, "error": error,
                       "sizes": sizes, "flags": flags})
    return groups, shutdown
