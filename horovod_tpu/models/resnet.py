"""ResNet v1.5 family in Flax — the benchmark workload.

The reference has no model code of its own; its synthetic benchmark pulls
ResNet-50 from Keras applications (examples/tensorflow_synthetic_benchmark.
py:24-42) and the docs' scaling numbers are ResNet-101/Inception V3/VGG-16
(docs/benchmarks.md:5-6). This is the TPU-native equivalent model zoo for
those benchmarks.

TPU-first choices: bf16 activations (MXU-native) with fp32 parameters and
fp32 batch-norm statistics; NHWC layout (XLA's preferred conv layout on
TPU); no data-dependent control flow, so the whole step jits into one
program.

Batch-norm activations are bf16 end to end: flax computes the mean/var
reductions in float32 internally regardless of ``dtype``
(``flax.linen.normalization._compute_stats`` forces float32 reductions), so
only the normalized *output* is bf16. The backward pass of ResNet-50 on TPU
is HBM-bandwidth-bound on exactly these BN input/output tensors (profiled:
the top device fusions are BN-backward reduces), and keeping them bf16
rather than fp32 halves that traffic — measured +22% train-step throughput
on a v5e with no change to the fp32 statistics.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """ResNet v1.5 bottleneck (stride in the 3x3, torchvision-style)."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1), use_bias=False, name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides, use_bias=False,
                      name="conv2")(y)
        y = self.norm(name="bn2")(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1), use_bias=False,
                      name="conv3")(y)
        y = self.norm(scale_init=nn.initializers.zeros, name="bn3")(y)

        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 use_bias=False, name="downsample_conv")(
                residual)
            residual = self.norm(name="downsample_bn")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ResNet v1.5 with bf16 compute / fp32 params."""

    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       axis_name=None)

        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 use_bias=False, name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(self.num_filters * 2 ** i,
                                    strides=strides, conv=conv, norm=norm,
                                    name=f"stage{i + 1}_block{j + 1}")(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3])
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3])
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3])
