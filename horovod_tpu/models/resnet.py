"""ResNet v1.5 family in Flax — the benchmark workload.

The reference has no model code of its own; its synthetic benchmark pulls
ResNet-50 from Keras applications (examples/tensorflow_synthetic_benchmark.
py:24-42) and the docs' scaling numbers are ResNet-101/Inception V3/VGG-16
(docs/benchmarks.md:5-6). This is the TPU-native equivalent model zoo for
those benchmarks.

TPU-first choices: bf16 activations (MXU-native) with fp32 parameters and
fp32 batch-norm statistics; NHWC layout (XLA's preferred conv layout on
TPU); no data-dependent control flow, so the whole step jits into one
program.

Batch-norm activations are bf16 end to end: flax computes the mean/var
reductions in float32 internally regardless of ``dtype``
(``flax.linen.normalization._compute_stats`` forces float32 reductions), so
only the normalized *output* is bf16. The backward pass of ResNet-50 on TPU
is HBM-bandwidth-bound on exactly these BN input/output tensors (profiled:
the top device fusions are BN-backward reduces), and keeping them bf16
rather than fp32 halves that traffic — measured +22% train-step throughput
on a v5e with no change to the fp32 statistics.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from ..ops import fused_bn

ModuleDef = Any


class FusedBNAct(nn.Module):
    """Train/eval batch-norm with the residual add and ReLU fused into
    the op (ops/fused_bn.py) — a hand-written 2+3-pass custom VJP
    instead of flax autodiff's graph. Parameter/stat layout matches
    ``nn.BatchNorm`` ('scale'/'bias' params, batch_stats 'mean'/'var',
    biased fp32 moments, same momentum update), so checkpoints are
    interchangeable with the unfused model."""

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    relu: bool = True
    scale_init: Callable = nn.initializers.ones
    impl: str = "auto"  # fused_bn.bn_act impls; 'auto' measured fastest

    @nn.compact
    def __call__(self, x, residual=None):
        c = x.shape[-1]
        gamma = self.param("scale", self.scale_init, (c,), jnp.float32)
        beta = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean",
            lambda s: jnp.zeros(s, jnp.float32), (c,))
        ra_var = self.variable(
            "batch_stats", "var",
            lambda s: jnp.ones(s, jnp.float32), (c,))
        if self.use_running_average:
            return fused_bn.bn_act_inference(
                x, gamma, beta, ra_mean.value, ra_var.value,
                residual=residual, eps=self.epsilon, relu=self.relu)
        y, mean, var = fused_bn.bn_act(
            x, gamma, beta, residual=residual, eps=self.epsilon,
            relu=self.relu, impl=self.impl)
        if not self.is_initializing():
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1.0 - m) * mean
            ra_var.value = m * ra_var.value + (1.0 - m) * var
        return y


class BottleneckBlock(nn.Module):
    """ResNet v1.5 bottleneck (stride in the 3x3, torchvision-style).

    With ``fused_norm`` set (a FusedBNAct partial), each bn+relu pair is
    one fused op and the block's residual join (bn3 + add + relu) is a
    single bn_act with the residual fused in — same parameter tree as
    the flax path."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu
    fused_norm: Optional[ModuleDef] = None

    @nn.compact
    def __call__(self, x):
        residual = x
        fused = self.fused_norm
        if fused is not None and self.act is not nn.relu:
            # The fused op hardcodes ReLU; honoring a custom activation
            # silently with ReLU instead would make the two impls
            # (documented as computing the same function) diverge.
            raise ValueError(
                "fused_norm supports act=nn.relu only; use the flax "
                "norm path (bn_impl='flax') with a custom activation")
        y = self.conv(self.filters, (1, 1), use_bias=False, name="conv1")(x)
        if fused is not None:
            y = fused(name="bn1")(y)
        else:
            y = self.act(self.norm(name="bn1")(y))
        y = self.conv(self.filters, (3, 3), self.strides, use_bias=False,
                      name="conv2")(y)
        if fused is not None:
            y = fused(name="bn2")(y)
        else:
            y = self.act(self.norm(name="bn2")(y))
        y = self.conv(self.filters * 4, (1, 1), use_bias=False,
                      name="conv3")(y)

        if residual.shape[-1] != self.filters * 4 or self.strides != (1, 1):
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 use_bias=False, name="downsample_conv")(
                residual)
            if fused is not None:
                residual = fused(relu=False, name="downsample_bn")(residual)
            else:
                residual = self.norm(name="downsample_bn")(residual)
        if fused is not None:
            return fused(scale_init=nn.initializers.zeros,
                         name="bn3")(y, residual=residual)
        y = self.norm(scale_init=nn.initializers.zeros, name="bn3")(y)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ResNet v1.5 with bf16 compute / fp32 params.

    ``bn_impl`` selects the batch-norm implementation: 'flax' (default)
    is plain ``nn.BatchNorm`` + separate relu/add; anything else routes
    through the fused bn(+residual)(+relu) custom-VJP op
    (ops/fused_bn.py) with that string as its impl
    ('auto'/'jnp'/'pallas'/'interpret'). Both paths share one parameter
    tree. 'flax' is the default because it MEASURES fastest end to end
    on v5e (full train step, in-process A/B, experiments/resnet_ab.py:
    flax 2312 img/s vs hand-structured jnp VJP 1586 vs Pallas kernels
    1002): XLA's whole-graph fusion of the autodiff backward beats
    locally pass-optimal but fusion-opaque custom ops — see
    docs/benchmarks.md for the full measurement ladder.

    ``bn_axis_name`` enables distributed batch norm
    (docs/data.md#sync-bn): batch statistics psum'd across the named
    mesh axis — the large-batch technique of arXiv 1909.09756 — with
    the same parameter/stat tree as the local paths. Requires the
    model to run inside ``shard_map``/``pmap`` over that axis, and
    ``bn_impl='flax'`` (the fused custom-VJP op computes its stats
    internally)."""

    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    bn_impl: str = "flax"
    bn_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, dtype=self.dtype)
        if self.bn_axis_name is not None:
            if self.bn_impl != "flax":
                raise ValueError(
                    "bn_axis_name (distributed batch norm) requires "
                    "bn_impl='flax': the fused bn op computes its "
                    "statistics inside its custom VJP and cannot psum "
                    "them (docs/data.md#sync-bn)")
            from ..data.sync_bn import SyncBatchNorm
            norm = partial(SyncBatchNorm, use_running_average=not train,
                           axis_name=self.bn_axis_name, momentum=0.9,
                           epsilon=1e-5, dtype=self.dtype)
        else:
            norm = partial(nn.BatchNorm, use_running_average=not train,
                           momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                           axis_name=None)
        fused = None
        if self.bn_impl != "flax":
            fused = partial(FusedBNAct, use_running_average=not train,
                            momentum=0.9, epsilon=1e-5,
                            impl=self.bn_impl)

        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 use_bias=False, name="conv_init")(x)
        if fused is not None:
            x = fused(name="bn_init")(x)
        else:
            x = nn.relu(norm(name="bn_init")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(self.num_filters * 2 ** i,
                                    strides=strides, conv=conv, norm=norm,
                                    fused_norm=fused,
                                    name=f"stage{i + 1}_block{j + 1}")(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3])
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3])
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3])
