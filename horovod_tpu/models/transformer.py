"""Flagship Transformer LM — the model that exercises every parallelism
axis the framework offers (dp / tp / sp / ep; pp via parallel.pipeline).

The reference carries no model code (SURVEY.md §2: "no model code"); this
is the TPU-native flagship used by __graft_entry__ and the long-context
benchmarks. Design:

  - Decoder-only pre-norm Transformer, GPT-style.
  - bf16 activations, fp32 params/layernorms, MXU-shaped matmuls.
  - Written shard_map-style: the *functional* apply takes the mesh axis
    names active for tensor ('tp') and sequence ('sp') parallelism; the
    attention runs ring attention when 'sp' is active.
  - Optional MoE MLP every other block over 'ep'.
  - ``jax.checkpoint`` (remat) around each block: HBM-for-FLOPs trade.

Parameters are created with plain ``init`` and sharded by
:func:`param_specs`, so jit-level code can use ordinary NamedSharding
constraint-based partitioning.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.ring_attention import ring_attention, full_attention
from ..parallel.expert import moe_apply, moe_init


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32000
    d_model: int = 512
    # TPU sizing: when n_heads is None it is derived as
    # max(1, d_model // 128) so head_dim == 128 — the MXU is 128 lanes
    # wide, and every attention matmul contracts over head_dim, so
    # head_dim 64 runs the systolic array half empty. Measured (v5e, 12
    # layers, d_model 768, seq 8192): 12 heads (d=64) 8.1k tok/s vs 6
    # heads (d=128) 16.9k tok/s — 2.1x from this knob alone.
    # CHANGELOG: before round 3 the default was a fixed head count (8 in
    # round 1, 4 in round 2). QKV projection shapes are d_model x d_model
    # either way, so old checkpoints LOAD cleanly but compute different
    # attention under a different head count — pass n_heads explicitly
    # when restoring a checkpoint trained under an old default.
    n_heads: Optional[int] = None
    n_layers: int = 4
    d_ff: int = 2048
    max_seq: int = 2048
    dtype: Any = jnp.bfloat16
    # parallelism axis names; None disables that axis
    tp_axis: Optional[str] = None
    sp_axis: Optional[str] = None
    ep_axis: Optional[str] = None
    # sequence-parallel attention: 'ring' (K/V ppermute ring, any head
    # count) or 'ulysses' (two all_to_alls, heads % sp_size == 0)
    sp_impl: str = "ring"
    # single-shard attention via the Pallas flash kernel
    # (ops/flash_attention.py) instead of XLA full attention. None (the
    # default) auto-selects by sequence length: measured on v5e, XLA wins
    # at 2k (32.6k vs 20.5k tok/s full step, 125M params) and flash wins
    # 8.1x at 8k (8.8k vs 1.1k tok/s) — crossover ~4k, where the [S, S]
    # score matrix stops fitting on chip.
    use_flash: Optional[bool] = None
    # MoE: when set, every other block's MLP is a top-1 MoE
    num_experts: int = 0
    capacity_factor: float = 2.0
    # jax.checkpoint around each block. Default ON (the safe choice for
    # long sequences / big models); when activations fit HBM, turning it
    # off is worth ~1.3x (measured v5e, seq 8192: 16.9k -> 21.5k tok/s).
    remat: bool = True

    def __post_init__(self):
        if self.n_heads is None:
            # Largest head count that DIVIDES d_model with head_dim >=
            # 128 (a blind d_model // 128 can fail the divisibility
            # check, e.g. d_model=448 -> 3).
            n = max(1, self.d_model // 128)
            while self.d_model % n:
                n -= 1
            object.__setattr__(self, "n_heads", n)
        if self.num_experts and not self.ep_axis:
            raise ValueError(
                "num_experts > 0 requires ep_axis (the expert-parallel mesh "
                "axis the MoE all_to_all routes over)")
        if self.sp_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"sp_impl must be 'ring' or 'ulysses', got "
                f"{self.sp_impl!r}")


def _axis_size(axis: Optional[str]) -> int:
    return lax.axis_size(axis) if axis else 1


def init_params(cfg: TransformerConfig, rng) -> Dict:
    """Initialize GLOBAL parameters (unsharded; shard via param_specs)."""
    keys = jax.random.split(rng, cfg.n_layers + 2)
    d, h, f = cfg.d_model, cfg.n_heads, cfg.d_ff
    scale = d ** -0.5

    def dense(key, shape, s):
        return jax.random.normal(key, shape, jnp.float32) * s

    layers = []
    for i in range(cfg.n_layers):
        k = jax.random.split(keys[i], 8)
        layer = {
            "ln1": jnp.ones((d,), jnp.float32),
            "ln2": jnp.ones((d,), jnp.float32),
            "wq": dense(k[0], (d, d), scale),
            "wk": dense(k[1], (d, d), scale),
            "wv": dense(k[2], (d, d), scale),
            "wo": dense(k[3], (d, d), scale),
        }
        if cfg.num_experts and i % 2 == 1:
            layer["moe"] = moe_init(
                k[4], num_experts=cfg.num_experts,
                experts_per_shard=cfg.num_experts,  # global at init
                features=d, hidden=f)
        else:
            layer["wi"] = dense(k[5], (d, f), scale)
            layer["wo_mlp"] = dense(k[6], (f, d), f ** -0.5)
        layers.append(layer)

    return {
        "embed": dense(keys[-2], (cfg.vocab, d), 1.0),
        "pos": dense(keys[-1], (cfg.max_seq, d), 0.02),
        "ln_f": jnp.ones((d,), jnp.float32),
        "layers": layers,
    }


def param_specs(cfg: TransformerConfig) -> Dict:
    """PartitionSpecs for jit-level sharding (scaling-book style):
    tensor-parallel weights split on the head/ff dimension over 'tp',
    experts over 'ep', everything else replicated (dp shards data, not
    params)."""
    tp = cfg.tp_axis
    ep = cfg.ep_axis
    layer_specs = []
    for i in range(cfg.n_layers):
        spec = {
            "ln1": P(), "ln2": P(),
            # Column-parallel QKV (split output dim), row-parallel out-proj
            # (split input dim) — Megatron pairing, one psum per block.
            "wq": P(None, tp), "wk": P(None, tp), "wv": P(None, tp),
            "wo": P(tp, None),
        }
        if cfg.num_experts and i % 2 == 1:
            spec["moe"] = {"router": P(), "wi": P(ep, None, None),
                           "wo": P(ep, None, None)}
        else:
            spec["wi"] = P(None, tp)
            spec["wo_mlp"] = P(tp, None)
        layer_specs.append(spec)
    return {"embed": P(), "pos": P(), "ln_f": P(), "layers": layer_specs}


def _layernorm(x, g):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + 1e-5) * g).astype(x.dtype)


def _block(params, x, cfg: TransformerConfig, layer_idx: int):
    """One decoder block, shard_map-level (per-shard views).

    x: [B, S_local, D]. Attention: heads are split over 'tp' (the wq/wk/wv
    shards produce local heads), sequence over 'sp' (ring attention).
    """
    d = cfg.d_model
    tp_n = _axis_size(cfg.tp_axis)
    if cfg.n_heads % tp_n:
        raise ValueError(
            f"n_heads ({cfg.n_heads}) must be divisible by the tensor-"
            f"parallel axis size ({tp_n})")
    if d % cfg.n_heads:
        raise ValueError(
            f"d_model ({d}) must be divisible by n_heads ({cfg.n_heads})")
    h_local = cfg.n_heads // tp_n
    hd = d // cfg.n_heads
    dt = cfg.dtype

    y = _layernorm(x, params["ln1"])
    b, s, _ = y.shape
    q = (y @ params["wq"].astype(dt)).reshape(b, s, h_local, hd)
    k = (y @ params["wk"].astype(dt)).reshape(b, s, h_local, hd)
    v = (y @ params["wv"].astype(dt)).reshape(b, s, h_local, hd)

    import jax as _jax
    flash_interp = _jax.default_backend() != "tpu"  # interpret off-TPU
    # Auto policy: compiled flash from 4k *attended* sequence (the
    # measured crossover, config field comment); never auto-select the
    # interpreter off-TPU, and key on this trace's length, not max_seq —
    # a short batch under a long-context config stays on XLA attention.
    # Under Ulysses the local attention runs over the GLOBAL sequence
    # (all-to-all gathers it), so the threshold compares s * sp_size.
    attended_s = s
    if cfg.sp_axis and cfg.sp_impl == "ulysses":
        attended_s = s * lax.axis_size(cfg.sp_axis)
    use_flash = (cfg.use_flash if cfg.use_flash is not None
                 else (not flash_interp and attended_s >= 4096))
    if cfg.sp_axis and cfg.sp_impl == "ulysses":
        from ..parallel.ulysses import ulysses_attention
        attn = ulysses_attention(q, k, v, axis_name=cfg.sp_axis,
                                 causal=True, use_flash=use_flash,
                                 flash_interpret=flash_interp)
    elif cfg.sp_axis:
        # Ring attention is already blockwise-O(S/n); use_flash does not
        # apply to its inner per-block matmuls.
        attn = ring_attention(q, k, v, axis_name=cfg.sp_axis, causal=True)
    elif use_flash:
        from ..ops.flash_attention import flash_attention
        # block sizes None -> tuned defaults (512 compiled / 128 interp)
        attn = flash_attention(q, k, v, True, None, None, None,
                               flash_interp)
    else:
        attn = full_attention(q, k, v, causal=True)
    attn = attn.reshape(b, s, h_local * hd)
    o = attn @ params["wo"].astype(dt)
    if cfg.tp_axis:
        o = lax.psum(o, cfg.tp_axis)   # row-parallel out-proj
    x = x + o

    y = _layernorm(x, params["ln2"])
    if cfg.num_experts and layer_idx % 2 == 1:
        tokens = y.reshape(b * s, d)
        # Under tp, split tokens across the tp axis so expert work is done
        # once per tp group (not duplicated per rank) and every parameter's
        # gradient stays a PARTIAL sum over tp — keeping the train-step's
        # uniform reduction rule (psum over model axes) correct.
        if cfg.tp_axis and tp_n > 1:
            t_local = tokens.shape[0] // tp_n
            i = lax.axis_index(cfg.tp_axis)
            tokens = lax.dynamic_slice_in_dim(tokens, i * t_local, t_local)
        out = moe_apply(params["moe"], tokens,
                        num_experts=cfg.num_experts,
                        capacity_factor=cfg.capacity_factor,
                        axis_name=cfg.ep_axis, act=jax.nn.gelu, dtype=dt)
        if cfg.tp_axis and tp_n > 1:
            out = lax.all_gather(out, cfg.tp_axis, axis=0, tiled=True)
        m = out.reshape(b, s, d)
    else:
        hmid = jax.nn.gelu(y @ params["wi"].astype(dt))
        m = hmid @ params["wo_mlp"].astype(dt)
        if cfg.tp_axis:
            m = lax.psum(m, cfg.tp_axis)
    return x + m


def apply(params, tokens, cfg: TransformerConfig):
    """Forward pass (shard_map-level). tokens: [B, S_local] int32.
    Returns logits [B, S_local, vocab] (fp32)."""
    dt = cfg.dtype
    sp_n = _axis_size(cfg.sp_axis)
    s_local = tokens.shape[1]
    if cfg.sp_axis:
        offset = lax.axis_index(cfg.sp_axis) * s_local
    else:
        offset = 0
    pos = params["pos"][offset + jnp.arange(s_local)]

    x = params["embed"].astype(dt)[tokens] + pos.astype(dt)

    block = _block
    if cfg.remat:
        block = jax.checkpoint(_block, static_argnums=(2, 3))
    for i, layer in enumerate(params["layers"]):
        x = block(layer, x, cfg, i)

    x = _layernorm(x, params["ln_f"])
    logits = x.astype(jnp.float32) @ params["embed"].T
    return logits


def loss_fn(params, tokens, targets, cfg: TransformerConfig):
    """Next-token cross-entropy, mean over local tokens; psum-mean over
    'dp'/'sp' happens via the caller's pmean."""
    logits = apply(params, tokens, cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -ll.mean()
