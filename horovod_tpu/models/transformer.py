"""Flagship Transformer LM — the model that exercises every parallelism
axis the framework offers (dp / tp / sp / ep; pp via parallel.pipeline).

The reference carries no model code (SURVEY.md §2: "no model code"); this
is the TPU-native flagship used by __graft_entry__ and the long-context
benchmarks. Design:

  - Decoder-only pre-norm Transformer, GPT-style.
  - bf16 activations, fp32 params/layernorms, MXU-shaped matmuls.
  - Written shard_map-style: the *functional* apply takes the mesh axis
    names active for tensor ('tp') and sequence ('sp') parallelism; the
    attention runs ring attention when 'sp' is active.
  - Optional MoE MLP every other block over 'ep'.
  - ``jax.checkpoint`` (remat) around each block: HBM-for-FLOPs trade.

Parameters are created with plain ``init`` and sharded by
:func:`param_specs`, so jit-level code can use ordinary NamedSharding
constraint-based partitioning.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.ring_attention import ring_attention, full_attention
from ..parallel.expert import moe_apply, moe_init


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32000
    d_model: int = 512
    # TPU sizing: when n_heads is None it is derived as
    # max(1, d_model // 128) so head_dim == 128 — the MXU is 128 lanes
    # wide, and every attention matmul contracts over head_dim, so
    # head_dim 64 runs the systolic array half empty. Measured (v5e, 12
    # layers, d_model 768, seq 8192): 12 heads (d=64) 8.1k tok/s vs 6
    # heads (d=128) 16.9k tok/s — 2.1x from this knob alone.
    # CHANGELOG: before round 3 the default was a fixed head count (8 in
    # round 1, 4 in round 2). QKV projection shapes are d_model x d_model
    # either way, so old checkpoints LOAD cleanly but compute different
    # attention under a different head count — pass n_heads explicitly
    # when restoring a checkpoint trained under an old default.
    n_heads: Optional[int] = None
    n_layers: int = 4
    d_ff: int = 2048
    max_seq: int = 2048
    dtype: Any = jnp.bfloat16
    # parallelism axis names; None disables that axis
    tp_axis: Optional[str] = None
    sp_axis: Optional[str] = None
    ep_axis: Optional[str] = None
    # sequence-parallel attention: 'ring' (K/V ppermute ring, any head
    # count) or 'ulysses' (two all_to_alls, heads % sp_size == 0)
    sp_impl: str = "ring"
    # single-shard attention via the Pallas flash kernel
    # (ops/flash_attention.py) instead of XLA full attention. None (the
    # default) auto-selects by sequence length: with the 512-block
    # kernel, measured on v5e (111M LM, full train step, in-process
    # A/B, BENCH_LM.json): flash wins ~1.5x at 2048 (137.1k vs 90.4k
    # tok/s) and 1.14x at 1024; XLA edges it at 512 (90.8k vs 86.3k)
    # — crossover ~1k.
    # (The round-2 128-block kernel crossed at ~4k; the block tuning
    # moved it.)
    use_flash: Optional[bool] = None
    # Flash kernel block size (block_q == block_k, overriding EVERY
    # kernel). None = the tuned per-kernel defaults (fwd 1024x1024,
    # dkv 512x1024, dq 1024x512 compiled / 128 interpreted —
    # ops/flash_attention.py _default_block). Exposed for
    # long-sequence block sweeps — the optimum can shift with seq
    # length and head_dim (1024 measured ~1% faster at seq 8192 but
    # intermittently fails to compile at larger batch*heads). Applies
    # to the single-shard and Ulysses paths; ring attention is its own
    # blockwise schedule (shard-sized blocks) and takes no flash block.
    flash_block: Optional[int] = None
    # MoE: when set, every other block's MLP is a top-1 MoE
    num_experts: int = 0
    capacity_factor: float = 2.0
    # jax.checkpoint around each block. Default ON (the safe choice for
    # long sequences / big models); when activations fit HBM, turning it
    # off is worth ~1.3x (measured v5e, seq 8192: 16.9k -> 21.5k tok/s).
    remat: bool = True
    # Checkpoint policy when remat is on: "full" recomputes everything;
    # "dots" saves matmul outputs and recomputes only elementwise ops
    # (jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims) — the
    # standard middle ground that buys most of no-remat's speed at a
    # fraction of its memory.
    remat_policy: str = "full"
    # Compute the vocab-projection matmul in the activation dtype (bf16)
    # instead of fp32, casting to fp32 only for the softmax. The [d,V]
    # contraction is the single largest matmul in the model and fp32
    # runs the MXU at a fraction of its bf16 rate; loss numerics keep an
    # fp32 softmax either way. Off by default (bit-compatibility with
    # checkpointed logits).
    logits_bf16: bool = False
    # Chunked cross-entropy: compute the vocab projection + log-softmax
    # over sequence chunks of this many tokens (0 = whole sequence).
    # The fp32 [B, S, V] logits tensor is the largest allocation of an
    # LM step (batch 32, seq 2048, vocab 32000: 8.4 GB — more than the
    # model); chunking with per-chunk rematerialization caps it at
    # [B, chunk, V] and unlocks batch sizes the monolithic loss cannot
    # fit. Applies to loss_fn (training); apply() still returns full
    # logits for inference callers.
    loss_chunk: int = 0

    def __post_init__(self):
        if self.n_heads is None:
            # Largest head count that DIVIDES d_model with head_dim >=
            # 128 (a blind d_model // 128 can fail the divisibility
            # check, e.g. d_model=448 -> 3).
            n = max(1, self.d_model // 128)
            while self.d_model % n:
                n -= 1
            object.__setattr__(self, "n_heads", n)
        if self.num_experts and not self.ep_axis:
            raise ValueError(
                "num_experts > 0 requires ep_axis (the expert-parallel mesh "
                "axis the MoE all_to_all routes over)")
        if self.sp_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"sp_impl must be 'ring' or 'ulysses', got "
                f"{self.sp_impl!r}")
        if self.remat_policy not in ("full", "dots"):
            raise ValueError(
                f"remat_policy must be 'full' or 'dots', got "
                f"{self.remat_policy!r}")
        if self.loss_chunk < 0:
            raise ValueError(
                f"loss_chunk must be >= 0, got {self.loss_chunk}")


def _axis_size(axis: Optional[str]) -> int:
    return lax.axis_size(axis) if axis else 1


def init_params(cfg: TransformerConfig, rng) -> Dict:
    """Initialize GLOBAL parameters (unsharded; shard via param_specs)."""
    keys = jax.random.split(rng, cfg.n_layers + 2)
    d, h, f = cfg.d_model, cfg.n_heads, cfg.d_ff
    scale = d ** -0.5

    def dense(key, shape, s):
        return jax.random.normal(key, shape, jnp.float32) * s

    layers = []
    for i in range(cfg.n_layers):
        k = jax.random.split(keys[i], 8)
        layer = {
            "ln1": jnp.ones((d,), jnp.float32),
            "ln2": jnp.ones((d,), jnp.float32),
            "wq": dense(k[0], (d, d), scale),
            "wk": dense(k[1], (d, d), scale),
            "wv": dense(k[2], (d, d), scale),
            "wo": dense(k[3], (d, d), scale),
        }
        if cfg.num_experts and i % 2 == 1:
            layer["moe"] = moe_init(
                k[4], num_experts=cfg.num_experts,
                experts_per_shard=cfg.num_experts,  # global at init
                features=d, hidden=f)
        else:
            layer["wi"] = dense(k[5], (d, f), scale)
            layer["wo_mlp"] = dense(k[6], (f, d), f ** -0.5)
        layers.append(layer)

    return {
        "embed": dense(keys[-2], (cfg.vocab, d), 1.0),
        "pos": dense(keys[-1], (cfg.max_seq, d), 0.02),
        "ln_f": jnp.ones((d,), jnp.float32),
        "layers": layers,
    }


def param_specs(cfg: TransformerConfig) -> Dict:
    """PartitionSpecs for jit-level sharding (scaling-book style):
    tensor-parallel weights split on the head/ff dimension over 'tp',
    experts over 'ep', everything else replicated (dp shards data, not
    params)."""
    tp = cfg.tp_axis
    ep = cfg.ep_axis
    layer_specs = []
    for i in range(cfg.n_layers):
        spec = {
            "ln1": P(), "ln2": P(),
            # Column-parallel QKV (split output dim), row-parallel out-proj
            # (split input dim) — Megatron pairing, one psum per block.
            "wq": P(None, tp), "wk": P(None, tp), "wv": P(None, tp),
            "wo": P(tp, None),
        }
        if cfg.num_experts and i % 2 == 1:
            spec["moe"] = {"router": P(), "wi": P(ep, None, None),
                           "wo": P(ep, None, None)}
        else:
            spec["wi"] = P(None, tp)
            spec["wo_mlp"] = P(tp, None)
        layer_specs.append(spec)
    return {"embed": P(), "pos": P(), "ln_f": P(), "layers": layer_specs}


def _layernorm(x, g):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + 1e-5) * g).astype(x.dtype)


def _block(params, x, cfg: TransformerConfig, layer_idx: int):
    """One decoder block, shard_map-level (per-shard views).

    x: [B, S_local, D]. Attention: heads are split over 'tp' (the wq/wk/wv
    shards produce local heads), sequence over 'sp' (ring attention).
    """
    d = cfg.d_model
    tp_n = _axis_size(cfg.tp_axis)
    if cfg.n_heads % tp_n:
        raise ValueError(
            f"n_heads ({cfg.n_heads}) must be divisible by the tensor-"
            f"parallel axis size ({tp_n})")
    if d % cfg.n_heads:
        raise ValueError(
            f"d_model ({d}) must be divisible by n_heads ({cfg.n_heads})")
    h_local = cfg.n_heads // tp_n
    hd = d // cfg.n_heads
    dt = cfg.dtype

    y = _layernorm(x, params["ln1"])
    b, s, _ = y.shape
    q = (y @ params["wq"].astype(dt)).reshape(b, s, h_local, hd)
    k = (y @ params["wk"].astype(dt)).reshape(b, s, h_local, hd)
    v = (y @ params["wv"].astype(dt)).reshape(b, s, h_local, hd)

    import jax as _jax
    flash_interp = _jax.default_backend() != "tpu"  # interpret off-TPU
    # Auto policy: compiled flash from 1k *attended* sequence (the
    # measured crossover, config field comment); never auto-select the
    # interpreter off-TPU, and key on this trace's length, not max_seq —
    # a short batch under a long-context config stays on XLA attention.
    # Under Ulysses the local attention runs over the GLOBAL sequence
    # (all-to-all gathers it), so the threshold compares s * sp_size.
    attended_s = s
    if cfg.sp_axis and cfg.sp_impl == "ulysses":
        attended_s = s * lax.axis_size(cfg.sp_axis)
    use_flash = (cfg.use_flash if cfg.use_flash is not None
                 else (not flash_interp and attended_s >= 1024))
    if cfg.sp_axis and cfg.sp_impl == "ulysses":
        from ..parallel.ulysses import ulysses_attention
        attn = ulysses_attention(q, k, v, axis_name=cfg.sp_axis,
                                 causal=True, use_flash=use_flash,
                                 flash_block=cfg.flash_block,
                                 flash_interpret=flash_interp)
    elif cfg.sp_axis:
        # Ring attention is blockwise ACROSS shards, but its plain
        # inner op still materializes [shard, shard] scores; use_flash
        # keys the per-shard-pair computation on this trace's SHARD
        # length (each ring step attends q-shard x kv-shard).
        attn = ring_attention(q, k, v, axis_name=cfg.sp_axis, causal=True,
                              use_flash=use_flash,
                              flash_block=cfg.flash_block,
                              flash_interpret=flash_interp)
    elif use_flash:
        from ..ops.flash_attention import flash_attention
        # block sizes None -> tuned defaults (512 compiled / 128 interp)
        attn = flash_attention(q, k, v, True, None, cfg.flash_block,
                               cfg.flash_block, flash_interp)
    else:
        attn = full_attention(q, k, v, causal=True)
    attn = attn.reshape(b, s, h_local * hd)
    o = attn @ params["wo"].astype(dt)
    if cfg.tp_axis:
        o = lax.psum(o, cfg.tp_axis)   # row-parallel out-proj
    x = x + o

    y = _layernorm(x, params["ln2"])
    if cfg.num_experts and layer_idx % 2 == 1:
        tokens = y.reshape(b * s, d)
        # Under tp, split tokens across the tp axis so expert work is done
        # once per tp group (not duplicated per rank) and every parameter's
        # gradient stays a PARTIAL sum over tp — keeping the train-step's
        # uniform reduction rule (psum over model axes) correct.
        if cfg.tp_axis and tp_n > 1:
            t_local = tokens.shape[0] // tp_n
            i = lax.axis_index(cfg.tp_axis)
            tokens = lax.dynamic_slice_in_dim(tokens, i * t_local, t_local)
        out = moe_apply(params["moe"], tokens,
                        num_experts=cfg.num_experts,
                        capacity_factor=cfg.capacity_factor,
                        axis_name=cfg.ep_axis, act=jax.nn.gelu, dtype=dt)
        if cfg.tp_axis and tp_n > 1:
            out = lax.all_gather(out, cfg.tp_axis, axis=0, tiled=True)
        m = out.reshape(b, s, d)
    else:
        hmid = jax.nn.gelu(y @ params["wi"].astype(dt))
        m = hmid @ params["wo_mlp"].astype(dt)
        if cfg.tp_axis:
            m = lax.psum(m, cfg.tp_axis)
    return x + m


def apply_hidden(params, tokens, cfg: TransformerConfig):
    """Forward pass up to the final layernorm (shard_map-level).
    tokens: [B, S_local] int32; returns hidden [B, S_local, d]."""
    dt = cfg.dtype
    s_local = tokens.shape[1]
    if cfg.sp_axis:
        offset = lax.axis_index(cfg.sp_axis) * s_local
    else:
        offset = 0
    pos = params["pos"][offset + jnp.arange(s_local)]

    x = params["embed"].astype(dt)[tokens] + pos.astype(dt)

    block = _block
    if cfg.remat:
        policy = None
        if cfg.remat_policy == "dots":
            policy = (jax.checkpoint_policies
                      .checkpoint_dots_with_no_batch_dims)
        block = jax.checkpoint(_block, static_argnums=(2, 3),
                               policy=policy)
    for i, layer in enumerate(params["layers"]):
        x = block(layer, x, cfg, i)

    return _layernorm(x, params["ln_f"])


def _project_logits(params, x, cfg: TransformerConfig):
    if cfg.logits_bf16:
        return (x @ params["embed"].astype(cfg.dtype).T).astype(
            jnp.float32)
    return x.astype(jnp.float32) @ params["embed"].T


def apply(params, tokens, cfg: TransformerConfig):
    """Forward pass (shard_map-level). tokens: [B, S_local] int32.
    Returns logits [B, S_local, vocab] (fp32)."""
    return _project_logits(params, apply_hidden(params, tokens, cfg), cfg)


# --------------------------------------------------------------------------
# Incremental decode — block-sliced KV cache (the serving tier's forward)
# --------------------------------------------------------------------------
#
# The cache is a list (one entry per layer) of {"k", "v"} arrays of shape
# [n_blocks, block_size, n_heads, head_dim]: a flat pool of fixed-size
# token blocks, vLLM-style, so sequences of any length share one
# allocation and freeing a finished request returns whole blocks to the
# pool instead of fragmenting a contiguous [B, S_max] cache. A sequence
# addresses its tokens through a *block table*: entry ``j`` of its table
# names the pool block holding absolute positions ``[j*bs, (j+1)*bs)``.
# Block 0 is reserved as a scratch block (serving/kv_cache.py never
# hands it out): padded or inactive slots write their garbage K/V there,
# where no live sequence can read it.
#
# Quantized pool (``kv_quant``): the same layout with the payload held
# in int8 / fp8-e4m3 and fp32 absmax scales per channel block — the
# wire format of quantization.py (EQuARX, arXiv 2506.17615) applied at
# rest instead of in flight. Scales are per (block, token, head,
# head_dim-chunk) with the chunk = ``channel_block(head_dim, 256)``, so
# blocks never straddle heads and a tensor-parallel head shard
# quantizes bit-identically to the same head at tp=1. Dequantization
# happens on read, fused into the attention program; the block-table
# indirection (and with it every allocator/eviction invariant) is
# untouched.


def _kv_spec(kv_quant):
    from .. import quantization as q
    return q.parse(kv_quant)


def init_cache(cfg: TransformerConfig, n_blocks: int, block_size: int,
               kv_quant=None):
    """Zeroed GLOBAL KV pool (shard via :func:`cache_specs`): per layer
    ``{"k", "v"}`` of [n_blocks, block_size, n_heads, head_dim] in the
    activation dtype — or, with ``kv_quant`` ("int8"/"fp8"/a WireSpec),
    the wire-dtype payload plus ``{"ks", "vs"}`` fp32 channel-block
    scales."""
    from .. import quantization as q
    hd = cfg.d_model // cfg.n_heads
    shape = (int(n_blocks), int(block_size), cfg.n_heads, hd)
    spec = _kv_spec(kv_quant)
    if spec is None:
        return [{"k": jnp.zeros(shape, cfg.dtype),
                 "v": jnp.zeros(shape, cfg.dtype)}
                for _ in range(cfg.n_layers)]
    qdt = getattr(jnp, spec.wire_dtype)
    sshape = shape[:3] + (hd // q.channel_block(hd, spec.block_size),)
    return [{"k": jnp.zeros(shape, qdt), "v": jnp.zeros(shape, qdt),
             "ks": jnp.ones(sshape, jnp.float32),
             "vs": jnp.ones(sshape, jnp.float32)}
            for _ in range(cfg.n_layers)]


def cache_specs(cfg: TransformerConfig, kv_quant=None):
    """PartitionSpecs for the KV pool — heads over 'tp' (the same axis
    the wq/wk/wv column splits produce the local heads on), block and
    token dims replicated. Quantized pools shard the scales on the same
    head axis, so each shard's payload travels with its scales."""
    spec = P(None, None, cfg.tp_axis, None)
    if _kv_spec(kv_quant) is None:
        return [{"k": spec, "v": spec} for _ in range(cfg.n_layers)]
    return [{"k": spec, "v": spec, "ks": spec, "vs": spec}
            for _ in range(cfg.n_layers)]


def kv_bytes_per_block(cfg: TransformerConfig, block_size: int,
                       kv_quant=None) -> int:
    """Resident HBM bytes ONE pool block costs across all layers (K and
    V, scales included) — what the engine's ``kv_bytes_resident`` gauge
    multiplies in-use blocks by, and what the 4x-sequences-per-byte
    claim of the quantized pool is measured against."""
    from .. import quantization as q
    hd = cfg.d_model // cfg.n_heads
    elems = int(block_size) * cfg.n_heads * hd
    spec = _kv_spec(kv_quant)
    import numpy as _np
    if spec is None:
        per = elems * _np.dtype(cfg.dtype).itemsize
    else:
        scales = elems // q.channel_block(hd, spec.block_size)
        per = elems * 1 + scales * 4
    return 2 * per * cfg.n_layers


def _decode_block(params, x, layer_cache, tables, pos,
                  cfg: TransformerConfig, kv_spec=None,
                  exact_chunk: bool = False):
    """One decoder block over the KV cache (shard_map-level, per-shard
    views: under 'tp' the projections produce local heads and the cache
    holds the matching head shard).

    x: [B, Q, D] new-token activations; pos: [B, Q] absolute positions;
    tables: [B, T] block ids. Writes this chunk's K/V into the pool,
    then attends causally over everything cached so far (numerics mirror
    :func:`full_attention` so incremental logits match the full-context
    ``apply`` bit-for-bit up to fp reassociation).

    With ``kv_spec`` the pool holds wire-dtype payload + fp32 channel
    scales; the write quantizes, the read dequantizes inside this same
    program. ``exact_chunk`` additionally overwrites THIS chunk's rows
    of the gathered K/V with the exact pre-quantization values — the
    prefill mode, making a from-empty prefill bit-identical to the fp32
    pool (only *past* tokens ever pay quantization error). Decode and
    speculative verification run with it OFF, so a [slots, k] verify
    reads the chunk exactly as the [slots, 1] decode path would have
    re-read it — the greedy token-identity guarantee between the two.
    """
    from .. import quantization as quant
    kc, vc = layer_cache["k"], layer_cache["v"]
    d = cfg.d_model
    tp_n = _axis_size(cfg.tp_axis)
    if cfg.n_heads % tp_n:
        raise ValueError(
            f"n_heads ({cfg.n_heads}) must be divisible by the tensor-"
            f"parallel axis size ({tp_n})")
    h_local = cfg.n_heads // tp_n
    hd = d // cfg.n_heads
    dt = cfg.dtype
    b, q_len, _ = x.shape
    bs = kc.shape[1]

    y = _layernorm(x, params["ln1"])
    q = (y @ params["wq"].astype(dt)).reshape(b, q_len, h_local, hd)
    k = (y @ params["wk"].astype(dt)).reshape(b, q_len, h_local, hd)
    v = (y @ params["wv"].astype(dt)).reshape(b, q_len, h_local, hd)

    # Scatter the chunk's K/V into its blocks: position p lives at
    # (table[p // bs], p % bs). Distinct live sequences own disjoint
    # blocks (the allocator's invariant), so the scatter never collides
    # except on the shared scratch block 0 — whose content is never
    # visible under the causal mask below. Positions past the table
    # (a speculative chunk overrunning the reserved region) divert to
    # scratch instead of clobbering a neighbour's block.
    T = tables.shape[1]
    blk = jnp.take_along_axis(tables, jnp.minimum(pos // bs, T - 1),
                              axis=1)                           # [B, Q]
    blk = jnp.where(pos < T * bs, blk, 0)
    off = pos % bs
    out_cache = {}
    if kv_spec is None:
        kc = kc.at[blk, off].set(k.astype(kc.dtype))
        vc = vc.at[blk, off].set(v.astype(vc.dtype))
    else:
        qk, sk = quant.quantize_channels(k, kv_spec)
        qv, sv = quant.quantize_channels(v, kv_spec)
        kc = kc.at[blk, off].set(qk)
        vc = vc.at[blk, off].set(qv)
        ks = layer_cache["ks"].at[blk, off].set(sk)
        vs = layer_cache["vs"].at[blk, off].set(sv)
        out_cache["ks"], out_cache["vs"] = ks, vs
    out_cache["k"], out_cache["v"] = kc, vc

    # Gather the sequence's pages back in table order — entry j covers
    # positions [j*bs, (j+1)*bs), so the flattened page axis IS the
    # absolute-position axis and the causal mask is a plain arange
    # comparison. Unwritten tail blocks are masked off (their positions
    # exceed every query position).
    s_pad = T * bs
    if kv_spec is None:
        keys = kc[tables].reshape(b, s_pad, h_local, hd)
        vals = vc[tables].reshape(b, s_pad, h_local, hd)
    else:
        # Dequant-on-read, fused into this attention program: payload
        # pages and their scales gather through the same table.
        keys = quant.dequantize_channels(
            kc[tables], ks[tables], kv_spec).reshape(
            b, s_pad, h_local, hd).astype(dt)
        vals = quant.dequantize_channels(
            vc[tables], vs[tables], kv_spec).reshape(
            b, s_pad, h_local, hd).astype(dt)
        if exact_chunk:
            # Prefill: this chunk's own rows attend at full precision
            # (mode="drop" skips the scratch-diverted overrun rows).
            rows = jnp.arange(b)[:, None]
            keys = keys.at[rows, pos].set(k, mode="drop")
            vals = vals.at[rows, pos].set(v, mode="drop")
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, keys.astype(q.dtype),
                        preferred_element_type=jnp.float32) * (hd ** -0.5)
    visible = (jnp.arange(s_pad)[None, None, None, :]
               <= pos[:, None, :, None])
    scores = jnp.where(visible, scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    attn = jnp.einsum("bhqk,bkhd->bqhd", p.astype(vals.dtype), vals,
                      preferred_element_type=jnp.float32).astype(x.dtype)
    o = attn.reshape(b, q_len, h_local * hd) @ params["wo"].astype(dt)
    if cfg.tp_axis:
        o = lax.psum(o, cfg.tp_axis)   # row-parallel out-proj
    x = x + o

    y = _layernorm(x, params["ln2"])
    hmid = jax.nn.gelu(y @ params["wi"].astype(dt))
    m = hmid @ params["wo_mlp"].astype(dt)
    if cfg.tp_axis:
        m = lax.psum(m, cfg.tp_axis)
    return x + m, out_cache


def prefill_spans(n_tokens: int, chunk: int, start: int = 0):
    """``(start, length)`` spans that consume ``n_tokens`` prompt
    positions (from absolute position ``start``) in chunks of at most
    ``chunk`` — the calling convention for multi-chunk prefill through
    :func:`apply_decode`: feed each span's tokens with ``starts`` set
    to the span start, same block tables every call. Pure host-side
    arithmetic; the serving engine's budget policy sizes chunks
    adaptively instead, but composes calls the same way."""
    if n_tokens < 0:
        raise ValueError(f"n_tokens must be >= 0, got {n_tokens}")
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    out = []
    pos = int(start)
    end = int(start) + int(n_tokens)
    while pos < end:
        n = min(int(chunk), end - pos)
        out.append((pos, n))
        pos += n
    return out


def apply_decode(params, tokens, starts, block_tables, cache,
                 cfg: TransformerConfig, kv_quant=None,
                 exact_chunk: bool = False):
    """Incremental forward through the block-sliced KV cache — the
    serving counterpart of :func:`apply`, sharing its weights and
    :func:`param_specs` (shard_map-level; wrap in shard_map over 'tp'
    for tensor-parallel decode, or call directly on one device).

    tokens: [B, Q] int32 — the NEW tokens only (a prompt chunk at
    prefill, one token per live slot at decode, the draft chunk at a
    speculative verify); starts: [B] int32 — absolute position of
    ``tokens[:, 0]`` per sequence; block_tables: [B, T] int32 block ids
    (entry j covers positions [j*bs, (j+1)*bs)); cache: from
    :func:`init_cache`. Returns ``(logits, cache)`` with logits
    [B, Q, vocab] fp32 — at prefill, row ``n-1`` is the first-token
    distribution; at decode, row 0 is the next-token one.

    ``kv_quant`` must match the ``init_cache`` the pool was built with;
    ``exact_chunk`` (prefill only — see :func:`_decode_block`) keeps a
    from-empty quantized prefill bit-identical to the fp32 pool.

    Multi-chunk prefill: a prompt may be consumed as several calls —
    ``tokens`` the next span, ``starts`` where the previous call ended
    (:func:`prefill_spans` computes the split). Each call's causal
    attention covers its own chunk exactly plus everything already
    resident in the blocks, so the composition is the same computation
    as one monolithic call; under ``kv_quant`` the earlier chunks are
    read back dequantized (``exact_chunk`` covers only the current
    span), which the serving tier treats like the prefix-cache case:
    greedy-token-identical in practice, not bitwise on logits.
    """
    if cfg.sp_axis:
        raise ValueError(
            "apply_decode does not support sequence parallelism; build "
            "the serving config with sp_axis=None (decode is one token "
            "per sequence — there is no sequence to shard)")
    if cfg.num_experts:
        raise ValueError(
            "apply_decode does not support MoE layers yet; serve a "
            "dense checkpoint (num_experts=0)")
    kv_spec = _kv_spec(kv_quant)
    dt = cfg.dtype
    b, q_len = tokens.shape
    pos = starts[:, None] + jnp.arange(q_len)[None, :]
    x = params["embed"].astype(dt)[tokens] + params["pos"][pos].astype(dt)
    new_cache = []
    for i, layer in enumerate(params["layers"]):
        x, out = _decode_block(layer, x, cache[i], block_tables, pos,
                               cfg, kv_spec, exact_chunk)
        new_cache.append(out)
    h = _layernorm(x, params["ln_f"])
    return _project_logits(params, h, cfg), new_cache


def loss_fn(params, tokens, targets, cfg: TransformerConfig):
    """Next-token cross-entropy, mean over local tokens; psum-mean over
    'dp'/'sp' happens via the caller's pmean.

    With ``cfg.loss_chunk`` the vocab projection + log-softmax run over
    sequence chunks under per-chunk rematerialization, so the fp32
    [B, S, V] logits tensor — the largest allocation of an LM train
    step — never materializes (memory: [B, chunk, V])."""
    if not cfg.loss_chunk:
        logits = apply(params, tokens, cfg)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return -ll.mean()

    h = apply_hidden(params, tokens, cfg)
    b, s, _ = h.shape
    chunk = min(cfg.loss_chunk, s)
    if s % chunk:
        raise ValueError(
            f"loss_chunk ({chunk}) must divide the local sequence ({s})")

    @jax.checkpoint
    def chunk_nll(c):
        hs = lax.dynamic_slice_in_dim(h, c * chunk, chunk, axis=1)
        tg = lax.dynamic_slice_in_dim(targets, c * chunk, chunk, axis=1)
        logits = _project_logits(params, hs, cfg)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, tg[..., None], axis=-1)[..., 0]
        return -ll.sum()

    total = lax.map(chunk_nll, jnp.arange(s // chunk))
    return total.sum() / (b * s)
