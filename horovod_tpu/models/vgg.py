"""VGG family in Flax — benchmark workload.

VGG-16 is the reference's hardest scaling benchmark (68% at 512 GPUs,
docs/benchmarks.md:5-6): ~138M parameters, most of them in the fc layers,
which makes gradient allreduce bandwidth the bottleneck. On TPU the same
model stresses HBM and ICI the same way, so it stays in the zoo as the
communication-bound stress test.

TPU-first choices: bf16 activations / fp32 params, NHWC, static shapes.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

# Block specs: number of conv layers x output channels per stage.
_VGG16 = ((2, 64), (2, 128), (3, 256), (3, 512), (3, 512))
_VGG19 = ((2, 64), (2, 128), (4, 256), (4, 512), (4, 512))


class VGG(nn.Module):
    """Configurable VGG (Simonyan & Zisserman 2014) with batch norm off by
    default, matching the classic benchmark configuration."""

    cfg: Sequence = _VGG16
    num_classes: int = 1000
    use_bn: bool = False
    dtype: Any = jnp.bfloat16
    # Distributed batch norm over the named mesh axis
    # (docs/data.md#sync-bn); needs use_bn=True and a shard_map/pmap
    # context binding the axis. Same param/stat tree as the local BN.
    bn_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, kernel_size=(3, 3), padding=[(1, 1), (1, 1)],
                       dtype=self.dtype)
        if self.bn_axis_name is not None:
            from ..data.sync_bn import SyncBatchNorm
            bn = partial(SyncBatchNorm, use_running_average=not train,
                         axis_name=self.bn_axis_name, momentum=0.9,
                         epsilon=1e-5, dtype=jnp.float32)
        else:
            bn = partial(nn.BatchNorm, use_running_average=not train,
                         momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        x = x.astype(self.dtype)
        for i, (n_layers, ch) in enumerate(self.cfg):
            for j in range(n_layers):
                x = conv(ch, name=f"conv{i + 1}_{j + 1}")(x)
                if self.use_bn:
                    x = bn(name=f"bn{i + 1}_{j + 1}")(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc1")(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc2")(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


VGG16 = partial(VGG, cfg=_VGG16)
VGG19 = partial(VGG, cfg=_VGG19)
