"""Model zoo for benchmarks and examples.

The reference ships no models (it benchmarks Keras-applications ResNet-50,
examples/tensorflow_synthetic_benchmark.py:24-42); these are the TPU-native
equivalents plus the flagship Transformer used for the parallelism layers.
"""

from .resnet import ResNet, ResNet50, ResNet101, ResNet152
from .vgg import VGG, VGG16, VGG19
from .inception import InceptionV3
from .mnist import MnistConvNet

__all__ = ["ResNet", "ResNet50", "ResNet101", "ResNet152",
           "VGG", "VGG16", "VGG19", "InceptionV3", "MnistConvNet"]
