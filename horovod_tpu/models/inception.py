"""Inception V3 in Flax — benchmark workload.

Inception V3 is the reference's best-scaling benchmark (90% at 512 GPUs,
docs/benchmarks.md:5-6): compute-heavy with relatively few parameters, so
allreduce traffic is small relative to FLOPs. The TPU equivalent keeps the
same property — a good MXU-utilization benchmark.

Faithful to Szegedy et al. 2015 (the torchvision/slim graph): stem,
3x InceptionA, InceptionB, 4x InceptionC, InceptionD, 2x InceptionE,
global pool + fc. Aux classifier omitted (benchmarks run without it).

TPU-first choices: bf16 activations / fp32 params + fp32 BN, NHWC.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp


class ConvBN(nn.Module):
    filters: int
    kernel: tuple
    strides: tuple = (1, 1)
    padding: Any = "SAME"
    dtype: Any = jnp.bfloat16
    # Distributed batch norm over the named mesh axis when set
    # (docs/data.md#sync-bn) — same param/stat tree as nn.BatchNorm.
    bn_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.filters, self.kernel, self.strides,
                    padding=self.padding, use_bias=False,
                    dtype=self.dtype)(x)
        if self.bn_axis_name is not None:
            from ..data.sync_bn import SyncBatchNorm
            # Pinned name: the local path's auto-generated module name,
            # so local and sync-BN checkpoints stay interchangeable.
            x = SyncBatchNorm(use_running_average=not train,
                              axis_name=self.bn_axis_name, momentum=0.9,
                              epsilon=1e-3, dtype=jnp.float32,
                              name="BatchNorm_0")(x)
        else:
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             epsilon=1e-3, dtype=jnp.float32)(x)
        return nn.relu(x)


def _avgpool_same(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.bfloat16
    bn_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype,
                      bn_axis_name=self.bn_axis_name)
        b1 = cbn(64, (1, 1))(x, train)
        b2 = cbn(48, (1, 1))(x, train)
        b2 = cbn(64, (5, 5))(b2, train)
        b3 = cbn(64, (1, 1))(x, train)
        b3 = cbn(96, (3, 3))(b3, train)
        b3 = cbn(96, (3, 3))(b3, train)
        b4 = cbn(self.pool_features, (1, 1))(_avgpool_same(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionB(nn.Module):
    """Grid reduction 35x35 -> 17x17."""

    dtype: Any = jnp.bfloat16
    bn_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype,
                      bn_axis_name=self.bn_axis_name)
        b1 = cbn(384, (3, 3), (2, 2), "VALID")(x, train)
        b2 = cbn(64, (1, 1))(x, train)
        b2 = cbn(96, (3, 3))(b2, train)
        b2 = cbn(96, (3, 3), (2, 2), "VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    """Factorized 7x7 branches."""

    channels_7x7: int
    dtype: Any = jnp.bfloat16
    bn_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype,
                      bn_axis_name=self.bn_axis_name)
        c7 = self.channels_7x7
        b1 = cbn(192, (1, 1))(x, train)
        b2 = cbn(c7, (1, 1))(x, train)
        b2 = cbn(c7, (1, 7))(b2, train)
        b2 = cbn(192, (7, 1))(b2, train)
        b3 = cbn(c7, (1, 1))(x, train)
        b3 = cbn(c7, (7, 1))(b3, train)
        b3 = cbn(c7, (1, 7))(b3, train)
        b3 = cbn(c7, (7, 1))(b3, train)
        b3 = cbn(192, (1, 7))(b3, train)
        b4 = cbn(192, (1, 1))(_avgpool_same(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionD(nn.Module):
    """Grid reduction 17x17 -> 8x8."""

    dtype: Any = jnp.bfloat16
    bn_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype,
                      bn_axis_name=self.bn_axis_name)
        b1 = cbn(192, (1, 1))(x, train)
        b1 = cbn(320, (3, 3), (2, 2), "VALID")(b1, train)
        b2 = cbn(192, (1, 1))(x, train)
        b2 = cbn(192, (1, 7))(b2, train)
        b2 = cbn(192, (7, 1))(b2, train)
        b2 = cbn(192, (3, 3), (2, 2), "VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionE(nn.Module):
    """Expanded-filter-bank output blocks."""

    dtype: Any = jnp.bfloat16
    bn_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype,
                      bn_axis_name=self.bn_axis_name)
        b1 = cbn(320, (1, 1))(x, train)
        b2 = cbn(384, (1, 1))(x, train)
        b2 = jnp.concatenate([cbn(384, (1, 3))(b2, train),
                              cbn(384, (3, 1))(b2, train)], axis=-1)
        b3 = cbn(448, (1, 1))(x, train)
        b3 = cbn(384, (3, 3))(b3, train)
        b3 = jnp.concatenate([cbn(384, (1, 3))(b3, train),
                              cbn(384, (3, 1))(b3, train)], axis=-1)
        b4 = cbn(192, (1, 1))(_avgpool_same(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    """Inception V3 for 299x299 inputs (works on any >= 75x75)."""

    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    bn_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype,
                      bn_axis_name=self.bn_axis_name)
        x = x.astype(self.dtype)
        # Stem
        x = cbn(32, (3, 3), (2, 2), "VALID")(x, train)
        x = cbn(32, (3, 3), padding="VALID")(x, train)
        x = cbn(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = cbn(80, (1, 1), padding="VALID")(x, train)
        x = cbn(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        # Inception stacks
        x = InceptionA(32, dtype=self.dtype,
                       bn_axis_name=self.bn_axis_name)(x, train)
        x = InceptionA(64, dtype=self.dtype,
                       bn_axis_name=self.bn_axis_name)(x, train)
        x = InceptionA(64, dtype=self.dtype,
                       bn_axis_name=self.bn_axis_name)(x, train)
        x = InceptionB(dtype=self.dtype,
                       bn_axis_name=self.bn_axis_name)(x, train)
        x = InceptionC(128, dtype=self.dtype,
                       bn_axis_name=self.bn_axis_name)(x, train)
        x = InceptionC(160, dtype=self.dtype,
                       bn_axis_name=self.bn_axis_name)(x, train)
        x = InceptionC(160, dtype=self.dtype,
                       bn_axis_name=self.bn_axis_name)(x, train)
        x = InceptionC(192, dtype=self.dtype,
                       bn_axis_name=self.bn_axis_name)(x, train)
        x = InceptionD(dtype=self.dtype,
                       bn_axis_name=self.bn_axis_name)(x, train)
        x = InceptionE(dtype=self.dtype,
                       bn_axis_name=self.bn_axis_name)(x, train)
        x = InceptionE(dtype=self.dtype,
                       bn_axis_name=self.bn_axis_name)(x, train)
        # Head
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)
