"""Skip-gram word2vec with NCE loss — pure JAX.

The reference ships a distributed word2vec example
(examples/tensorflow_word2vec.py, 249 LoC: skip-gram batches, NCE loss,
embedding lookups trained data-parallel). This is the TPU-native model
behind ``examples/jax_word2vec.py``: functional params, a jittable NCE
loss with in-program negative sampling, and similarity scoring.

TPU-first: the NCE loss is one batched gather + two matmul-shaped
contractions — no per-example Python, everything vectorized for the MXU.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class Word2VecParams(NamedTuple):
    embeddings: jax.Array   # [vocab, dim] input embeddings
    nce_weights: jax.Array  # [vocab, dim] output (context) embeddings
    nce_biases: jax.Array   # [vocab]


def init_params(vocab_size: int, embedding_dim: int,
                rng: jax.Array) -> Word2VecParams:
    """Uniform(-1,1) embeddings, truncated-normal NCE weights, zero biases
    (the reference's initialization, tensorflow_word2vec.py:154-166)."""
    k1, k2 = jax.random.split(rng)
    emb = jax.random.uniform(k1, (vocab_size, embedding_dim),
                             minval=-1.0, maxval=1.0)
    scale = 1.0 / jnp.sqrt(embedding_dim)
    nce_w = jax.random.truncated_normal(
        k2, -2.0, 2.0, (vocab_size, embedding_dim)) * scale
    return Word2VecParams(emb, nce_w, jnp.zeros((vocab_size,)))


def nce_loss(params: Word2VecParams, centers: jax.Array,
             contexts: jax.Array, rng: jax.Array,
             num_negatives: int = 64, vocab_size: int | None = None
             ) -> jax.Array:
    """Noise-contrastive estimation loss for a skip-gram batch.

    centers/contexts: [B] int32 token ids. Negatives are drawn uniformly
    in-program (log-uniform in the reference; uniform keeps the sampler a
    single stateless jax.random call — the distinction does not change the
    benchmark's compute shape).
    """
    vocab = vocab_size or params.embeddings.shape[0]
    emb = params.embeddings[centers]                       # [B, D]
    true_w = params.nce_weights[contexts]                  # [B, D]
    true_b = params.nce_biases[contexts]                   # [B]
    true_logits = jnp.sum(emb * true_w, axis=-1) + true_b  # [B]

    neg_ids = jax.random.randint(rng, (num_negatives,), 0, vocab)
    neg_w = params.nce_weights[neg_ids]                    # [N, D]
    neg_b = params.nce_biases[neg_ids]                     # [N]
    neg_logits = emb @ neg_w.T + neg_b[None, :]            # [B, N]

    # Binary logistic: true pairs -> 1, sampled pairs -> 0.
    pos = jnp.logaddexp(0.0, -true_logits)                 # -log sigmoid
    neg = jnp.logaddexp(0.0, neg_logits).sum(axis=-1)
    return jnp.mean(pos + neg)


def skipgram_batch(data: jnp.ndarray, step: int, batch_size: int,
                   skip_window: int = 1) -> tuple:
    """Deterministic skip-gram pairs from a token stream: each center is
    paired with one neighbor, alternating left/right. Static shapes, so
    the training step stays jittable over ``step``."""
    n = data.shape[0]
    idx = (step * batch_size + jnp.arange(batch_size)) % (
        n - 2 * skip_window) + skip_window
    offset = jnp.where(jnp.arange(batch_size) % 2 == 0,
                       -skip_window, skip_window)
    return data[idx], data[idx + offset]


def nearest(params: Word2VecParams, word_ids: jax.Array, k: int = 8
            ) -> jax.Array:
    """Top-k nearest token ids by cosine similarity (the reference's
    eval loop, tensorflow_word2vec.py:188-206)."""
    norm = params.embeddings / jnp.linalg.norm(
        params.embeddings, axis=-1, keepdims=True)
    sims = norm[word_ids] @ norm.T                         # [Q, vocab]
    return jax.lax.top_k(sims, k + 1)[1][:, 1:]            # drop self
