"""MNIST ConvNet — the examples' workhorse model.

The reference's MNIST examples all use the same small conv net (two convs,
two fc — examples/tensorflow_mnist.py:conv_model, examples/pytorch_mnist.py
Net, examples/mxnet_mnist.py conv_nets). This is its Flax equivalent, used
by every example in ``examples/``.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class MnistConvNet(nn.Module):
    """conv32(5x5) -> pool -> conv64(5x5) -> pool -> fc1024 -> fc10."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        # x: [B, 28, 28, 1]
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(32, (5, 5), name="conv1")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (5, 5), name="conv2")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(1024, name="fc1")(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="fc2")(x)
        return x.astype(jnp.float32)
