"""Post-mortem diagnosis CLI over flight-recorder dumps
(docs/postmortem.md).

``horovod_tpu/observability/flight_recorder.py`` leaves one
``blackbox-rank{rank}.jsonl`` per rank in the HOROVOD_TPU_BLACKBOX
directory when a rank crashes, is SIGTERMed, escalates a stall, or is
evicted. Each dump is a clock header (carrying the PR 5
``offset_to_rank0_us`` fields from the control-plane handshake)
followed by the last N seconds of structured events. This tool merges
the per-rank dumps onto rank 0's clock — the same alignment
``tools/trace`` applies to per-rank timelines — and answers the 3am
questions:

  - What was the LAST fused collective group each rank completed?
  - Where did the fleet DIVERGE — the first group sequence number not
    completed by every rank?
  - Which rank died (or stalled) FIRST, and in which phase (inside a
    collective, mid-step in compute/input, at a fault injection)?
  - What was the adaptation ladder doing at the time of death?
  - Did the NUMBERS go bad before the process did — the first
    nonfinite (step, rank) and any cross-rank divergence fingerprint
    mismatches the numerics plane recorded (docs/numerics.md)?

Usage::

    python -m horovod_tpu.tools.postmortem /path/to/blackbox-dir
    python -m horovod_tpu.tools.postmortem blackbox-rank*.jsonl --json out.json

Tolerant by construction: a dump truncated mid-line (the writer was
killed while dumping) parses up to the torn tail; a rank with no dump
at all (SIGKILL, kernel panic, host loss) is reported as missing and
becomes primary evidence — the ranks that could not say goodbye are
usually the ones that died hardest.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
from typing import Dict, List, Optional, Tuple

_DUMP_GLOB = "blackbox-rank*.jsonl"
_RANK_RE = re.compile(r"blackbox-rank(-?\d+)\.jsonl$")

# Dump reasons ordered by how strongly they indicate the ORIGIN of the
# failure (vs collateral damage): a rank that dumped at an injected
# crash died by construction; "exception"/"stall_escalation" mean the
# failure surfaced there; "sigterm" is usually the driver reaping
# survivors after someone else died; "inflight" means the rank was
# hard-killed with no final gasp — its file is the last periodic
# snapshot (handled as wordless-death evidence in the cascade, like a
# missing dump); "exit" is a clean shutdown.
_REASON_BLAME = {"fault_crash": 3, "stall_escalation": 2, "exception": 1,
                 "eviction": 1, "sigterm": 0, "inflight": 0, "exit": 0}


class RankDump:
    """One rank's parsed blackbox file."""

    def __init__(self, path: str, header: dict, events: List[dict],
                 truncated: bool):
        self.path = path
        self.header = header
        self.events = events
        self.truncated = truncated
        m = _RANK_RE.search(os.path.basename(path))
        self.rank = int(header.get("rank",
                                   m.group(1) if m else -1))

    @property
    def offset_us(self) -> float:
        return float(self.header.get("offset_to_rank0_us", 0.0))

    @property
    def clock_synced(self) -> bool:
        return bool(self.header.get("clock_synced", False))

    def aligned_us(self, event: dict) -> float:
        """Event time in rank-0's monotonic domain (microseconds)."""
        return float(event.get("t_us", 0)) + self.offset_us


def load_dump(path: str) -> Optional[RankDump]:
    """Parse one dump, skipping undecodable lines (a killed writer
    leaves a valid-prefix JSONL with at most one torn tail line).
    Returns None when not even a header survives."""
    header: Optional[dict] = None
    events: List[dict] = []
    truncated = False
    try:
        with open(path, errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    truncated = True
                    continue
                if header is None and obj.get("blackbox"):
                    header = obj
                elif "kind" in obj:
                    events.append(obj)
    except OSError:
        return None
    if header is None:
        # Headerless (dump killed instantly): keep the events if any —
        # rank from the filename, zero clock offset.
        if not events:
            return None
        header = {}
        truncated = True
    return RankDump(path, header, events, truncated)


def discover(paths: List[str]) -> List[str]:
    """Expand a directory / glob / explicit file list into dump files."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, _DUMP_GLOB))))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(glob.glob(p)))
        elif os.path.exists(p):
            out.append(p)
    if not out:
        raise FileNotFoundError(
            f"no blackbox dumps found under {paths} (expected "
            f"{_DUMP_GLOB} files — set HOROVOD_TPU_BLACKBOX / "
            "--blackbox-dir on the run)")
    return out


# --------------------------------------------------------------------------
# Analysis
# --------------------------------------------------------------------------

def _group_state(dump: RankDump) -> Tuple[Optional[int], Optional[int]]:
    """(last completed group seq, last delivered-but-not-completed seq)
    for one rank. Seqs may be None on dumps with no group traffic."""
    done = [e["seq"] for e in dump.events
            if e.get("kind") == "group_done" and e.get("seq") is not None]
    delivered = [e["seq"] for e in dump.events
                 if e.get("kind") == "group_deliver"
                 and e.get("seq") is not None]
    last_done = max(done) if done else None
    done_set = set(done)
    open_seqs = [s for s in delivered if s not in done_set]
    return last_done, (max(open_seqs) if open_seqs else None)


def _pipeline_state(dump: RankDump) -> Optional[dict]:
    """The most recently built pipeline program, if any — recorded at
    build time so an in-step death can be attributed to a schedule
    (docs/pipeline.md)."""
    for e in reversed(dump.events):
        if e.get("kind") == "pipeline":
            return e
    return None


def _death_phase(dump: RankDump) -> str:
    """Best-effort phase the rank was in when the dump fired, from the
    tail of its event stream."""
    last_done, open_seq = _group_state(dump)
    if open_seq is not None and (last_done is None or open_seq > last_done):
        return f"collective (group seq {open_seq} delivered, never " \
               "completed)"
    for e in reversed(dump.events):
        kind = e.get("kind")
        if kind == "fault":
            if str(e.get("fault")) == "crash":
                return (f"fault injection (crash at enqueue path, tick "
                        f"{e.get('tick')})")
            if str(e.get("fault")) == "replica_crash":
                return (f"fault injection (serving replica crash at "
                        f"decode tick {e.get('tick')})")
            break
        if kind == "serving":
            return (f"serving ({e.get('event')}, "
                    f"{e.get('active')} request(s) in flight)")
        if kind == "step_end":
            return f"between steps (step {e.get('idx')} completed)"
        if kind == "step":
            pipe = _pipeline_state(dump)
            inside = ""
            if pipe is not None:
                inside = (
                    f", inside a pipelined step (schedule "
                    f"{pipe.get('schedule')}, "
                    f"{pipe.get('warmup')}/{pipe.get('steady')}/"
                    f"{pipe.get('drain')} warmup/steady/drain ticks)")
            return (f"in-step (step {e.get('idx')} began, never "
                    f"finished — compute/input/comm submission{inside})")
        if kind in ("group_done", "group_deliver", "group_error",
                    "failure", "stall", "coord_error", "adapt",
                    "wire_epoch", "checkpoint", "elastic", "init"):
            break
    # Fall back on the last event kind / dump reason.
    if dump.events:
        return f"after {dump.events[-1].get('kind')}"
    return f"unknown (empty dump, reason {dump.header.get('reason')})"


def _inflight_requests(dump: RankDump) -> List[dict]:
    """Serving requests this replica was holding when the dump fired
    (docs/serving.md#request-tracing): replay the ``request`` lifecycle
    events — admit opens a request (phase ``prefill``), first_token
    moves it to ``decode``, evict/finish closes it. What remains open
    at the tail is exactly what the replica took down with it — the
    requests the router had to fail over."""
    state: Dict[str, str] = {}
    for e in dump.events:
        if e.get("kind") != "request":
            continue
        ev, trace = str(e.get("event")), str(e.get("trace"))
        if ev == "admit":
            state[trace] = "prefill"
        elif ev == "first_token":
            state[trace] = "decode"
        elif ev in ("evict", "finish"):
            state.pop(trace, None)
    return [{"trace": t, "phase": p} for t, p in state.items()]


def _data_cursor(dump: RankDump) -> Optional[dict]:
    """The last committed input-pipeline cursor this rank recorded
    (docs/data.md#exactly-once): where the loader will resume, and the
    first thing to compare across ranks when a resumed job's samples
    look wrong."""
    for e in reversed(dump.events):
        if e.get("kind") == "data" and \
                str(e.get("event")) == "cursor_commit":
            return {"epoch": e.get("epoch"), "offset": e.get("offset")}
    return None


def _numerics_evidence(dumps: List[RankDump]) -> Optional[dict]:
    """Numerics-plane evidence chain (docs/numerics.md#postmortem).

    In a NaN cascade every rank eventually reports nonfinite payloads —
    the poisoned gradient propagates through the next allreduce — so
    the ORIGIN is the numerically FIRST observation (lowest step, then
    earliest aligned time), not the loudest rank. Divergence rows come
    from rank 0's fingerprint comparisons: each names the leaf and the
    outvoted rank, which is the bitflip/corruption story in one line."""
    nonfinite: List[dict] = []
    divergence: List[dict] = []
    for d in dumps:
        for e in d.events:
            if e.get("kind") != "numerics":
                continue
            row = {"step": e.get("step"), "rank": e.get("who"),
                   "observed_by": d.rank,
                   "t_rank0_us": d.aligned_us(e)}
            if str(e.get("event")) == "nonfinite":
                row["elements"] = e.get("value")
                row["source"] = e.get("detail")
                nonfinite.append(row)
            elif str(e.get("event")) == "divergence":
                row["leaf"] = e.get("detail")
                divergence.append(row)
    if not nonfinite and not divergence:
        return None

    def _order(row: dict) -> Tuple[float, float]:
        # step -1 means "observed outside a numbered step" (e.g. a
        # collective payload scan) — order those by aligned time only.
        step = row.get("step")
        step = float(step) if isinstance(step, (int, float)) \
            and step >= 0 else float("inf")
        return (step, row["t_rank0_us"])

    nonfinite.sort(key=_order)
    divergence.sort(key=_order)
    return {
        "first_nonfinite": nonfinite[0] if nonfinite else None,
        "nonfinite_events": len(nonfinite),
        "nonfinite_ranks": sorted({r["rank"] for r in nonfinite
                                   if r.get("rank") is not None}),
        "divergence": divergence,
    }


def _blamed_ranks(dumps: List[RankDump]) -> Dict[int, int]:
    """Votes per rank from survivors' recorded failure events."""
    votes: Dict[int, int] = {}
    for d in dumps:
        for e in d.events:
            if e.get("kind") == "failure":
                r = int(e.get("rank", -1))
                if r >= 0:
                    votes[r] = votes.get(r, 0) + 1
    return votes


def analyze(dumps: List[RankDump]) -> dict:
    """The merged post-mortem report (see module docstring)."""
    dumps = sorted(dumps, key=lambda d: d.rank)
    world = max([d.header.get("world", 0) for d in dumps] + [0])
    present = {d.rank for d in dumps}
    missing = sorted(set(range(world)) - present) if world else []

    per_rank = {}
    death_t_us: Dict[int, float] = {}
    for d in dumps:
        last_done, open_seq = _group_state(d)
        t_dump = float(d.header.get("mono_us", 0)) + d.offset_us
        death_t_us[d.rank] = t_dump
        pipe = _pipeline_state(d)
        per_rank[str(d.rank)] = {
            "reason": d.header.get("reason"),
            "error": d.header.get("error"),
            "generation": d.header.get("generation", 0),
            "last_group_seq": last_done,
            "open_group_seq": open_seq,
            "death_phase": _death_phase(d),
            "pipeline_schedule": (pipe.get("schedule")
                                  if pipe is not None else None),
            "data_cursor": _data_cursor(d),
            "inflight_requests": _inflight_requests(d),
            "events": len(d.events),
            "truncated_dump": d.truncated,
            "clock_synced": d.clock_synced,
            "dump_t_rank0_us": t_dump,
        }

    # Divergence: the first group seq not completed by every dumped
    # rank, given at least one rank progressed past the common floor
    # (a step begun, a group delivered, or a later completion).
    last_seqs = {d.rank: _group_state(d)[0] for d in dumps}
    numeric = [s for s in last_seqs.values() if s is not None]
    first_divergent = None
    if numeric:
        floor = min(numeric)
        if any(s != floor for s in numeric):
            first_divergent = floor + 1
        else:
            # Everyone completed the same last seq: the job diverged at
            # the NEXT group iff some rank shows evidence of attempting
            # it (an open delivery or a step begun after the floor).
            for d in dumps:
                _, open_seq = _group_state(d)
                if open_seq is not None and open_seq > floor:
                    first_divergent = floor + 1
                    break
                # A step begun but never finished: the rank entered the
                # next iteration and stalled in the group after the
                # common floor.
                begun = [e.get("idx", -1) for e in d.events
                         if e.get("kind") == "step"]
                ended = [e.get("idx", -1) for e in d.events
                         if e.get("kind") == "step_end"]
                if begun and (not ended or max(begun) > max(ended)):
                    first_divergent = floor + 1
                    break

    # Who died first: injected-crash dumps and missing ranks are the
    # strongest evidence; then survivor failure-event consensus; then
    # the earliest dump on the aligned clock.
    votes = _blamed_ranks(dumps)
    died_first: Optional[int] = None
    died_how = None

    def _earliest(cands: List[RankDump]) -> RankDump:
        return min(cands,
                   key=lambda d: death_t_us.get(d.rank, float("inf")))

    crash_dumps = [d for d in dumps
                   if _REASON_BLAME.get(d.header.get("reason"), 0) >= 2]
    origin_dumps = [d for d in dumps
                    if _REASON_BLAME.get(d.header.get("reason"), 0) == 1]
    if crash_dumps:
        d = _earliest(crash_dumps)
        died_first, died_how = d.rank, d.header.get("reason")
    elif votes:
        died_first = max(votes, key=lambda r: votes[r])
        died_how = "blamed by survivor failure events"
    elif missing:
        died_first = missing[0]
        died_how = "no dump written (hard kill / host loss)"
    elif any(d.header.get("reason") == "inflight" for d in dumps):
        # Wordless death: the file is the last periodic snapshot — the
        # process never got a final gasp (SIGKILL / runtime LOG(FATAL)).
        d = _earliest([d for d in dumps
                       if d.header.get("reason") == "inflight"])
        died_first = d.rank
        died_how = "hard-killed (last dump is an in-flight snapshot)"
    elif origin_dumps:
        d = _earliest(origin_dumps)
        died_first, died_how = d.rank, d.header.get("reason")
    elif death_t_us:
        died_first = min(death_t_us, key=lambda r: death_t_us[r])
        died_how = per_rank[str(died_first)]["reason"]
    death_phase = (per_rank[str(died_first)]["death_phase"]
                   if died_first is not None
                   and str(died_first) in per_rank
                   else ("no dump — died without a final gasp"
                         if died_first is not None else None))

    # Adaptation ladder at death: rank 0 records the policy transitions;
    # replay them up to the death time.
    ladder = None
    rank0 = next((d for d in dumps if d.rank == 0), None)
    if rank0 is not None:
        cutoff = (min(death_t_us.values()) if death_t_us else None)
        tier, active, evicted = 0, [], []
        for e in rank0.events:
            if e.get("kind") != "adapt":
                continue
            if cutoff is not None and rank0.aligned_us(e) > cutoff + 1e6:
                break
            if e.get("action") == "escalate":
                if e.get("name") == "evict":
                    evicted.append(int(e.get("rank", -1)))
                else:
                    tier = int(e.get("tier", tier))
                    active.append(str(e.get("name")))
            elif e.get("action") == "deescalate":
                tier = int(e.get("tier", tier))
                if active:
                    active.pop()
        ladder = {"tier": tier, "active_tiers": active,
                  "evicted_ranks": evicted}

    unsynced = sorted(d.rank for d in dumps
                      if not d.clock_synced and d.rank != 0)
    return {
        "world": world,
        "ranks_dumped": sorted(present),
        "ranks_missing": missing,
        "per_rank": per_rank,
        "first_divergent_group_seq": first_divergent,
        "common_last_group_seq": (min(numeric) if numeric else None),
        "died_first": {"rank": died_first, "how": died_how,
                       "phase": death_phase},
        "failure_votes": {str(r): v for r, v in sorted(votes.items())},
        "adaptation_at_death": ladder,
        "numerics": _numerics_evidence(dumps),
        "clock_unsynced_ranks": unsynced,
    }


def format_report(report: dict) -> str:
    lines = [
        f"Post-mortem — world size {report['world']}, "
        f"{len(report['ranks_dumped'])} blackbox dump(s)"
        + (f", ranks with NO dump: {report['ranks_missing']}"
           if report["ranks_missing"] else ""),
        "",
        f"{'rank':>4}  {'reason':<18} {'last seq':>8}  death phase",
    ]
    for r in sorted(report["per_rank"], key=int):
        row = report["per_rank"][r]
        seq = row["last_group_seq"]
        lines.append(
            f"{r:>4}  {str(row['reason']):<18} "
            f"{('-' if seq is None else seq):>8}  {row['death_phase']}"
            + ("  [truncated dump]" if row["truncated_dump"] else ""))
    for r in report["ranks_missing"]:
        lines.append(f"{r:>4}  {'<no dump>':<18} {'-':>8}  "
                     "died without a final gasp (hard kill / host loss)")
    died = report["died_first"]
    lines.append("")
    if died["rank"] is not None:
        lines.append(
            f"Verdict: rank {died['rank']} went first ({died['how']}); "
            f"phase: {died['phase']}")
    if report["first_divergent_group_seq"] is not None:
        lines.append(
            f"First divergent group seq: "
            f"{report['first_divergent_group_seq']} (all dumped ranks "
            f"completed seq {report['common_last_group_seq']})")
    elif report["common_last_group_seq"] is not None:
        lines.append(
            f"No divergence recorded: every dumped rank stopped at "
            f"group seq {report['common_last_group_seq']}")
    num = report.get("numerics")
    if num:
        first = num.get("first_nonfinite")
        if first is not None:
            step = first.get("step")
            lines.append(
                "First nonfinite: "
                + (f"step {step}" if isinstance(step, (int, float))
                   and step >= 0 else "outside a numbered step")
                + f" on rank {first.get('rank')} "
                f"({first.get('elements')} element(s), source "
                f"{first.get('source')}); {num['nonfinite_events']} "
                f"nonfinite event(s) total across ranks "
                f"{num['nonfinite_ranks']}")
        for q in num.get("divergence", []):
            lines.append(
                f"Cross-rank divergence at step {q.get('step')}: rank "
                f"{q.get('rank')} disagrees on leaf {q.get('leaf')} "
                f"(fingerprint comparison on rank {q.get('observed_by')})")
    inflight = {r: row["inflight_requests"]
                for r, row in report["per_rank"].items()
                if row.get("inflight_requests")}
    for r, reqs in sorted(inflight.items(), key=lambda kv: int(kv[0])):
        lines.append(
            f"In-flight requests on rank {r} at death: " + ", ".join(
                f"{q['trace']} ({q['phase']})" for q in reqs))
    cursors = {r: row["data_cursor"]
               for r, row in report["per_rank"].items()
               if row.get("data_cursor")}
    if cursors:
        lines.append("Last committed data cursor per rank: " + "; ".join(
            f"rank {r}: epoch {c['epoch']} offset {c['offset']}"
            for r, c in sorted(cursors.items(), key=lambda kv: int(kv[0]))))
    ladder = report.get("adaptation_at_death")
    if ladder is not None:
        lines.append(
            f"Adaptation ladder at death: tier {ladder['tier']}"
            + (f" ({', '.join(ladder['active_tiers'])})"
               if ladder["active_tiers"] else " (baseline)")
            + (f"; evicted ranks: {ladder['evicted_ranks']}"
               if ladder["evicted_ranks"] else ""))
    if report["clock_unsynced_ranks"]:
        lines.append(
            "WARNING: clock offset unsynced for ranks "
            f"{report['clock_unsynced_ranks']} — their event times "
            "carry the raw inter-host clock skew.")
    return "\n".join(lines)


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def _main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.tools.postmortem",
        description="Merge per-rank flight-recorder dumps "
                    "(blackbox-rank{rank}.jsonl) onto rank 0's clock and "
                    "report who died first, in which phase, and where "
                    "the fleet diverged (docs/postmortem.md)")
    ap.add_argument("dumps", nargs="+",
                    help="blackbox directory, glob, or explicit dump "
                         "files")
    ap.add_argument("--json", default=None,
                    help="also write the report JSON here")
    args = ap.parse_args(argv)

    dumps = [d for d in (load_dump(p) for p in discover(args.dumps))
             if d is not None]
    if not dumps:
        raise SystemExit("no parseable blackbox dumps found")
    report = analyze(dumps)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
            f.write("\n")
    print(format_report(report))
    return report


if __name__ == "__main__":  # pragma: no cover - thin CLI
    _main()
