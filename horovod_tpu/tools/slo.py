"""Goodput reporting: merge open-loop load results + fleet history
into goodput-vs-offered-load tables (docs/serving.md#slo).

::

    python -m horovod_tpu.tools.slo BENCH_SLO.json
    python -m horovod_tpu.tools.slo run_rps*.json --target-ttft-ms 500
    python -m horovod_tpu.tools.slo BENCH_SLO.json --history /var/hist
    python -m horovod_tpu.tools.slo BENCH_SLO.json --baseline old.json

Inputs are either ``BENCH_SLO.json`` (the ``--slo`` bench artifact —
its ``sweep`` arms ARE the table) or raw ``serving.loadgen`` run files
(``{"offered": .., "results": [...]}``, summarized here). The table
answers the question closed-loop benches structurally cannot: at what
offered load does goodput stop tracking offered load — the **knee**
where p99 TTFT crosses target and shed/violations absorb the rest.

``--history`` folds in the fleet history store (PR 15): per-label
``hvdtpu_slo_*`` counters become per-replica goodput/violation deltas
over the recorded window, so a live fleet's trend sits next to the
bench table. ``--baseline`` A/Bs two reports and exits 3 when any
matching arm's goodput fraction regressed more than 10% — the same
gate-the-CI contract ``tools/health --baseline`` uses.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

from ..observability import health as _health
from ..observability import history as _history
from ..serving import loadgen as _loadgen

# A sweep arm past this goodput fraction is "keeping up"; below it the
# fleet is shedding/violating its way through the offered load.
KNEE_GOODPUT_FRAC = 0.9


def _arm_from_run(name: str, run: dict,
                  offered_rps: Optional[float] = None) -> dict:
    """Normalize one loadgen run into a table arm."""
    summary = run.get("summary") or _loadgen.summarize(run)
    totals = summary["totals"]
    wall = float(run.get("wall_s") or 0.0)
    if offered_rps is None:
        offered_rps = run.get("offered_rps")
    if offered_rps is None and wall > 0:
        offered_rps = totals["offered"] / wall
    ttft = sorted(float(r["ttft_ms"]) for r in run.get("results", [])
                  if r.get("status") == "completed" and "ttft_ms" in r)
    return {
        "name": name,
        "offered_rps": round(float(offered_rps or 0.0), 3),
        "offered": totals["offered"],
        "dropped": totals["dropped"],
        "goodput": totals["goodput"],
        "goodput_frac": totals["goodput_frac"],
        "goodput_rps": round(totals["goodput"] / wall, 3)
        if wall > 0 else None,
        "ttft_p50_ms": round(_loadgen._percentile(ttft, 0.50), 3),
        "ttft_p99_ms": round(_loadgen._percentile(ttft, 0.99), 3),
        "tenants": summary["tenants"],
        **({"by_class": summary["by_class"]}
           if "by_class" in summary else {}),
    }


def load_arms(paths: List[str]) -> List[dict]:
    """Table arms from input files: a BENCH_SLO.json contributes every
    sweep arm; a raw loadgen run file contributes one."""
    arms: List[dict] = []
    for path in paths:
        with open(path) as f:
            doc = json.load(f)
        if "sweep" in doc:       # BENCH_SLO.json
            for name, arm in doc["sweep"].items():
                arm = dict(arm)
                arm.setdefault("name", name)
                arms.append(arm)
        elif "results" in doc:   # raw loadgen run
            name = doc.get("name") or path.rsplit("/", 1)[-1]
            arms.append(_arm_from_run(name, doc))
        else:
            raise ValueError(
                f"{path}: neither a BENCH_SLO.json (sweep) nor a "
                f"loadgen run (results)")
    arms.sort(key=lambda a: a.get("offered_rps") or 0.0)
    return arms


def find_knee(arms: List[dict],
              target_ttft_ms: Optional[float] = None
              ) -> Optional[dict]:
    """First arm (by offered load) where the fleet stops keeping up:
    goodput fraction under :data:`KNEE_GOODPUT_FRAC`, or p99 TTFT over
    the target."""
    for arm in arms:
        frac = arm.get("goodput_frac")
        p99 = arm.get("ttft_p99_ms")
        if frac is not None and frac < KNEE_GOODPUT_FRAC:
            return arm
        if (target_ttft_ms is not None and p99 is not None
                and p99 > target_ttft_ms):
            return arm
    return None


def violation_breakdown(arms: List[dict]) -> Dict[str, dict]:
    """Per-tenant rollup across every arm: offered / goodput /
    violations / shed."""
    out: Dict[str, dict] = {}
    for arm in arms:
        for name, t in (arm.get("tenants") or {}).items():
            agg = out.setdefault(name, {
                "offered": 0, "goodput": 0, "slo_violations": 0,
                "shed": 0})
            agg["offered"] += t.get("offered", 0)
            agg["goodput"] += t.get("goodput", 0)
            agg["slo_violations"] += t.get("slo_violations", 0)
            agg["shed"] += t.get("shed", 0)
    for agg in out.values():
        agg["goodput_frac"] = round(
            agg["goodput"] / agg["offered"], 4) if agg["offered"] \
            else 0.0
    return out


def class_breakdown(arms: List[dict]) -> Dict[str, dict]:
    """Per-priority-class rollup across every arm that carries a
    ``by_class`` section (docs/serving.md#qos) — empty when no arm was
    run with class-tagged tenants."""
    out: Dict[str, dict] = {}
    for arm in arms:
        for cls, t in (arm.get("by_class") or {}).items():
            agg = out.setdefault(cls, {
                "offered": 0, "goodput": 0, "slo_violations": 0,
                "shed": 0})
            agg["offered"] += t.get("offered", 0)
            agg["goodput"] += t.get("goodput", 0)
            agg["slo_violations"] += t.get("slo_violations", 0)
            agg["shed"] += t.get("shed", 0)
    for agg in out.values():
        agg["goodput_frac"] = round(
            agg["goodput"] / agg["offered"], 4) if agg["offered"] \
            else 0.0
    return out


def history_slo_summary(directory: str) -> List[dict]:
    """Per-label hvdtpu_slo_* rollup over the recorded window. The
    history plane stores counters as per-second rates under the bare
    series key — integrating rate x sample-gap recovers each label's
    goodput / violation totals; histogram ``|p99`` keeps its last
    value."""
    rows = []
    for hf in _history.load_history([directory]):
        totals: Dict[str, float] = {}
        for key, points in hf.series().items():
            fam, labels, suffix = _health.split_series_key(key)
            if not fam.startswith("hvdtpu_slo_"):
                continue
            short = fam[len("hvdtpu_slo_"):]
            name = f"{short}{{{labels}}}" if labels else short
            if suffix == "" and fam.endswith("_total"):
                # Counter rate series: Δt ≈ median sample gap (the
                # sampler's cadence is steady).
                ts = [t for t, _ in points]
                dt = 0.0
                if len(ts) >= 2:
                    gaps = sorted(b - a for a, b in zip(ts, ts[1:]))
                    dt = gaps[len(gaps) // 2]
                totals[name] = round(
                    sum(v for _, v in points) * dt, 1)
            elif suffix == "p99":
                totals[f"{name}|p99"] = points[-1][1]
        if totals:
            rows.append({"label": hf.label,
                         "replica": hf.meta.get("replica"),
                         "slo": totals})
    return rows


def compare_baseline(cur: List[dict], base: List[dict],
                     threshold: float = 0.10) -> dict:
    """A/B matching arms by name; a goodput-fraction drop beyond
    ``threshold`` (absolute) is a regression."""
    base_by = {a["name"]: a for a in base}
    regressions, improvements = [], []
    for arm in cur:
        b = base_by.get(arm["name"])
        if b is None or b.get("goodput_frac") is None \
                or arm.get("goodput_frac") is None:
            continue
        delta = arm["goodput_frac"] - b["goodput_frac"]
        row = {"name": arm["name"],
               "baseline_goodput_frac": b["goodput_frac"],
               "goodput_frac": arm["goodput_frac"],
               "delta": round(delta, 4)}
        if delta < -threshold:
            regressions.append(row)
        elif delta > threshold:
            improvements.append(row)
    return {"verdict": "regressed" if regressions else "ok",
            "regressions": regressions,
            "improvements": improvements}


def qos_sections(paths: List[str]) -> List[dict]:
    """The ``qos`` blocks of any BENCH_SLO.json inputs — the
    priority-plane bench arm (docs/serving.md#qos): interactive
    TTFT-inflation headline, shed/quota counts, scale events."""
    out = []
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict) and isinstance(doc.get("qos"), dict):
            q = {"source": path, **doc["qos"]}
            # Summary counters the report prints: shed per arm (QoS
            # replay arms + the autoscale ladder) and scale decisions
            # by direction/why.
            shed = {}
            arms = [q.get("interactive_only"),
                    q.get("with_bulk_burst")]
            auto = q.get("autoscale")
            if isinstance(auto, dict):
                arms += list((auto.get("sweep") or {}).values())
            for arm in arms:
                if not isinstance(arm, dict):
                    continue
                n = sum(t.get("shed", 0) for t in
                        (arm.get("tenants") or {}).values())
                if n:
                    shed[arm.get("name", "?")] = n
            if shed:
                q["shed"] = shed
            if isinstance(auto, dict) and auto.get("scale_events"):
                counts = {}
                for e in auto["scale_events"]:
                    key = f"{e.get('direction')}/{e.get('why')}"
                    counts[key] = counts.get(key, 0) + 1
                q["scale_events"] = counts
            out.append(q)
    return out


def build_report(paths: List[str],
                 target_ttft_ms: Optional[float] = None,
                 history_dir: Optional[str] = None,
                 qos: bool = False) -> dict:
    arms = load_arms(paths)
    knee = find_knee(arms, target_ttft_ms)
    report = {
        "arms": arms,
        "knee": None if knee is None else {
            "name": knee["name"],
            "offered_rps": knee.get("offered_rps"),
            "goodput_frac": knee.get("goodput_frac"),
            "ttft_p99_ms": knee.get("ttft_p99_ms")},
        "target_ttft_ms": target_ttft_ms,
        "tenants": violation_breakdown(arms),
    }
    classes = class_breakdown(arms)
    if classes:
        report["classes"] = classes
    if qos:
        report["qos"] = qos_sections(paths)
    if history_dir:
        report["history"] = history_slo_summary(history_dir)
    return report


def format_report(report: dict) -> str:
    lines = ["Goodput vs offered load", "",
             f"{'arm':<18} {'rps':>7} {'offered':>8} {'goodput':>8} "
             f"{'frac':>6} {'p99 ttft':>9} {'dropped':>8}"]
    knee = (report.get("knee") or {}).get("name")
    for a in report["arms"]:
        mark = "  <-- knee" if a["name"] == knee else ""
        p99 = a.get("ttft_p99_ms")
        lines.append(
            f"{a['name']:<18} {a.get('offered_rps') or 0:>7.2f} "
            f"{a['offered']:>8} {a['goodput']:>8} "
            f"{a.get('goodput_frac') or 0:>6.1%} "
            f"{(f'{p99:.0f}ms' if p99 is not None else '-'):>9} "
            f"{a.get('dropped', 0):>8}{mark}")
    if report.get("target_ttft_ms") is not None:
        lines.append(f"(target TTFT {report['target_ttft_ms']} ms)")
    if knee is None:
        lines.append("no knee: goodput tracked offered load on "
                     "every arm")
    lines += ["", "Per-tenant:",
              f"{'tenant':<16} {'offered':>8} {'goodput':>8} "
              f"{'frac':>6} {'violations':>10} {'shed':>6}"]
    for name, t in sorted(report["tenants"].items()):
        lines.append(
            f"{name:<16} {t['offered']:>8} {t['goodput']:>8} "
            f"{t['goodput_frac']:>6.1%} {t['slo_violations']:>10} "
            f"{t['shed']:>6}")
    if report.get("classes"):
        lines += ["", "Per-class (docs/serving.md#qos):",
                  f"{'class':<16} {'offered':>8} {'goodput':>8} "
                  f"{'frac':>6} {'violations':>10} {'shed':>6}"]
        for name, t in sorted(report["classes"].items()):
            lines.append(
                f"{name:<16} {t['offered']:>8} {t['goodput']:>8} "
                f"{t['goodput_frac']:>6.1%} {t['slo_violations']:>10} "
                f"{t['shed']:>6}")
    for q in report.get("qos") or []:
        lines.append("")
        lines.append(f"QoS arm [{q.get('source', '-')}]")
        for key in ("interactive_p99_inflation_qos",
                    "interactive_p99_inflation_baseline",
                    "reserved_slots", "schedule_checksum"):
            if key in q:
                lines.append(f"  {key:<36} {q[key]}")
        for key in ("shed", "scale_events"):
            if isinstance(q.get(key), dict):
                for k, v in sorted(q[key].items()):
                    lines.append(f"  {key}.{k:<30} {v}")
    for row in report.get("history", []):
        lines.append("")
        lines.append(f"History [{row['label']}]"
                     + (f" replica {row['replica']}"
                        if row.get("replica") is not None else ""))
        for name, v in sorted(row["slo"].items()):
            lines.append(f"  {name:<48} {v}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.tools.slo",
        description="Goodput-vs-offered-load report over open-loop "
                    "load results and the fleet history store "
                    "(docs/serving.md#slo)")
    ap.add_argument("results", nargs="+",
                    help="BENCH_SLO.json and/or loadgen run JSON "
                         "files")
    ap.add_argument("--target-ttft-ms", type=float, default=None,
                    help="TTFT target for knee detection")
    ap.add_argument("--history", default=None,
                    help="fleet history directory to fold in")
    ap.add_argument("--qos", action="store_true",
                    help="include the QoS sections of BENCH_SLO.json "
                         "inputs (priority-plane headlines, shed and "
                         "scale-event counts; docs/serving.md#qos)")
    ap.add_argument("--baseline", default=None,
                    help="baseline report/bench JSON to A/B against "
                         "(exit 3 on goodput regression)")
    ap.add_argument("--json", default=None,
                    help="also write the report JSON here")
    args = ap.parse_args(argv)

    report = build_report(args.results,
                          target_ttft_ms=args.target_ttft_ms,
                          history_dir=args.history, qos=args.qos)
    rc = 0
    if args.baseline:
        base = load_arms([args.baseline])
        ab = compare_baseline(report["arms"], base)
        report["baseline"] = ab
        if ab["verdict"] == "regressed":
            rc = 3
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
    print(format_report(report))
    if args.baseline:
        ab = report["baseline"]
        print()
        print(f"Baseline verdict: {ab['verdict']}")
        for r in ab["regressions"]:
            print(f"  REGRESSED {r['name']}: "
                  f"{r['baseline_goodput_frac']:.1%} -> "
                  f"{r['goodput_frac']:.1%}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
