"""Fleet health report over merged telemetry history files.

``python -m horovod_tpu.tools.health <dir-or-files...>`` merges the
per-rank / per-replica history files the sampler writes
(``history-rank{N}.jsonl`` + rotated segments, docs/health.md),
realigns them onto rank 0's clock via each segment's header offset,
and renders:

  - per-metric **sparkline trends** for the headline series (step
    time, MFU, HBM live, collective share, queue depths) plus any
    series a detector fired on;
  - **detector verdicts** — the SAME detector plane the live sampler
    runs (observability/health.py, offline mode) replayed over each
    label's samples, with the window that tripped each alert;
  - a **top-regressions-since-t0 ranking**: first-quartile vs
    last-quartile medians per series, direction-aware (a rising step
    time and a falling MFU are both regressions);
  - ``--baseline other_dir/`` **A/B mode**: steady-state medians of
    two runs diffed series-by-series — the seed of perf-regression CI
    (two identical runs report no regressions; an injected slowdown
    ranks its series on top).

``--json`` emits the full report dict for scripting/tests.
"""

from __future__ import annotations

import argparse
import json
import math
import statistics
import sys
from typing import Dict, List, Optional, Tuple

from ..observability import health as _health
from ..observability import history as _history

SPARK_BLOCKS = "▁▂▃▄▅▆▇█"

# Headline series always worth a sparkline when present (suffix-aware
# key matching; anything a detector fires on is added dynamically).
KEY_SERIES_FAMILIES = (
    "hvdtpu_step_seconds",
    "hvdtpu_mfu",
    "hvdtpu_hbm_bytes_in_use",
    "hvdtpu_collective_step_share",
    "hvdtpu_samples_per_second",
    "hvdtpu_serving_queue_depth",
    "hvdtpu_fleet_replica_queue_depth",
    "hvdtpu_serving_requests_per_second",
    "hvdtpu_slo_goodput_total",
    "hvdtpu_slo_violations_total",
    "hvdtpu_serving_shed_total",
    "hvdtpu_fleet_scale_events_total",
    "hvdtpu_fleet_target_replicas",
    # Numerics plane (docs/numerics.md): grad norm and loss trend
    # lines are the first thing to eyeball after a NaN page, and the
    # nonfinite counter's sparkline shows when the cascade started.
    "hvdtpu_numerics_grad_norm",
    "hvdtpu_numerics_loss",
    "hvdtpu_numerics_update_ratio",
    "hvdtpu_numerics_nonfinite_total",
)

# Direction-aware regression semantics: which way is WORSE.
# _DOWN_WORSE is checked first, so "goodput" wins over the "_total"
# suffix a counter family carries.
_UP_WORSE = ("seconds", "queue_depth", "bytes_in_use", "share",
             "lateness", "restarts_total", "failures_total",
             "errors_total", "stalled", "blocked", "violations",
             "shed", "scale_events", "nonfinite", "ef_residual",
             "skipped_steps")
_DOWN_WORSE = ("mfu", "per_second", "replicas_live", "replicas_ready",
               "acceptance", "goodput")


def _direction(series_key: str) -> int:
    """+1: up is worse; -1: down is worse; 0: not ranked."""
    fam, _, _ = _health.split_series_key(series_key)
    for marker in _DOWN_WORSE:
        if marker in fam:
            return -1
    for marker in _UP_WORSE:
        if marker in fam:
            return 1
    return 0


def sparkline(values: List[float], width: int = 40) -> str:
    """Resample to ``width`` columns and render unicode blocks."""
    if not values:
        return ""
    if len(values) > width:
        # Mean-pool into width buckets (trend display, not archival).
        step = len(values) / width
        pooled = []
        for i in range(width):
            chunk = values[int(i * step): max(int((i + 1) * step),
                                              int(i * step) + 1)]
            pooled.append(sum(chunk) / len(chunk))
        values = pooled
    lo, hi = min(values), max(values)
    if not math.isfinite(lo) or not math.isfinite(hi):
        return ""
    span = hi - lo
    if span <= 0:
        return SPARK_BLOCKS[0] * len(values)
    return "".join(
        SPARK_BLOCKS[min(len(SPARK_BLOCKS) - 1,
                         int((v - lo) / span * len(SPARK_BLOCKS)))]
        for v in values)


def _median(xs: List[float]) -> float:
    return statistics.median(xs) if xs else 0.0


def _mad(xs: List[float], center: float) -> float:
    return _median([abs(x - center) for x in xs])


def _quartile_change(points: List[Tuple[float, float]]
                     ) -> Optional[Tuple[float, float]]:
    """(first-quartile median, last-quartile median) over a time
    series — None with too few samples, or when the change does not
    dominate the series' own noise (a microsecond-scale jitter series
    can triple and still mean nothing; the gate is the same
    signal-vs-residual principle the online detectors use)."""
    if len(points) < 4:
        return None
    n = max(1, len(points) // 4)
    first = [v for _, v in points[:n]]
    last = [v for _, v in points[-n:]]
    base, recent = _median(first), _median(last)
    noise = max(_mad(first, base), _mad(last, recent))
    if abs(recent - base) <= 3.0 * noise:
        return None
    return base, recent


def _regression_score(base: float, recent: float, direction: int
                      ) -> float:
    """Signed relative change vs BASELINE, positive = got worse — the
    standard "% regression" semantics (a 20% slowdown scores +0.20 on
    step time and only −16.7% → +0.167 on its inverse, samples/sec, so
    the causal series outranks its derived mirror). A series appearing
    from a ~zero baseline is scored against its recent value instead
    (bounded at 1) so it cannot take over the ranking on a division
    artifact."""
    denom = abs(base) if abs(base) > 1e-12 else max(abs(recent), 1e-12)
    return direction * (recent - base) / denom


# --------------------------------------------------------------------------
# Single-run analysis
# --------------------------------------------------------------------------

def analyze(files: List[_history.HistoryFile], top: int = 10) -> dict:
    """The report dict: labels, detector verdicts, regressions ranking,
    sparklines."""
    labels = []
    alerts: List[dict] = []
    regressions: List[dict] = []
    sparks: Dict[str, Dict[str, dict]] = {}
    alerted_series: Dict[str, set] = {}

    for hf in files:
        series = hf.series()
        span = 0.0
        ts = [s.get("t_aligned_us", s.get("t_us", 0))
              for s in hf.samples]
        if len(ts) >= 2:
            span = (max(ts) - min(ts)) / 1e6
        labels.append({
            "label": hf.label,
            "rank": hf.meta.get("rank"),
            "replica": hf.meta.get("replica"),
            "generation": hf.meta.get("generation"),
            "samples": len(hf.samples),
            "span_s": round(span, 1),
            "clock_synced": bool(hf.meta.get("clock_synced", False)),
        })

        # Detector verdicts: replay the live plane offline, per label.
        monitor = _health.HealthMonitor(
            emit=False,
            rank=hf.meta.get("rank", -1)
            if hf.meta.get("rank") is not None else -1,
            replica=hf.meta.get("replica", -1)
            if hf.meta.get("replica") is not None else -1,
            refire_s=float("inf"))  # one verdict per (kind, series)
        for s in hf.samples:
            vals = {k: v for k, v in (s.get("s") or {}).items()
                    if v is not None}
            if not vals:
                continue
            t = s.get("t_aligned_us", s.get("t_us", 0)) / 1e6
            monitor.observe(vals, t=t, t_unix=s.get("u", 0.0))
        for a in monitor.alerts:
            d = a.to_dict()
            d["label"] = hf.label
            alerts.append(d)
            alerted_series.setdefault(hf.label, set()).add(a.series)

        # Regressions since t0, direction-aware.
        for key, points in series.items():
            direction = _direction(key)
            if direction == 0:
                continue
            qc = _quartile_change(points)
            if qc is None:
                continue
            base, recent = qc
            score = _regression_score(base, recent, direction)
            if score > 0.02:   # ignore noise-level drift
                regressions.append({
                    "label": hf.label, "series": key,
                    "baseline": base, "recent": recent,
                    "change_frac": round(score, 4),
                    "direction": "up" if direction > 0 else "down"})

    regressions.sort(key=lambda r: -r["change_frac"])
    if top:
        regressions = regressions[:top]

    # Sparklines: headline families + whatever alerted.
    for hf in files:
        series = hf.series()
        want = alerted_series.get(hf.label, set())
        rows = {}
        for key, points in sorted(series.items()):
            fam, _, suffix = _health.split_series_key(key)
            headline = fam in KEY_SERIES_FAMILIES and suffix in ("",
                                                                "mean")
            if not headline and key not in want:
                continue
            vals = [v for _, v in points]
            if len(vals) < 2:
                continue
            rows[key] = {
                "spark": sparkline(vals),
                "min": min(vals), "max": max(vals),
                "last": vals[-1], "n": len(vals)}
        if rows:
            sparks[hf.label] = rows

    alerts.sort(key=lambda a: a.get("t_unix", 0.0))
    return {"labels": labels, "alerts": alerts,
            "top_regressions": regressions, "sparklines": sparks}


# --------------------------------------------------------------------------
# Baseline A/B
# --------------------------------------------------------------------------

def compare_baseline(cur: List[_history.HistoryFile],
                     base: List[_history.HistoryFile],
                     threshold: float = 0.10, top: int = 10) -> dict:
    """Steady-state (last-half median) diff of two runs, matched on
    (label, series); ``threshold`` is the relative change past which a
    series counts as a regression (identical runs sit at ~0)."""

    def steady(files) -> Dict[Tuple[str, str], Tuple[float, float]]:
        out = {}
        for hf in files:
            for key, points in hf.series().items():
                if len(points) < 2:
                    continue
                vals = [v for _, v in points[len(points) // 2:]]
                med = _median(vals)
                out[(hf.label, key)] = (med, _mad(vals, med))
        return out

    cur_v, base_v = steady(cur), steady(base)
    rows = []
    for k in sorted(set(cur_v) & set(base_v)):
        label, key = k
        direction = _direction(key)
        if direction == 0:
            continue
        (cv, c_mad), (bv, b_mad) = cur_v[k], base_v[k]
        # Significance gate: the A/B delta must dominate both runs'
        # own steady-state noise, or a jitter-scale series drowns the
        # ranking in meaningless triple-digit "regressions". Gated
        # series still count as compared — compared and found equal.
        score = _regression_score(bv, cv, direction)
        if abs(cv - bv) <= 3.0 * max(c_mad, b_mad):
            score = 0.0
        rows.append({"label": label, "series": key,
                     "baseline_value": bv,
                     "current_value": cv,
                     "change_frac": round(score, 4)})
    rows.sort(key=lambda r: -r["change_frac"])
    regressions = [r for r in rows if r["change_frac"] >= threshold]
    improvements = [r for r in rows
                    if r["change_frac"] <= -threshold]
    return {
        "threshold": threshold,
        "series_compared": len(rows),
        "regressions": regressions[:top] if top else regressions,
        "improvements": (sorted(improvements,
                                key=lambda r: r["change_frac"])[:top]
                         if top else improvements),
        "verdict": ("regressions" if regressions
                    else "no_regressions"),
    }


# --------------------------------------------------------------------------
# Rendering
# --------------------------------------------------------------------------

def _fmt_v(v: float) -> str:
    if abs(v) >= 1e9 or (0 < abs(v) < 1e-3):
        return f"{v:.3e}"
    return f"{v:.4g}"


def format_report(report: dict) -> str:
    lines = ["== horovod_tpu fleet health report =="]
    lines.append(f"{len(report['labels'])} history label(s):")
    for lab in report["labels"]:
        who = lab["label"]
        extra = []
        if lab.get("rank") is not None:
            extra.append(f"rank {lab['rank']}")
        if lab.get("replica") is not None:
            extra.append(f"replica {lab['replica']}")
        extra.append(f"{lab['samples']} samples")
        extra.append(f"{lab['span_s']:.0f}s span")
        if not lab.get("clock_synced"):
            extra.append("clock UNSYNCED")
        lines.append(f"  {who:<14} {', '.join(extra)}")

    lines.append("")
    lines.append("-- detector verdicts --")
    if not report["alerts"]:
        lines.append("  healthy: no detector fired on any label")
    for a in report["alerts"]:
        lines.append(
            f"  [{a['severity'].upper():>8}] {a['kind']} on "
            f"{a['label']}: {a['series']}")
        lines.append(
            f"             value {_fmt_v(a['value'])} vs baseline "
            f"{_fmt_v(a['baseline'])} over {a['window_s']:.0f}s window")

    lines.append("")
    lines.append("-- top regressions since t0 --")
    if not report["top_regressions"]:
        lines.append("  none above the noise floor")
    for i, r in enumerate(report["top_regressions"], 1):
        arrow = "↑" if r["direction"] == "up" else "↓"
        lines.append(
            f"  {i:>2}. {r['label']}: {r['series']} {arrow} "
            f"{r['change_frac'] * 100:+.1f}% "
            f"({_fmt_v(r['baseline'])} → {_fmt_v(r['recent'])})")

    if report.get("sparklines"):
        lines.append("")
        lines.append("-- trends --")
        for label, rows in sorted(report["sparklines"].items()):
            lines.append(f"  {label}:")
            for key, row in rows.items():
                lines.append(
                    f"    {key:<58} {row['spark']}  "
                    f"[{_fmt_v(row['min'])} .. {_fmt_v(row['max'])}] "
                    f"last {_fmt_v(row['last'])}")

    if "baseline" in report:
        b = report["baseline"]
        lines.append("")
        lines.append(f"-- baseline A/B ({b['series_compared']} series "
                     f"compared, threshold "
                     f"{b['threshold'] * 100:.0f}%) --")
        if b["verdict"] == "no_regressions":
            lines.append("  no regressions vs baseline")
        for i, r in enumerate(b["regressions"], 1):
            lines.append(
                f"  {i:>2}. REGRESSED {r['label']}: {r['series']} "
                f"{r['change_frac'] * 100:+.1f}% "
                f"({_fmt_v(r['baseline_value'])} → "
                f"{_fmt_v(r['current_value'])})")
        for r in b["improvements"]:
            lines.append(
                f"      improved  {r['label']}: {r['series']} "
                f"{r['change_frac'] * 100:+.1f}%")
    return "\n".join(lines)


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def _main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.tools.health",
        description="Merge per-rank/per-replica telemetry history "
                    "files and render a fleet health report "
                    "(docs/health.md)")
    ap.add_argument("inputs", nargs="+",
                    help="history directory (expands history-*.jsonl) "
                         "or explicit history files")
    ap.add_argument("--baseline", default=None, metavar="DIR",
                    help="A/B mode: diff this run against another "
                         "run's history directory (perf-regression "
                         "CI seed)")
    ap.add_argument("--top", type=int, default=10,
                    help="rows in the regression rankings (default 10)")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="baseline-mode relative-change threshold "
                         "(default 0.10)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON")
    args = ap.parse_args(argv)

    try:
        files = _history.load_history(args.inputs)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    report = analyze(files, top=args.top)
    if args.baseline:
        try:
            base_files = _history.load_history([args.baseline])
        except FileNotFoundError as e:
            print(str(e), file=sys.stderr)
            return 2
        report["baseline"] = compare_baseline(
            files, base_files, threshold=args.threshold, top=args.top)
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        print(format_report(report))
    if (args.baseline
            and report["baseline"]["verdict"] == "regressions"):
        # CI-shaped contract (docs/health.md#baseline): a baseline diff
        # that found regressions exits nonzero so a perf gate can be
        # one `health --baseline` invocation; 3 keeps it distinct from
        # argparse's 2 and the missing-input 2 above.
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(_main())
