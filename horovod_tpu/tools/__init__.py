"""Offline analysis tools (``python -m horovod_tpu.tools.<name>``).

Currently: :mod:`.trace` — merge N per-rank timeline captures into one
clock-aligned Perfetto trace and compute the per-fused-group critical
path / straggler attribution (docs/tracing.md).
"""
