"""Cross-rank trace merge + critical-path analysis CLI (docs/tracing.md).

The capture side (``HOROVOD_TPU_TIMELINE`` with a ``{rank}``
placeholder) leaves one catapult JSON per rank, each on its OWN clock:
event ``ts`` are microseconds since that rank's writer start, and the
trace clock header records the writer's monotonic epoch plus the rank's
estimated offset to rank 0 (the NTP-style control-plane handshake,
ops/control_plane.ClockProbeRequest). This tool puts the N files back
on one clock and answers the question the single-rank timeline cannot:
*which rank is slowing the job down, and in which phase?*

  merge   — one Perfetto/catapult trace: one trace "process" per rank,
            tensors as named threads, timestamps realigned through the
            recorded offsets.
  report  — per-fused-group critical path: for every coordinator group,
            which rank's NEGOTIATE tick arrived last; per-rank lateness
            distributions (p50/p90/p99 through the same log-bucket
            estimator as the live Prometheus plane,
            observability.histogram_percentiles); a ranked straggler
            table with the phase each rank loses time in
            (negotiate/queue/h2d/execute — or "upstream" when the skew
            originates before the collective path, i.e. compute/input).
  serving — per-REQUEST latency-budget report over serving request
            traces (docs/serving.md#request-tracing): the router and
            every replica each write one catapult file
            (serving/reqtrace.py) whose rows are trace ids; this
            subcommand groups each request's spans across ALL the
            processes it touched and reports where its latency went
            (queue / prefill / decode / failover shares of the
            measured wall), the slowest requests, and failover chains
            with the re-prefill cost on the resume replica.

Usage::

    python -m horovod_tpu.tools.trace merge /tmp/trace.{rank}.json \
        -o merged.json --report report.json
    python -m horovod_tpu.tools.trace report /tmp/trace.*.json
    python -m horovod_tpu.tools.trace serving /tmp/reqtrace-dir \
        --report budget.json

Groups are keyed by the coordinator sequence number the Python writer
records on each NEGOTIATE span — identical on every rank for the
same fused collective. Traces without group ids (the native C++ writer)
fall back to per-tensor occurrence pairing, which holds as long as
every rank executed the same collectives in the same order (the SPMD
contract the coordinator enforces anyway).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
from typing import Dict, List, Optional, Tuple

import bisect
import math

from ..observability.export import histogram_percentiles
from ..observability.registry import LATENCY_BUCKETS
from ..ops.timeline_jit import _load_timeline
from ..ops.timeline_py import TRACE_META_EVENT, clock_sidecar_path

PHASES = ("negotiate", "queue", "h2d", "execute", "input", "compute")

# Verdict buckets (docs/tracing.md): which phases mean the rank is
# losing time to communication, to the input pipeline, or to compute.
_BUCKET_OF = {"negotiate": "comm", "queue": "comm", "execute": "comm",
              "h2d": "input", "input": "input", "compute": "compute"}
_VERDICT_OF = {"comm": "comm-bound", "input": "input-bound",
               "compute": "compute-bound"}

_PHASE_OF = {"QUEUE": "queue", "MEMCPY_IN_FUSION_BUFFER": "h2d",
             # StepTimer's per-step attribution spans (docs/metrics.md):
             # emitted on the "_step" pseudo-process when a shim
             # StepTimer runs next to the Python timeline writer.
             "STEP_INPUT": "input", "STEP_H2D": "h2d",
             "STEP_COMPUTE": "compute"}


def _phase_of(name: str) -> Optional[str]:
    if name.startswith("NEGOTIATE_"):
        return "negotiate"
    if name.startswith("XLA_") and name != "XLA_STEP":
        return "execute"
    return _PHASE_OF.get(name)


# --------------------------------------------------------------------------
# Loading
# --------------------------------------------------------------------------

class RankTrace:
    """One rank's capture: raw events, pid→tensor names, clock meta."""

    def __init__(self, path: str, events: List[dict], meta: dict):
        self.path = path
        self.events = events
        self.meta = meta
        self.rank = meta.get("rank")
        # Serving request-trace writers name their process ("router",
        # "replica1/gen0") instead of speaking in ranks.
        self.proc = meta.get("proc")
        self.tensor_of: Dict[int, str] = {
            e["pid"]: str(e["args"]["name"]) for e in events
            if e.get("ph") == "M" and e.get("name") == "process_name"
            and "pid" in e and e.get("args", {}).get("name") is not None}

    @property
    def shift_us(self) -> float:
        """Local trace ts → rank-0 monotonic microseconds."""
        return (float(self.meta.get("start_mono_us", 0))
                + float(self.meta.get("offset_to_rank0_us", 0.0)))

    @property
    def clock_missing(self) -> bool:
        """No clock metadata at all (neither in-band nor sidecar) —
        alignment degraded to zero offset."""
        return not self.meta


def _read_meta(path: str, events: List[dict]) -> dict:
    """Clock metadata: the LAST in-trace meta event (a sync supersedes
    the unsynced header) or the sidecar; empty dict when neither exists
    (offset 0 — single-host captures still merge correctly since all
    writers share one monotonic clock only if starts are recorded, so a
    missing header degrades alignment to per-file-relative time).

    A missing or corrupt ``.clock.json`` sidecar must DEGRADE, not fail
    the whole merge: the native writer's sidecar is easily lost when
    only the trace files are copied off the pod, and N-1 good traces
    are still worth aligning. The fallback is zero offset, flagged so
    the report header can warn."""
    meta: dict = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == TRACE_META_EVENT:
            meta = dict(e.get("args") or {})
    if not meta:
        sidecar = clock_sidecar_path(path)
        try:
            if os.path.exists(sidecar):
                with open(sidecar) as f:
                    meta = json.load(f)
                if not isinstance(meta, dict):
                    meta = {}
        except (OSError, ValueError):
            meta = {}
    return meta


def load_rank_trace(path: str) -> RankTrace:
    events = _load_timeline(path)
    return RankTrace(path, events, _read_meta(path, events))


def expand_inputs(paths: List[str]) -> List[str]:
    """A single ``{rank}`` template expands to consecutive existing
    files starting at rank 0; a single directory expands to its
    ``*.trace.json`` captures (the serving request-trace layout —
    one file per router/replica process)."""
    if len(paths) == 1 and os.path.isdir(paths[0]):
        out = sorted(glob.glob(os.path.join(paths[0], "*.trace.json")))
        if not out:
            raise FileNotFoundError(
                f"no *.trace.json captures under {paths[0]}")
        return out
    if len(paths) == 1 and "{rank}" in paths[0]:
        out = []
        rank = 0
        while True:
            p = paths[0].replace("{rank}", str(rank))
            if not os.path.exists(p):
                break
            out.append(p)
            rank += 1
        if not out:
            raise FileNotFoundError(
                f"no trace files match template {paths[0]}")
        return out
    return list(paths)


def load_traces(paths: List[str]) -> List[RankTrace]:
    traces = [load_rank_trace(p) for p in expand_inputs(paths)]
    # Rank identity from the clock header; positional fallback for
    # headerless files, collision = operator error (two copies of one
    # rank's file would silently halve every lateness).
    seen = set()
    for i, t in enumerate(traces):
        if t.rank is None:
            t.rank = i
        if t.rank in seen:
            raise ValueError(
                f"duplicate rank {t.rank} among inputs ({t.path})")
        seen.add(t.rank)
    traces.sort(key=lambda t: t.rank)
    return traces


# --------------------------------------------------------------------------
# Merge
# --------------------------------------------------------------------------

def merge_traces(traces: List[RankTrace], out_path: str) -> str:
    """One Perfetto/catapult file: pid = rank, tid = interned tensor,
    timestamps realigned via each trace's recorded clock shift and
    rebased so the earliest event sits at 0."""
    shifts = {t.rank: t.shift_us for t in traces}
    base = None
    for t in traces:
        for e in t.events:
            if e.get("ph") in ("M", None) or "ts" not in e:
                continue
            at = e["ts"] + shifts[t.rank]
            base = at if base is None else min(base, at)
    base = base or 0.0
    merged: List[dict] = []
    for t in traces:
        merged.append({"name": "process_name", "ph": "M", "pid": t.rank,
                       "args": {"name": t.proc or f"rank {t.rank}"}})
        merged.append({"name": "process_sort_index", "ph": "M",
                       "pid": t.rank, "args": {"sort_index": t.rank}})
        tids: Dict[int, int] = {}
        for pid, tensor in t.tensor_of.items():
            tids[pid] = len(tids)
            merged.append({"name": "thread_name", "ph": "M", "pid": t.rank,
                           "tid": tids[pid], "args": {"name": tensor}})
        for e in t.events:
            if e.get("ph") == "M":
                continue  # re-interned above (incl. the clock header)
            ev = dict(e)
            ev["pid"] = t.rank
            ev["tid"] = tids.setdefault(e.get("pid", 0),
                                        len(tids))
            if "ts" in ev:
                ev["ts"] = int(ev["ts"] + shifts[t.rank] - base)
            if ev.get("s") == "g":
                # A cycle marker is per-rank state; in the merged view a
                # global-scope instant would draw across ALL ranks.
                ev["s"] = "p"
            merged.append(ev)
    with open(out_path, "w") as f:
        json.dump(merged, f)
    return out_path


# --------------------------------------------------------------------------
# Analysis
# --------------------------------------------------------------------------

def _spans(events: List[dict]) -> List[dict]:
    """Pair B/E (and accept X) into spans per (pid, tid). Tolerates
    truncated captures: unmatched closers are dropped, unclosed openers
    are skipped — exactly what a killed writer leaves behind."""
    stacks: Dict[Tuple, List[dict]] = {}
    spans: List[dict] = []
    for e in events:
        ph = e.get("ph")
        key = (e.get("pid"), e.get("tid", 0))
        if ph == "B":
            stacks.setdefault(key, []).append(e)
        elif ph == "E":
            st = stacks.get(key)
            if not st:
                continue
            b = st.pop()
            args = dict(b.get("args") or {})
            args.update(e.get("args") or {})
            spans.append({"pid": key[0], "name": b.get("name", ""),
                          "ts": b.get("ts", 0),
                          "dur": max(0, e.get("ts", 0) - b.get("ts", 0)),
                          "args": args})
        elif ph == "X":
            spans.append({"pid": key[0], "name": e.get("name", ""),
                          "ts": e.get("ts", 0),
                          "dur": int(e.get("dur", 0)),
                          "args": dict(e.get("args") or {})})
    return spans


def _arrivals(trace: RankTrace) -> Dict[str, float]:
    """Per-group arrival microseconds in the rank-0 clock domain.
    Arrival of a group on a rank = the LAST member tensor's NEGOTIATE
    start (the group cannot be agreed before the rank's final
    announce); groups keyed by the recorded coordinator seq, falling
    back to per-tensor occurrence index."""
    shift = trace.shift_us
    occurrence: Dict[str, int] = {}
    arrivals: Dict[str, float] = {}
    for s in _spans(trace.events):
        if not s["name"].startswith("NEGOTIATE_"):
            continue
        tensor = trace.tensor_of.get(s["pid"], str(s["pid"]))
        if tensor.startswith(("jit::", "_cycles", "_step")):
            continue
        group = s["args"].get("group")
        if group is not None:
            key = f"g{int(group)}"
        else:
            k = occurrence.get(tensor, 0)
            occurrence[tensor] = k + 1
            key = f"{tensor}#{k}"
        at = s["ts"] + shift
        arrivals[key] = max(arrivals.get(key, at), at)
    return arrivals


def _phase_stats(trace: RankTrace
                 ) -> Tuple[Dict[str, float], Dict[str, float]]:
    """(mean span duration, total seconds) per lifecycle phase on this
    rank — one pass over the spans. Totals feed the bucket shares the
    bound-verdict is computed from; means feed the fleet-median
    deviation attribution."""
    sums = {p: 0.0 for p in PHASES}
    counts = {p: 0 for p in PHASES}
    for s in _spans(trace.events):
        phase = _phase_of(s["name"])
        if phase is None:
            continue
        sums[phase] += s["dur"] / 1e6
        counts[phase] += 1
    means = {p: (sums[p] / counts[p] if counts[p] else 0.0)
             for p in PHASES}
    return means, sums


def _hist_snapshot(samples: List[float]) -> dict:
    """Registry-format histogram snapshot of raw samples over the live
    plane's LATENCY_BUCKETS — feeds the same percentile estimator, so
    offline and Prometheus numbers agree to within one bucket width."""
    counts = [0] * (len(LATENCY_BUCKETS) + 1)
    for v in samples:
        counts[bisect.bisect_left(LATENCY_BUCKETS, v)] += 1
    out = []
    cum = 0
    for le, c in zip(LATENCY_BUCKETS, counts[:-1]):
        cum += c
        out.append([le, cum])
    out.append([math.inf, cum + counts[-1]])
    return {"buckets": out, "sum": float(sum(samples)),
            "count": len(samples)}


def analyze(traces: List[RankTrace], top: int = 0) -> dict:
    """The straggler report (see module docstring). ``top`` limits the
    per-group critical-path listing (0 = omit raw groups)."""
    ranks = [t.rank for t in traces]
    arrivals_by_rank = {t.rank: _arrivals(t) for t in traces}
    common = set.intersection(*(set(a) for a in arrivals_by_rank.values())) \
        if traces else set()
    lateness: Dict[int, List[float]] = {r: [] for r in ranks}
    last_count: Dict[int, int] = {r: 0 for r in ranks}
    group_rows = []
    for key in sorted(common,
                      key=lambda k: min(arrivals_by_rank[r][k]
                                        for r in ranks)):
        arr = {r: arrivals_by_rank[r][key] for r in ranks}
        t0 = min(arr.values())
        critical = max(arr, key=lambda r: arr[r])
        last_count[critical] += 1
        for r in ranks:
            lateness[r].append((arr[r] - t0) / 1e6)
        group_rows.append({
            "group": key, "critical_rank": critical,
            "lateness_s": round((arr[critical] - t0) / 1e6, 6)})
    stats = {t.rank: _phase_stats(t) for t in traces}
    phase_means = {r: stats[r][0] for r in ranks}
    phase_totals = {r: stats[r][1] for r in ranks}
    # Lower median: with an even rank count the upper median would let a
    # single slow rank set its own baseline and mask itself.
    fleet_median = {
        p: sorted(phase_means[r][p] for r in ranks)[(len(ranks) - 1) // 2]
        for p in PHASES}
    per_rank = {}
    bucket_fleet = {"input": 0.0, "compute": 0.0, "comm": 0.0}
    any_step_spans = False
    for r in ranks:
        samples = lateness[r]
        # Same estimator as the live hvdtpu_negotiate_lateness_seconds
        # plane: route samples through the identical log-bucket layout
        # (independent of the runtime metrics flag — this is an offline
        # tool).
        pct = histogram_percentiles(_hist_snapshot(samples),
                                    qs=(0.5, 0.9, 0.99))
        mean = sum(samples) / len(samples) if samples else 0.0
        dev = {p: phase_means[r][p] - fleet_median[p] for p in PHASES}
        worst_phase = max(PHASES, key=lambda p: dev[p])
        # When no collective-path phase explains the skew, the rank is
        # late ARRIVING — the time is lost upstream (compute, input
        # pipeline, host scheduling), not inside the engine.
        loses_in = (worst_phase
                    if dev[worst_phase] > max(1e-6, 0.1 * mean)
                    else "upstream(compute/input)")
        # Bound verdict (docs/tracing.md): where does this rank's time
        # GO, in absolute terms? With StepTimer step spans in the trace
        # the input/compute buckets are real and the shares over
        # input+compute+comm decide; without them the deviation-based
        # attribution is the only evidence (a uniformly slow input
        # pipeline is invisible to deviation — instrument the loop with
        # a StepTimer to expose it).
        totals = phase_totals[r]
        bucket = {"input": totals["input"] + totals["h2d"],
                  "compute": totals["compute"],
                  "comm": (totals["negotiate"] + totals["queue"]
                           + totals["execute"])}
        has_step = (totals["input"] + totals["compute"]) > 0
        bucket_total = sum(bucket.values())
        shares = {b: (v / bucket_total if bucket_total > 0 else 0.0)
                  for b, v in bucket.items()}
        if has_step:
            any_step_spans = True
            for b, v in bucket.items():
                bucket_fleet[b] += v
            verdict = _VERDICT_OF[max(bucket, key=bucket.get)]
        elif loses_in.startswith("upstream"):
            verdict = "upstream(compute/input)"
        else:
            verdict = _VERDICT_OF[_BUCKET_OF[loses_in]]
        per_rank[str(r)] = {
            "groups": len(samples),
            "groups_last": last_count[r],
            "lateness": {
                "p50_s": round(pct.get("p50", 0.0), 6),
                "p90_s": round(pct.get("p90", 0.0), 6),
                "p99_s": round(pct.get("p99", 0.0), 6),
                "mean_s": round(mean, 6),
                "max_s": round(max(samples), 6) if samples else 0.0,
            },
            "phase_mean_s": {p: round(phase_means[r][p], 6)
                             for p in PHASES},
            "phase_share": {b: round(shares[b], 4) for b in shares},
            "loses_most_in": loses_in,
            "verdict": verdict,
        }
    order = sorted(ranks,
                   key=lambda r: (per_rank[str(r)]["lateness"]["p50_s"],
                                  per_rank[str(r)]["lateness"]["mean_s"],
                                  per_rank[str(r)]["groups_last"]),
                   reverse=True)
    stragglers = [{"rank": r, **per_rank[str(r)]["lateness"],
                   "groups_last": per_rank[str(r)]["groups_last"],
                   "loses_most_in": per_rank[str(r)]["loses_most_in"],
                   "verdict": per_rank[str(r)]["verdict"]}
                  for r in order]
    # Run-level bound verdict: the fleet's dominant cost bucket. Only
    # meaningful when step spans exist — without input/compute data the
    # trace ONLY contains collective spans and "comm" would win
    # vacuously.
    fleet_total = sum(bucket_fleet.values())
    bound = (_VERDICT_OF[max(bucket_fleet, key=bucket_fleet.get)]
             if any_step_spans and fleet_total > 0 else None)
    report = {
        "ranks": ranks,
        "groups_scored": len(common),
        "clock": {str(t.rank): {
            "offset_to_rank0_us": float(
                t.meta.get("offset_to_rank0_us", 0.0)),
            "rtt_us": float(t.meta.get("rtt_us", 0.0)),
            "synced": bool(t.meta.get("clock_synced", False)),
            "meta_missing": t.clock_missing,
        } for t in traces},
        "per_rank": per_rank,
        "stragglers": stragglers,
        "top_straggler": stragglers[0] if stragglers else None,
        "bound": bound,
        "fleet_share": ({b: round(v / fleet_total, 4)
                         for b, v in bucket_fleet.items()}
                        if fleet_total > 0 else None),
    }
    if top:
        worst = sorted(group_rows, key=lambda g: -g["lateness_s"])[:top]
        report["worst_groups"] = worst
    return report


def format_report(report: dict) -> str:
    """Human-readable rendering of :func:`analyze`'s JSON."""
    header = (f"Cross-rank trace report — {len(report['ranks'])} ranks, "
              f"{report['groups_scored']} fused groups scored")
    if report.get("bound"):
        fs = report.get("fleet_share") or {}
        header += (f"; run verdict: {report['bound']}"
                   + (f" (input {fs.get('input', 0):.0%} / compute "
                      f"{fs.get('compute', 0):.0%} / comm "
                      f"{fs.get('comm', 0):.0%})" if fs else ""))
    lines = [header]
    missing = [r for r, c in report["clock"].items()
               if c.get("meta_missing")]
    if missing:
        lines.append(
            "WARNING: no clock metadata (.clock.json sidecar or in-band "
            f"header) for ranks {', '.join(sorted(missing))} — zero-"
            "offset fallback; their timestamps carry the raw inter-host "
            "clock skew.")
    lines += [
        "",
        f"{'rank':>4}  {'p50 late':>10}  {'p99 late':>10}  "
        f"{'mean':>10}  {'last-in':>8}  {'verdict':<14} loses most in",
    ]
    for s in report["stragglers"]:
        lines.append(
            f"{s['rank']:>4}  {s['p50_s'] * 1e3:>8.2f}ms  "
            f"{s['p99_s'] * 1e3:>8.2f}ms  {s['mean_s'] * 1e3:>8.2f}ms  "
            f"{s['groups_last']:>8}  {s['verdict']:<14} "
            f"{s['loses_most_in']}")
    top = report.get("top_straggler")
    if top and top["mean_s"] > 0:
        lines += ["", f"Top straggler: rank {top['rank']} "
                      f"(p50 lateness {top['p50_s'] * 1e3:.2f} ms, "
                      f"last to arrive in {top['groups_last']} of "
                      f"{report['groups_scored']} groups; "
                      f"loses time in: {top['loses_most_in']}; "
                      f"verdict: {top['verdict']})"]
    unsynced = [r for r, c in report["clock"].items()
                if not c["synced"] and r != "0"
                and not c.get("meta_missing")]
    if unsynced:
        lines += ["", "WARNING: clock offset unsynced for ranks "
                      f"{', '.join(unsynced)} — lateness numbers for "
                      "them carry the raw inter-host clock skew."]
    return "\n".join(lines)


# --------------------------------------------------------------------------
# Serving request-trace analysis (docs/serving.md#request-tracing)
# --------------------------------------------------------------------------

# Span name → latency-budget phase. FAILOVER is handled separately: its
# detection→resume window OVERLAPS the resume replica's queue/prefill
# spans (the re-dispatch is what ends it), so only the time not already
# attributed to a concrete phase counts as "failover" — the budget then
# partitions instead of double-counting.
_REQ_PHASE_OF = {"QUEUE_WAIT": "queue", "PREFILL": "prefill",
                 "DECODE": "decode", "EGRESS": "egress"}
REQ_PHASES = ("queue", "prefill", "decode", "failover", "egress")


def _union(ivs: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """Merge possibly-overlapping intervals into a sorted disjoint set."""
    out: List[Tuple[float, float]] = []
    for a, b in sorted(ivs):
        if b <= a:
            continue
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _total(ivs: List[Tuple[float, float]]) -> float:
    return sum(b - a for a, b in ivs)


def _subtract(ivs: List[Tuple[float, float]],
              cover: List[Tuple[float, float]]
              ) -> List[Tuple[float, float]]:
    """``ivs`` minus ``cover`` (both disjoint-sorted)."""
    out: List[Tuple[float, float]] = []
    for a, b in ivs:
        cur = a
        for ca, cb in cover:
            if cb <= cur or ca >= b:
                continue
            if ca > cur:
                out.append((cur, ca))
            cur = max(cur, cb)
            if cur >= b:
                break
        if cur < b:
            out.append((cur, b))
    return out


def serving_report(traces: List[RankTrace], top: int = 10) -> dict:
    """Per-request latency-budget report over serving request traces:
    each request's spans are gathered ACROSS processes by its trace id
    (the row name every writer uses), aligned onto one clock, and
    attributed to queue / prefill / decode / failover (/ egress)
    phases. ``attributed_frac`` is the budget's share of the measured
    wall — the acceptance bar is that it explains the wall to within
    10% for a failed-over request."""
    rows: Dict[str, dict] = {}
    for t in traces:
        shift = t.shift_us
        pname = t.proc or f"rank {t.rank}"
        for s in _spans(t.events):
            tid = t.tensor_of.get(s["pid"])
            if tid is None:
                continue
            rec = rows.setdefault(tid, {"spans": [], "procs": set()})
            rec["procs"].add(pname)
            rec["spans"].append({
                "name": s["name"], "t0": s["ts"] + shift,
                "t1": s["ts"] + shift + s["dur"], "dur": s["dur"],
                "args": s["args"], "proc": pname})
    requests: Dict[str, dict] = {}
    for tid, rec in rows.items():
        spans = sorted(rec["spans"], key=lambda x: x["t0"])
        request = next((x for x in spans if x["name"] == "REQUEST"),
                       None)
        # The wall: the router's REQUEST span when present (the client-
        # observed latency), span extremes otherwise (engine-only
        # captures).
        if request is not None:
            t0, t1 = request["t0"], request["t1"]
        else:
            t0 = min(x["t0"] for x in spans)
            t1 = max(x["t1"] for x in spans)
        wall_us = max(0.0, t1 - t0)
        ivs: Dict[str, List[Tuple[float, float]]] = {
            p: [] for p in ("queue", "prefill", "decode", "egress")}
        failover_spans = []
        for x in spans:
            ph = _REQ_PHASE_OF.get(x["name"])
            if ph is not None:
                ivs[ph].append((x["t0"], x["t1"]))
            elif x["name"] == "FAILOVER":
                failover_spans.append(x)
        unions = {p: _union(v) for p, v in ivs.items()}
        phase_us = {p: _total(u) for p, u in unions.items()}
        covered = _union([iv for u in unions.values() for iv in u])
        fo_union = _union([(x["t0"], x["t1"]) for x in failover_spans])
        phase_us["failover"] = _total(_subtract(fo_union, covered))
        attributed = sum(phase_us[p]
                         for p in ("queue", "prefill", "decode",
                                   "failover"))
        failovers = []
        for x in failover_spans:
            # The failover chain: detection → resume, plus the
            # re-prefill it forced on the replacement replica (the
            # first PREFILL starting inside/after the window).
            reprefill = next(
                (p for p in spans if p["name"] == "PREFILL"
                 and p["t0"] >= x["t0"]), None)
            failovers.append({
                "phase": x["args"].get("phase"),
                "from_replica": x["args"].get("from"),
                "to_replica": x["args"].get("to"),
                "detect_to_resume_ms": round(x["dur"] / 1e3, 3),
                "reprefill_ms": (round(reprefill["dur"] / 1e3, 3)
                                 if reprefill else None),
                "reprefill_tokens": (reprefill["args"].get("tokens")
                                     if reprefill else None),
                "reprefill_proc": (reprefill["proc"]
                                   if reprefill else None),
            })
        # Tenant + SLO verdict ride the span args: the router stamps
        # REQUEST, the replica's egress stamps EGRESS — either names
        # the tenant, and slo_met is the judged verdict
        # (docs/serving.md#slo).
        tenant = None
        slo_met = None
        for x in ([request] if request is not None else []) + [
                s for s in spans if s["name"] == "EGRESS"]:
            if tenant is None:
                tenant = x["args"].get("tenant")
            if slo_met is None:
                slo_met = x["args"].get("slo_met")
        requests[tid] = {
            "wall_ms": round(wall_us / 1e3, 3),
            "processes": sorted(rec["procs"]),
            "tenant": tenant,
            "slo_met": slo_met,
            "spans": len(spans),
            "phase_ms": {p: round(phase_us.get(p, 0.0) / 1e3, 3)
                         for p in REQ_PHASES},
            "phase_share": {p: (round(phase_us.get(p, 0.0) / wall_us, 4)
                                if wall_us > 0 else 0.0)
                            for p in REQ_PHASES},
            "attributed_frac": (round(attributed / wall_us, 4)
                                if wall_us > 0 else 0.0),
            "failovers": failovers,
        }
    slowest = sorted(requests,
                     key=lambda k: -requests[k]["wall_ms"])[:top]
    return {
        "n_requests": len(requests),
        "processes": sorted({p for r in requests.values()
                             for p in r["processes"]}),
        "requests": requests,
        "slowest": [{"trace": k, "wall_ms": requests[k]["wall_ms"],
                     "phase_share": requests[k]["phase_share"],
                     "tenant": requests[k]["tenant"],
                     "slo_met": requests[k]["slo_met"],
                     "failovers": len(requests[k]["failovers"])}
                    for k in slowest],
        "n_failovers": sum(len(r["failovers"])
                           for r in requests.values()),
    }


def format_serving_report(report: dict) -> str:
    """Human-readable per-request budget table, slowest first."""
    lines = [
        f"Serving request-trace report — {report['n_requests']} "
        f"request(s) across {len(report['processes'])} process(es) "
        f"({', '.join(report['processes'])}), "
        f"{report['n_failovers']} failover(s)",
        "",
        f"{'trace id':<20}  {'wall':>9}  {'queue':>6} {'prefil':>6} "
        f"{'decode':>6} {'failov':>6}  {'attrib':>6}  {'slo':>4}  "
        f"procs",
    ]
    for row in report["slowest"]:
        r = report["requests"][row["trace"]]
        sh = r["phase_share"]
        slo = ("-" if r.get("slo_met") is None
               else "met" if r["slo_met"] else "MISS")
        lines.append(
            f"{row['trace']:<20}  {r['wall_ms']:>7.1f}ms  "
            f"{sh['queue']:>6.1%} {sh['prefill']:>6.1%} "
            f"{sh['decode']:>6.1%} {sh['failover']:>6.1%}  "
            f"{r['attributed_frac']:>6.1%}  {slo:>4}  "
            f"{len(r['processes'])}"
            + (f"  tenant={r['tenant']}" if r.get("tenant") else "")
            + ("  [failover]" if r["failovers"] else ""))
    chains = [(tid, f) for tid, r in report["requests"].items()
              for f in r["failovers"]]
    if chains:
        lines.append("")
        for tid, f in chains:
            lines.append(
                f"Failover: {tid} — {f['phase']} on replica "
                f"{f['from_replica']} → {f['to_replica']}; detection→"
                f"resume {f['detect_to_resume_ms']} ms"
                + (f", re-prefill {f['reprefill_tokens']} tokens in "
                   f"{f['reprefill_ms']} ms on {f['reprefill_proc']}"
                   if f["reprefill_ms"] is not None else ""))
    return "\n".join(lines)


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def _main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.tools.trace",
        description="Merge per-rank horovod_tpu timeline captures into "
                    "one clock-aligned Perfetto trace and report the "
                    "per-group critical path / straggler attribution")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_merge = sub.add_parser(
        "merge", help="merge + analyze (writes the merged trace)")
    p_report = sub.add_parser("report", help="analyze only")
    p_serving = sub.add_parser(
        "serving", help="per-request latency-budget report over "
                        "serving request traces "
                        "(docs/serving.md#request-tracing)")
    for p in (p_merge, p_report, p_serving):
        p.add_argument("traces", nargs="+",
                       help="per-process trace files, ONE path template "
                            "containing {rank}, or ONE directory of "
                            "*.trace.json captures")
        p.add_argument("--report", default=None,
                       help="also write the report JSON here")
        p.add_argument("--top", type=int, default=10,
                       help="include the N worst groups/requests in "
                            "the JSON")
    p_merge.add_argument("-o", "--out", default=None,
                         help="merged trace path (default: "
                              "<first input>.merged.json)")
    args = ap.parse_args(argv)

    traces = load_traces(args.traces)
    if args.cmd == "merge":
        out = args.out or expand_inputs(args.traces)[0] + ".merged.json"
        merge_traces(traces, out)
        print(f"merged trace: {out}")
    if args.cmd == "serving":
        report = serving_report(traces, top=args.top)
        fmt = format_serving_report(report)
    else:
        report = analyze(traces, top=args.top)
        fmt = format_report(report)
    if args.report:
        with open(args.report, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
            f.write("\n")
    print(fmt)


if __name__ == "__main__":  # pragma: no cover - thin CLI
    _main()
