"""XLA collective executor — the TPU-native data plane.

This module is the equivalent of the *execution half* of the reference's
``PerformOperation`` (horovod/common/operations.cc:768-1621): where the
reference memcpys tensors into a fusion buffer and calls
``MPI_Allreduce`` / ``ncclAllReduce`` / ``MPI_Allgatherv`` / ``MPI_Bcast``,
we build (and cache) jitted ``shard_map`` programs over the device mesh that
do the same thing with XLA collectives:

  ==========================================  =================================
  Reference (MPI/NCCL)                        TPU-native (XLA over ICI)
  ==========================================  =================================
  MPI_Allreduce / ncclAllReduce               ``jax.lax.psum``
  hierarchical ReduceScatter+MPI+AllGather    ``psum_scatter`` over 'ici' +
  (operations.cc:1284-1436)                   ``psum`` over 'dcn' +
                                              ``all_gather`` over 'ici'
  MPI_Allgatherv (variable first dim)         pad + ``all_gather`` + trim
  (operations.cc:843-1113)                    (static shapes for XLA)
  MPI_Bcast (operations.cc:1592-1612)         masked ``psum`` from root shard
  fusion buffer memcpy in/out                 flatten + concat / split inside
  (operations.cc:1221-1243, 1491-1586)        the same jitted program (XLA
                                              fuses the copies away)
  ==========================================  =================================

Fused programs are compiled once per (shapes, dtypes, op) signature and
cached — the analogue of NCCL communicator/stream caching
(operations.cc:1117-1191) is jit's executable cache.

Numerics: fp16/bf16 sums are accumulated in fp32 inside the program (the
reference instead registers a custom fp16 MPI op with AVX intrinsics,
horovod/common/half.cc:42-90 — on TPU the MXU/VPU natively handles bf16, and
fp32 accumulation is the idiomatic way to keep small-dtype reductions exact).
"""

from __future__ import annotations

import functools
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import time

from . import quantization as _quant
from . import topology as _topo
from .observability import numerics as _numerics
from .observability import registry as _obs


class _ExecMetrics:
    """Registry handles for executor counters — module-global so every
    executor instance feeds the same process-wide totals: the snapshot
    survives ``reset_default_executor()`` (the per-instance ints below
    remain as deprecation aliases for existing steady-state tests)."""

    _instance = None

    def __init__(self):
        r = _obs.registry()
        self.cache_hits = r.counter(
            "hvdtpu_executor_cache_hits_total",
            "Fused-program cache hits").labels()
        self.cache_misses = r.counter(
            "hvdtpu_executor_cache_misses_total",
            "Fused-program cache misses (program builds)").labels()
        self.device_puts = r.counter(
            "hvdtpu_executor_device_puts_total",
            "Host-to-device transfers for collective inputs").labels()
        self.compile_seconds = r.histogram(
            "hvdtpu_executor_compile_seconds",
            "Wall seconds building + jitting one collective program",
            buckets=_obs.LATENCY_BUCKETS).labels()

    @classmethod
    def get(cls) -> "_ExecMetrics":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

# Ops wire-enum kept numerically aligned with the native runtime
# (runtime/src/message.h) and the reference's MPIRequest::RequestType
# (horovod/common/mpi_message.h:52-58).
ALLREDUCE = 0
ALLGATHER = 1
BROADCAST = 2


# Fusion-buffer size quantization for the host-assembled multi-process
# path (min 512 elements; a power of two is always a multiple of the
# reference fusion buffer's 64-byte atomic unit,
# FUSION_BUFFER_ATOMIC_UNIT, operations.h:52-54).
def _fusion_padded_size(n: int) -> int:
    """Padded size with at most 3 significant mantissa bits (1, 1.125,
    ... 1.875 x 2^k; minimum 512). Two forces pull on this quantization:

    - COMPILE STABILITY: linear (fine-quantum) padding let the
      coordinator's timing-dependent group compositions produce a fresh
      padded size almost every step, and padded size keys BOTH the
      fused reduce program and the per-tensor unpack slices — a
      120-tensor MP group measured 11 s/step of per-composition
      recompiles. Few distinct sizes per octave => caches converge.
    - TRAFFIC: the padded size is what the shm plane moves and the
      reduce program chews; pure power-of-two padding (round-5 first
      fix) costs up to 2x on mid-octave buffers and measurably dragged
      the np=8 weak-scaling proxy (0.95 -> 0.80 capacity-adjusted).

    Three mantissa bits bounds overhead at 12.5% with 8 sizes per
    octave; every value stays a multiple of 64 bytes at any dtype width
    (the reference fusion buffer's atomic unit)."""
    if n <= 512:
        return 512
    k = n.bit_length() - 1          # floor(log2(n))
    step = 1 << max(k - 3, 0)       # 1/8 of the leading power of two
    return ((n + step - 1) // step) * step


def _accum_dtype(dtype) -> Optional[np.dtype]:
    """Accumulation dtype for exact small-float / bool reductions."""
    d = np.dtype(dtype)
    if (d == np.dtype(np.float16) or str(d) == "bfloat16"
            or str(d).startswith("float8")):
        return np.dtype(np.float32)
    if d == np.dtype(bool):
        return np.dtype(np.int32)
    return None


# Cached unpack programs keyed by (tensor shape/dtype, buffer
# shape/dtype) with the OFFSET as a traced scalar — the same
# compile-stability trick as _pack_device. An eager dynamic_slice bakes
# the Python-int offset in as a constant, so every timing-dependent MP
# group composition recompiled one slice program per tensor per step
# (measured: 13 s of a 15 s step on a 120-tensor group; the round-5
# autotune sweep's 10x "threshold pocket" was exactly this cost).
# Bounded LRU: shape churn (ragged gathers, changing batch shapes) must
# not grow the program table without limit over a long job — each entry
# pins a compiled XLA executable.
_UNPACK_CACHE: OrderedDict = OrderedDict()
_UNPACK_CACHE_MAX = 512

# The traced offset rides the wire as int32 (cheap, and a traced int64
# would be downcast anyway without jax_enable_x64); a fused buffer big
# enough to overflow it cannot be sliced correctly.
_INT32_MAX = 2 ** 31 - 1


def _unpack(out, arrs, idxs, results, align: int = 1) -> None:
    """Device-side unpack of a fused buffer shared by every
    _run_fused_buffers branch: slice each tensor's span back out,
    reshape, restore its dtype. ``align`` mirrors the pack-side span
    alignment (quantized wire formats align each tensor to whole
    blocks)."""
    if int(out.size) > _INT32_MAX:
        raise ValueError(
            f"fused buffer has {int(out.size)} elements; unpack offsets "
            "are traced as int32 and would overflow. Lower the fusion "
            "threshold (HOROVOD_TPU_FUSION_THRESHOLD) below 2**31 "
            "elements per buffer.")
    off = 0
    for i in idxs:
        a = arrs[i]
        key = (tuple(a.shape), str(a.dtype), out.shape, str(out.dtype))
        prog = _UNPACK_CACHE.get(key)
        if prog is None:
            size, shape, dt = int(a.size), tuple(a.shape), a.dtype
            prog = jax.jit(
                lambda b, o, _s=size, _sh=shape, _dt=dt:
                jax.lax.dynamic_slice(b, (o,), (_s,))
                .reshape(_sh).astype(_dt))
            _UNPACK_CACHE[key] = prog
            while len(_UNPACK_CACHE) > _UNPACK_CACHE_MAX:
                _UNPACK_CACHE.popitem(last=False)
        else:
            _UNPACK_CACHE.move_to_end(key)
        results[i] = prog(out, np.int32(off))
        off += _quant.padded_size(int(a.size), align)


def _fused_reduce(vals, reduce_fn, prescale: float, postscale: float,
                  wire=None, axis: str = "dp", world: int = 1):
    """The fusion-buffer body shared by the single- and multi-process
    allreduce programs: group per-shard values by dtype, flatten + concat
    (the "fusion buffer", operations.cc:1221-1243), reduce each buffer
    with ``reduce_fn``, split back out. One collective per dtype mirrors
    one collective per fused response (operations.cc:2149-2265).

    With ``wire`` set (a quantization.WireSpec) floating groups run the
    dual block-quantized allreduce over ``axis`` instead of ``reduce_fn``:
    each tensor's flat span is padded to whole blocks (block boundaries
    never cross tensors, so the optimizer's per-leaf error-feedback
    residual matches the wire exactly), the buffer is padded to
    ``world * block_size``, and quantization.allreduce_blocks moves wire
    bytes — not fp32 bytes — through the collectives."""
    by_dtype = {}
    for i, v in enumerate(vals):
        by_dtype.setdefault(v.dtype, []).append((i, v))
    results = [None] * len(vals)
    for dt, items in by_dtype.items():
        if (wire is not None and jnp.issubdtype(jnp.dtype(dt), jnp.floating)
                and sum(int(v.size) for _, v in items) > 0):
            _fused_reduce_quantized(items, wire, axis, world, prescale,
                                    postscale, results)
            continue
        acc = _accum_dtype(dt)
        flat = [jnp.ravel(v).astype(acc or dt) for _, v in items]
        if prescale != 1.0:
            flat = [f * prescale for f in flat]
        buf = jnp.concatenate(flat) if len(flat) > 1 else flat[0]
        red = reduce_fn(buf)
        if postscale != 1.0:
            red = red * postscale
        off = 0
        for (i, v), f in zip(items, flat):
            n = f.size
            piece = jax.lax.dynamic_slice(red, (off,), (n,))
            results[i] = piece.reshape(v.shape).astype(dt)
            off += n
    return tuple(results)


def _fused_reduce_quantized(items, wire, axis: str, world: int,
                            prescale: float, postscale: float,
                            results) -> None:
    """Quantized-wire fusion-buffer body: per-tensor block padding +
    concat, dual-quantized allreduce over ``axis``, split back out."""
    bs = wire.block_size
    pieces = []
    spans = []
    off = 0
    for i, v in items:
        f = jnp.ravel(v).astype(jnp.float32)
        if prescale != 1.0:
            f = f * prescale
        n = int(f.size)
        m = _quant.padded_size(max(n, 1), bs)
        if m != n:
            f = jnp.concatenate([f, jnp.zeros((m - n,), jnp.float32)])
        pieces.append(f)
        spans.append((off, n))
        off += m
    extra = (-off) % (world * bs)
    if extra:
        pieces.append(jnp.zeros((extra,), jnp.float32))
    buf = jnp.concatenate(pieces) if len(pieces) > 1 else pieces[0]
    red = _quant.allreduce_blocks(buf, axis, wire, world)
    if postscale != 1.0:
        red = red * postscale
    for (i, v), (o, n) in zip(items, spans):
        piece = jax.lax.dynamic_slice(red, (o,), (n,))
        results[i] = piece.reshape(v.shape).astype(v.dtype)


def _hier_reduce(buf, ici: int):
    """Hierarchical fused-buffer reduction (operations.cc:1284-1436 as
    XLA collectives): psum_scatter over 'ici' -> psum over 'dcn' on the
    scattered shard -> all_gather over 'ici'. The buffer pads so its
    length divides the ici size, as the reference rounds its fusion
    buffer to local_size x FUSION_BUFFER_ATOMIC_UNIT
    (operations.cc:742-764). Shared by the single- and multi-process
    allreduce programs."""
    n = buf.size
    pad = (-n) % ici
    if pad:
        buf = jnp.concatenate([buf, jnp.zeros((pad,), buf.dtype)])
    piece = jax.lax.psum_scatter(buf, "ici", tiled=True)
    piece = jax.lax.psum(piece, "dcn")
    out = jax.lax.all_gather(piece, "ici", tiled=True)
    return out[:n] if pad else out


def _hier_gather(x, tiled: bool):
    """Two-stage hierarchical allgather (operations.cc:929-1032 — node
    shared-memory window + cross-node MPI_Allgatherv — as XLA
    collectives): gather within the slice over 'ici', then across slices
    over 'dcn'. The hierarchical mesh is the flat device list reshaped to
    (dcn, ici) (topology.py:112-117), so the dcn-major/ici-minor result
    ordering is bit-identical to a flat all_gather over 'dp'."""
    g = jax.lax.all_gather(x, "ici", axis=0, tiled=tiled)
    return jax.lax.all_gather(g, "dcn", axis=0, tiled=True)


def _trim_concat(gathered, per_rank_dims):
    """Trim a padded [n, max_dim, ...] gather back to ragged segments and
    concatenate — the MPI_Allgatherv displacement math
    (operations.cc:862-897)."""
    segs = [jax.lax.slice_in_dim(gathered[i], 0, int(d), axis=0)
            for i, d in enumerate(per_rank_dims)]
    return jnp.concatenate(segs, axis=0)


class CollectiveExecutor:
    """Builds and caches jitted collective programs for one mesh."""

    def __init__(self, mesh: Optional[Mesh] = None,
                 hier_mesh: Optional[Mesh] = None,
                 hierarchical_allreduce: bool = False,
                 hierarchical_allgather: bool = False):
        self._mesh = mesh
        self._hier_mesh = hier_mesh
        self.hierarchical_allreduce = hierarchical_allreduce
        self.hierarchical_allgather = hierarchical_allgather
        self._cache = {}
        self._shm_checked = False
        self._shm_transport = None
        self._device_pack_flag: Optional[bool] = None
        # Observability counters: fused-program cache behaviour and input
        # transfers (tests guard that replicated inputs neither recompile
        # nor re-transfer — the hot-loop steady state). DEPRECATION
        # ALIASES: per-instance views of the registry counters
        # (hvdtpu_executor_*_total), which are the canonical series and
        # survive reset_default_executor().
        self.cache_hits = 0
        self.cache_misses = 0
        self.device_put_count = 0
        self._metrics = _ExecMetrics.get()

    @property
    def mesh(self) -> Mesh:
        return self._mesh if self._mesh is not None else _topo.mesh()

    @property
    def hier_mesh(self) -> Mesh:
        if self._hier_mesh is not None:
            return self._hier_mesh
        return _topo.hierarchical_mesh()

    @property
    def world_size(self) -> int:
        return self.mesh.devices.size

    # ---------------------------------------------------------------- helpers

    def _replicated(self, x):
        """Device-put a host / single-device array replicated on the mesh."""
        return self._put_replicated([x], self.mesh)[0]

    def _put_replicated(self, tensors, mesh: Mesh) -> List[jax.Array]:
        """Replicate inputs on ``mesh``, skipping the transfer for arrays
        that already carry the replicated sharding — in a steady-state
        training loop the outputs of step N are the inputs of step N+1
        and re-running device_put on them is a per-tensor dispatch for
        nothing. Everything that DOES transfer rides ONE ``device_put``
        call: on a latency-heavy host↔device link each put is its own
        round-trip dispatch, so a fused group of host gradients (the
        torch/keras shim shape) pays the floor once, not once per
        tensor."""
        sh = NamedSharding(mesh, P())
        out: List = [None] * len(tensors)
        moving = []
        for i, t in enumerate(tensors):
            if isinstance(t, jax.Array):
                try:
                    if t.sharding.is_equivalent_to(sh, t.ndim):
                        out[i] = t
                        continue
                except Exception:
                    pass
            moving.append(i)
        if moving:
            self.device_put_count += len(moving)
            self._metrics.device_puts.inc(len(moving))
            put = jax.device_put([tensors[i] for i in moving], sh)
            for i, a in zip(moving, put):
                out[i] = a
        return out

    def _program(self, key, builder):
        prog = self._cache.get(key)
        if prog is None:
            self.cache_misses += 1
            self._metrics.cache_misses.inc()
            built = builder()
            metrics, cache = self._metrics, self._cache

            def timed_first_call(*args, **kwargs):
                # jax.jit is lazy: trace + lower + compile all happen on
                # the first invocation, so THAT is what the compile
                # histogram must time (building the closure above is
                # microseconds). After the first call the raw program
                # replaces this shim in the cache.
                t0 = time.perf_counter()
                out = built(*args, **kwargs)
                metrics.compile_seconds.observe(time.perf_counter() - t0)
                cache[key] = built
                return out

            cache[key] = timed_first_call
            return timed_first_call
        self.cache_hits += 1
        self._metrics.cache_hits.inc()
        return prog

    # -------------------------------------------------------------- allreduce

    def allreduce_fused(self, tensors: Sequence[jax.Array],
                        prescale: float = 1.0,
                        postscale: float = 1.0,
                        wire=None) -> List[jax.Array]:
        """Sum-allreduce a fused group of replicated tensors.

        Semantics: every virtual rank (device) contributes its copy, so a
        replicated input comes back multiplied by ``size`` — identical to
        every Horovod rank passing the same tensor. ``prescale``/``postscale``
        implement compression/averaging scaling hooks.

        The whole group runs as ONE jitted program: flatten → concat (the
        "fusion buffer", operations.cc:1221-1243) → psum → split.

        ``wire`` (a quantization spec, e.g. "int8x256") switches floating
        tensors to the dual block-quantized allreduce — quantize →
        reduce-scatter in the wire domain → fp32 dequant-accumulate →
        requantize → allgather — inside the same fused program. The
        quantized path always runs on the flat 'dp' mesh: its all_to_all
        reduce-scatter is already the bandwidth-optimal single-phase
        exchange, so the two-level hierarchy buys nothing on top.
        """
        wire = _quant.parse(wire)
        hier = self.hierarchical_allreduce and wire is None
        mesh = self.hier_mesh if hier else self.mesh
        ici = int(mesh.shape["ici"]) if hier else 1
        world = int(mesh.devices.size)
        shapes = tuple(t.shape for t in tensors)
        dtypes = tuple(str(np.dtype(t.dtype) if t.dtype != jnp.bfloat16
                           else "bfloat16") for t in tensors)
        key = ("ar", shapes, dtypes, float(prescale), float(postscale),
               hier, wire.encoded() if wire else None, id(mesh))

        def reduce_buf(buf):
            if not hier:
                return jax.lax.psum(buf, "dp")
            return _hier_reduce(buf, ici)

        def build():
            def fused(*xs):
                def shard_fn(*ys):
                    return _fused_reduce(ys, reduce_buf, prescale,
                                         postscale, wire=wire,
                                         axis="dp", world=world)

                return jax.shard_map(
                    shard_fn, mesh=mesh,
                    in_specs=tuple(P() for _ in xs),
                    out_specs=tuple(P() for _ in xs),
                    check_vma=False)(*xs)

            return jax.jit(fused)

        prog = self._program(key, build)
        outs = prog(*self._put_replicated(tensors, mesh))
        return list(outs)

    # ------------------------------------------------------------- radcast &c

    def broadcast_fused(self, tensors: Sequence[jax.Array],
                        root_rank: int) -> List[jax.Array]:
        """Broadcast each tensor from virtual rank ``root_rank``.

        Implemented as a masked psum from the root shard — with replicated
        eager inputs every rank already holds the root's value, but the
        program still moves the data through the collective so the semantics
        (and the timeline/fusion machinery around it) match
        operations.cc:1592-1612.
        """
        mesh = self.mesh
        shapes = tuple(t.shape for t in tensors)
        dtypes = tuple(str(t.dtype) for t in tensors)
        key = ("bc", shapes, dtypes, int(root_rank), id(mesh))

        def build():
            def fused(*xs):
                def shard_fn(*ys):
                    idx = jax.lax.axis_index("dp")
                    outs = []
                    for y in ys:
                        acc = _accum_dtype(y.dtype)
                        z = y.astype(acc) if acc is not None else y
                        masked = jnp.where(idx == root_rank, z,
                                           jnp.zeros_like(z))
                        out = jax.lax.psum(masked, "dp")
                        outs.append(out.astype(y.dtype))
                    return tuple(outs)
                return jax.shard_map(
                    shard_fn, mesh=mesh,
                    in_specs=tuple(P() for _ in xs),
                    out_specs=tuple(P() for _ in xs),
                    check_vma=False)(*xs)
            return jax.jit(fused)

        prog = self._program(key, build)
        ins = [self._replicated(t) for t in tensors]
        return list(prog(*ins))

    def allgather_fused(self, tensors: Sequence[jax.Array]) -> List[jax.Array]:
        """Allgather along dim 0 from every virtual rank.

        Replicated input ⇒ output is ``size`` stacked copies along dim 0,
        exactly what the reference returns when all ranks pass the same
        tensor (operations.cc:843-1113). Per-rank distinct inputs use
        :meth:`allgather_sharded`. With ``hierarchical_allgather`` set
        (HOROVOD_TPU_HIERARCHICAL_ALLGATHER), the gather runs in two
        stages over ('ici', 'dcn') — the reference's shared-memory-window
        + cross-node path (operations.cc:929-1032).
        """
        hier = self.hierarchical_allgather
        mesh = self.hier_mesh if hier else self.mesh
        shapes = tuple(t.shape for t in tensors)
        dtypes = tuple(str(t.dtype) for t in tensors)
        key = ("ag", shapes, dtypes, hier, id(mesh))

        def build():
            def fused(*xs):
                def shard_fn(*ys):
                    if hier:
                        return tuple(_hier_gather(y, tiled=True)
                                     for y in ys)
                    return tuple(
                        jax.lax.all_gather(y, "dp", axis=0, tiled=True)
                        for y in ys)
                return jax.shard_map(
                    shard_fn, mesh=mesh,
                    in_specs=tuple(P() for _ in xs),
                    out_specs=tuple(P() for _ in xs),
                    check_vma=False)(*xs)
            return jax.jit(fused)

        prog = self._program(key, build)
        return list(prog(*self._put_replicated(tensors, mesh)))

    # ---------------------------------------------- per-rank (sharded) inputs

    def allreduce_sharded(self, x: jax.Array, average: bool = False,
                          prescale: float = 1.0, postscale: float = 1.0):
        """Allreduce where ``x[i]`` is virtual rank i's tensor (leading axis
        sharded over 'dp'). Returns the reduced tensor of shape x.shape[1:]."""
        mesh = self.mesh
        n = self.world_size
        if x.shape[0] != n:
            raise ValueError(
                f"sharded allreduce expects leading axis == size ({n}), "
                f"got shape {x.shape}")
        key = ("ars", x.shape, str(x.dtype), bool(average), float(prescale),
               float(postscale), id(mesh))

        def build():
            def fn(y):
                def shard_fn(z):
                    acc = _accum_dtype(z.dtype)
                    w = z[0].astype(acc) if acc is not None else z[0]
                    if prescale != 1.0:
                        w = w * prescale
                    out = jax.lax.psum(w, "dp")
                    if postscale != 1.0:
                        out = out * postscale
                    if average:
                        out = out / n
                    return out.astype(z.dtype)
                return jax.shard_map(
                    shard_fn, mesh=mesh, in_specs=P("dp"),
                    out_specs=P(), check_vma=False)(y)
            return jax.jit(fn)

        prog = self._program(key, build)
        xin = jax.device_put(x, NamedSharding(mesh, P("dp")))
        return prog(xin)

    def broadcast_sharded(self, x: jax.Array, root_rank: int):
        """Broadcast where ``x[i]`` is rank i's value; returns root's slice."""
        mesh = self.mesh
        n = self.world_size
        key = ("bcs", x.shape, str(x.dtype), int(root_rank), id(mesh))

        def build():
            def fn(y):
                def shard_fn(z):
                    idx = jax.lax.axis_index("dp")
                    v = z[0]
                    acc = _accum_dtype(v.dtype)
                    w = v.astype(acc) if acc is not None else v
                    masked = jnp.where(idx == root_rank, w, jnp.zeros_like(w))
                    return jax.lax.psum(masked, "dp").astype(v.dtype)
                return jax.shard_map(
                    shard_fn, mesh=mesh, in_specs=P("dp"),
                    out_specs=P(), check_vma=False)(y)
            return jax.jit(fn)

        prog = self._program(key, build)
        xin = jax.device_put(x, NamedSharding(mesh, P("dp")))
        return prog(xin)

    def allgather_ragged(self, per_rank: Sequence[jax.Array]) -> jax.Array:
        """Allgather of per-rank tensors with *different first dims* —
        the reference's MPI_Allgatherv path (operations.cc:862-897,
        1037-1094). XLA needs static shapes, so: pad every rank's tensor to
        the max first dim, all_gather, then trim each segment and concat.
        """
        n = self.world_size
        if len(per_rank) != n:
            raise ValueError(f"need one tensor per rank ({n}), got "
                             f"{len(per_rank)}")
        first_dims = [int(t.shape[0]) for t in per_rank]
        rest = per_rank[0].shape[1:]
        dtype = per_rank[0].dtype
        for t in per_rank:
            if t.shape[1:] != rest or t.dtype != dtype:
                raise ValueError(
                    "allgather tensors must agree on dtype and all dims "
                    "except the first (mpi_message validation, "
                    "operations.cc:398-446)")
        m = max(first_dims)
        hier = self.hierarchical_allgather
        mesh = self.hier_mesh if hier else self.mesh
        axes = ("dcn", "ici") if hier else ("dp",)
        key = ("agr", (m,) + tuple(rest), str(dtype), tuple(first_dims),
               hier, id(mesh))

        def build():
            def fn(stacked):
                def shard_fn(z):
                    if hier:
                        return _hier_gather(z[0], tiled=False)
                    return jax.lax.all_gather(z[0], "dp", axis=0, tiled=False)
                return jax.shard_map(
                    shard_fn, mesh=mesh, in_specs=P(axes),
                    out_specs=P(), check_vma=False)(stacked)
            return jax.jit(fn)

        padded = np.zeros((n, m) + tuple(rest), dtype=np.dtype(
            dtype if dtype != jnp.bfloat16 else "bfloat16"))
        for i, t in enumerate(per_rank):
            padded[i, : first_dims[i]] = np.asarray(t)
        prog = self._program(key, build)
        gathered = prog(jax.device_put(
            padded, NamedSharding(mesh, P(axes))))
        return _trim_concat(gathered, first_dims)


    # ------------------------------------------- multi-process (multi-host)
    #
    # In multi-process mode the mesh spans devices this process cannot
    # address, and each process holds *different* eager values, so the
    # replicated-input programs above would lie to XLA about consistency.
    # Instead every tensor becomes a global [size, ...] array whose leading
    # axis is sharded over 'dp' — each device holds its process's value —
    # built from process-local data only. The group sequence executed here
    # is agreed through the TCP coordinator (ops/control_plane.py), so all
    # processes enter the same program in the same order (the SPMD
    # requirement the reference meets with its MPI_Bcast'd response list,
    # operations.cc:2282-2287).

    def _shm(self):
        """Shared-memory data plane for same-host jobs (ops/shm_transport
        — the reference's MPI shared-memory CPU path), or None. Gated on
        the launcher's placement verdict (HOROVOD_TPU_ALL_LOCAL) or the
        explicit HOROVOD_TPU_SHM knob; every process of a job sees the
        same launcher env, so the fleet gates identically."""
        if not self._shm_checked:
            self._shm_checked = True
            from .utils import env as _env
            # Everything below the (launcher-uniform) env gate and the
            # (uniform) process count is per-process fallible, so the
            # fleet-wide agreement must run UNCONDITIONALLY once past
            # those two gates — a rank whose topology probe or segment
            # creation failed must still vote, or the fleet's XLA
            # program order diverges at the handshake itself.
            if _env.shm_data_plane() and jax.process_count() > 1:
                transport = None
                try:
                    # The shm reduction scales the process-sum by ONE
                    # local device count and maps virtual root ranks by
                    # division, both valid only for homogeneous
                    # placements (equal devices per process) — the same
                    # init-time invariant the reference asserts
                    # (operations.cc:1772-1790).
                    homogeneous = (
                        jax.local_device_count() * jax.process_count()
                        == jax.device_count())
                    homogeneous = (homogeneous
                                   and _topo._get().is_homogeneous)
                    if homogeneous:
                        from .ops import shm_transport
                        transport = shm_transport.get(
                            jax.process_index(), jax.process_count())
                except Exception as e:
                    transport = None
                    from .utils.logging import get_logger
                    get_logger("executor").warning(
                        "shared-memory data plane disabled: %s "
                        "(falling back to XLA collectives)", e)
                # Readiness handshake: the launcher env gates all ranks
                # identically, but the plane can still fail on a SUBSET
                # (per-process segment-creation error) — and a split
                # fleet deadlocks: shm-side ranks die on the 120 s spin
                # while XLA-side ranks hang in collective rendezvous.
                # Agree once through the XLA data plane (always
                # available, same program on every process at this point
                # in the agreed group order): the plane is used only if
                # EVERY process reports it up.
                if self._agree_all(transport is not None):
                    self._shm_transport = transport
                elif transport is not None:
                    # Release the locally-created segments — the job
                    # keeps running on XLA and must not pin bucket-sized
                    # /dev/shm allocations for its lifetime.
                    from .ops import shm_transport
                    shm_transport.reset()
                    from .utils.logging import get_logger
                    get_logger("executor").warning(
                        "shared-memory data plane up locally but not on "
                        "every process; whole fleet falls back to XLA "
                        "collectives")
        return self._shm_transport

    def _agree_all(self, ok: bool) -> bool:
        """True iff every process votes ``ok`` — one tiny psum over the
        'dp' mesh (each device votes its process's verdict)."""
        mesh = self.mesh
        arr = self._mp_stacked(
            np.asarray([1.0 if ok else 0.0], np.float32), mesh=mesh)
        prog = self._program(
            ("shm_agree", id(mesh)),
            lambda: jax.jit(jax.shard_map(
                lambda y: jax.lax.psum(y[0], "dp"), mesh=mesh,
                in_specs=P("dp"), out_specs=P(), check_vma=False)))
        return float(np.asarray(prog(arr))[0]) >= self.world_size

    def _mp_stacked(self, x, mesh: Optional[Mesh] = None,
                    axes=("dp",)) -> jax.Array:
        """Global [size, ...] array with the leading axis sharded over
        ``axes``; every local device holds this process's value."""
        mesh = mesh if mesh is not None else self.mesh
        local_devices = [d for d in mesh.devices.flat
                         if d.process_index == jax.process_index()]
        arr = np.asarray(x)
        local = np.broadcast_to(arr, (len(local_devices),) + arr.shape)
        return jax.make_array_from_process_local_data(
            NamedSharding(mesh, P(axes)), local)

    def _device_pack(self) -> bool:
        """Device-resident MP fusion buffers (VERDICT r3 #5): on by
        default on accelerator backends, off on CPU (where host memory
        IS device memory and numpy packing is cheaper than a
        dynamic-update-slice program cascade).
        HOROVOD_TPU_DEVICE_PACK=1/0 forces; resolved once."""
        if self._device_pack_flag is None:
            from .utils import env as _env
            forced = _env.device_pack()
            self._device_pack_flag = (
                forced if forced is not None
                else jax.default_backend() != "cpu")
        return self._device_pack_flag

    def _pack_device(self, ts: Sequence[jax.Array], padded: int,
                     buf_dt, align: int = 1) -> jax.Array:
        """Build the size-quantized fusion buffer on device: one cached
        zero-init program per (padded, dtype) plus one cached
        dynamic-update-slice program per (tensor shape/dtype, padded) —
        offsets are traced scalars, so any group composition reuses the
        same executables (the compile-stability property the host pack
        was built for), while the payload never leaves the device."""
        dt_s = str(np.dtype(buf_dt))
        zero = self._program(
            ("pack_zero", padded, dt_s),
            lambda: jax.jit(lambda: jnp.zeros((padded,), buf_dt)))
        buf = zero()
        dev = next(iter(buf.devices()))
        off = 0
        for t in ts:
            if t.devices() != {dev}:
                # Inputs committed to another local device (or
                # replicated across several) would make the jitted
                # DUS raise 'incompatible devices'; a D2D put onto
                # the buffer's device keeps the cascade legal — the
                # host pack accepted any placement, so must this.
                t = jax.device_put(t, dev)
            key = ("pack_dus", tuple(t.shape), str(t.dtype), padded, dt_s)
            prog = self._program(key, lambda: jax.jit(
                lambda b, v, o: jax.lax.dynamic_update_slice(
                    b, v.ravel().astype(buf_dt), (o,)),
                donate_argnums=(0,)))
            buf = prog(buf, t, np.int32(off))
            off += _quant.padded_size(int(t.size), align)
        return buf

    def _mp_stacked_device(self, buf: jax.Array, mesh: Mesh,
                           axes) -> jax.Array:
        """Device-side _mp_stacked: assemble the global [ndev, n] array
        from per-local-device copies of the packed buffer (D2D, no host
        staging)."""
        local_devices = [d for d in mesh.devices.flat
                         if d.process_index == jax.process_index()]
        row = buf.reshape((1,) + buf.shape)
        shards = [jax.device_put(row, d) for d in local_devices]
        global_shape = (len(list(mesh.devices.flat)),) + buf.shape
        return jax.make_array_from_single_device_arrays(
            global_shape, NamedSharding(mesh, P(axes)), shards)

    def allreduce_fused_mp(self, tensors: Sequence[jax.Array],
                           prescale: float = 1.0,
                           postscale: float = 1.0,
                           wire=None) -> List[jax.Array]:
        """Fused sum-allreduce across processes: every virtual rank
        (device) contributes its process's copy.

        The fusion buffer is assembled HOST-SIDE (numpy concat into a
        size-quantized flat buffer — the reference's memcpy into the
        fusion buffer, operations.cc:1221-1243), so the compiled XLA
        program is keyed only by (padded size, dtype): the coordinator
        may legitimately cut one step's burst into different group
        compositions on different steps (announce chunking is timing-
        dependent), and per-composition programs would mean a fresh XLA
        compile per step instead of a cache hit. The eager MP path
        already stages through the host (_mp_stacked), so the concat
        adds no extra device transfer.

        With hierarchical mode on, the reduction pipelines over the
        ('dcn', 'ici') mesh — psum_scatter on ICI, psum across DCN on
        the scattered shard, all_gather back on ICI — the reference's
        2-level NCCL+MPI allreduce (operations.cc:1284-1436) as XLA
        collectives; otherwise one flat psum over 'dp'.
        """
        wire = _quant.parse(wire)
        # The quantized path runs on the flat mesh (see allreduce_fused)
        # and through XLA — the shm plane reduces host-side in full
        # precision and would silently skip the wire format.
        hier = self.hierarchical_allreduce and wire is None
        mesh = self.hier_mesh if hier else self.mesh
        axes = ("dcn", "ici") if hier else ("dp",)
        ici = int(mesh.shape["ici"]) if hier else 1
        world = int(mesh.devices.size)

        shm = None if (hier or wire is not None) else self._shm()
        if shm is not None:
            # Same-host fast path: reduce the host-staged fusion buffer
            # through /dev/shm instead of a socket ring. Every VIRTUAL
            # rank contributes its process's copy, so the process-sum is
            # scaled by the (homogeneous) local device count.
            local = max(1, self.world_size // jax.process_count())

            def host_op(buf):
                if prescale != 1.0:
                    buf = buf * prescale
                out = shm.allreduce(buf)
                scale = float(local) * postscale
                if scale != 1.0:
                    out = out * scale
                return out

            return self._run_fused_buffers(
                tensors, None, key_fn=None, mesh=mesh, axes=axes,
                host_op=host_op)

        def reduce_buf(buf):
            if not hier:
                return jax.lax.psum(buf, "dp")
            return _hier_reduce(buf, ici)

        def build(padded, buf_dt):
            quantize = (wire is not None and
                        jnp.issubdtype(jnp.dtype(buf_dt), jnp.floating))

            def fused(x):
                def shard_fn(y):
                    v = y[0]  # this device's block of [size, n]
                    if prescale != 1.0:
                        v = v * prescale
                    if quantize:
                        # The packed buffer is already size-quantized
                        # (multiples of 512 ⊇ whole 256-blocks for the
                        # default block size); pad the tail so every
                        # rank's shard is whole blocks. Unlike the SP
                        # path the host pack is back-to-back, so blocks
                        # may span tensor boundaries here — the error
                        # stays bounded by block absmax either way.
                        n = int(v.size)
                        m = _quant.padded_size(
                            max(n, 1), world * wire.block_size)
                        b = (jnp.concatenate(
                                [v.astype(jnp.float32),
                                 jnp.zeros((m - n,), jnp.float32)])
                             if m != n else v.astype(jnp.float32))
                        red = _quant.allreduce_blocks(
                            b, "dp", wire, world)[:n].astype(v.dtype)
                    else:
                        red = reduce_buf(v)
                    if postscale != 1.0:
                        red = red * postscale
                    return red

                return jax.shard_map(
                    shard_fn, mesh=mesh, in_specs=P(axes),
                    out_specs=P(), check_vma=False)(x)

            return jax.jit(fused)

        return self._run_fused_buffers(
            tensors, build,
            key_fn=lambda padded, dt: ("armp_buf", padded, dt,
                                       float(prescale), float(postscale),
                                       hier, wire.encoded() if wire
                                       else None, id(mesh)),
            mesh=mesh, axes=axes,
            align=wire.block_size if wire is not None else 1)

    def _run_fused_buffers(self, tensors, build, key_fn, mesh, axes,
                           host_op=None, align: int = 1):
        """Shared host-assembled fusion-buffer scaffolding for the MP
        collectives (the reference's memcpy into the fusion buffer,
        operations.cc:1221-1243): group by accumulation dtype (one
        collective per dtype, like one fused response per dtype,
        operations.cc:2149-2265), pack into a size-QUANTIZED flat buffer
        so the compiled program is keyed by padded size instead of group
        composition, run ``build(padded, dtype_str)``'s program, and
        unpack device-side (no D2H round trip of the payload).

        ``host_op(buf) -> np.ndarray`` replaces the XLA program with a
        host-side reduction over the packed buffer (the shared-memory
        data plane); pack and unpack stay in numpy — no device round
        trip at all.

        On accelerator backends with jax.Array inputs, the packing also
        happens ON DEVICE (``_pack_device``): the reference's GPU path
        keeps its fusion buffer device-side end to end
        (operations.cc:1221-1243 memcpyAsync into a device buffer, NCCL
        on device memory), and a host-staged pack on a real pod pays a
        full D2H+H2D of the gradient payload every step. The device
        pack builds the quantized buffer with one cached
        dynamic-update-slice program per (tensor shape, buffer size) —
        the offset is a traced scalar, so timing-dependent group
        compositions still hit the program cache (the reason the host
        path packed host-side in the first place)."""
        device_pack = (host_op is None and self._device_pack()
                       and all(isinstance(t, jax.Array) for t in tensors))
        arrs = (list(tensors) if device_pack
                else [np.asarray(t) for t in tensors])
        by_dtype: Dict = {}
        for i, a in enumerate(arrs):
            acc = _accum_dtype(a.dtype)
            by_dtype.setdefault(np.dtype(acc) if acc else a.dtype,
                                []).append(i)
        results: List[Optional[jax.Array]] = [None] * len(arrs)
        for buf_dt, idxs in by_dtype.items():
            # ``align`` > 1 (the quantized wire): each tensor's span is
            # padded to whole blocks so block scales never mix tensors
            # of different magnitudes — same layout the SP fused path
            # and the optimizer's error-feedback residual assume.
            n = int(sum(_quant.padded_size(int(arrs[i].size), align)
                        for i in idxs))
            padded = _fusion_padded_size(n)

            if device_pack:
                buf = self._pack_device([arrs[i] for i in idxs], padded,
                                        buf_dt, align)
                key = key_fn(padded, str(buf_dt))
                prog = self._program(key, lambda: build(padded, buf_dt))
                out = prog(self._mp_stacked_device(buf, mesh, axes))
                _unpack(out, arrs, idxs, results, align)
                continue

            buf = np.zeros((padded,), dtype=buf_dt)
            off = 0
            for i in idxs:
                flat = arrs[i].ravel()
                buf[off:off + flat.size] = flat.astype(buf_dt)
                off += _quant.padded_size(int(flat.size), align)

            # Numerics sentinel (docs/numerics.md): the pack above just
            # touched every byte, so one isfinite pass over the same
            # contiguous LOCAL buffer is the cheapest possible place to
            # catch a NaN *before* the reduction spreads it to every
            # rank. Single flag check when the plane is off.
            if _numerics.enabled():
                _numerics.scan_payload(buf)

            if host_op is not None:
                # The reduced buffer is HOST memory (the shm plane's
                # truth): ONE whole-buffer jnp.asarray, then the cached
                # traced-offset device slices (_UNPACK_CACHE). One
                # transfer beats per-tensor jnp.asarray (each is its
                # own copy+dispatch — measured as a drag on the np=8
                # scaling proxy when tried), and the compile storm the
                # device slicing used to have is fixed by the
                # offset-traced programs + quantized padding.
                _unpack(jnp.asarray(np.asarray(host_op(buf))),
                        arrs, idxs, results, align)
                continue

            key = key_fn(padded, str(buf_dt))
            prog = self._program(
                key, lambda: build(padded, buf_dt))
            out = prog(self._mp_stacked(buf, mesh=mesh, axes=axes))
            _unpack(out, arrs, idxs, results, align)
        return [r for r in results]

    def broadcast_fused_mp(self, tensors: Sequence[jax.Array],
                           root_rank: int) -> List[jax.Array]:
        """Cross-process broadcast from virtual rank ``root_rank``.

        Host-assembled, size-quantized fusion buffer like
        allreduce_fused_mp: a parameter-broadcast burst (hundreds of
        variables at job start) must compile one program keyed by padded
        buffer size, not one per group composition.
        """
        mesh = self.mesh
        shm = self._shm()
        if shm is not None:
            # Root VIRTUAL rank maps to its owning process (homogeneous
            # local device counts, checked at init).
            local = max(1, self.world_size // jax.process_count())
            root_proc = int(root_rank) // local
            return self._run_fused_buffers(
                tensors, None, key_fn=None, mesh=mesh, axes=("dp",),
                host_op=lambda buf: shm.broadcast(buf, root_proc))

        def build(padded, buf_dt):
            def fused(x):
                def shard_fn(y):
                    v = y[0]
                    idx = jax.lax.axis_index("dp")
                    masked = jnp.where(idx == root_rank, v,
                                       jnp.zeros_like(v))
                    return jax.lax.psum(masked, "dp")

                return jax.shard_map(
                    shard_fn, mesh=mesh, in_specs=P("dp"),
                    out_specs=P(), check_vma=False)(x)

            return jax.jit(fused)

        return self._run_fused_buffers(
            tensors, build,
            key_fn=lambda padded, dt: ("bcmp_buf", padded, dt,
                                       int(root_rank), id(mesh)),
            mesh=mesh, axes=("dp",))

    def allgather_fused_mp(self, tensors: Sequence[jax.Array]
                           ) -> List[jax.Array]:
        """Cross-process allgather, equal first dims: one segment per
        virtual rank, concatenated along dim 0. Hierarchical mode gathers
        over 'ici' first (intra-host), then 'dcn' (operations.cc:929-1032)."""
        hier = self.hierarchical_allgather
        mesh = self.hier_mesh if hier else self.mesh
        axes = ("dcn", "ici") if hier else ("dp",)
        shapes = tuple(tuple(t.shape) for t in tensors)
        dtypes = tuple(str(t.dtype) for t in tensors)
        key = ("agmp", shapes, dtypes, hier, id(mesh))

        def build():
            def fused(*xs):
                def shard_fn(*ys):
                    if hier:
                        return tuple(_hier_gather(y[0], tiled=True)
                                     for y in ys)
                    return tuple(
                        jax.lax.all_gather(y[0], "dp", axis=0, tiled=True)
                        for y in ys)
                return jax.shard_map(
                    shard_fn, mesh=mesh,
                    in_specs=tuple(P(axes) for _ in xs),
                    out_specs=tuple(P() for _ in xs),
                    check_vma=False)(*xs)
            return jax.jit(fused)

        prog = self._program(key, build)
        return list(prog(*[self._mp_stacked(t, mesh=mesh, axes=axes)
                           for t in tensors]))

    def allgather_sharded_mp(self, x: jax.Array) -> jax.Array:
        """Allgather of a global array already sharded P('dp') on the
        leading axis: each virtual rank contributes its row block; the
        result is the same rows, replicated. (The single-process path
        routes this through allgather_ragged; a multi-host sharded array
        cannot be pulled to one host, so it is re-gathered in place.)"""
        hier = self.hierarchical_allgather
        mesh = self.hier_mesh if hier else self.mesh
        axes = ("dcn", "ici") if hier else ("dp",)
        key = ("agsmp", tuple(x.shape), str(x.dtype), hier, id(mesh))

        def build():
            def fn(z):
                def shard_fn(y):
                    if hier:
                        return _hier_gather(y, tiled=True)
                    return jax.lax.all_gather(y, "dp", axis=0, tiled=True)
                return jax.shard_map(
                    shard_fn, mesh=mesh, in_specs=P(axes),
                    out_specs=P(), check_vma=False)(z)
            return jax.jit(fn)

        xin = jax.device_put(x, NamedSharding(mesh, P(axes)))
        return self._program(key, build)(xin)

    def allgather_ragged_mp(self, tensor: jax.Array,
                            per_device_dims: Sequence[int]) -> jax.Array:
        """Cross-process MPI_Allgatherv: first dims differ per process.
        ``per_device_dims`` (one per virtual rank, from the coordinator's
        announced shapes) drives pad-to-max + gather + trim."""
        hier = self.hierarchical_allgather
        mesh = self.hier_mesh if hier else self.mesh
        axes = ("dcn", "ici") if hier else ("dp",)
        n = self.world_size
        m = max(int(d) for d in per_device_dims)
        arr = np.asarray(tensor)
        rest = arr.shape[1:]
        key = ("agrmp", (m,) + tuple(rest), str(tensor.dtype),
               tuple(int(d) for d in per_device_dims), hier, id(mesh))

        def build():
            def fn(stacked):
                def shard_fn(z):
                    if hier:
                        return _hier_gather(z[0], tiled=False)
                    return jax.lax.all_gather(z[0], "dp", axis=0,
                                              tiled=False)
                return jax.shard_map(
                    shard_fn, mesh=mesh, in_specs=P(axes),
                    out_specs=P(), check_vma=False)(stacked)
            return jax.jit(fn)

        padded = np.zeros((m,) + rest, dtype=arr.dtype)
        padded[: arr.shape[0]] = arr
        prog = self._program(key, build)
        gathered = prog(self._mp_stacked(padded, mesh=mesh, axes=axes))
        return _trim_concat(gathered, per_device_dims)


_default_executor: Optional[CollectiveExecutor] = None


def default_executor() -> CollectiveExecutor:
    global _default_executor
    if _default_executor is None:
        from .utils import env as _env
        _default_executor = CollectiveExecutor(
            hierarchical_allreduce=_env.hierarchical_allreduce(),
            hierarchical_allgather=_env.hierarchical_allgather())
    return _default_executor


def reset_default_executor() -> None:
    """Drop the default executor (and its jitted-program cache).

    Counter state is NOT lost: the canonical cache-hit/miss/device-put
    series live on the process-global metrics registry
    (hvdtpu_executor_*_total) and are mirrored live, so a snapshot taken
    after a reset still accounts for everything the dropped instance did
    — only the per-instance deprecation aliases restart at zero."""
    global _default_executor
    _default_executor = None
