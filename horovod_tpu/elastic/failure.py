"""Typed worker-failure events and the driver-side failure detector.

The seed's only answer to a lost worker is the coordinated-shutdown path:
stall *warnings* (ops/collective.py `_maybe_check_stalls`, the
coordinator's `check_stalls`) followed by every handle dying with
SHUT_DOWN_ERROR once someone notices. Elastic recovery needs the loss to
surface as a *typed event* that names who failed and why, early enough
to act on — so:

  - :class:`WorkerFailure` is the event type. It subclasses
    ``HorovodInternalError`` so existing ``except`` clauses keep working,
    but carries structured ``rank``/``host``/``kind``/``detail`` fields
    the elastic driver dispatches on (which host to penalize, whether to
    shrink or abort).
  - :class:`FailureConfig` holds the escalation knobs — detection
    timeout, restart budget, backoff schedule, host blacklist window.
  - :class:`FailureDetector` is the driver-side monitor: it polls a
    launched job's workers and raises ``WorkerFailure`` for the first
    dead one (a SIGKILLed worker reports a negative returncode within
    one poll interval).

Worker-side escalation lives where the signals already are: the rank-0
coordinator tracks per-rank control-plane heartbeats and stalled-tensor
ages and ships failure events through the fetch response
(ops/control_plane.py), and the engine escalates its own stall detector
past ``failure_timeout`` (ops/collective.py) — both gated on
``HOROVOD_TPU_FAILURE_TIMEOUT`` so non-elastic jobs keep today's
warn-only behavior.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional

from ..ops.collective import HorovodInternalError


class WorkerFailure(HorovodInternalError):
    """A worker was lost (process death, heartbeat loss, or a stall past
    the failure timeout). ``rank``/``host`` are -1/None when the failing
    party cannot be attributed (e.g. a stall names missing ranks in
    ``detail`` instead)."""

    def __init__(self, rank: int = -1, host: Optional[str] = None,
                 kind: str = "exit", detail: str = ""):
        self.rank = int(rank)
        self.host = host
        self.kind = kind
        self.detail = detail
        self.timestamp = time.time()
        where = f"rank {rank}" + (f" on {host}" if host else "")
        super().__init__(
            f"worker failure ({kind}): {where}"
            + (f" — {detail}" if detail else ""))

    def __reduce__(self):  # exceptions with kw-ish init need explicit pickle
        return (type(self), (self.rank, self.host, self.kind,
                             self.detail))


class SlowRankFailure(WorkerFailure):
    """A rank evicted by the adaptation policy (docs/adaptation.md):
    alive but persistently too late for every fused collective, after
    the graceful-degradation ladder failed to absorb it. The elastic
    driver dispatches on the type — the host gets the SHORT slow-rank
    blacklist window and a readmission probe instead of the crash
    blacklist, because a slow host (thermal throttle, noisy neighbor,
    flaky NIC) often recovers and should grow back in."""

    def __init__(self, rank: int = -1, host: Optional[str] = None,
                 kind: str = "slow_rank", detail: str = ""):
        super().__init__(rank=rank, host=host, kind=kind, detail=detail)


def failure_from_event(event: dict) -> WorkerFailure:
    """Typed WorkerFailure from a coordinator failure event dict
    (``{rank, kind, detail}`` — the fetch side-channel's shape)."""
    kind = str(event.get("kind", "unknown"))
    cls = SlowRankFailure if kind == "slow_rank" else WorkerFailure
    return cls(rank=int(event.get("rank", -1)), kind=kind,
               detail=str(event.get("detail", "")))


@dataclasses.dataclass
class FailureConfig:
    """Escalation knobs for elastic runs.

    ``failure_timeout_s`` is exported to workers as
    ``HOROVOD_TPU_FAILURE_TIMEOUT`` — the window after which the
    coordinator's heartbeat/stall tracking and the engine's stall
    detector escalate to :class:`WorkerFailure` instead of warning.
    ``max_restarts`` bounds relaunch attempts; the backoff fields pace
    them; ``blacklist_s`` is how long a failed host's lost slot stays
    excluded before the driver lets it grow back in.

    Slow-rank eviction (docs/adaptation.md): a
    :class:`SlowRankFailure` penalizes its host for the shorter
    ``slow_blacklist_s`` window. When a penalty expires and
    ``readmit_probe`` is set (a ``host -> bool`` callable, e.g.
    :func:`horovod_tpu.elastic.discovery.host_alive`), the slot only
    returns if the probe passes; a failed probe renews the penalty with
    the window scaled by ``readmit_backoff_factor`` (capped at
    ``max_blacklist_s``) — a still-sick host is re-probed ever more
    lazily instead of flapping in and out of the world."""

    failure_timeout_s: float = 30.0
    max_restarts: int = 3
    backoff_s: float = 1.0
    backoff_factor: float = 2.0
    max_backoff_s: float = 30.0
    blacklist_s: float = 300.0
    poll_interval_s: float = 0.2
    slow_blacklist_s: float = 60.0
    readmit_probe: Optional[Callable[[str], bool]] = None
    readmit_backoff_factor: float = 2.0
    max_blacklist_s: float = 1800.0

    def next_backoff(self, current: float) -> float:
        return min(max(current, self.backoff_s) * self.backoff_factor,
                   self.max_backoff_s)


class FailureDetector:
    """Watches a launched job's workers; raises :class:`WorkerFailure`
    for the first dead one.

    Plugged into the driver's wait loops as the ``failfast`` callback
    (the role ``LaunchedJob.failfast_check`` plays for non-elastic runs,
    runner/launcher.py) — but instead of a generic RuntimeError it
    produces the typed event the elastic loop dispatches on, and it
    distinguishes signal deaths (negative returncode → ``kind='killed'``)
    from nonzero exits (``kind='exit'``)."""

    def __init__(self, job, rank_hosts: List[str],
                 config: Optional[FailureConfig] = None):
        self._job = job
        self._rank_hosts = list(rank_hosts)
        self.config = config or FailureConfig()
        self.failures: List[WorkerFailure] = []

    def check(self) -> None:
        """Poll every worker once; raise on the first failure found.
        All failures observed in this poll are recorded in
        ``self.failures`` first, so the driver can penalize every lost
        host even when several die together."""
        found: List[WorkerFailure] = []
        for rank, w in enumerate(self._job.workers):
            rc = w.poll()
            if rc is not None and rc != 0:
                host = (self._rank_hosts[rank]
                        if rank < len(self._rank_hosts) else None)
                kind = "killed" if rc < 0 else "exit"
                found.append(WorkerFailure(
                    rank=rank, host=host, kind=kind,
                    detail=f"worker exited with code {rc}"))
        if found:
            self.failures.extend(found)
            self._job.terminate()
            raise found[0]

    def wait(self, done, timeout: Optional[float] = None) -> None:
        """Poll ``done()`` until it returns True, checking workers at the
        configured interval; TimeoutError past ``timeout``."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while not done():
            self.check()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"elastic attempt did not finish within {timeout}s")
            time.sleep(self.config.poll_interval_s)
