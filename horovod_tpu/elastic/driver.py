"""Elastic driver loop — ``run_elastic(fn, ...)`` and the command-mode
equivalent behind ``python -m horovod_tpu.runner --elastic``.

The non-elastic ``runner.run`` (runner/api.py) launches one fixed world
and fail-fasts the whole job on any worker death. This driver makes the
world a *variable*: each attempt launches a generation of workers over
the hosts a :class:`~horovod_tpu.elastic.discovery.HostProvider`
currently reports, watches them with a
:class:`~horovod_tpu.elastic.failure.FailureDetector`, and on a
:class:`WorkerFailure`:

  1. penalizes the failed worker's host slot (for ``blacklist_s``
     seconds — the slot returns afterwards, which is how the world grows
     back when a replacement appears or the host recovers),
  2. re-discovers, shrinking the next generation to the surviving slots
     (clamped to ``[min_np, max_np]``; below ``min_np`` the driver keeps
     re-discovering with backoff until the restart budget is spent),
  3. relaunches with a bumped ``HOROVOD_TPU_ELASTIC_GENERATION``; the
     new generation re-negotiates rendezvous from scratch through the
     launcher's env contract — fresh JAX coordinator, fresh rank-0
     control plane — and the worker function resumes from its last
     committed :class:`ElasticState` (``state.restore()``).

Rendezvous re-negotiation is deliberately *relaunch-based*: a multi-host
XLA program is SPMD over a fixed device set, so a changed world needs a
new ``jax.distributed`` world anyway — re-forming it through the
launcher's existing plane reuses every tested code path instead of
inventing a second rendezvous protocol. State survives the relaunch
through ElasticState's commit dir, not process memory.

Worker functions signal failure semantics by *how* they die: a Python
exception is registered with the driver and aborts the job (a bug
re-runs identically — retrying it hides it); process death (SIGKILL,
OOM, host loss) is a :class:`WorkerFailure` and triggers recovery.
"""

from __future__ import annotations

import contextlib
import os
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..observability import registry as _obs
from ..runner.driver_service import DriverService
from ..runner.launcher import expand_slots, launch
from ..runner.secret import SECRET_ENV, encode_key, make_secret_key
from ..runner.timeout import Timeout
from ..utils.logging import get_logger
from .discovery import HostProvider, HostSlots, get_provider
from .failure import (FailureConfig, FailureDetector, SlowRankFailure,
                      WorkerFailure)
from .state import ELASTIC_DIR_ENV

_log = get_logger("elastic.driver")


class _ElasticMetrics:
    """Driver-side health telemetry (docs/metrics.md): world size and
    generation gauges, failure counters by kind, and re-rendezvous
    duration — the numbers the structured ``elastic_health`` log line is
    rendered from (one source of truth, the registry)."""

    def __init__(self):
        r = _obs.registry()
        self.world_size = r.gauge(
            "hvdtpu_elastic_world_size",
            "Ranks in the current elastic generation").labels()
        self.generation = r.gauge(
            "hvdtpu_elastic_generation",
            "Current elastic generation number").labels()
        self._failures = r.counter(
            "hvdtpu_elastic_worker_failures_total",
            "Worker failures the elastic driver recovered from, by kind")
        self.failures_all = self._failures.labels(kind="all")
        self.rendezvous = r.histogram(
            "hvdtpu_elastic_rendezvous_seconds",
            "Discover → launch time of each generation (includes "
            "blacklist backoff after a failure)",
            buckets=_obs.LATENCY_BUCKETS).labels()
        self.last_rendezvous_ms = r.gauge(
            "hvdtpu_elastic_last_rendezvous_ms",
            "Milliseconds the most recent re-rendezvous took").labels()

    def failure(self, kind: str) -> None:
        self.failures_all.inc()
        self._failures.labels(kind=kind or "unknown").inc()

    def health_line(self, event: str, np_now: int, generation: int,
                    hosts_str: str) -> None:
        """One structured, grep-able line per world-size event, rendered
        from the registry (replaces the free-form generation prints)."""
        _log.info(
            "elastic_health event=%s generation=%d world_size=%d "
            "failures_total=%d last_rendezvous_ms=%.0f hosts=%s",
            event, generation, np_now, int(self.failures_all.value),
            self.last_rendezvous_ms.value, hosts_str or "-")

GENERATION_ENV = "HOROVOD_TPU_ELASTIC_GENERATION"
FAILURE_TIMEOUT_ENV = "HOROVOD_TPU_FAILURE_TIMEOUT"


class _SlotPenalties:
    """Per-host lost-slot ledger with expiry and readmission probing.

    A failure on ``host`` removes ONE slot there (not the whole host:
    a single-host job that loses one of two local workers must shrink
    to one, not to zero) until its window passes — at which point the
    slot is offered again and the world can grow back. Each penalty
    carries its own window (crash vs slow-rank blacklists differ).

    With a ``probe`` (host -> bool), an EXPIRED penalty is only lifted
    once the probe passes; a failing probe renews it with the window
    scaled by ``backoff_factor`` (capped at ``max_blacklist_s``), so a
    host that stays sick is re-checked ever more lazily instead of
    flapping in and out of the membership."""

    def __init__(self, blacklist_s: float, probe=None,
                 backoff_factor: float = 2.0,
                 max_blacklist_s: float = 1800.0):
        self._blacklist_s = blacklist_s
        self._probe = probe
        self._backoff_factor = backoff_factor
        self._max_blacklist_s = max_blacklist_s
        # host -> [[expiry, window_s], ...]
        self._until: Dict[str, List[List[float]]] = {}

    def penalize(self, host: Optional[str],
                 window_s: Optional[float] = None) -> None:
        if host is None:
            return
        w = self._blacklist_s if window_s is None else window_s
        self._until.setdefault(host, []).append([time.monotonic() + w, w])

    def apply(self, slots: HostSlots) -> HostSlots:
        now = time.monotonic()
        out: HostSlots = []
        for host, n in slots:
            pend: List[List[float]] = []
            for expiry, window in self._until.get(host, []):
                if expiry > now:
                    pend.append([expiry, window])
                    continue
                if self._probe is not None and not self._probe(host):
                    # Still sick: renew with backoff instead of
                    # readmitting a host that would fail again.
                    window = min(window * self._backoff_factor,
                                 self._max_blacklist_s)
                    _log.warning(
                        "readmission probe failed for %s; re-penalizing "
                        "for %.0fs", host, window)
                    pend.append([now + window, window])
                # probe passed (or no probe): penalty lifted
            self._until[host] = pend
            n = max(0, n - len(pend))
            if n > 0:
                out.append((host, n))
        return out


def _clamp_world(slots: HostSlots, min_np: int, max_np: Optional[int]
                 ) -> Tuple[int, str, List[str]]:
    """Turn discovered slots into (np, hosts_str, rank→host map), capped
    at ``max_np``; raises WorkerFailure-shaped capacity info via np <
    min_np being returned as 0."""
    total = sum(n for _, n in slots)
    if total < min_np:
        return 0, "", []
    np_now = total if max_np is None else min(total, max_np)
    # Trim trailing slots past the cap, keeping hosts contiguous the way
    # the launcher orders ranks.
    trimmed: HostSlots = []
    left = np_now
    for host, n in slots:
        take = min(n, left)
        if take > 0:
            trimmed.append((host, take))
            left -= take
        if left == 0:
            break
    hosts_str = ",".join(f"{h}:{n}" for h, n in trimmed)
    return np_now, hosts_str, expand_slots(trimmed, np_now)


def _elastic_env(extra_env: Optional[Dict[str, str]], generation: int,
                 state_dir: Optional[str], config: FailureConfig
                 ) -> Dict[str, str]:
    env = dict(extra_env or {})
    env[GENERATION_ENV] = str(generation)
    if state_dir:
        env[ELASTIC_DIR_ENV] = state_dir
    env[FAILURE_TIMEOUT_ENV] = str(config.failure_timeout_s)
    return env


def _run_generation(fn_bytes: bytes, np_now: int, hosts_str: str,
                    rank_hosts: List[str], env: Dict[str, str],
                    config: FailureConfig,
                    start_timeout: float, run_timeout: Optional[float],
                    stdout, stderr) -> List[Any]:
    """One generation: launch, rendezvous, collect — api.run's flow with
    the FailureDetector as the failfast authority."""
    key = make_secret_key()
    driver = DriverService(np_now, key, fn_bytes)
    try:
        env = dict(env)
        env[SECRET_ENV] = encode_key(key)
        env["HOROVOD_TPU_DRIVER"] = ",".join(
            f"{h}:{p}" for h, p in driver.addresses())
        job = launch([sys.executable, "-m",
                      "horovod_tpu.runner.task_exec"],
                     np=np_now, hosts=hosts_str, extra_env=env,
                     stdout=stdout, stderr=stderr)
        detector = FailureDetector(job, rank_hosts, config)
        try:
            reg = Timeout(
                start_timeout,
                "Timed out waiting for {timeout} s for all ranks to "
                "register with the elastic driver.")
            driver.wait_for_registration(reg, failfast=detector.check)
            total = Timeout(
                run_timeout if run_timeout is not None else 10 ** 9,
                "Timed out after {timeout} s waiting for results.")
            try:
                results = driver.wait_for_results(total,
                                                  failfast=detector.check)
            except WorkerFailure as wf:
                # Typed failure registered by a worker (e.g. a
                # slow_rank eviction): attribute the host so the loop
                # can penalize the right slot.
                if wf.host is None and 0 <= wf.rank < len(rank_hosts):
                    wf.host = rank_hosts[wf.rank]
                raise
            with contextlib.suppress(TimeoutError):
                job.wait(timeout=60)
            return results
        finally:
            job.terminate()
    finally:
        driver.shutdown()


def _elastic_loop(provider: HostProvider, min_np: int,
                  max_np: Optional[int], config: FailureConfig,
                  attempt: Callable[[int, str, List[str], int], Any]
                  ) -> Any:
    """Shared discover → attempt → penalize/backoff loop for function
    and command mode. ``attempt(np, hosts_str, rank_hosts, generation)``
    returns the job result or raises WorkerFailure."""
    penalties = _SlotPenalties(
        config.blacklist_s, probe=config.readmit_probe,
        backoff_factor=config.readmit_backoff_factor,
        max_blacklist_s=config.max_blacklist_s)
    metrics = _ElasticMetrics()
    generation = 0
    restarts = 0
    backoff = config.backoff_s
    last_failure: Optional[WorkerFailure] = None
    prev_np: Optional[int] = None
    t_event = time.monotonic()   # loop entry / last failure — the
    #                              re-rendezvous clock's epoch
    while True:
        slots = penalties.apply(provider.discover())
        np_now, hosts_str, rank_hosts = _clamp_world(slots, min_np, max_np)
        if np_now == 0:
            if restarts >= config.max_restarts:
                raise WorkerFailure(
                    kind="capacity", detail=(
                        f"{provider.describe()} offers "
                        f"{sum(n for _, n in slots)} usable slots; "
                        f"min_np={min_np} and the restart budget "
                        f"({config.max_restarts}) is spent")
                ) from last_failure
            restarts += 1
            _log.warning(
                "below min_np=%d; re-discovering in %.1fs "
                "(restart %d/%d)", min_np, backoff, restarts,
                config.max_restarts)
            time.sleep(backoff)
            backoff = config.next_backoff(backoff)
            continue
        rendezvous_s = time.monotonic() - t_event
        metrics.rendezvous.observe(rendezvous_s)
        metrics.last_rendezvous_ms.set(rendezvous_s * 1000.0)
        metrics.world_size.set(np_now)
        metrics.generation.set(generation)
        event = ("launch" if prev_np is None
                 else "grow" if np_now > prev_np
                 else "shrink" if np_now < prev_np else "relaunch")
        prev_np = np_now
        metrics.health_line(event, np_now, generation, hosts_str)
        try:
            return attempt(np_now, hosts_str, rank_hosts, generation)
        except WorkerFailure as wf:
            last_failure = wf
            metrics.failure(wf.kind)
            t_event = time.monotonic()
            if restarts >= config.max_restarts:
                raise
            restarts += 1
            # Slow-rank evictions (docs/adaptation.md) get the SHORT
            # blacklist window: the host is alive, just late — the
            # readmission probe decides when it grows back in.
            penalties.penalize(
                wf.host,
                window_s=(config.slow_blacklist_s
                          if isinstance(wf, SlowRankFailure) else None))
            _log.warning(
                "%s; shrinking and relaunching in %.1fs "
                "(restart %d/%d)", wf, backoff, restarts,
                config.max_restarts)
            time.sleep(backoff)
            backoff = config.next_backoff(backoff)
            generation += 1


def run_elastic(fn: Callable, args: tuple = (),
                kwargs: Optional[dict] = None, *,
                min_np: int = 1, max_np: Optional[int] = None,
                hosts: Optional[str] = None,
                discovery: Optional[str] = None,
                hostfile: Optional[str] = None,
                provider: Optional[HostProvider] = None,
                state_dir: Optional[str] = None,
                config: Optional[FailureConfig] = None,
                extra_env: Optional[Dict[str, str]] = None,
                start_timeout: Optional[float] = None,
                run_timeout: Optional[float] = None,
                stdout=None, stderr=None) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` on an elastic world of ``min_np`` to
    ``max_np`` ranks; returns the final generation's results in rank
    order.

    ``fn`` should wrap its training state in an :class:`ElasticState`
    (``state_dir`` is exported to workers as ``HOROVOD_TPU_ELASTIC_DIR``)
    and call ``state.restore()`` before its step loop — on a relaunch
    after worker loss, the surviving/replacement ranks resume from the
    last committed step instead of scratch."""
    kwargs = kwargs or {}
    config = config or FailureConfig()
    if start_timeout is None:
        from ..runner.api import START_TIMEOUT_ENV
        start_timeout = float(os.environ.get(START_TIMEOUT_ENV, 600))
    prov = provider or get_provider(discovery, hosts=hosts,
                                    hostfile=hostfile)

    try:
        import cloudpickle as pickler
    except ImportError:  # pragma: no cover
        import pickle as pickler
    fn_bytes = pickler.dumps((fn, args, kwargs))

    def attempt(np_now, hosts_str, rank_hosts, generation):
        env = _elastic_env(extra_env, generation, state_dir, config)
        return _run_generation(fn_bytes, np_now, hosts_str, rank_hosts,
                               env, config, start_timeout, run_timeout,
                               stdout, stderr)

    return _elastic_loop(prov, min_np, max_np, config, attempt)


def run_elastic_command(command: List[str], *,
                        min_np: int = 1, max_np: Optional[int] = None,
                        provider: Optional[HostProvider] = None,
                        hosts: Optional[str] = None,
                        discovery: Optional[str] = None,
                        hostfile: Optional[str] = None,
                        state_dir: Optional[str] = None,
                        config: Optional[FailureConfig] = None,
                        extra_env: Optional[Dict[str, str]] = None,
                        tag_output: bool = True,
                        run_timeout: Optional[float] = None) -> int:
    """Command-mode elastic launch (the ``--elastic`` CLI path): relaunch
    ``command`` on the surviving world after a worker is lost. Returns
    the final generation's exit code (0 on success)."""
    config = config or FailureConfig()
    prov = provider or get_provider(discovery, hosts=hosts,
                                    hostfile=hostfile)

    def attempt(np_now, hosts_str, rank_hosts, generation):
        env = _elastic_env(extra_env, generation, state_dir, config)
        job = launch(list(command), np=np_now, hosts=hosts_str,
                     extra_env=env, tag_output=tag_output)
        detector = FailureDetector(job, rank_hosts, config)
        deadline = (None if run_timeout is None
                    else time.monotonic() + run_timeout)
        try:
            while True:
                detector.check()   # raises WorkerFailure on a dead worker
                rcs = [w.poll() for w in job.workers]
                if all(rc == 0 for rc in rcs):
                    return 0
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError("elastic job did not finish in time")
                time.sleep(config.poll_interval_s)
        finally:
            job.terminate()

    return _elastic_loop(prov, min_np, max_np, config, attempt)


def generation() -> int:
    """This worker's elastic generation (0 in the first launch and for
    non-elastic jobs) — from the driver-exported env."""
    return int(os.environ.get(GENERATION_ENV, "0") or 0)
