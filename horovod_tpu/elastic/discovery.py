"""Worker discovery — the ``HostProvider`` interface and its backends.

This closes the launcher's cluster-manager gap (SURVEY M7): the
reference's L4 rides a real cluster manager — Spark executors announce
themselves to the driver and mpirun is bridged through them
(horovod/spark/__init__.py:80-196, driver/driver_service.py). The
TPU-native analogue of "run on my cluster" is *discovering* the pod's
worker hosts and feeding them to the existing ssh/RPC launch plane
(:mod:`horovod_tpu.runner.launcher`), which already knows how to spawn
local/ssh ranks once it has a host list.

Three backends:

  - :class:`HostfileProvider` — a static hostfile (mpirun's ``-hostfile``
    syntax: ``host slots=N`` / ``host:N`` / bare host). Re-read on every
    ``discover()`` call so an elastic job can grow when the operator adds
    replacement hosts.
  - :class:`SSHProbeProvider` — candidate hosts filtered by an ssh
    reachability probe (the rsh-agent liveness check); a host that stops
    answering ssh disappears from the discovered set.
  - :class:`TPUPodProvider` — GCE metadata server discovery for Cloud TPU
    pods: every TPU VM exposes the pod's worker endpoints under
    ``computeMetadata/v1/instance/attributes/worker-network-endpoints``.
    The metadata base address comes from ``HOROVOD_TPU_METADATA_ADDR``
    so tests (and non-GCP environments) can point it at a fake server —
    no real GCP dependency anywhere in the code path.

Every provider returns ``[(host, slots), ...]`` — the launcher's
``parse_hosts`` shape — and is intentionally *re-entrant*: elastic
recovery calls ``discover()`` again after every membership change.
"""

from __future__ import annotations

import os
import subprocess
import threading
from typing import Callable, List, Optional, Sequence, Tuple

from ..utils.logging import get_logger

_log = get_logger("elastic.discovery")

HostSlots = List[Tuple[str, int]]

METADATA_ADDR_ENV = "HOROVOD_TPU_METADATA_ADDR"
DEFAULT_METADATA_ADDR = "http://metadata.google.internal"
# The attribute every Cloud TPU VM carries: comma-separated worker
# endpoints, each ``uid:ip:port`` (older stacks ship bare ``ip``).
WORKER_ENDPOINTS_PATH = (
    "/computeMetadata/v1/instance/attributes/worker-network-endpoints")


class HostProvider:
    """Source of the job's current worker host list.

    ``discover()`` returns the *currently available* ``(host, slots)``
    pairs; elastic drivers call it repeatedly, so implementations must
    reflect membership changes (lost hosts vanish, replacements appear)
    rather than caching the first answer forever.
    """

    def discover(self) -> HostSlots:
        raise NotImplementedError

    def describe(self) -> str:
        return type(self).__name__


class StaticProvider(HostProvider):
    """A fixed host list (the non-elastic ``-H host:slots`` path lifted
    into the provider interface so one code path serves both)."""

    def __init__(self, host_slots: Sequence[Tuple[str, int]]):
        self._host_slots = [(h, int(s)) for h, s in host_slots]

    def discover(self) -> HostSlots:
        return list(self._host_slots)

    def describe(self) -> str:
        return "static:" + ",".join(f"{h}:{s}" for h, s in self._host_slots)


class HostfileProvider(HostProvider):
    """mpirun-style hostfile, re-read per discovery.

    Accepted line forms (comments with ``#`` and blank lines ignored)::

        host1 slots=2
        host2:2
        host3
    """

    def __init__(self, path: str):
        self.path = path

    def discover(self) -> HostSlots:
        out: HostSlots = []
        with open(self.path) as f:
            for raw in f:
                line = raw.split("#", 1)[0].strip()
                if not line:
                    continue
                slots = 1
                if "slots=" in line:
                    host, _, rest = line.partition(" ")
                    for tok in rest.split():
                        if tok.startswith("slots="):
                            slots = int(tok.split("=", 1)[1])
                    host = host.strip()
                elif ":" in line:
                    host, s = line.rsplit(":", 1)
                    slots = int(s)
                else:
                    host = line
                out.append((host, slots))
        return out

    def describe(self) -> str:
        return f"hostfile:{self.path}"


def _ssh_alive(host: str, connect_timeout: float = 5.0) -> bool:
    """One reachability probe: can we run ``true`` on the host?
    BatchMode forbids password prompts (a dead host must fail, not
    hang on interactive auth)."""
    try:
        rc = subprocess.run(
            ["ssh", "-o", "BatchMode=yes", "-o", "StrictHostKeyChecking=no",
             "-o", f"ConnectTimeout={int(connect_timeout)}", host, "true"],
            capture_output=True, timeout=connect_timeout + 10).returncode
        return rc == 0
    except Exception:
        return False


def host_alive(host: str, connect_timeout: float = 5.0) -> bool:
    """Readmission probe (docs/adaptation.md): is ``host`` worth
    offering slots on again? Local names are trivially alive (the
    launcher spawns plain subprocesses there); remote ones get the ssh
    reachability probe. Used by the elastic driver's blacklist expiry
    so an evicted-then-recovered host grows back in, while a
    still-dead one has its penalty renewed with backoff."""
    from ..runner.launcher import is_local_host
    return is_local_host(host) or _ssh_alive(host, connect_timeout)


class SSHProbeProvider(HostProvider):
    """Candidate hosts filtered to the ssh-reachable subset.

    Probes run concurrently (a 32-host pod must not pay 32 sequential
    connect timeouts when half the hosts are down). Local names skip the
    probe — the launcher spawns those as plain subprocesses. ``probe``
    is injectable for tests."""

    def __init__(self, host_slots: Sequence[Tuple[str, int]],
                 connect_timeout: float = 5.0,
                 probe: Optional[Callable[[str], bool]] = None):
        self._host_slots = [(h, int(s)) for h, s in host_slots]
        self._timeout = connect_timeout
        self._probe = probe

    def discover(self) -> HostSlots:
        from ..runner.launcher import is_local_host
        probe = self._probe or (
            lambda h: _ssh_alive(h, self._timeout))
        alive: dict = {}
        threads = []

        def check(host):
            alive[host] = is_local_host(host) or probe(host)

        for host, _ in self._host_slots:
            t = threading.Thread(target=check, args=(host,), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(self._timeout + 15.0)
        out = [(h, s) for h, s in self._host_slots if alive.get(h)]
        dead = [h for h, _ in self._host_slots if not alive.get(h)]
        if dead:
            _log.warning("ssh probe dropped unreachable hosts: %s",
                         ", ".join(dead))
        return out

    def describe(self) -> str:
        return "ssh:" + ",".join(f"{h}:{s}" for h, s in self._host_slots)


def _parse_worker_endpoints(text: str) -> List[str]:
    """Parse the ``worker-network-endpoints`` attribute value.

    Observed forms per entry (comma-separated): ``uid:ip:port``,
    ``ip:port``, and bare ``ip``. The host is the field that the rest of
    the entry qualifies — second of three, first of two, only of one."""
    hosts: List[str] = []
    for entry in text.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) >= 3:
            host = parts[1]
        else:
            host = parts[0]
        host = host.strip()
        if host and host not in hosts:
            hosts.append(host)
    return hosts


class TPUPodProvider(HostProvider):
    """Cloud TPU pod discovery through the GCE metadata server.

    Fetches ``worker-network-endpoints`` from the instance metadata (the
    attribute the TPU runtime itself uses to wire pod workers) and
    returns one entry per worker VM. ``slots_per_host`` defaults to 1 —
    JAX on TPU runs one process per host driving all local chips
    (topology.py's single-controller mapping).

    The metadata address is ``HOROVOD_TPU_METADATA_ADDR`` (default the
    real GCE server); tests point it at a local fake HTTP server, so the
    full code path — HTTP fetch, header, parsing — runs with no GCP."""

    def __init__(self, metadata_addr: Optional[str] = None,
                 slots_per_host: Optional[int] = None,
                 timeout: float = 10.0):
        self.metadata_addr = (
            metadata_addr or os.environ.get(METADATA_ADDR_ENV)
            or DEFAULT_METADATA_ADDR).rstrip("/")
        self.slots_per_host = int(
            slots_per_host
            if slots_per_host is not None
            else os.environ.get("HOROVOD_TPU_SLOTS_PER_HOST", 1))
        self.timeout = timeout

    def _fetch(self, path: str) -> str:
        import urllib.request
        req = urllib.request.Request(
            self.metadata_addr + path,
            headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read().decode("utf-8", "replace")

    def discover(self) -> HostSlots:
        try:
            text = self._fetch(WORKER_ENDPOINTS_PATH)
        except Exception as e:
            raise RuntimeError(
                f"TPU-pod discovery failed: could not read "
                f"{WORKER_ENDPOINTS_PATH} from {self.metadata_addr} "
                f"({e}). Outside a TPU VM, set {METADATA_ADDR_ENV} to a "
                "metadata endpoint or use --discovery hostfile/ssh."
            ) from e
        hosts = _parse_worker_endpoints(text)
        if not hosts:
            raise RuntimeError(
                "TPU-pod discovery returned no worker endpoints "
                f"(attribute value: {text!r})")
        return [(h, self.slots_per_host) for h in hosts]

    def describe(self) -> str:
        return f"tpu-pod:{self.metadata_addr}"


def get_provider(discovery: Optional[str] = None,
                 hosts: Optional[str] = None,
                 hostfile: Optional[str] = None,
                 metadata_addr: Optional[str] = None,
                 slots_per_host: Optional[int] = None) -> HostProvider:
    """Resolve a provider from CLI/API arguments.

    ``discovery`` ∈ {None, 'hostfile', 'ssh', 'tpu-pod'}; with None a
    ``hosts`` string (mpirun ``-H`` syntax) becomes a StaticProvider and
    no hosts at all means localhost."""
    from ..runner.launcher import parse_hosts
    if discovery in (None, "", "static"):
        if hostfile:
            return HostfileProvider(hostfile)
        if hosts:
            return StaticProvider(parse_hosts(hosts))
        return StaticProvider([("localhost", os.cpu_count() or 1)])
    if discovery == "hostfile":
        if not hostfile:
            raise ValueError("--discovery hostfile requires --hostfile PATH")
        return HostfileProvider(hostfile)
    if discovery == "ssh":
        if not hosts:
            raise ValueError(
                "--discovery ssh requires -H/--hosts candidates to probe")
        return SSHProbeProvider(parse_hosts(hosts))
    if discovery == "tpu-pod":
        return TPUPodProvider(metadata_addr=metadata_addr,
                              slots_per_host=slots_per_host)
    raise ValueError(
        f"unknown discovery backend {discovery!r} "
        "(expected hostfile, ssh, or tpu-pod)")
