"""Elastic training subsystem — survive worker loss without restarting
from scratch.

Four pieces (see docs/elastic.md for the full contract):

  - discovery (:mod:`.discovery`): the :class:`HostProvider` interface
    with static-hostfile, ssh-probe, and GCE-metadata/TPU-pod backends
    feeding the existing runner launch plane.
  - failure detection (:mod:`.failure`): the typed
    :class:`WorkerFailure` event, escalation knobs
    (:class:`FailureConfig`), and the driver-side
    :class:`FailureDetector`; worker-side escalation lives in the
    engine/coordinator behind ``HOROVOD_TPU_FAILURE_TIMEOUT``.
  - elastic state (:mod:`.state`): :class:`ElasticState` —
    commit/rollback/restore over the checkpoint convention, with
    broadcast-on-rejoin.
  - driver loop (:mod:`.driver`): :func:`run_elastic` /
    :func:`run_elastic_command` — discover, launch a generation, detect
    failure, shrink/grow, re-rendezvous.
"""

from .discovery import (HostfileProvider, HostProvider, SSHProbeProvider,
                        StaticProvider, TPUPodProvider, get_provider,
                        host_alive)
from .failure import (FailureConfig, FailureDetector, SlowRankFailure,
                      WorkerFailure, failure_from_event)
from .state import ElasticState
from .driver import generation, run_elastic, run_elastic_command

__all__ = [
    "HostProvider", "StaticProvider", "HostfileProvider",
    "SSHProbeProvider", "TPUPodProvider", "get_provider", "host_alive",
    "WorkerFailure", "SlowRankFailure", "failure_from_event",
    "FailureConfig", "FailureDetector",
    "ElasticState", "run_elastic", "run_elastic_command", "generation",
]
