"""ElasticState — commit/rollback training state that survives worker loss.

Built on the repo's checkpoint convention (utils/checkpoint.py: rank-0
atomic save, broadcast-on-restore) and extended with the elastic
contract:

  commit(step)   durably record the wrapped trees as of ``step``:
                 rank 0 writes ``<dir>/<step>.pkl`` then atomically
                 repoints ``<dir>/LATEST``; every rank keeps an
                 in-memory host copy for I/O-free rollback; a barrier
                 collective keeps ranks from racing past an unfinished
                 commit.
  rollback()     restore the wrapped trees from the last in-memory
                 commit (same process — e.g. after a caught
                 WorkerFailure, before re-entering the step loop).
  restore()      cold-start path for a (re)joined process: load the
                 LATEST commit from disk on rank 0 and broadcast it so
                 every rank — old survivor or fresh replacement — resumes
                 from identical state. With no commit on disk the
                 *initial* trees are broadcast from rank 0 instead, which
                 is exactly the reference's BroadcastGlobalVariablesHook
                 restart recipe.

Backends (``backend=``):

  ``"pickle"``   the default — the rank-0 single-pickle convention above,
                 unchanged for compatibility.
  ``"sharded"``  rides :class:`horovod_tpu.checkpoint.CheckpointEngine`
                 (docs/checkpoint.md): each process writes only its
                 addressable shards, serialization happens on a
                 background thread (``commit`` returns after the host
                 snapshot; the engine's two-phase manifest/LATEST flip
                 keeps every instant crash-consistent), and ``restore``
                 reads from the shared checkpoint directory on every
                 rank — ZeRO-1 optimizer shards never transit one host,
                 and a changed world size restores through the manifest
                 resharding path instead of a full broadcast. Requires a
                 directory on a filesystem all ranks share.

Both backends apply keep-last-N retention after each commit
(``HOROVOD_TPU_CHECKPOINT_KEEP``, default 10, 0 = unlimited; the commit
``LATEST`` names is never deleted) — previously ``commit`` grew the
state directory without bound.

The state directory defaults to ``HOROVOD_TPU_ELASTIC_DIR`` (exported by
``run_elastic``); without one, commits are memory-only — rollback works,
but a killed-and-relaunched worker starts from the initial trees (fine
for single-process use and tests of the in-memory path).

Trees are arbitrary JAX pytrees addressed by name::

    state = ElasticState(params=params, opt_state=opt_state)
    state.restore()
    for step in range(state.step, total_steps):
        params, opt_state, loss = train_step(...)
        state.params, state.opt_state = params, opt_state
        if (step + 1) % commit_every == 0:
            state.commit(step + 1)

``state.step`` is the step index training should resume from — 0 before
any commit, the committed ``step`` argument after.
"""

from __future__ import annotations

import os
import pickle
import re
from typing import Any, Dict, Optional

import jax

from .. import topology as _topo
from ..utils.checkpoint import (_fsync_dir, restore_checkpoint,
                                save_checkpoint)
from ..utils.env import checkpoint_keep
from ..utils.logging import get_logger

_log = get_logger("elastic.state")

ELASTIC_DIR_ENV = "HOROVOD_TPU_ELASTIC_DIR"
_LATEST = "LATEST"
_BACKENDS = ("pickle", "sharded")
_PKL_RE = re.compile(r"^(\d+)\.pkl$")


class ElasticState:
    """Named pytrees with commit/rollback/restore semantics."""

    def __init__(self, directory: Optional[str] = None,
                 backend: str = "pickle",
                 keep_last: Optional[int] = None, **trees: Any):
        if not trees:
            raise ValueError(
                "ElasticState needs at least one named tree, e.g. "
                "ElasticState(params=params, opt_state=opt_state)")
        if backend not in _BACKENDS:
            raise ValueError(
                f"unknown checkpoint backend {backend!r}; "
                f"choose from {_BACKENDS}")
        # All bookkeeping attrs go through object.__setattr__ so the
        # tree-name __setattr__ below stays unambiguous.
        object.__setattr__(self, "_dir",
                           directory or os.environ.get(ELASTIC_DIR_ENV))
        object.__setattr__(self, "_backend", backend)
        object.__setattr__(self, "_keep",
                           checkpoint_keep() if keep_last is None
                           else int(keep_last))
        object.__setattr__(self, "_engine", None)
        object.__setattr__(self, "_trees", dict(trees))
        object.__setattr__(self, "_committed", None)
        object.__setattr__(self, "step", 0)
        if backend == "sharded" and not self._dir:
            raise ValueError(
                "backend='sharded' needs a checkpoint directory on a "
                "shared filesystem (directory= or "
                f"{ELASTIC_DIR_ENV})")

    # ----------------------------------------------------- tree access

    def __getattr__(self, name: str) -> Any:
        trees = object.__getattribute__(self, "_trees")
        if name in trees:
            return trees[name]
        raise AttributeError(name)

    def __setattr__(self, name: str, value: Any) -> None:
        if name == "step":
            object.__setattr__(self, name, value)
            return
        self._trees[name] = value

    def tree_names(self):
        return tuple(self._trees)

    @property
    def backend(self) -> str:
        return self._backend

    # ------------------------------------------------------- internals

    def _latest_path(self) -> Optional[str]:
        return os.path.join(self._dir, _LATEST) if self._dir else None

    def _snapshot(self) -> Optional[Dict[str, Any]]:
        # Host copies: device buffers may be donated/overwritten by the
        # next jitted step, so the rollback copy must not alias them.
        # With multi-host-sharded trees (sharded backend) the global
        # values are not addressable from one process — rollback then
        # falls back to a disk restore instead of a memory copy.
        try:
            return {"step": int(self.step),
                    "trees": jax.device_get(self._trees)}
        except Exception:
            if self._backend == "sharded":
                return None
            raise

    def _is_rank0(self) -> bool:
        try:
            return _topo._get().process_index == 0
        except Exception:
            return True

    def _adopt(self, payload: Dict[str, Any]) -> None:
        object.__setattr__(self, "_trees", dict(payload["trees"]))
        object.__setattr__(self, "step", int(payload["step"]))

    def _get_engine(self):
        if self._engine is None:
            from ..checkpoint import CheckpointEngine
            object.__setattr__(
                self, "_engine",
                CheckpointEngine(self._dir, keep_last=self._keep))
        return self._engine

    # ------------------------------------------------------- contract

    def commit(self, step: Optional[int] = None,
               block: bool = False) -> "ElasticState":
        """Durably record the current trees as of ``step``.

        Ordering guarantee (both backends): the LATEST pointer is
        repointed only after the commit data is fully on disk, so a
        crash at any instant leaves LATEST naming a complete commit.

        Pickle backend: rank 0 serializes the whole state and the
        closing barrier means no rank runs past a commit its peers have
        not durably finished. Sharded backend: ``commit`` returns after
        the device→host snapshot; serialization, the cross-rank commit
        barrier and the LATEST flip run on the engine's background
        thread (joined by the next commit, ``wait()``, or
        ``block=True``) — until the flip, LATEST keeps naming the
        previous complete commit."""
        if step is not None:
            object.__setattr__(self, "step", int(step))
        snap = self._snapshot()
        object.__setattr__(self, "_committed", snap)
        from ..observability import flight_recorder as _flight
        _flight.recorder().note("checkpoint",
                                ("commit", int(self.step), self._backend))
        if self._backend == "sharded":
            self._get_engine().save(self._trees, self.step,
                                    extra={"elastic": True},
                                    block=block)
            return self
        if self._dir and self._is_rank0():
            os.makedirs(self._dir, exist_ok=True)
            save_checkpoint(snap, self._dir, step=self.step)
            tmp = self._latest_path() + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(self.step))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._latest_path())
            _fsync_dir(self._dir)
            self._gc_pickle()
        self._barrier(f"elastic.commit.{self.step}")
        return self

    def wait(self) -> "ElasticState":
        """Join an in-flight sharded commit (no-op for pickle)."""
        if self._engine is not None:
            self._engine.wait()
        return self

    def rollback(self) -> "ElasticState":
        """Restore trees from the last in-memory commit (no I/O). With
        no commit yet, this is a no-op on the initial trees. (Sharded
        backend with non-addressable trees: falls back to a disk
        restore of the committed step.)"""
        if self._committed is not None:
            self._adopt(self._committed)
        elif self._backend == "sharded" and \
                self._get_engine().latest_step() is not None:
            self.restore()
        return self

    def restore(self, step: Optional[int] = None) -> "ElasticState":
        """(Re)join path: adopt the last durable commit — or the initial
        trees — identically on every rank.

        Rank 0 resolves ``step`` (explicit, else LATEST, else none);
        with the pickle backend the broadcast built into
        ``restore_checkpoint`` ships the payload to all ranks, so a
        replacement worker with no shared filesystem still receives
        full state. The sharded backend instead has EVERY rank read
        from the shared directory through the engine (manifest
        resharding path) — only the resolved step is broadcast."""
        resolved = step
        if resolved is None and self._dir and self._is_rank0():
            if self._backend == "sharded":
                resolved = self._get_engine().latest_step()
            else:
                latest = self._latest_path()
                if latest and os.path.exists(latest):
                    with open(latest) as f:
                        resolved = int(f.read().strip())
        multi = self._process_count() > 1
        if multi:
            # Every rank must agree whether a commit exists before anyone
            # enters the conditional load (a split decision deadlocks the
            # broadcast). Rank 0 announces the resolved step. Explicit
            # names: cross-rank agreement must not depend on the engine's
            # per-process name counters lining up.
            from ..optimizer import broadcast_object
            resolved = broadcast_object(resolved, root_rank=0,
                                        name="elastic.restore.step")
        if resolved is None:
            if multi:
                from ..optimizer import broadcast_object
                self._adopt(broadcast_object(self._snapshot(), root_rank=0,
                                             name="elastic.restore.init"))
            object.__setattr__(self, "_committed", self._snapshot())
            return self
        if self._backend == "sharded":
            trees = self._get_engine().restore(step=int(resolved),
                                               template=self._trees)
            self._adopt({"step": int(resolved), "trees": trees})
        else:
            payload = restore_checkpoint(self._dir, step=int(resolved),
                                         broadcast=multi)
            self._adopt(payload)
        object.__setattr__(self, "_committed", self._snapshot())
        _log.info("restored elastic state at step %d", self.step)
        return self

    # -------------------------------------------------------- plumbing

    def _gc_pickle(self) -> None:
        """Keep-last-N retention for the pickle backend (rank 0, after
        the LATEST flip). Never deletes the step LATEST names."""
        if self._keep <= 0:
            return
        steps = []
        for name in os.listdir(self._dir):
            m = _PKL_RE.match(name)
            if m:
                steps.append(int(m.group(1)))
        steps.sort()
        keep = set(steps[-self._keep:])
        keep.add(int(self.step))
        for s in steps:
            if s not in keep:
                try:
                    os.remove(os.path.join(self._dir, f"{s}.pkl"))
                except OSError:
                    pass

    def _process_count(self) -> int:
        try:
            return _topo._get().process_count
        except Exception:
            return 1

    def _barrier(self, name: str) -> None:
        """Commit barrier: a tiny allreduce every rank must enter. Only
        meaningful (and only run) across processes."""
        if self._process_count() <= 1:
            return
        import jax.numpy as jnp

        from ..ops import collective as _coll
        _coll.allreduce(jnp.zeros((1,), jnp.float32), average=False,
                        name=name)
