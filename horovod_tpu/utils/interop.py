"""DLPack zero-copy framework boundary.

BASELINE.json's north star names DLPack explicitly: the TF/Keras/PyTorch
``DistributedOptimizer`` wrappers hand gradients to the JAX collective
path *via DLPack*. The reference's torch adapter operates directly on the
tensor's own memory with zero host copies
(/root/reference/horovod/torch/adapter_v2.cc:40-105 — ``tensor_util``
resize/copy exists only for the CudaOnCPU staging path); the TPU-native
analogue is buffer aliasing across the DLPack boundary:

  ingress  torch/TF CPU tensor --``__dlpack__``--> ``jax.Array`` on the
           JAX CPU backend (zero-copy alias, bf16/fp16 carried natively);
           the engine's ``device_put`` onto the collective mesh is then
           the ONE unavoidable host->device transfer.
  egress   engine output (replicated over the mesh) -> shard-0
           single-device buffer --``__dlpack__``--> torch/TF tensor.
           Zero-copy on the CPU mesh. On a real TPU the device buffer
           cannot export DLPack directly, so egress transfers it onto
           the always-present JAX *CPU backend* first (``jax.device_put``
           — the one unavoidable D2H copy, batched for a whole handle
           group) and exports THAT buffer: still exactly one host copy,
           but the torch tensor aliases it instead of paying the numpy
           materialize + ``torch.from_numpy`` + ``.copy()`` chain.
           bf16 rides the same path; where the DLPack exchange refuses
           bfloat16, the buffer crosses as a uint16 bitcast and is
           re-viewed as bf16 on the torch side (bitcast transport).

Fallbacks (the numpy path) cover everything DLPack cannot carry exactly:

- 64-bit dtypes in 32-bit JAX mode: ``jax.dlpack.from_dlpack`` silently
  TRUNCATES int64/float64 to 32 bits (measured: 2**40 -> 0), so those
  route through the shims' explicit guards / int32 bit-pair transport.
- non-CPU or non-contiguous source tensors, sharded-but-not-replicated
  outputs, and any ``__dlpack__`` refusal.

Aliasing contract (identical to the reference's): a tensor handed to an
async collective must not be mutated until ``synchronize()`` returns;
egress tensors alias buffers that nothing else references once the
handle is cleared from the handle table.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = [
    "try_torch_to_jax", "try_jax_to_torch", "torch_egress_many",
    "transfer_egress_supported",
    "try_tf_to_jax", "try_jax_to_tf", "jax_to_tf",
    "exportable_buffer", "to_host", "stats", "reset_stats",
]

# Observability: tests assert the fast path actually ran; the A/B bench
# reports the split. The same four series are mirrored into the metrics
# registry (hvdtpu_interop_transfers_total{direction,path}) so the
# steady-state split is visible next to the engine counters; this dict
# stays the reset-able per-process view tests and benches diff.
_stats = {"dlpack_in": 0, "numpy_in": 0, "dlpack_out": 0, "numpy_out": 0}

_reg_children = None


def _bump(key: str, n: int = 1) -> None:
    global _reg_children
    _stats[key] += n
    if _reg_children is None:
        from ..observability import registry as _obs
        fam = _obs.registry().counter(
            "hvdtpu_interop_transfers_total",
            "Framework-boundary tensor crossings by direction and path "
            "(dlpack = zero-copy / single-transfer export, numpy = host "
            "materialize fallback)")
        _reg_children = {
            k: fam.labels(direction=k.split("_")[1], path=k.split("_")[0])
            for k in _stats}
    _reg_children[key].inc(n)


def stats() -> dict:
    return dict(_stats)


def reset_stats() -> None:
    for k in _stats:
        _stats[k] = 0


def _x64_enabled() -> bool:
    import jax
    return bool(jax.config.jax_enable_x64)


def _enabled() -> bool:
    from . import env
    return env.dlpack_boundary()


# ---------------------------------------------------------------------------
# Ingress
# ---------------------------------------------------------------------------

def try_torch_to_jax(tensor) -> Optional["jax.Array"]:
    """torch.Tensor -> jax.Array via DLPack, or None if the numpy fallback
    must be used. Zero-copy for contiguous CPU tensors; bf16 crosses
    natively (no uint16 bit-reinterpret dance)."""
    import torch
    import jax

    t = tensor.detach()
    if not _enabled() or t.device.type != "cpu" or not t.is_contiguous():
        _bump("numpy_in")
        return None
    wide = (torch.int64, torch.float64, torch.complex128,
            getattr(torch, "uint64", torch.int64))
    if t.dtype in wide and not _x64_enabled():
        # DLPack import would truncate (int64/uint64 -> 32-bit,
        # complex128 -> complex64, all measured); the shim's
        # guard/bits transport handles 64-bit explicitly.
        _bump("numpy_in")
        return None
    try:
        a = jax.dlpack.from_dlpack(t)
    except Exception:
        _bump("numpy_in")
        return None
    _bump("dlpack_in")
    return a


def try_tf_to_jax(tensor) -> Optional["jax.Array"]:
    """tf.Tensor (eager) -> jax.Array via DLPack, or None for fallback.
    TF eager tensors expose ``__dlpack__``/``__dlpack_device__``; CPU
    tensors import zero-copy."""
    import jax

    if not _enabled():
        _bump("numpy_in")
        return None
    dt = getattr(tensor, "dtype", None)
    if dt is not None and getattr(dt, "name", "") in (
            "int64", "uint64", "float64", "complex128") \
            and not _x64_enabled():
        _bump("numpy_in")
        return None
    if not hasattr(tensor, "__dlpack__") \
            or not hasattr(tensor, "__dlpack_device__"):
        _bump("numpy_in")
        return None
    try:
        if tensor.__dlpack_device__()[0] != 1:  # kDLCPU
            _bump("numpy_in")
            return None
        a = jax.dlpack.from_dlpack(tensor)
    except Exception:
        _bump("numpy_in")
        return None
    _bump("dlpack_in")
    return a


# ---------------------------------------------------------------------------
# Egress
# ---------------------------------------------------------------------------

def _single_buffer(a):
    """The single-device array behind ``a``: ``a`` itself when unsharded,
    shard 0 when fully replicated (every shard holds the same bytes),
    else None."""
    import jax

    if not isinstance(a, jax.Array):
        return None
    try:
        if len(a.sharding.device_set) > 1:
            if not (a.sharding.is_fully_replicated and a.is_fully_addressable):
                return None
            a = a.addressable_shards[0].data
    except Exception:
        return None
    return a


def exportable_buffer(a):
    """Like :func:`_single_buffer` but only when the buffer can export
    DLPack — jax refuses non-CPU platforms ("__dlpack__ device only
    supported for CPU and GPU", and GPU never occurs here)."""
    buf = _single_buffer(a)
    if buf is None:
        return None
    try:
        if next(iter(buf.sharding.device_set)).platform != "cpu":
            return None
    except Exception:
        return None
    return buf


def try_jax_to_torch(a) -> Optional["torch.Tensor"]:
    """jax.Array -> torch.Tensor aliasing the engine buffer (no copy), or
    None for fallback. The DLPack capsule keeps the XLA buffer alive for
    the torch tensor's lifetime."""
    import torch

    buf = exportable_buffer(a) if _enabled() else None
    if buf is None:
        _bump("numpy_out")
        return None
    try:
        t = torch.from_dlpack(buf)
    except Exception:
        _bump("numpy_out")
        return None
    _bump("dlpack_out")
    return t


_transfer_probe: Optional[bool] = None


def _buffer_platform(buf) -> Optional[str]:
    """Platform string of a single-device buffer, or None when it cannot
    be determined (fallback slot). Separated out so tests can simulate a
    chip-resident buffer on the CPU backend."""
    try:
        return next(iter(buf.sharding.device_set)).platform
    except Exception:
        return None


def _cpu_device():
    import jax
    try:
        return jax.devices("cpu")[0]
    except Exception:
        return None


def transfer_egress_supported() -> bool:
    """Capability probe, resolved once: can a default-backend buffer be
    copied onto the always-present JAX CPU backend and exported through
    DLPack? This is what lets egress stay on the DLPack path on a real
    chip, whose device buffers refuse ``__dlpack__`` directly. Trivially
    true when the default backend IS cpu; False disables the transfer
    leg and egress falls back to numpy (``HOROVOD_TPU_DLPACK=0`` kills
    both)."""
    global _transfer_probe
    if _transfer_probe is None:
        _transfer_probe = _probe_transfer()
    return _transfer_probe


def _probe_transfer() -> bool:
    try:
        import jax
        import jax.numpy as jnp
        import torch

        dev = _cpu_device()
        if dev is None:
            return False
        moved = jax.device_put(jnp.zeros((2,), jnp.float32), dev)
        torch.from_dlpack(moved)
        return True
    except Exception:
        return False


def _export_cpu_buffer_torch(buf) -> Optional["torch.Tensor"]:
    """CPU jax buffer -> torch tensor aliasing it, or None. bf16 exports
    natively where the exchange allows; otherwise it crosses as a uint16
    bitcast re-viewed as bf16 torch-side (bitcast transport — the bits
    buffer is a fresh CPU array the capsule keeps alive)."""
    import torch

    if str(buf.dtype) == "bfloat16":
        try:
            return torch.from_dlpack(buf)
        except Exception:
            pass
        try:
            import jax
            import jax.numpy as jnp
            bits = jax.lax.bitcast_convert_type(buf, jnp.uint16)
            return torch.from_dlpack(bits).view(torch.bfloat16)
        except Exception:
            return None
    try:
        return torch.from_dlpack(buf)
    except Exception:
        return None


def torch_egress_many(arrays) -> list:
    """Batched DLPack egress for a group of engine outputs: one slot per
    input, each ``None`` (numpy fallback needed) or ``(tensor, private)``.

    ``private=False``: the tensor ALIASES an engine-retained buffer (the
    zero-copy CPU-mesh case) — out-of-place callers must clone before
    releasing it to user code. ``private=True``: the tensor aliases a
    buffer created by this call's device→CPU transfer, which nothing
    else references — safe to hand out directly, so the chip path stays
    at exactly one host copy.

    All device→CPU transfers in the group ride ONE ``jax.device_put``
    call (each read through a latency-heavy link is its own round trip —
    the to_host_many lesson applied to the DLPack path). Counts one
    dlpack_out or numpy_out per slot; callers falling back must not
    re-count."""
    n = len(arrays)
    results: list = [None] * n
    if n == 0:
        return results
    if not _enabled():
        _bump("numpy_out", n)
        return results
    import jax

    bufs = [_single_buffer(a) for a in arrays]
    moved = [False] * n
    transfer = []
    for i, buf in enumerate(bufs):
        if buf is None:
            continue
        plat = _buffer_platform(buf)
        if plat is None:
            bufs[i] = None
        elif plat != "cpu":
            transfer.append(i)
    if transfer:
        if transfer_egress_supported():
            try:
                put = jax.device_put([bufs[i] for i in transfer],
                                     _cpu_device())
                for i, m in zip(transfer, put):
                    bufs[i] = m
                    moved[i] = True
            except Exception:
                for i in transfer:
                    bufs[i] = None
        else:
            for i in transfer:
                bufs[i] = None
    for i, buf in enumerate(bufs):
        if buf is None:
            _bump("numpy_out")
            continue
        t = _export_cpu_buffer_torch(buf)
        if t is None:
            _bump("numpy_out")
            continue
        _bump("dlpack_out")
        results[i] = (t, moved[i])
    return results


def try_jax_to_tf(a):
    """Gated zero-copy jax -> tf egress, or None for fallback (the
    HOROVOD_TPU_DLPACK kill switch and the stats counters both apply —
    callers that batch their own fallback readback must come through
    here, not exportable_buffer, or the A/B lever lies)."""
    import tensorflow as tf

    buf = exportable_buffer(a) if _enabled() else None
    if buf is None:
        _bump("numpy_out")
        return None
    try:
        out = tf.experimental.dlpack.from_dlpack(buf.__dlpack__())
    except Exception:
        _bump("numpy_out")
        return None
    _bump("dlpack_out")
    return out


def jax_to_tf(a):
    """jax.Array -> tf.Tensor, zero-copy via DLPack when the buffer is an
    exportable CPU buffer, else one host copy via numpy. Always returns a
    tf.Tensor (this is the py_function host-side return path)."""
    import tensorflow as tf

    out = try_jax_to_tf(a)
    if out is not None:
        return out
    return tf.convert_to_tensor(to_host(a))


def to_host(a) -> np.ndarray:
    """One-copy host materialization: read shard 0 of a replicated array
    (works for TPU buffers too — this is the D2H transfer) rather than
    letting numpy assemble the global view."""
    buf = _single_buffer(a)
    return np.asarray(buf if buf is not None else a)


def to_host_many(arrays) -> list:
    """Batched host materialization: ONE ``jax.device_get`` over the
    whole list instead of a per-array readback. Each read through a
    latency-heavy device link is its own round trip (~70 ms floor on
    the axon tunnel, measured); batching the group is ~2x on a
    ResNet-50-shaped gradient set. Shard-0 extraction as in
    :func:`to_host`."""
    import jax

    gets = []
    for a in arrays:
        buf = _single_buffer(a)
        gets.append(buf if buf is not None else a)
    return [np.asarray(h) for h in jax.device_get(gets)]
