"""DLPack zero-copy framework boundary.

BASELINE.json's north star names DLPack explicitly: the TF/Keras/PyTorch
``DistributedOptimizer`` wrappers hand gradients to the JAX collective
path *via DLPack*. The reference's torch adapter operates directly on the
tensor's own memory with zero host copies
(/root/reference/horovod/torch/adapter_v2.cc:40-105 — ``tensor_util``
resize/copy exists only for the CudaOnCPU staging path); the TPU-native
analogue is buffer aliasing across the DLPack boundary:

  ingress  torch/TF CPU tensor --``__dlpack__``--> ``jax.Array`` on the
           JAX CPU backend (zero-copy alias, bf16/fp16 carried natively);
           the engine's ``device_put`` onto the collective mesh is then
           the ONE unavoidable host->device transfer.
  egress   engine output (replicated over the mesh) -> shard-0
           single-device buffer --``__dlpack__``--> torch/TF tensor.
           Zero-copy on the CPU mesh; on a real TPU the device buffer
           cannot export DLPack, so egress falls back to numpy (one D2H
           copy — also unavoidable) and the shims alias that.

Fallbacks (the numpy path) cover everything DLPack cannot carry exactly:

- 64-bit dtypes in 32-bit JAX mode: ``jax.dlpack.from_dlpack`` silently
  TRUNCATES int64/float64 to 32 bits (measured: 2**40 -> 0), so those
  route through the shims' explicit guards / int32 bit-pair transport.
- non-CPU or non-contiguous source tensors, sharded-but-not-replicated
  outputs, and any ``__dlpack__`` refusal.

Aliasing contract (identical to the reference's): a tensor handed to an
async collective must not be mutated until ``synchronize()`` returns;
egress tensors alias buffers that nothing else references once the
handle is cleared from the handle table.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = [
    "try_torch_to_jax", "try_jax_to_torch",
    "try_tf_to_jax", "try_jax_to_tf", "jax_to_tf",
    "exportable_buffer", "to_host", "stats", "reset_stats",
]

# Observability: tests assert the fast path actually ran; the A/B bench
# reports the split.
_stats = {"dlpack_in": 0, "numpy_in": 0, "dlpack_out": 0, "numpy_out": 0}


def stats() -> dict:
    return dict(_stats)


def reset_stats() -> None:
    for k in _stats:
        _stats[k] = 0


def _x64_enabled() -> bool:
    import jax
    return bool(jax.config.jax_enable_x64)


def _enabled() -> bool:
    from . import env
    return env.dlpack_boundary()


# ---------------------------------------------------------------------------
# Ingress
# ---------------------------------------------------------------------------

def try_torch_to_jax(tensor) -> Optional["jax.Array"]:
    """torch.Tensor -> jax.Array via DLPack, or None if the numpy fallback
    must be used. Zero-copy for contiguous CPU tensors; bf16 crosses
    natively (no uint16 bit-reinterpret dance)."""
    import torch
    import jax

    t = tensor.detach()
    if not _enabled() or t.device.type != "cpu" or not t.is_contiguous():
        _stats["numpy_in"] += 1
        return None
    wide = (torch.int64, torch.float64, torch.complex128,
            getattr(torch, "uint64", torch.int64))
    if t.dtype in wide and not _x64_enabled():
        # DLPack import would truncate (int64/uint64 -> 32-bit,
        # complex128 -> complex64, all measured); the shim's
        # guard/bits transport handles 64-bit explicitly.
        _stats["numpy_in"] += 1
        return None
    try:
        a = jax.dlpack.from_dlpack(t)
    except Exception:
        _stats["numpy_in"] += 1
        return None
    _stats["dlpack_in"] += 1
    return a


def try_tf_to_jax(tensor) -> Optional["jax.Array"]:
    """tf.Tensor (eager) -> jax.Array via DLPack, or None for fallback.
    TF eager tensors expose ``__dlpack__``/``__dlpack_device__``; CPU
    tensors import zero-copy."""
    import jax

    if not _enabled():
        _stats["numpy_in"] += 1
        return None
    dt = getattr(tensor, "dtype", None)
    if dt is not None and getattr(dt, "name", "") in (
            "int64", "uint64", "float64", "complex128") \
            and not _x64_enabled():
        _stats["numpy_in"] += 1
        return None
    if not hasattr(tensor, "__dlpack__") \
            or not hasattr(tensor, "__dlpack_device__"):
        _stats["numpy_in"] += 1
        return None
    try:
        if tensor.__dlpack_device__()[0] != 1:  # kDLCPU
            _stats["numpy_in"] += 1
            return None
        a = jax.dlpack.from_dlpack(tensor)
    except Exception:
        _stats["numpy_in"] += 1
        return None
    _stats["dlpack_in"] += 1
    return a


# ---------------------------------------------------------------------------
# Egress
# ---------------------------------------------------------------------------

def _single_buffer(a):
    """The single-device array behind ``a``: ``a`` itself when unsharded,
    shard 0 when fully replicated (every shard holds the same bytes),
    else None."""
    import jax

    if not isinstance(a, jax.Array):
        return None
    try:
        if len(a.sharding.device_set) > 1:
            if not (a.sharding.is_fully_replicated and a.is_fully_addressable):
                return None
            a = a.addressable_shards[0].data
    except Exception:
        return None
    return a


def exportable_buffer(a):
    """Like :func:`_single_buffer` but only when the buffer can export
    DLPack — jax refuses non-CPU platforms ("__dlpack__ device only
    supported for CPU and GPU", and GPU never occurs here)."""
    buf = _single_buffer(a)
    if buf is None:
        return None
    try:
        if next(iter(buf.sharding.device_set)).platform != "cpu":
            return None
    except Exception:
        return None
    return buf


def try_jax_to_torch(a) -> Optional["torch.Tensor"]:
    """jax.Array -> torch.Tensor aliasing the engine buffer (no copy), or
    None for fallback. The DLPack capsule keeps the XLA buffer alive for
    the torch tensor's lifetime."""
    import torch

    buf = exportable_buffer(a) if _enabled() else None
    if buf is None:
        _stats["numpy_out"] += 1
        return None
    try:
        t = torch.from_dlpack(buf)
    except Exception:
        _stats["numpy_out"] += 1
        return None
    _stats["dlpack_out"] += 1
    return t


def try_jax_to_tf(a):
    """Gated zero-copy jax -> tf egress, or None for fallback (the
    HOROVOD_TPU_DLPACK kill switch and the stats counters both apply —
    callers that batch their own fallback readback must come through
    here, not exportable_buffer, or the A/B lever lies)."""
    import tensorflow as tf

    buf = exportable_buffer(a) if _enabled() else None
    if buf is None:
        _stats["numpy_out"] += 1
        return None
    try:
        out = tf.experimental.dlpack.from_dlpack(buf.__dlpack__())
    except Exception:
        _stats["numpy_out"] += 1
        return None
    _stats["dlpack_out"] += 1
    return out


def jax_to_tf(a):
    """jax.Array -> tf.Tensor, zero-copy via DLPack when the buffer is an
    exportable CPU buffer, else one host copy via numpy. Always returns a
    tf.Tensor (this is the py_function host-side return path)."""
    import tensorflow as tf

    out = try_jax_to_tf(a)
    if out is not None:
        return out
    return tf.convert_to_tensor(to_host(a))


def to_host(a) -> np.ndarray:
    """One-copy host materialization: read shard 0 of a replicated array
    (works for TPU buffers too — this is the D2H transfer) rather than
    letting numpy assemble the global view."""
    buf = _single_buffer(a)
    return np.asarray(buf if buf is not None else a)


def to_host_many(arrays) -> list:
    """Batched host materialization: ONE ``jax.device_get`` over the
    whole list instead of a per-array readback. Each read through a
    latency-heavy device link is its own round trip (~70 ms floor on
    the axon tunnel, measured); batching the group is ~2x on a
    ResNet-50-shaped gradient set. Shard-0 extraction as in
    :func:`to_host`."""
    import jax

    gets = []
    for a in arrays:
        buf = _single_buffer(a)
        gets.append(buf if buf is not None else a)
    return [np.asarray(h) for h in jax.device_get(gets)]
