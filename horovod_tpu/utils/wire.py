"""Exact 64-bit transport for data-movement collectives on numpy payloads.

Without ``jax_enable_x64`` the engine narrows 64-bit values to 32-bit.
For *movement* collectives (broadcast/allgather) no arithmetic happens,
so a 64-bit array can travel as int32 bit pairs and be reinterpreted on
the way out — the same trick the torch shim uses for tensors
(horovod_tpu/torch/mpi_ops.py). Reductions cannot use this (bits are not
additive); those still require x64 mode.
"""

from __future__ import annotations

import numpy as np

_64BIT = (np.dtype(np.int64), np.dtype(np.float64))


def _x64_enabled() -> bool:
    import jax
    return bool(jax.config.jax_enable_x64)


def movement_payload(arr: np.ndarray):
    """Returns ``(wire_array, from_bits)``; 64-bit dtypes become int32 bit
    pairs when JAX is in 32-bit mode."""
    arr = np.ascontiguousarray(arr)
    if arr.dtype in _64BIT and not _x64_enabled():
        flat = arr.reshape(1) if arr.ndim == 0 else arr
        return flat.view(np.int32), True
    return arr, False


def movement_restore(out, orig_dtype, orig_shape, from_bits: bool):
    """Invert :func:`movement_payload` on the collective's result."""
    arr = np.ascontiguousarray(np.asarray(out))
    if from_bits:
        arr = arr.view(orig_dtype)
    return arr.reshape(orig_shape)
