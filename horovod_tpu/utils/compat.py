"""JAX version compatibility shims.

The codebase targets the stable ``jax.shard_map`` API (jax >= 0.5-era:
top-level export, ``check_vma`` kwarg). On older installs the same
machinery lives at ``jax.experimental.shard_map.shard_map`` with the
replication check named ``check_rep``. Rather than scatter try/except
over every call site (the executor alone builds a dozen shard_map
programs), this module installs a forward-compatible ``jax.shard_map``
once, at package import:

  - same call shape as the stable API, including ``check_vma``;
  - delegates to the experimental implementation, translating
    ``check_vma`` -> ``check_rep`` (both gate the output-replication
    check; the rename tracked jax's varying-manual-axes rework).

Likewise ``jax.lax.axis_size`` (stable API) is backfilled from the old
``jax.core.axis_frame`` (which returns the static size of a bound mesh
axis on those versions).

On jax versions that already export these names this module does
nothing.
"""

from __future__ import annotations

import jax
from jax import lax


def _install_shard_map() -> None:
    if hasattr(jax, "shard_map"):
        return
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
    except ImportError:  # pragma: no cover - very old jax, nothing to do
        return

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, **kwargs):
        if check_vma is not None and "check_rep" not in kwargs:
            kwargs["check_rep"] = check_vma
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kwargs)

    jax.shard_map = shard_map


def _install_axis_size() -> None:
    if hasattr(lax, "axis_size"):
        return

    def axis_size(axis_name):
        from jax import core
        size = core.axis_frame(axis_name)
        if not isinstance(size, int):  # newer frame object spelling
            size = size.size
        return size

    lax.axis_size = axis_size


def install() -> None:
    _install_shard_map()
    _install_axis_size()


install()
