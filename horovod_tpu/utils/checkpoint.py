"""Checkpoint convention helpers — rank-0 save, broadcast-on-restore.

The reference ships no checkpoint engine; its *convention* is: save on
rank 0 only and broadcast state on (re)start (SURVEY.md §5.4 —
README usage steps 5-6, torch broadcast_parameters /
broadcast_optimizer_state, the rank-0 `checkpoint_dir` gating in every
example). These helpers make that convention one call each for JAX
pytrees.

Format: a single self-contained pickle of the host-fetched pytree. This
is deliberate — it round-trips any pytree and stays readable regardless
of how many processes exist at save vs. restore time. Orbax is the right
tool for sharded/async multi-host checkpoints, but it runs its own
cross-process barriers, which contradicts this module's rank-0-only
contract (a rank-0-only orbax call in a multi-process job deadlocks);
use orbax directly from all ranks if you want that machinery. Fancier
checkpointing remains delegated to the host framework, exactly as the
reference delegates it (docs/inference.md:1-16).

.. warning::
   Pickle executes code during deserialization. Only restore checkpoints
   you trust: loading a file from an untrusted path is arbitrary code
   execution on every rank (``restore_checkpoint`` broadcasts the loaded
   object, re-pickling it across the control plane). The same applies to
   any pickle-based loader (``torch.load``, joblib, …).
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional

import jax

from .. import topology as _topo


def _file(path: str, step: Optional[int]) -> str:
    if step is not None:
        if path.endswith(".pkl"):
            raise ValueError(
                "pass a directory path with step= (a '.pkl' file path "
                "plus a step would create a directory named like a file)")
        return os.path.join(path, f"{step}.pkl")
    return path if path.endswith(".pkl") else path + ".pkl"


def save_checkpoint(state: Any, path: str,
                    *, step: Optional[int] = None) -> Optional[str]:
    """Write ``state`` (any JAX pytree) to ``path`` from rank 0 only.

    Returns the written file on rank 0, None elsewhere. Other ranks do
    not wait — pair a later restore with the broadcast this module does,
    or allreduce a dummy as a barrier if you need one.
    """
    if _topo._get().process_index != 0:
        return None
    target = _file(path, step)
    parent = os.path.dirname(os.path.abspath(target))
    if parent:
        os.makedirs(parent, exist_ok=True)
    # Atomic AND durable: a crash mid-write (spot/preemptible restarts
    # are the whole point of checkpointing) must never truncate the
    # previous copy — and the rename alone is not enough: without
    # fsyncing the data before the replace (and the directory entry
    # after), power loss can keep the rename while dropping the data
    # blocks, leaving a complete-looking but empty/truncated target.
    tmp = target + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(jax.device_get(state), f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, target)
    _fsync_dir(parent)
    return target


def _fsync_dir(path: str) -> None:
    """Durable directory entry after a rename (best-effort: platforms
    that refuse O_RDONLY directory fds also do not need it). The sharded
    engine's writer (checkpoint/writer.py) applies the same discipline."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def restore_checkpoint(path: str, *, step: Optional[int] = None,
                       broadcast: bool = True) -> Any:
    """Load a checkpoint and (by default) broadcast it from rank 0 so
    every rank resumes from identical state — the reference's
    load-on-rank-0 + BroadcastGlobalVariablesHook restart recipe. Only
    rank 0 needs the file; with ``broadcast=False`` every caller reads
    locally.

    .. warning::
       The file is unpickled: restoring a checkpoint from an untrusted
       source is arbitrary code execution. Only load checkpoints you
       (or your job) wrote."""
    topo = _topo._get()
    state = None
    err: Optional[str] = None
    if topo.process_index == 0 or not broadcast:
        try:
            with open(_file(path, step), "rb") as f:
                state = pickle.load(f)
        except Exception as e:
            if not broadcast or topo.process_count == 1:
                raise
            # The other ranks are (or will be) blocked in the broadcast;
            # ship the failure so the job dies loudly on EVERY rank
            # instead of hanging them on a rank-0-only exception.
            err = f"{type(e).__name__}: {e}"
    if not broadcast or topo.process_count == 1:
        return state
    from ..optimizer import broadcast_object
    # Rank 0 ships the tree structure + leaves; everyone receives.
    payload = broadcast_object({"state": state, "error": err}, root_rank=0)
    if payload["error"] is not None:
        raise RuntimeError(
            f"rank 0 failed to load checkpoint {path!r}: "
            f"{payload['error']}")
    return payload["state"]
