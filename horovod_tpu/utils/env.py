"""Environment-variable config system.

The reference configures its runtime entirely via ``HOROVOD_*`` env vars
read once at background-thread start (horovod/common/operations.cc:1824-1909,
operations.h:57-66). We honor the same names (for drop-in compatibility)
plus ``HOROVOD_TPU_*`` overrides.
"""

from __future__ import annotations

import os
from typing import Optional

# Defaults — operations.cc:1838 (64 MiB) and :1846 (5 ms). The TPU engine
# defaults the cycle to 1 ms: there is no MPI negotiation round-trip to
# amortize within a single-controller process.
DEFAULT_FUSION_THRESHOLD_MB = 64
DEFAULT_CYCLE_TIME_MS = 1.0
DEFAULT_STALL_WARNING_SECS = 60  # STALL_WARNING_TIME, operations.cc:258


def _get(name: str) -> Optional[str]:
    v = os.environ.get("HOROVOD_TPU_" + name)
    if v is None:
        v = os.environ.get("HOROVOD_" + name)
    return v


def fusion_threshold_bytes() -> int:
    v = _get("FUSION_THRESHOLD")
    if v is not None:
        return int(v)
    return DEFAULT_FUSION_THRESHOLD_MB * 1024 * 1024


def torch_bucket_mb() -> float:
    """Gradient-bucket size target for the torch DistributedOptimizer's
    backward-overlap bucketing (docs/torch.md). Default 64 MB — matched
    to the engine's fusion threshold so each bucket fills one fused
    group; 0 disables bucketing (per-tensor hooks, the pre-bucketing
    path). Overridden per-optimizer by ``bucket_cap_mb=``."""
    v = _get("TORCH_BUCKET_MB")
    if v is not None:
        return float(v)
    return float(DEFAULT_FUSION_THRESHOLD_MB)


def torch_grad_view() -> bool:
    """Default for the torch DistributedOptimizer's
    ``gradient_as_bucket_view`` (docs/torch.md): alias each ``p.grad``
    into its bucket's flat wire buffer at wrap time so autograd
    accumulates straight into the fused-collective payload and the
    hook-time pack memcpy (and the post-allreduce scatter-back)
    disappear. Off by default — it changes the identity of ``p.grad``
    tensors, which code that stashes or replaces gradient tensors may
    not expect."""
    return _get("TORCH_GRAD_VIEW") not in (None, "", "0")


def torch_skip_nonfinite() -> bool:
    """Default for the torch DistributedOptimizer's
    ``skip_nonfinite_steps`` (docs/numerics.md#torch): when the bucket
    pack observed nonfinite gradient elements this step, ``step()``
    still synchronizes (collective parity across ranks) but skips the
    inner optimizer update, so one rank's NaN batch does not poison
    the weights. Off by default; needs HOROVOD_TPU_NUMERICS=1 for the
    counts to exist."""
    return _get("TORCH_SKIP_NONFINITE") not in (None, "", "0")


def cycle_time_ms() -> float:
    v = _get("CYCLE_TIME")
    if v is not None:
        return float(v)
    return DEFAULT_CYCLE_TIME_MS


def stall_warning_secs() -> float:
    if _get("STALL_CHECK_DISABLE") not in (None, "", "0"):
        return 0.0
    # HOROVOD_TPU_STALL_WARNING overrides the 60 s default — short
    # windows let the stall→failure escalation (docs/adaptation.md)
    # react in seconds on jobs whose steps are subsecond.
    v = _get("STALL_WARNING")
    if v not in (None, ""):
        return float(v)
    return DEFAULT_STALL_WARNING_SECS


def failure_timeout_secs() -> float:
    """Window after which the stall detector / coordinator heartbeats
    escalate to a typed WorkerFailure (elastic recovery) instead of the
    warn-only behavior. 0 (the default) disables escalation — exactly
    the seed's coordinated-shutdown-only semantics. Exported to workers
    by the elastic driver as HOROVOD_TPU_FAILURE_TIMEOUT."""
    v = _get("FAILURE_TIMEOUT")
    if v in (None, ""):
        return 0.0
    return float(v)


def fault_spec() -> Optional[str]:
    """Declarative per-rank fault-injection spec (docs/adaptation.md):
    ``rank=2:delay=80ms:from_step=50; rank=1:crash_at=30``. None/empty
    disables injection entirely — the engine then carries a single
    ``is None`` check on the enqueue path and nothing else."""
    v = _get("FAULT_SPEC")
    return v or None


def adaptation_enabled() -> bool:
    """Rank-0 closed-loop adaptation policy (docs/adaptation.md):
    HOROVOD_TPU_ADAPTATION=1 arms the coordinator-side control loop that
    escalates graceful-degradation tiers on sustained straggler
    lateness. Default off — observability stays passive."""
    return _get("ADAPTATION") in ("1",)


def adapt_threshold_s() -> float:
    """Straggler lateness (decay-weighted mean seconds) above which the
    adaptation policy starts its sustain clock."""
    v = _get("ADAPT_THRESHOLD")
    return float(v) if v not in (None, "") else 0.1


def adapt_sustain_s() -> float:
    """Seconds the lateness must stay above threshold before EACH
    escalation step (hysteresis against transient spikes)."""
    v = _get("ADAPT_SUSTAIN")
    return float(v) if v not in (None, "") else 5.0


def adapt_cooldown_s() -> float:
    """Seconds the lateness must stay below threshold *
    deescalate-ratio before each de-escalation step."""
    v = _get("ADAPT_COOLDOWN")
    return float(v) if v not in (None, "") else 30.0


def adapt_interval_s() -> float:
    """Policy evaluation cadence (piggybacked on coordinator fetches)."""
    v = _get("ADAPT_INTERVAL")
    return float(v) if v not in (None, "") else 1.0


def adapt_tiers() -> Optional[str]:
    """Comma-separated degradation ladder override
    (HOROVOD_TPU_ADAPT_TIERS, e.g. "shrink,int8x256,evict"); None keeps
    the default shrink → bf16 → int8x256 → fp8x256 → evict ladder."""
    return _get("ADAPT_TIERS")


def coord_retries() -> int:
    """Post-rendezvous coordinator RPC retry budget (each retried with
    exponential backoff + jitter before CoordinatorUnreachableError)."""
    v = _get("COORD_RETRIES")
    return int(v) if v not in (None, "") else 6


def coord_backoff_s() -> float:
    """Base backoff between coordinator RPC retries (doubles per
    attempt, capped at ~2 s, ±50% deterministic per-rank jitter)."""
    v = _get("COORD_BACKOFF")
    return float(v) if v not in (None, "") else 0.1


def checkpoint_keep() -> int:
    """Keep-last-N retention for committed checkpoints (both the elastic
    pickle backend and the sharded engine, docs/checkpoint.md). 0 means
    unlimited — the seed's keep-everything behavior. Default 10: spot
    jobs commit often and nothing ever deleted old steps before."""
    v = _get("CHECKPOINT_KEEP")
    if v in (None, ""):
        return 10
    return int(v)


def blackbox_dir() -> Optional[str]:
    """Directory for flight-recorder crash dumps (docs/postmortem.md):
    on a crash, SIGTERM, stall escalation or eviction, each rank writes
    ``blackbox-rank{rank}.jsonl`` here. None/empty disables dumping —
    the in-memory ring buffer still records (its cost is one tuple
    append), but nothing ever reaches disk."""
    v = _get("BLACKBOX")
    return v or None


def blackbox_window_secs() -> float:
    """How many seconds of history a blackbox dump carries (the ring
    buffer is additionally bounded by ``blackbox_capacity`` events)."""
    v = _get("BLACKBOX_WINDOW")
    if v in (None, ""):
        return 120.0
    return float(v)


def blackbox_interval_secs() -> float:
    """Cadence of the periodic in-flight blackbox dump. The JAX
    coordination service hard-kills surviving clients (LOG(FATAL))
    within ~100 ms of any peer's death — no Python exit hook can run —
    so the recorder continuously persists its ring like a real flight
    recorder; the final-gasp dump overwrites with the precise reason
    when the process does get a last word. 0 disables the periodic
    writer (death-path dumps only)."""
    v = _get("BLACKBOX_INTERVAL")
    if v in (None, ""):
        return 5.0
    return float(v)


def blackbox_capacity() -> int:
    """Ring-buffer size (events) of the always-on flight recorder."""
    v = _get("BLACKBOX_EVENTS")
    if v in (None, ""):
        return 4096
    return int(v)


def history_dir() -> Optional[str]:
    """Directory for the telemetry history ring (docs/health.md): when
    set, a background sampler appends windowed registry deltas to
    ``history-rank{rank}.jsonl`` here every history_interval_secs and
    the online health detectors run over the live window. None/empty
    disables the whole plane — no thread, no file, no detectors."""
    v = _get("HISTORY")
    return v or None


def history_interval_secs() -> float:
    """Cadence of the telemetry history sampler (and therefore the
    detector window granularity). Default 5 s — fine enough to catch a
    minutes-scale regression, coarse enough that a day of history fits
    in a few rotated segments."""
    v = _get("HISTORY_INTERVAL")
    if v in (None, ""):
        return 5.0
    return float(v)


def history_max_bytes() -> int:
    """Per-segment size cap of a history file; past it the writer
    rotates (``.1`` .. ``.N`` suffixes, oldest deleted). Default 4 MiB."""
    v = _get("HISTORY_MAX_BYTES")
    if v in (None, ""):
        return 4 * 1024 * 1024
    return int(v)


def history_segments() -> int:
    """Rotated history segments kept per rank (on top of the live
    file). Total on-disk bound = (segments + 1) * max_bytes per rank."""
    v = _get("HISTORY_SEGMENTS")
    if v in (None, ""):
        return 4
    return int(v)


def health_detectors_enabled() -> bool:
    """Online anomaly detectors over the live history window
    (docs/health.md). Default on whenever the history sampler runs;
    HOROVOD_TPU_HEALTH=0 keeps the history file but fires no alerts."""
    return _get("HEALTH") not in ("0",)


def numerics_enabled() -> bool:
    """Numerics observability plane (docs/numerics.md):
    HOROVOD_TPU_NUMERICS=1 arms the nonfinite sentinels, gradient/loss
    telemetry and fingerprint probes at hvd.init(). Default off — every
    hook site then carries a single flag check."""
    return _get("NUMERICS") in ("1",)


def numerics_fp_interval() -> int:
    """Cross-rank param-fingerprint cadence in training steps
    (docs/numerics.md#fingerprints). 0 disables the probe while keeping
    the rest of the numerics plane armed."""
    v = _get("NUMERICS_FP_INTERVAL")
    if v in (None, ""):
        return 50
    return int(v)


def alert_url() -> Optional[str]:
    """Optional webhook for health alerts (docs/health.md#webhook):
    rank 0 / the fleet supervisor POSTs each typed alert as JSON here,
    fire-and-forget with a short timeout — an unreachable receiver can
    never stall the sampler."""
    v = _get("ALERT_URL")
    return v or None


def adapt_alert_hold_s() -> float:
    """How long a health alert (step-time regression / HBM leak) keeps
    exerting ladder pressure on the adaptation policy after it fired —
    the alert-triggered escalation input, hysteresis-guarded exactly
    like measured lateness (docs/health.md#adaptation)."""
    v = _get("ADAPT_ALERT_HOLD")
    return float(v) if v not in (None, "") else 30.0


def peak_flops() -> Optional[float]:
    """Peak FLOP/s of this process's devices for the MFU gauge
    (HOROVOD_TPU_PEAK_FLOPS, total across local devices). None =
    autodetect from the device kind (TPU generations only); MFU is not
    exported when neither source yields a number."""
    v = _get("PEAK_FLOPS")
    if v in (None, ""):
        return None
    return float(v)


def timeline_path() -> Optional[str]:
    return _get("TIMELINE")


def resolved_timeline_path(rank: int) -> Optional[str]:
    """Timeline file this process should write, or None.

    A ``{rank}`` placeholder in HOROVOD_TPU_TIMELINE expands to the
    process index and EVERY rank writes its own trace (the cross-rank
    capture mode, docs/tracing.md — mirroring the
    HOROVOD_TPU_METRICS_FILE convention). Without a placeholder only
    process 0 writes, the reference's single-viewpoint behavior
    (operations.cc:1824-1829): a second writer on one path would
    truncate rank 0's file."""
    path = timeline_path()
    if not path:
        return None
    if "{rank}" in path:
        return path.replace("{rank}", str(rank))
    return path if rank == 0 else None


def trace_clock_probes() -> int:
    """Clock-alignment handshake pings per rank (NTP-style, min-RTT
    sample wins) recorded in each per-rank trace's clock metadata;
    0 disables the handshake (offset recorded as unsynced)."""
    v = _get("TRACE_CLOCK_PROBES")
    if v in (None, ""):
        return 8
    return int(v)


def exemplar_ttl_secs() -> float:
    """How long a histogram exemplar (the trace id of the worst recent
    observation, docs/metrics.md#exemplars) stays champion before ANY
    newer exemplar-carrying observation may replace it regardless of
    value — "worst recent", not "worst ever". Default 60 s."""
    v = _get("EXEMPLAR_TTL")
    if v in (None, ""):
        return 60.0
    return float(v)


def metrics_enabled() -> bool:
    """Metrics registry recording (docs/metrics.md). Default ON — a
    guarded counter add is nanoseconds (the BENCH_METRICS overhead test
    holds it under 3% of the fused-allreduce hot loop);
    HOROVOD_TPU_METRICS=0 turns every mutator into a single flag
    check."""
    return _get("METRICS") not in ("0", "")


def metrics_file() -> Optional[str]:
    """Path for periodic JSON metric snapshots (atomic rewrite every
    metrics_interval_secs). A ``{rank}`` placeholder expands to the
    process index; without one only process 0 writes."""
    return _get("METRICS_FILE")


def metrics_port(rank: int = 0) -> Optional[int]:
    """Prometheus/JSON HTTP endpoint port for ``rank`` (0 = ephemeral);
    None disables the endpoint.

    Three forms (docs/metrics.md):
      - ``9091``        — plain port, served by process 0 only.
      - ``909{rank}``   — ``{rank}`` placeholder, every rank serves its
                          substituted port.
      - ``9091+rank``   — base + process index, every rank serves
                          ``base + rank``.
    The per-rank forms make every process scrapeable in multi-process
    mode instead of aggregates-through-rank-0 only."""
    v = _get("METRICS_PORT")
    if v in (None, ""):
        return None
    v = v.strip()
    if "{rank}" in v:
        return int(v.replace("{rank}", str(rank)))
    if v.endswith("+rank"):
        return int(v[: -len("+rank")]) + rank
    return int(v)


def metrics_port_per_rank() -> bool:
    """True when HOROVOD_TPU_METRICS_PORT uses a per-rank form
    (``{rank}`` placeholder or ``base+rank``), i.e. every process — not
    just 0 — should bind its endpoint."""
    v = _get("METRICS_PORT")
    if v in (None, ""):
        return False
    v = v.strip()
    return "{rank}" in v or v.endswith("+rank")


def metrics_interval_secs() -> float:
    v = _get("METRICS_INTERVAL")
    if v in (None, ""):
        return 15.0
    return float(v)


def serving_port() -> int:
    """HTTP port of the serving front end (``python -m
    horovod_tpu.serving``); 0 binds an ephemeral port. Default 8400 —
    distinct from the metrics endpoint, which stays on
    HOROVOD_TPU_METRICS_PORT (the serving tier never binds a second
    metrics port; docs/serving.md)."""
    v = _get("SERVING_PORT")
    if v in (None, ""):
        return 8400
    return int(v)


def serving_queue() -> int:
    """Bounded admission-queue depth of the serving engine (requests
    past it are rejected with HTTP 429). Default 32."""
    v = _get("SERVING_QUEUE")
    if v in (None, ""):
        return 32
    return int(v)


def serving_tick_budget_ms() -> Optional[float]:
    """Target decode-tick gap for chunked prefill (docs/serving.md):
    when set, the engine's chunk budget policy shrinks prefill-chunk
    size (down to ``min_prefill_bucket``) until the measured per-chunk
    prefill time fits under this many milliseconds, bounding how long
    any live decode slot waits behind an interleaved chunk. None (the
    default) keeps the configured ``prefill_chunk`` cap as-is."""
    v = _get("SERVING_TICK_BUDGET_MS")
    if v in (None, ""):
        return None
    return float(v)


def reqtrace_dir() -> Optional[str]:
    """Directory for per-process serving request traces
    (docs/serving.md#request-tracing): when set, the fleet router
    writes ``reqtrace-router.trace.json`` and every replica writes
    ``reqtrace-replica{id}-gen{g}.trace.json`` there (one catapult file
    per process, the PR 5 tuple-enqueue writer), merged and analyzed by
    ``python -m horovod_tpu.tools.trace``. None/empty disables request
    tracing entirely — the serving hot path then carries one ``is
    None`` check per decode step."""
    v = _get("REQTRACE")
    return v or None


def replica_id() -> Optional[int]:
    """This process's serving-fleet replica id, exported by the fleet
    supervisor (docs/serving.md#fleet): blackbox dumps are named
    ``blackbox-rank{replica}.jsonl`` and fault-spec ``rank=`` clauses
    target it. None outside a fleet."""
    v = _get("REPLICA_ID")
    if v in (None, ""):
        return None
    return int(v)


def fleet_probe_interval_secs() -> float:
    """Cadence of the fleet supervisor's replica health probes and the
    router's queue-gauge scrapes (docs/serving.md#fleet)."""
    v = _get("FLEET_PROBE_INTERVAL")
    if v in (None, ""):
        return 0.25
    return float(v)


def fleet_probe_failures() -> int:
    """Consecutive failed health probes before the supervisor declares
    a replica dead and restarts it (crash-via-process-exit is detected
    immediately; this catches the hung-but-alive case)."""
    v = _get("FLEET_PROBE_FAILURES")
    if v in (None, ""):
        return 4
    return int(v)


def slo_ttft_ms() -> Optional[float]:
    """Fleet-default time-to-first-token SLO target in milliseconds
    (docs/serving.md#slo). Used when a request carries no explicit
    ``slo`` field and its tenant has no entry in the SLO config file.
    None (the default) attaches no TTFT target."""
    v = _get("SLO_TTFT_MS")
    if v in (None, ""):
        return None
    return float(v)


def slo_tpot_ms() -> Optional[float]:
    """Fleet-default time-per-output-token SLO target in milliseconds
    (docs/serving.md#slo), same resolution order as
    :func:`slo_ttft_ms`. None attaches no TPOT target."""
    v = _get("SLO_TPOT_MS")
    if v in (None, ""):
        return None
    return float(v)


def slo_config() -> Optional[str]:
    """Path to the fleet SLO config file (docs/serving.md#slo): JSON
    ``{"tenants": {name: {"ttft_ms", "tpot_ms"}}, "default": {...}}``
    giving per-tenant default targets. None/empty means no per-tenant
    defaults — only the env-level targets apply."""
    v = _get("SLO_CONFIG")
    return v or None


def serving_reserved_slots() -> int:
    """Decode-batch slots reserved for the top priority class
    (docs/serving.md#qos): bulk/default admissions stop once occupancy
    would leave fewer than this many slots for ``interactive`` work.
    Default 0 — no reservation."""
    v = _get("SERVING_RESERVED_SLOTS")
    if v in (None, ""):
        return 0
    return max(0, int(v))


def qos_scale_high() -> float:
    """Autoscaler scale-up threshold: fleet queued+active work per
    decode slot above which sustained load triggers a scale-up
    (docs/serving.md#qos). Default 1.5."""
    v = _get("QOS_SCALE_HIGH")
    if v in (None, ""):
        return 1.5
    return float(v)


def qos_scale_low() -> float:
    """Autoscaler scale-down threshold: load per slot below which the
    fleet shrinks after the cooldown (docs/serving.md#qos).
    Default 0.25."""
    v = _get("QOS_SCALE_LOW")
    if v in (None, ""):
        return 0.25
    return float(v)


def qos_scale_sustain_s() -> float:
    """Seconds the scale-up pressure must hold before the autoscaler
    acts (docs/serving.md#qos) — brief spikes don't grow the fleet.
    Default 3."""
    v = _get("QOS_SCALE_SUSTAIN_S")
    if v in (None, ""):
        return 3.0
    return float(v)


def qos_scale_cooldown_s() -> float:
    """Seconds of continuously low load before the autoscaler drains a
    replica, and the minimum gap after any scale action before the next
    (docs/serving.md#qos). Default 15."""
    v = _get("QOS_SCALE_COOLDOWN_S")
    if v in (None, ""):
        return 15.0
    return float(v)


def qos_scale_interval_s() -> float:
    """Autoscaler observation period in seconds (docs/serving.md#qos).
    Default 1."""
    v = _get("QOS_SCALE_INTERVAL_S")
    if v in (None, ""):
        return 1.0
    return float(v)


def max_tenants() -> int:
    """Cardinality cap on the ``tenant`` metric label
    (docs/serving.md#slo): the first N distinct tenant names keep
    their own label value; later ones collapse into the ``"other"``
    overflow bucket so a client fabricating tenant names cannot grow
    the registry without bound. Default 16."""
    v = _get("MAX_TENANTS")
    if v in (None, ""):
        return 16
    return max(1, int(v))


def timeline_mark_cycles() -> bool:
    return _get("TIMELINE_MARK_CYCLES") not in (None, "", "0")


def shm_data_plane() -> bool:
    """Shared-memory data plane for same-host eager collectives (the
    reference's MPI shared-memory CPU path). HOROVOD_TPU_SHM=1/0 forces;
    default follows the launcher's placement verdict
    (HOROVOD_TPU_ALL_LOCAL) — every process of a job sees the same
    launcher env, so the fleet gates identically."""
    v = _get("SHM")
    if v is not None:
        return v not in ("", "0")
    return os.environ.get("HOROVOD_TPU_ALL_LOCAL") == "1"


def producer_fence() -> Optional[bool]:
    """Force (1) or suppress (0) the eager engine's producer fence —
    blocking on input producers before launching a fused collective.
    Default None = automatic: fence only when this process addresses
    more than one device (see CollectiveEngine._fence_producers — with
    one device every launch lands in one FIFO queue and the rendezvous
    inversion the fence prevents cannot occur)."""
    v = _get("PRODUCER_FENCE")
    if v in (None, ""):
        return None
    return v != "0"


def device_pack() -> Optional[bool]:
    """Force (1) or suppress (0) device-resident MP fusion-buffer
    packing. Default None = automatic: on for accelerator backends,
    off on CPU (executor._device_pack)."""
    v = _get("DEVICE_PACK")
    if v in (None, ""):
        return None
    return v != "0"


def ordered_launch() -> bool:
    """HOROVOD_TPU_ORDERED_LAUNCH=1: replace the producer completion
    fence with enqueue-ordering under a process-global launch lock
    (ops.collective.launch_lock()). PROTOTYPE, default off: measured on
    the CPU backend (experiments/ordered_launch_ab.py), PJRT's
    cross-device fan-out happens after the Python execute call returns,
    so host-side ordering cannot prevent rendezvous inversion there —
    the completion fence remains the safe default. The flag exists for
    real multi-chip TPU experimentation, where per-device enqueue is
    host-call-ordered."""
    return _get("ORDERED_LAUNCH") == "1"


def dlpack_boundary() -> bool:
    """DLPack zero-copy at the framework-shim boundary (utils/interop).
    Default on; HOROVOD_TPU_DLPACK=0 forces the numpy fallback path —
    the A/B lever for measuring the shim tax (experiments/interop_ab)."""
    return _get("DLPACK") not in ("0",)


def hierarchical_allreduce() -> bool:
    return _get("HIERARCHICAL_ALLREDUCE") not in (None, "", "0")


def hierarchical_allgather() -> bool:
    return _get("HIERARCHICAL_ALLGATHER") not in (None, "", "0")


def autotune() -> bool:
    """The LEGACY eager-path Bayesian tuner (parameter_manager parity).
    Reads ONLY ``HOROVOD_AUTOTUNE`` — deliberately not the usual
    ``HOROVOD_TPU_`` override chain, because ``HOROVOD_TPU_AUTOTUNE``
    enables the GLOBAL online tuner (:func:`autotune_global`,
    docs/autotune.md) and the two switches must not alias."""
    return os.environ.get("HOROVOD_AUTOTUNE") not in (None, "", "0")


def autotune_global() -> bool:
    """The global online autotuner (docs/autotune.md):
    ``HOROVOD_TPU_AUTOTUNE=1`` (or the runner's ``--autotune``) turns
    on the knob-registry driver guarded by the health plane."""
    return os.environ.get("HOROVOD_TPU_AUTOTUNE") not in (None, "", "0")


def autotune_log() -> Optional[str]:
    return _get("AUTOTUNE_LOG")


def autotune_guard_rel() -> float:
    """Rollback guard threshold for global-tuner moves: a post-move
    window worse than the pre-move baseline by more than this fraction
    rolls the move back (docs/autotune.md). Default matches the
    ``tools/health --baseline`` regression threshold."""
    v = _get("AUTOTUNE_GUARD_REL")
    return float(v) if v is not None else 0.10


def autotune_trial_budget() -> int:
    """Measurement windows the global tuner scores each candidate on."""
    v = _get("AUTOTUNE_TRIAL_BUDGET")
    return int(v) if v is not None else 2


def log_level() -> str:
    return (_get("LOG_LEVEL") or "warning").lower()


def log_hide_time() -> bool:
    return _get("LOG_HIDE_TIME") not in (None, "", "0")
