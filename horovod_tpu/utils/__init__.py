from . import env
from .logging import get_logger

__all__ = ["env", "get_logger"]
