"""Logging — equivalent of horovod/common/logging.{h,cc}.

The reference provides stream-style ``LOG(severity[, rank])`` macros with
levels TRACE…FATAL controlled by ``HOROVOD_LOG_LEVEL`` and timestamp
suppression via ``HOROVOD_LOG_HIDE_TIME`` (logging.cc:76-92). The Python
layer keeps the same env controls on top of stdlib logging; the native
runtime has its own C++ mirror (runtime/src/logging.h).
"""

from __future__ import annotations

import logging
import sys

from . import env as _env

_LEVELS = {
    "trace": logging.DEBUG - 5,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

logging.addLevelName(_LEVELS["trace"], "TRACE")

_configured = False


def _configure():
    global _configured
    if _configured:
        return
    root = logging.getLogger("horovod_tpu")
    handler = logging.StreamHandler(sys.stderr)
    if _env.log_hide_time():
        fmt = "[%(levelname)s] %(name)s: %(message)s"
    else:
        fmt = "%(asctime)s [%(levelname)s] %(name)s: %(message)s"
    handler.setFormatter(logging.Formatter(fmt))
    root.addHandler(handler)
    root.setLevel(_LEVELS.get(_env.log_level(), logging.WARNING))
    root.propagate = False
    _configured = True


def get_logger(name: str = "") -> logging.Logger:
    _configure()
    return logging.getLogger(
        "horovod_tpu" + ("." + name if name else ""))
