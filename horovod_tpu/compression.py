"""Gradient compression — parity with horovod/tensorflow/compression.py and
horovod/torch/compression.py (identical files in the reference, 75 LoC).

``Compression.none`` passes tensors through; ``Compression.fp16`` casts
floating tensors to fp16 for the wire and back after
(compression.py:33-75). On TPU we additionally provide ``Compression.bf16``
— bfloat16 is the hardware-native 16-bit format (same exponent range as
fp32, MXU-friendly), and is the idiomatic choice on this platform.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Interface for compressing/decompressing before/after collectives
    (compression.py:23-31)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """No-op (compression.py:33-43)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype = None

    @classmethod
    def compress(cls, tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            tensor = tensor.astype(cls.wire_dtype)
        return tensor, ctx

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None and tensor.dtype != ctx and \
                jnp.issubdtype(jnp.dtype(ctx), jnp.floating):
            tensor = tensor.astype(ctx)
        return tensor


class FP16Compressor(_CastCompressor):
    """fp16 wire format (compression.py:46-61)."""
    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    """bfloat16 wire format — TPU-native extension (no reference equivalent;
    bf16 is the platform's 16-bit type)."""
    wire_dtype = jnp.bfloat16


class FP8Compressor(_CastCompressor):
    """float8_e4m3 wire format — TPU-native extension: half of fp16's
    wire/HBM bytes with no per-block scales (the cast-compressor shape
    the reference's fp16 uses, unlike scaled int8 schemes). e4m3's ±448
    dynamic range suits gradients post-LR-scaling; reductions still
    accumulate in fp32 inside the fused program (executor._accum_dtype)."""
    wire_dtype = jnp.float8_e4m3fn


class Compression:
    """Option enum (compression.py:64-75)."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    fp8 = FP8Compressor
