"""Gradient compression — parity with horovod/tensorflow/compression.py and
horovod/torch/compression.py (identical files in the reference, 75 LoC).

``Compression.none`` passes tensors through; ``Compression.fp16`` casts
floating tensors to fp16 for the wire and back after
(compression.py:33-75). On TPU we additionally provide ``Compression.bf16``
— bfloat16 is the hardware-native 16-bit format (same exponent range as
fp32, MXU-friendly), and is the idiomatic choice on this platform.

Beyond the reference's cast compressors, ``Compression.int8_blockwise``
and ``Compression.fp8_blockwise`` select the block-scaled quantized wire
(quantization.py, EQuARX-style): the tensor itself is NOT transformed
here — the quantize → reduce-scatter → fp32-accumulate → requantize →
allgather pipeline runs inside the fused XLA collective — so these
compressors are pass-through markers carrying the wire spec, plus
:meth:`local_roundtrip` for error-feedback residuals (optimizer.py).
"""

from __future__ import annotations

import jax.numpy as jnp


def _is_floating(dtype) -> bool:
    """Floating test covering the extended dtypes (bfloat16, fp8) whose
    numpy identity varies across jax/ml_dtypes versions — restoring a
    non-default floating input dtype must not silently fail."""
    try:
        if jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
            return True
    except TypeError:
        pass
    name = str(getattr(dtype, "name", None) or
               getattr(dtype, "__name__", None) or dtype)
    return name.startswith(("float", "bfloat"))


class Compressor:
    """Interface for compressing/decompressing before/after collectives
    (compression.py:23-31)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """No-op (compression.py:33-43)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype = None

    @classmethod
    def compress(cls, tensor):
        ctx = tensor.dtype
        if _is_floating(tensor.dtype):
            tensor = tensor.astype(cls.wire_dtype)
        return tensor, ctx

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None and tensor.dtype != ctx and _is_floating(ctx):
            tensor = tensor.astype(ctx)
        return tensor


class FP16Compressor(_CastCompressor):
    """fp16 wire format (compression.py:46-61)."""
    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    """bfloat16 wire format — TPU-native extension (no reference equivalent;
    bf16 is the platform's 16-bit type)."""
    wire_dtype = jnp.bfloat16


class FP8Compressor(_CastCompressor):
    """float8_e4m3 wire format — TPU-native extension: half of fp16's
    wire/HBM bytes with no per-block scales (the cast-compressor shape
    the reference's fp16 uses, unlike scaled int8 schemes). e4m3's ±448
    dynamic range suits gradients post-LR-scaling; reductions still
    accumulate in fp32 inside the fused program (executor._accum_dtype)."""
    wire_dtype = jnp.float8_e4m3fn


class _BlockwiseCompressor(Compressor):
    """Block-scaled quantized wire format (quantization.py).

    ``compress``/``decompress`` only restore the logical dtype — the
    quantization itself is executed inside the fused collective program
    (executor._fused_reduce / quantization.allreduce_blocks), keyed off
    ``wire_spec``. ``local_roundtrip`` reproduces this rank's phase-1
    wire contribution for error-feedback residuals."""

    wire_spec = None  # "int8x256" / "fp8x256"

    @classmethod
    def compress(cls, tensor):
        return tensor, tensor.dtype

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None and tensor.dtype != ctx and _is_floating(ctx):
            tensor = tensor.astype(ctx)
        return tensor

    @classmethod
    def local_roundtrip(cls, tensor):
        from . import quantization as _q
        return _q.local_roundtrip(tensor, cls.wire_spec)


class Int8BlockwiseCompressor(_BlockwiseCompressor):
    """Absmax-scaled int8 blocks (256 elements/block): ~0.25x fp32 wire
    bytes with max error ~0.8% of each block's absmax across the dual
    quantization — the accuracy/bandwidth workhorse."""
    wire_spec = "int8x256"


class FP8BlockwiseCompressor(_BlockwiseCompressor):
    """Absmax-scaled e4m3 blocks: same wire bytes as int8_blockwise but
    ~6% relative error near each block's absmax (3 mantissa bits) and
    finer resolution for small elements — prefer int8_blockwise unless
    the hardware reduces fp8 natively."""
    wire_spec = "fp8x256"


class Compression:
    """Option enum (compression.py:64-75)."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    fp8 = FP8Compressor
    int8_blockwise = Int8BlockwiseCompressor
    fp8_blockwise = FP8BlockwiseCompressor
