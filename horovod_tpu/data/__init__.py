"""Pod-scale input pipeline (docs/data.md, ISSUE 13).

The subsystem that makes every training bench honest about where time
goes: deterministic per-rank sharded loaders, double-buffered
prefetch-to-device wired into the StepTimer attribution, elastic-aware
exactly-once resumable cursors riding the checkpoint engine, and
distributed batch norm for the conv zoo.

    from horovod_tpu import data

    src = data.synthetic("image", n=50_000, image_size=224,
                         num_classes=1000, seed=0)
    loader = data.build_loader(src, batch_size=32, seed=0)
    for batch in data.prefetch_to_device(loader, hvd.mesh(), depth=2,
                                         timer=step_timer):
        ...

``data.sync_bn`` (SyncBatchNorm) imports flax and is loaded lazily so
the loader/prefetch layers stay usable without the model stack.
"""

from .loader import (Batch, ShardedDataset, ShardedLoader, build_loader)
from .prefetch import DevicePrefetcher, prefetch_to_device, stage
from .sharding import epoch_permutation, total_microbatches, \
    usable_samples
from .sources import (ArraySource, CallableSource, FileListSource,
                      SyntheticSource, as_source, synthetic)

__all__ = [
    "ArraySource", "Batch", "CallableSource", "DevicePrefetcher",
    "FileListSource", "ShardedDataset", "ShardedLoader",
    "SyncBatchNorm", "SyntheticSource", "as_source", "build_loader",
    "epoch_permutation", "prefetch_to_device", "stage", "sync_bn",
    "synthetic", "sync_batch_norm", "total_microbatches",
    "usable_samples",
]


def __getattr__(name):
    # flax-dependent surface, resolved on first touch.
    if name in ("SyncBatchNorm", "sync_batch_norm", "batch_moments",
                "sync_bn"):
        from . import sync_bn as _sbn
        if name == "sync_bn":
            return _sbn
        return getattr(_sbn, name)
    raise AttributeError(name)
