"""Data sources — anything the sharded loader can index by sample id.

A source is ``__len__`` plus ``take(ids) -> tuple of np.ndarray``
(arrays batched on axis 0, one per field). Everything else — sharding,
shuffling, cursors, prefetch — is the loader's job, so a source stays a
dumb random-access reader:

  - :class:`ArraySource` — in-memory array(s).
  - :class:`FileListSource` — one file per sample (``read_fn`` defaults
    to ``np.load``); the pod-scale shape where the "dataset" is a
    manifest of shard files on a parallel filesystem.
  - :class:`CallableSource` — ``fn(ids) -> arrays`` with a declared
    length; the adapter for generator-style data with known length.
  - :func:`synthetic` — the deterministic synthetic workloads the bench
    and examples train on. Deliberately a *source*, not a bypass: the
    synthetic path exercises the identical shard/cursor/prefetch
    machinery as real data (ISSUE 13), so an input-bound verdict on a
    bench run means what it says.

Synthetic samples are a pure function of ``(seed, sample id)`` (one
PCG64 stream per id), so the same id yields the same sample no matter
which rank, batch, epoch or world size asks for it — the property the
exactly-once tests lean on.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import numpy as np

Arrays = Tuple[np.ndarray, ...]


def _as_tuple(x) -> Arrays:
    return tuple(x) if isinstance(x, (tuple, list)) else (x,)


class ArraySource:
    """In-memory array(s) indexed on axis 0."""

    def __init__(self, *arrays: np.ndarray):
        if not arrays:
            raise ValueError("ArraySource needs at least one array")
        self._arrays = tuple(np.asarray(a) for a in arrays)
        n = self._arrays[0].shape[0]
        for a in self._arrays[1:]:
            if a.shape[0] != n:
                raise ValueError(
                    "all arrays must share axis-0 length: "
                    f"{[a.shape[0] for a in self._arrays]}")
        self._n = n

    def __len__(self) -> int:
        return self._n

    def take(self, ids: np.ndarray) -> Arrays:
        return tuple(a[ids] for a in self._arrays)


class FileListSource:
    """One file per sample; ``read_fn(path)`` returns one sample (array
    or tuple of arrays), stacked into the batch. Paths are captured at
    construction — the *list* is the dataset, so its order and length
    are as stable as the manifest the caller built it from."""

    def __init__(self, paths: Sequence[str],
                 read_fn: Optional[Callable] = None):
        self._paths = list(paths)
        self._read = read_fn if read_fn is not None else np.load

    def __len__(self) -> int:
        return len(self._paths)

    def take(self, ids: np.ndarray) -> Arrays:
        samples = [_as_tuple(self._read(self._paths[int(i)]))
                   for i in ids]
        if not samples:
            return ()
        return tuple(np.stack([s[f] for s in samples])
                     for f in range(len(samples[0])))


class CallableSource:
    """``fn(ids) -> array | tuple of arrays`` with a declared length —
    the adapter for generator-backed data whose length is known."""

    def __init__(self, fn: Callable[[np.ndarray], Arrays], length: int):
        self._fn = fn
        self._n = int(length)

    def __len__(self) -> int:
        return self._n

    def take(self, ids: np.ndarray) -> Arrays:
        return _as_tuple(self._fn(ids))


class SyntheticSource:
    """Deterministic synthetic samples, one PCG64 stream per sample id
    (see module docstring). ``kind``:

      ``"image"``   (images [B,H,W,3] float32 in [0,1), labels [B] int32)
                    — class-prototype blobs like examples/_data.py, but
                    addressable by id.
      ``"tokens"``  (tokens [B,S] int32 in [0, vocab)) — the LM bench
                    feed.
    """

    def __init__(self, kind: str = "image", n: int = 4096, *,
                 image_size: int = 32, num_classes: int = 10,
                 seq_len: int = 128, vocab: int = 32000, seed: int = 0):
        if kind not in ("image", "tokens"):
            raise ValueError(f"unknown synthetic kind {kind!r}; "
                             "choose 'image' or 'tokens'")
        self.kind = kind
        self._n = int(n)
        self._image_size = int(image_size)
        self._classes = int(num_classes)
        self._seq = int(seq_len)
        self._vocab = int(vocab)
        self._seed = int(seed)
        if kind == "image":
            # Class prototypes are shared across all samples (drawn from
            # the seed stream alone) so the labels are learnable.
            proto_rng = np.random.Generator(np.random.PCG64(
                np.random.SeedSequence([self._seed, 0x9E3779B9])))
            self._protos = proto_rng.random(
                (self._classes, self._image_size, self._image_size, 3),
                dtype=np.float32)

    def __len__(self) -> int:
        return self._n

    def _rng(self, sample_id: int) -> np.random.Generator:
        return np.random.Generator(np.random.PCG64(
            np.random.SeedSequence([self._seed, int(sample_id)])))

    def take(self, ids: np.ndarray) -> Arrays:
        if self.kind == "tokens":
            rows = [self._rng(i).integers(0, self._vocab, size=self._seq,
                                          dtype=np.int64)
                    for i in ids]
            stack = (np.stack(rows).astype(np.int32) if rows
                     else np.empty((0, self._seq), np.int32))
            return (stack,)
        images, labels = [], []
        for i in ids:
            rng = self._rng(i)
            label = int(rng.integers(0, self._classes))
            noise = rng.standard_normal(
                (self._image_size, self._image_size, 3),
                dtype=np.float32)
            images.append(np.clip(
                self._protos[label] + 0.3 * noise, 0.0, 1.0))
            labels.append(label)
        if not images:
            s = self._image_size
            return (np.empty((0, s, s, 3), np.float32),
                    np.empty((0,), np.int32))
        return (np.stack(images), np.asarray(labels, np.int32))


def synthetic(kind: str = "image", n: int = 4096, **kwargs
              ) -> SyntheticSource:
    """The synthetic workload as a first-class source (see
    :class:`SyntheticSource`)."""
    return SyntheticSource(kind, n, **kwargs)


def as_source(obj, length: Optional[int] = None):
    """Coerce the accepted source shapes:

      - an object with ``take``/``__len__`` passes through,
      - an array or tuple/list of arrays → :class:`ArraySource`,
      - a list of path strings → :class:`FileListSource`,
      - a callable plus ``length=`` → :class:`CallableSource`.
    """
    if hasattr(obj, "take") and hasattr(obj, "__len__"):
        return obj
    if callable(obj):
        if length is None:
            raise ValueError(
                "a callable source needs length= (the loader must know "
                "the dataset size to build the epoch plan)")
        return CallableSource(obj, length)
    if isinstance(obj, (tuple, list)):
        if obj and isinstance(obj[0], (str, bytes)):
            return FileListSource(obj)
        return ArraySource(*obj)
    return ArraySource(obj)
