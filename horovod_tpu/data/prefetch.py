"""Double-buffered prefetch-to-device (docs/data.md#prefetch).

``prefetch_to_device(loader, sharding, depth=2)`` runs the loader AND
the host→device copy on a background thread, keeping up to ``depth``
batches resident on device ahead of the consumer. While the step
executes batch *k*, the thread is already materializing and staging
batch *k+1* — the overlap that turns "input-bound" into
"compute-bound" when the source can keep up, and the mechanism the
``tools/trace report`` bound verdict is calibrated against.

StepTimer wiring: the consumer's blocking wait inside ``__next__``
lands in the pre-step gap, which :class:`StepTimer` already attributes
to the ``input`` phase. When a ``timer=`` is passed, the prefetcher
additionally *credits* the staged copy time of each batch the consumer
actually waited for to the ``h2d`` phase
(:meth:`StepTimer.credit_h2d`), so the input/h2d split stays honest in
both regimes: fully overlapped (wait ≈ 0 → everything is compute),
and starved (wait > 0 → split between source time = ``input`` and copy
time = ``h2d``).

``sharding`` may be a ``jax.sharding.Sharding``, a ``Mesh`` (batches go
to ``PartitionSpec('dp')`` when the mesh has a ``dp`` axis, else its
first axis), or None (default device placement).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

from ..observability import registry as _reg
from .loader import Batch

_SENTINEL = object()


def _metrics():
    r = _reg.registry()
    return {
        "depth": r.gauge(
            "hvdtpu_data_prefetch_depth",
            "Configured device-prefetch depth of the most recently "
            "built prefetcher").labels(),
        "occupancy": r.gauge(
            "hvdtpu_data_prefetch_occupancy",
            "Batches resident on device ahead of the consumer at the "
            "last fetch (0 with a starved source: the consumer is "
            "waiting — the input-bound signature)").labels(),
        "wait": r.counter(
            "hvdtpu_data_wait_seconds_total",
            "Seconds the training loop blocked waiting on the "
            "prefetcher (input starvation as a number)").labels(),
        "h2d": r.counter(
            "hvdtpu_data_h2d_seconds_total",
            "Seconds spent copying batches host-to-device (on the "
            "prefetch thread — overlapped with the step unless the "
            "wait counter is climbing too)").labels(),
    }


_cached = None


def _m():
    global _cached
    if _cached is None:
        _cached = _metrics()
    return _cached


def _resolve_sharding(sharding):
    if sharding is None:
        return None
    from jax.sharding import Mesh, NamedSharding, PartitionSpec
    if isinstance(sharding, Mesh):
        axis = "dp" if "dp" in sharding.axis_names \
            else sharding.axis_names[0]
        return NamedSharding(sharding, PartitionSpec(axis))
    return sharding


class DevicePrefetcher:
    """Iterator produced by :func:`prefetch_to_device`."""

    def __init__(self, it, sharding=None, *, depth: int = 2,
                 timer=None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._it = iter(it)
        self._sharding = _resolve_sharding(sharding)
        self._timer = timer
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._closed = False
        _m()["depth"].set(depth)
        self._thread = threading.Thread(
            target=self._producer, name="hvd-tpu-data-prefetch",
            daemon=True)
        self._thread.start()

    def _stage(self, batch):
        import jax
        t0 = time.perf_counter()
        if self._sharding is not None:
            data = tuple(jax.device_put(a, self._sharding)
                         for a in batch.data)
        else:
            data = tuple(jax.device_put(a) for a in batch.data)
        jax.block_until_ready(data)
        h2d_s = time.perf_counter() - t0
        _m()["h2d"].inc(h2d_s)
        if isinstance(batch, Batch):
            batch = batch._replace(data=data)
        else:  # plain tuples/arrays prefetch too
            batch = data
        return batch, h2d_s

    def _producer(self):
        try:
            for batch in self._it:
                if self._closed:
                    return
                self._q.put(self._stage(batch))
            self._q.put(_SENTINEL)
        except BaseException as e:  # propagate into the consumer
            self._q.put(e)

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        t0 = time.perf_counter()
        item = self._q.get()
        wait_s = time.perf_counter() - t0
        mt = _m()
        mt["wait"].inc(wait_s)
        mt["occupancy"].set(self._q.qsize())
        if item is _SENTINEL:
            self._closed = True
            raise StopIteration
        if isinstance(item, BaseException):
            self._closed = True
            raise item
        batch, h2d_s = item
        if self._timer is not None and wait_s > 0:
            # The consumer stalled; the staged copy of THIS batch is the
            # h2d share of that stall, the rest was the source.
            self._timer.credit_h2d(min(wait_s, h2d_s))
        return batch

    def close(self) -> None:
        """Stop the background thread (the loader may be infinite)."""
        self._closed = True
        # Unblock a producer waiting on a full queue.
        try:
            self._q.get_nowait()
        except queue.Empty:
            pass


def prefetch_to_device(it, sharding=None, *, depth: int = 2,
                       timer=None) -> DevicePrefetcher:
    """Wrap a loader (or any iterator of :class:`Batch` / array tuples)
    with background host→device staging ``depth`` batches deep
    (``depth=2`` is classic double buffering). See module docstring for
    the StepTimer wiring."""
    return DevicePrefetcher(it, sharding, depth=depth, timer=timer)


def stage(batch, sharding=None, *, timer=None):
    """Synchronous (un-prefetched) device staging for simple loops:
    ``device_put`` + ``StepTimer.mark_h2d_done()``. The A in the
    prefetch A/B."""
    import jax
    sh = _resolve_sharding(sharding)
    data = batch.data if isinstance(batch, Batch) else batch
    t0 = time.perf_counter()
    if sh is not None:
        staged = tuple(jax.device_put(a, sh) for a in data)
    else:
        staged = tuple(jax.device_put(a) for a in data)
    jax.block_until_ready(staged)
    _m()["h2d"].inc(time.perf_counter() - t0)
    if timer is not None:
        timer.mark_h2d_done()
    if isinstance(batch, Batch):
        return batch._replace(data=staged)
    return staged
