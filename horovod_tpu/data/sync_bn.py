"""Distributed (cross-replica) batch normalization — the remaining
large-batch technique from "Scale MLPerf-0.6 models on Google TPU-v3
Pods" (arXiv 1909.09756) not yet carried (docs/data.md#sync-bn).

At pod scale the per-replica batch shrinks until local batch statistics
are too noisy to train on (MLPerf ResNet at batch 64/replica already
trains on cross-replica stats). :func:`sync_batch_norm` computes the
batch moments over the whole ``dp`` axis with **one** fused collective:
the local sum and sum-of-squares vectors are concatenated into a single
``[2C]`` buffer and psum'd together (one launch, one ring traversal —
the same fusion argument as the engine's tensor fusion, applied inside
the jitted program), then mean/var derive locally. The count is static
(`local batch × axis size`), so nothing else crosses the wire.

:class:`SyncBatchNorm` wraps it in the exact ``nn.BatchNorm`` layout —
params ``scale``/``bias``, ``batch_stats`` collection ``mean``/``var``,
biased fp32 moments, identical momentum update — so checkpoints are
interchangeable with the local-BN models and the conv zoo adopts it by
swapping the norm constructor (``ResNet50(bn_axis_name='dp')``).

Parity contract (tests/test_data.py): under ``shard_map`` over
``dp=K``, forward outputs and input/parameter gradients match a single
device running ``nn.BatchNorm`` on the concatenated batch at
rtol 1e-5.

Outside any mapped context (``axis_name=None``) it degrades to local
batch norm — the single-device path and the distributed path are one
implementation.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp

from ..parallel import collectives as _coll


def batch_moments(x: jnp.ndarray, axis_name: Optional[str] = "dp"
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Biased mean/var over all axes but the last, across ``axis_name``
    when given — one psum of the concatenated ``[sum, sum_sq]`` buffer
    (the fused collective path). Returns fp32 ``(mean, var)`` of shape
    ``[C]``."""
    x32 = x.astype(jnp.float32)
    axes = tuple(range(x32.ndim - 1))
    s1 = jnp.sum(x32, axis=axes)
    s2 = jnp.sum(x32 * x32, axis=axes)
    count = 1
    for d in axes:
        count *= x.shape[d]
    if axis_name is not None:
        fused = _coll.psum(jnp.concatenate([s1, s2]), axis_name)
        c = x.shape[-1]
        s1, s2 = fused[:c], fused[c:]
        count = count * _coll.axis_size(axis_name)
    mean = s1 / count
    var = s2 / count - mean * mean
    return mean, var


def sync_batch_norm(x: jnp.ndarray, scale: jnp.ndarray,
                    bias: jnp.ndarray, *,
                    axis_name: Optional[str] = "dp",
                    epsilon: float = 1e-5,
                    dtype: Optional[Any] = None
                    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Functional core: normalize ``x`` by the cross-replica batch
    moments. Returns ``(y, mean, var)`` — the moments are what the
    module folds into the running statistics."""
    mean, var = batch_moments(x, axis_name)
    y = _normalize(x, mean, var, scale, bias, epsilon, dtype)
    return y, mean, var


def _normalize(x, mean, var, scale, bias, epsilon, dtype):
    x32 = x.astype(jnp.float32)
    inv = jnp.reciprocal(jnp.sqrt(var + epsilon))
    y = (x32 - mean) * inv * scale + bias
    return y.astype(dtype if dtype is not None else x.dtype)


class SyncBatchNorm(nn.Module):
    """Drop-in ``nn.BatchNorm`` with cross-replica statistics (see
    module docstring). Same parameter/stat layout as ``nn.BatchNorm``
    and :class:`~horovod_tpu.models.resnet.FusedBNAct`, so the three
    norm implementations share checkpoints."""

    use_running_average: bool = False
    axis_name: Optional[str] = "dp"
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Optional[Any] = None
    scale_init: Callable = nn.initializers.ones

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        scale = self.param("scale", self.scale_init, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,),
                          jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean",
            lambda s: jnp.zeros(s, jnp.float32), (c,))
        ra_var = self.variable(
            "batch_stats", "var",
            lambda s: jnp.ones(s, jnp.float32), (c,))
        if self.use_running_average:
            # Inference needs no collective: running stats are already
            # replica-identical (they fold replica-identical batch
            # moments).
            return _normalize(x, ra_mean.value, ra_var.value, scale,
                              bias, self.epsilon, self.dtype)
        # Shape inference (init) commonly runs OUTSIDE the mapped
        # context where the axis is unbound; the moments are discarded
        # there, so local statistics are exactly as good.
        axis = None if self.is_initializing() else self.axis_name
        y, mean, var = sync_batch_norm(
            x, scale, bias, axis_name=axis,
            epsilon=self.epsilon, dtype=self.dtype)
        if not self.is_initializing():
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1.0 - m) * mean
            ra_var.value = m * ra_var.value + (1.0 - m) * var
        return y
