"""Sharded, resumable loaders — the per-rank face of the epoch plan.

``build_loader(source, batch_size=..., rank=..., world_size=...)``
returns a :class:`ShardedLoader` that walks the deterministic epoch
plan of :mod:`.sharding`: each global step, rank ``r`` materializes
microbatch ``offset + r`` of the current epoch permutation (or a
zero-weight filler batch when fewer than ``world_size`` microbatches
remain — shapes stay static through the epoch tail, and a masked mean
via ``Batch.weight`` stays exact).

Resumability is a **cursor**, not buffered state: ``(seed, epoch,
offset, batch_size)`` fully determines every sample any rank will ever
see next, so checkpointing the input pipeline is four integers riding
the same :class:`~horovod_tpu.elastic.ElasticState` commit as the model
(docs/data.md#exactly-once)::

    loader = data.build_loader(src, batch_size=32)
    state = hvd.ElasticState(params=params, data=loader.commit_cursor())
    state.restore()
    loader.restore(state.data)
    for batch in loader:
        ...
        state.params, state.data = params, loader.commit_cursor()
        state.commit(step)

Because the plan is world-size independent, a shrink or regrow between
generations replays no sample twice and skips none: the committed
cursor names the first unconsumed microbatch, the rolled-back steps'
samples are re-dealt (to however many ranks now exist), and the epoch's
consumed multiset stays exactly one clean epoch.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterator, NamedTuple, Optional, Tuple

import numpy as np

from ..observability import registry as _reg
from . import sharding as _sharding
from .sources import as_source

_CURSOR_VERSION = 1


class Batch(NamedTuple):
    """One per-rank batch. ``data`` is the tuple of field arrays (static
    shapes: ``[batch_size, ...]`` even for the filler), ``ids`` the
    sample ids delivered (empty for a filler), ``weight`` the number of
    real samples (0 for a filler — divide masked sums by the psum of
    weights, never by the static batch size), ``epoch`` the epoch the
    batch belongs to."""

    data: Tuple[np.ndarray, ...]
    ids: np.ndarray
    weight: int
    epoch: int


def _metrics():
    r = _reg.registry()
    return {
        "samples": r.counter(
            "hvdtpu_data_samples_total",
            "Samples delivered by sharded loaders on this process"
        ).labels(),
        "batches": r.counter(
            "hvdtpu_data_batches_total",
            "Batches delivered by sharded loaders (fillers included)"
        ).labels(),
        "epochs": r.counter(
            "hvdtpu_data_epochs_total",
            "Epoch boundaries crossed by sharded loaders").labels(),
        "load": r.counter(
            "hvdtpu_data_load_seconds_total",
            "Seconds spent materializing batches from the source "
            "(take + transform) on this process").labels(),
        "commits": r.counter(
            "hvdtpu_data_cursor_commits_total",
            "Loader cursors handed to a checkpoint commit").labels(),
        "skips": r.counter(
            "hvdtpu_data_resume_skips_total",
            "Samples fast-forwarded past on cursor restore (already "
            "consumed before the committed cursor — never re-delivered)"
        ).labels(),
    }


_cached_metrics: Optional[dict] = None


def _m() -> dict:
    global _cached_metrics
    if _cached_metrics is None:
        _cached_metrics = _metrics()
    return _cached_metrics


def _recorder():
    from ..observability import flight_recorder as _fr
    return _fr.recorder()


class ShardedDataset:
    """A source plus the epoch-plan parameters: everything global (no
    rank in sight). Loaders over the same dataset with any world shape
    agree on the plan."""

    def __init__(self, source, *, batch_size: int, seed: int = 0,
                 shuffle: bool = True, drop_remainder: bool = True,
                 length: Optional[int] = None):
        if not drop_remainder:
            raise ValueError(
                "drop_remainder=False is not supported: the epoch plan "
                "is defined in whole microbatches so its sample multiset "
                "is world-size independent (docs/data.md#sharding)")
        self.source = as_source(source, length=length)
        self.batch_size = int(batch_size)
        if self.batch_size <= 0:
            raise ValueError(f"batch_size must be > 0, got {batch_size}")
        self.seed = int(seed)
        self.shuffle = bool(shuffle)
        self.n = len(self.source)
        self.usable = _sharding.usable_samples(self.n, self.batch_size)
        self.total_microbatches = _sharding.total_microbatches(
            self.n, self.batch_size)
        if self.total_microbatches == 0:
            raise ValueError(
                f"dataset of {self.n} samples yields zero whole "
                f"microbatches of {self.batch_size}")

    def permutation(self, epoch: int) -> np.ndarray:
        return _sharding.epoch_permutation(self.n, self.seed, epoch,
                                           shuffle=self.shuffle)

    def epoch_ids(self, epoch: int) -> np.ndarray:
        """The epoch's full delivered multiset (drop-remainder applied)
        — what the exactly-once tests compare against."""
        return self.permutation(epoch)[:self.usable]


class ShardedLoader:
    """Per-rank iterator over a :class:`ShardedDataset` (see module
    docstring). Not thread-safe; wrap with
    :func:`~horovod_tpu.data.prefetch_to_device` for background
    staging."""

    def __init__(self, dataset: ShardedDataset, *, rank: int,
                 world_size: int, epochs: Optional[int] = None,
                 transform=None):
        if not (0 <= rank < world_size):
            raise ValueError(
                f"rank {rank} outside world of {world_size}")
        self.dataset = dataset
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.epochs = epochs
        self.transform = transform
        self.epoch = 0
        self.offset = 0          # global microbatch cursor within epoch
        self._perm: Optional[np.ndarray] = None
        self._perm_epoch = -1
        self._template: Optional[Tuple[np.ndarray, ...]] = None
        self._epochs_done = 0

    # ------------------------------------------------------------ cursor

    def cursor(self) -> Dict[str, Any]:
        """The resume point as a tiny pytree of ints — the first
        *unconsumed* global microbatch. Commit it in the same
        ElasticState commit as the model state it is consistent with."""
        return {"version": np.int64(_CURSOR_VERSION),
                "seed": np.int64(self.dataset.seed),
                "batch_size": np.int64(self.dataset.batch_size),
                "epoch": np.int64(self.epoch),
                "offset": np.int64(self.offset)}

    def commit_cursor(self) -> Dict[str, Any]:
        """:meth:`cursor` plus the observability trail: counts the
        commit and notes it in the flight recorder, so the postmortem
        can name the last committed cursor per rank
        (docs/postmortem.md)."""
        _m()["commits"].inc()
        _recorder().note("data", ("cursor_commit", int(self.epoch),
                                  int(self.offset), self.rank))
        return self.cursor()

    def restore(self, cursor: Dict[str, Any]) -> "ShardedLoader":
        """Adopt a committed cursor. The plan parameters must match —
        a changed seed or batch size silently reshuffles every epoch, so
        it is an error, not a fast-forward."""
        seed = int(cursor["seed"])
        batch = int(cursor["batch_size"])
        if seed != self.dataset.seed or batch != self.dataset.batch_size:
            raise ValueError(
                f"cursor was cut for seed={seed} batch_size={batch}; "
                f"this loader has seed={self.dataset.seed} "
                f"batch_size={self.dataset.batch_size} — the epoch plans "
                "differ and exactly-once cannot hold")
        self.epoch = int(cursor["epoch"])
        self.offset = int(cursor["offset"])
        self._epochs_done = self.epoch
        skipped = self.offset * self.dataset.batch_size
        if skipped:
            _m()["skips"].inc(skipped)
        _recorder().note("data", ("resume", self.epoch, self.offset,
                                  skipped))
        return self

    # --------------------------------------------------------- iteration

    def _permutation(self) -> np.ndarray:
        if self._perm_epoch != self.epoch:
            self._perm = self.dataset.permutation(self.epoch)
            self._perm_epoch = self.epoch
        return self._perm

    def _filler(self) -> Tuple[np.ndarray, ...]:
        """Zero arrays with the batch's static shapes — resolved once
        from a real microbatch (microbatch 0 always exists)."""
        if self._template is None:
            perm = self._permutation()
            ids = _sharding.microbatch_ids(perm, 0,
                                           self.dataset.batch_size)
            probe = self.dataset.source.take(ids)
            if self.transform is not None:
                probe = self.transform(probe)
            self._template = tuple(
                np.zeros_like(np.asarray(a)) for a in probe)
        return self._template

    def __iter__(self) -> Iterator[Batch]:
        return self

    def __next__(self) -> Batch:
        total = self.dataset.total_microbatches
        if self.offset >= total:
            # Epoch boundary: every rank derives it from the same
            # cursor math, so no rank needs to be told.
            self.epoch += 1
            self.offset = 0
            self._epochs_done += 1
            _m()["epochs"].inc()
            _recorder().note("data", ("epoch", self.epoch, 0,
                                      self.rank))
        if self.epochs is not None and self._epochs_done >= self.epochs:
            raise StopIteration
        m = _sharding.rank_microbatch(self.offset, self.rank,
                                      self.world_size, total)
        epoch = self.epoch
        t0 = time.perf_counter()
        if m < 0:
            arrays = self._filler()
            ids = np.empty((0,), np.int64)
            weight = 0
        else:
            ids = _sharding.microbatch_ids(self._permutation(), m,
                                           self.dataset.batch_size)
            arrays = self.dataset.source.take(ids)
            if self.transform is not None:
                arrays = self.transform(arrays)
            weight = int(ids.shape[0])
        mt = _m()
        mt["load"].inc(time.perf_counter() - t0)
        mt["batches"].inc()
        if weight:
            mt["samples"].inc(weight)
        self.offset = _sharding.advance(self.offset, self.world_size,
                                        total)
        return Batch(tuple(np.asarray(a) for a in arrays), ids, weight,
                     epoch)

    # ------------------------------------------------------- conveniences

    @property
    def samples_per_epoch(self) -> int:
        return self.dataset.usable

    @property
    def steps_per_epoch(self) -> int:
        """Global steps to finish an epoch at this world size (the last
        may hand fillers to the highest ranks)."""
        t, w = self.dataset.total_microbatches, self.world_size
        return -(-t // w)


def build_loader(source, *, batch_size: int, rank: Optional[int] = None,
                 world_size: Optional[int] = None, seed: int = 0,
                 shuffle: bool = True, drop_remainder: bool = True,
                 epochs: Optional[int] = None, length: Optional[int] = None,
                 transform=None) -> ShardedLoader:
    """The one-call entry point: wrap ``source`` in a
    :class:`ShardedDataset` and return this rank's
    :class:`ShardedLoader`. ``rank``/``world_size`` default to the live
    topology when ``hvd.init()`` has run, else to a single-rank world.
    ``transform`` runs on each materialized batch (augmentation,
    decode, ... — this is where a slow input pipeline actually burns
    its time, and where the throttled-loader tests inject theirs)."""
    if rank is None or world_size is None:
        try:
            from .. import topology as _topo
            t = _topo._get()
            rank = t.process_index if rank is None else rank
            world_size = (t.process_count if world_size is None
                          else world_size)
        except Exception:
            rank = 0 if rank is None else rank
            world_size = 1 if world_size is None else world_size
    ds = ShardedDataset(source, batch_size=batch_size, seed=seed,
                        shuffle=shuffle, drop_remainder=drop_remainder,
                        length=length)
    return ShardedLoader(ds, rank=int(rank), world_size=int(world_size),
                         epochs=epochs, transform=transform)
