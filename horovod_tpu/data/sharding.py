"""Deterministic epoch sharding — the math the whole input pipeline
rests on (docs/data.md).

At pod scale every rank must independently derive *the same* epoch plan
from nothing but ``(seed, epoch)`` — there is no coordinator that deals
samples, and a relaunched worker must reconstruct the exact plan a dead
one was following. The unit of dealing is the **microbatch**: the epoch
permutation of all ``n`` sample ids is cut into consecutive chunks of
``batch_size``; drop-remainder keeps the first ``n // batch_size``
chunks (the permutation's tail is what gets dropped, so *which* samples
fall out is itself deterministic per epoch). A world of ``W`` ranks
consumes ``W`` microbatches per global step — rank ``r`` takes
microbatch ``offset + r`` — which makes the epoch's sample multiset
independent of the world size: a job that shrinks from 4 ranks to 1
mid-epoch still consumes exactly the microbatches ``offset..total``
once each, because the plan is a function of the cursor, not of the
membership.

The permutation comes from numpy's Philox-free PCG64 seeded with
``SeedSequence([seed, epoch])`` — stable across processes, launches and
platforms for a fixed numpy, and different per epoch without any
carried RNG state (the cursor needs only ``(seed, epoch, offset)``).
"""

from __future__ import annotations

import numpy as np


def epoch_permutation(n: int, seed: int, epoch: int,
                      shuffle: bool = True) -> np.ndarray:
    """The epoch's sample-id order: a pure function of
    ``(n, seed, epoch)`` — every rank, every launch, every generation
    computes the identical array. ``shuffle=False`` is sequential order
    (still epoch-plan compatible: the cursor math is order-agnostic)."""
    if n < 0:
        raise ValueError(f"dataset length must be >= 0, got {n}")
    if not shuffle:
        return np.arange(n, dtype=np.int64)
    rng = np.random.Generator(
        np.random.PCG64(np.random.SeedSequence([int(seed), int(epoch)])))
    return rng.permutation(n).astype(np.int64)


def usable_samples(n: int, batch_size: int) -> int:
    """Drop-remainder sample count: whole microbatches only. Defined on
    ``(n, batch_size)`` alone — NOT on the world size — so the epoch's
    sample multiset survives elastic resizes."""
    if batch_size <= 0:
        raise ValueError(f"batch_size must be > 0, got {batch_size}")
    return (n // batch_size) * batch_size


def total_microbatches(n: int, batch_size: int) -> int:
    return usable_samples(n, batch_size) // batch_size


def microbatch_ids(perm: np.ndarray, index: int,
                   batch_size: int) -> np.ndarray:
    """Sample ids of microbatch ``index`` in the epoch permutation."""
    lo = index * batch_size
    return perm[lo:lo + batch_size]


def rank_microbatch(offset: int, rank: int, world_size: int,
                    total: int) -> int:
    """Microbatch index rank ``r`` consumes at global cursor ``offset``,
    or -1 when fewer than ``rank + 1`` microbatches remain (the rank
    receives a zero-weight filler batch that global step). All ranks
    advance the cursor identically by :func:`advance`."""
    m = offset + rank
    return m if m < total else -1


def advance(offset: int, world_size: int, total: int) -> int:
    """Next global cursor after one global step: ``offset + W`` capped
    at the epoch's end (the final step may consume fewer than ``W``
    microbatches; the filler ranks consumed nothing)."""
    return min(offset + world_size, total)
