"""Native runtime package: C++ control-plane core + ctypes binding.

See ``src/`` for the C++ sources (equivalents of reference components
N1-N10, SURVEY.md §2.1) and :mod:`native` for the Python binding.
"""

from .native import NativeCore, load

__all__ = ["NativeCore", "load"]
