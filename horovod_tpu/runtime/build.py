"""Native runtime build — the role of the reference's probing setup.py
(setup.py:294-553), reduced to what the TPU path needs: a plain g++ shared
object with no MPI/CUDA/NCCL discovery (XLA is the data plane). Invoked
lazily on first import and cached by source mtime.

Usage: ``python -m horovod_tpu.runtime.build [--force]``
"""

from __future__ import annotations

import os
import subprocess
import sys

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_OUT = os.path.join(os.path.dirname(__file__), "libhorovod_tpu_core.so")

SOURCES = [
    "message.cc",
    "coordinator.cc",
    "controller.cc",
    "fusion_buffer.cc",
    "logging.cc",
    "half.cc",
    "timeline.cc",
    "gaussian_process.cc",
    "bayesian_optimization.cc",
    "parameter_manager.cc",
    "core.cc",
]


def _stale() -> bool:
    if not os.path.exists(_OUT):
        return True
    out_mtime = os.path.getmtime(_OUT)
    for fn in os.listdir(_SRC_DIR):
        if fn.endswith((".cc", ".h")):
            if os.path.getmtime(os.path.join(_SRC_DIR, fn)) > out_mtime:
                return True
    return False


def build(force: bool = False, verbose: bool = False) -> str:
    """Compile the native core if missing/stale; returns the .so path."""
    if not force and not _stale():
        return _OUT
    srcs = [os.path.join(_SRC_DIR, s) for s in SOURCES]
    cmd = [
        "g++", "-std=c++17", "-O2", "-fPIC", "-shared", "-pthread",
        "-Wall", "-Wno-unused-function",
        # Version script exports only the hvdtpu_* C API — the role of
        # horovod.lds (reference N15): internal symbols stay local so the
        # .so coexists with other native extensions.
        f"-Wl,--version-script={os.path.join(_SRC_DIR, 'core.lds')}",
        "-o", _OUT, *srcs,
    ]
    if verbose:
        print(" ".join(cmd), file=sys.stderr)
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native core build failed:\n{proc.stderr[-4000:]}")
    return _OUT


if __name__ == "__main__":
    force = "--force" in sys.argv
    path = build(force=force, verbose=True)
    print(path)
