// Multi-process controller — the rank-0 coordinator of the reference's
// RunLoopOnce (horovod/common/operations.cc:2030-2380) as a native object
// behind a C API, driven by the Python TCP service (ops/control_plane.py).
//
// The reference's coordinator gathers serialized MPIRequestLists from every
// rank each cycle (MPI_Gather/Gatherv, operations.cc:2088-2134), counts
// announcements in a MessageTable (IncrementTensorCount, :287-313),
// validates cross-rank consistency (ConstructMPIResponse, :321-523), fuses
// ready tensors with look-ahead (:2149-2265), and broadcasts the ordered
// MPIResponseList (:2282-2287). This controller is that exact pipeline:
// the transport is the launcher's HMAC TCP RPC instead of MPI, the wire
// format is message.cc's codec (the N2 equivalent), and the planner is
// coordinator.cc's MessageTable/ConstructResponse/FuseResponses — ONE
// planner and ONE wire for cross-process negotiation.
//
// It also owns the cross-process autotuner (parameter_manager.cc:64-78,
// 213-246 SyncParams role): the controller tunes (fusion threshold, cycle
// time, hierarchical flag) from observed throughput; plan-affecting flags
// are stamped into each Response (SPMD-safe lockstep), and scalar knobs are
// served to workers through the fetch RPC.
//
// Threading: all entry points lock the controller mutex; the Python service
// calls from its handler threads. Long-poll waiting lives in Python (the
// service's condition variable), not here.

#include <algorithm>
#include <chrono>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "coordinator.h"
#include "logging.h"
#include "message.h"
#include "parameter_manager.h"

namespace hvdtpu {
namespace {

using Clock = std::chrono::steady_clock;

struct Controller {
  std::mutex mu;
  int nproc = 1;
  int virtual_size = 1;
  bool shutdown = false;

  MessageTable table;
  // Payload bytes and dtypes for fusion planning, keyed by tensor name
  // (the byte totals the reference reads off TensorTableEntry).
  std::unordered_map<std::string, int64_t> sizes_bytes;
  std::unordered_map<std::string, DataType> dtypes;

  // Fully-announced tensors awaiting planning. Groups are cut only when
  // the announce stream is QUIESCENT — no tensor partially announced and
  // no announce for >= plan_debounce_s (hvdtpu_ctl_maybe_plan, driven by
  // the service's fetch long-poll) — or via the fetch-timeout valve
  // (hvdtpu_ctl_plan). Planning eagerly on each announce would cut
  // groups at arbitrary announce-chunk boundaries (worker cycles drain
  // mid-burst), and on TPU every distinct group composition is a
  // distinct fused XLA program — nondeterministic chunking means a
  // recompile per step instead of a cache hit.
  std::deque<Response> pending;
  Clock::time_point last_announce = Clock::now();
  // When the oldest currently-pending response became ready. Bounds how
  // long quiescence-deferral can starve fully-announced work: under
  // continuously overlapping announce bursts (async submission, pipelined
  // steps) last_announce keeps refreshing and the quiet window never
  // opens, so maybe_plan cuts unconditionally once the oldest pending
  // response has waited kMaxDeferFactor debounce windows — mirroring the
  // client-side kDrainMaxDeferNs escape hatch in core.cc.
  Clock::time_point oldest_pending = Clock::now();
  bool has_pending_ts = false;
  // Quiet window before cutting groups; must match the Python fallback
  // service (ops/control_plane.py PLAN_DEBOUNCE_S) so both planners see
  // the same stream shape.
  double plan_debounce_s = 0.002;
  static constexpr double kMaxDeferFactor = 10.0;

  // Ordered group log. Serialized lazily at fetch; kept as objects so the
  // stall report and tests can inspect them. Pruned once every rank acked.
  std::vector<Response> groups;
  int64_t base_seq = 0;
  std::unordered_map<int32_t, int64_t> acked;

  // Autotuning (N5/N6): tuner lives HERE, on the coordinator, exactly as
  // the reference's (parameter_manager.cc:64-78). Hierarchical flags are
  // stamped per group; fusion threshold applies to this planner directly.
  ParameterManager pm;
  int64_t fusion_threshold = 64LL * 1024 * 1024;
  double cycle_time_ms = 1.0;
  bool env_hier_allgather = false;
  bool env_hier_allreduce = false;
  int64_t bytes_since_tick = 0;
  Clock::time_point last_tick = Clock::now();

  double stall_warning_sec = 60.0;
};

int32_t CurrentFlags(Controller& c) {
  int32_t f = 0;
  bool tuning = c.pm.IsAutoTuning();
  if (c.env_hier_allreduce || (tuning && c.pm.HierarchicalAllreduce()))
    f |= Response::HIERARCHICAL_ALLREDUCE;
  if (c.env_hier_allgather || (tuning && c.pm.HierarchicalAllgather()))
    f |= Response::HIERARCHICAL_ALLGATHER;
  return f;
}

// Plan every pending fully-announced tensor into fused response groups and
// append them to the group log (the coordinator half of RunLoopOnce).
void PlanLocked(Controller& c) {
  if (c.pending.empty()) return;
  std::deque<Response> ready;
  ready.swap(c.pending);
  c.has_pending_ts = false;
  auto plans = FuseResponses(std::move(ready), c.sizes_bytes, c.dtypes,
                             c.fusion_threshold);
  int32_t flags = CurrentFlags(c);
  for (auto& resp : plans) {
    resp.flags = flags;
    for (const auto& n : resp.tensor_names) {
      auto it = c.sizes_bytes.find(n);
      if (it != c.sizes_bytes.end()) {
        c.bytes_since_tick += it->second;
        c.sizes_bytes.erase(it);  // names are per-op unique: drop planned
      }
      c.dtypes.erase(n);  // entries or coordinator memory grows forever
    }
    c.groups.push_back(std::move(resp));
  }
}

}  // namespace
}  // namespace hvdtpu

using namespace hvdtpu;

extern "C" {

void* hvdtpu_ctl_create(int nproc, int virtual_size,
                        int64_t fusion_threshold, double cycle_time_ms,
                        double stall_warning_sec, int hier_allreduce,
                        int hier_allgather, int autotune,
                        const char* autotune_log) {
  auto* c = new Controller();
  c->nproc = nproc;
  c->virtual_size = virtual_size > 0 ? virtual_size : nproc;
  c->fusion_threshold = fusion_threshold;
  c->cycle_time_ms = cycle_time_ms;
  c->stall_warning_sec = stall_warning_sec;
  c->env_hier_allreduce = hier_allreduce != 0;
  c->env_hier_allgather = hier_allgather != 0;
  if (autotune) {
    c->pm.Initialize(0, autotune_log ? autotune_log : "");
    c->pm.SetCurrent(fusion_threshold / (1024.0 * 1024.0), cycle_time_ms);
    c->pm.SetAutoTuning(true);
  }
  return c;
}

void hvdtpu_ctl_destroy(void* h) { delete static_cast<Controller*>(h); }

// Feed one process's serialized RequestList. Returns the new total group
// count (base_seq + groups), or -1 on parse failure. Idempotency across
// RPC retries is enforced by the Python service layer (announce ids).
int64_t hvdtpu_ctl_announce(void* h, const uint8_t* data, int64_t len) {
  auto* c = static_cast<Controller*>(h);
  RequestList rl;
  if (!RequestList::ParseFrom(data, static_cast<size_t>(len), &rl))
    return -1;
  std::lock_guard<std::mutex> lk(c->mu);
  if (rl.shutdown) {
    // Any rank announcing shutdown stops the world — the reference ORs
    // the flag into the response list (operations.cc:2125-2128).
    c->shutdown = true;
    return c->base_seq + static_cast<int64_t>(c->groups.size());
  }
  for (auto& req : rl.requests) {
    const std::string name = req.tensor_name;
    c->sizes_bytes[name] =
        req.tensor_shape.num_elements() * DataTypeSize(req.tensor_type);
    c->dtypes[name] = req.tensor_type;
    if (c->table.Increment(req, c->nproc)) {
      auto reqs = c->table.Take(name);
      if (c->pending.empty() && !c->has_pending_ts) {
        c->oldest_pending = Clock::now();
        c->has_pending_ts = true;
      }
      c->pending.push_back(
          ConstructResponse(reqs, c->nproc, c->virtual_size));
    }
  }
  c->last_announce = Clock::now();
  return c->base_seq + static_cast<int64_t>(c->groups.size());
}

// Quiescence planner, polled from the service's fetch long-poll: cut
// groups once no tensor is partially announced and the announce stream
// has been quiet for the debounce window (all ranks' cycle-chunked
// announces of one burst have landed). Returns the total group count.
int64_t hvdtpu_ctl_maybe_plan(void* h) {
  auto* c = static_cast<Controller*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  auto now = Clock::now();
  bool quiet =
      c->table.size() == 0 &&
      std::chrono::duration<double>(now - c->last_announce).count() >=
          c->plan_debounce_s;
  // Bounded valve: never let continuous announce traffic defer ready
  // work past kMaxDeferFactor debounce windows.
  bool overdue =
      c->has_pending_ts &&
      std::chrono::duration<double>(now - c->oldest_pending).count() >=
          c->plan_debounce_s * Controller::kMaxDeferFactor;
  if (!c->pending.empty() && (quiet || overdue)) PlanLocked(*c);
  return c->base_seq + static_cast<int64_t>(c->groups.size());
}

// Eager planner for burst-complete announces: when a worker declares its
// announce a COMPLETE burst and no tensor is left partially announced,
// every rank's burst has landed in full — the group composition is
// already the whole burst, so cut it NOW instead of waiting out the
// quiet window (the window exists only to guard against mid-burst
// chunking, which a complete marker rules out). Returns the group count.
int64_t hvdtpu_ctl_plan_ready(void* h) {
  auto* c = static_cast<Controller*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  if (!c->pending.empty() && c->table.size() == 0) PlanLocked(*c);
  return c->base_seq + static_cast<int64_t>(c->groups.size());
}

// Fetch-timeout valve: plan whatever is fully announced even though some
// tensor is still partial (a lingering partial must not stall ready
// work — the reference plans per coordinator cycle regardless,
// operations.cc:2142-2147). Returns the new total group count.
int64_t hvdtpu_ctl_plan(void* h) {
  auto* c = static_cast<Controller*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  PlanLocked(*c);
  return c->base_seq + static_cast<int64_t>(c->groups.size());
}

int64_t hvdtpu_ctl_group_count(void* h) {
  auto* c = static_cast<Controller*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  return c->base_seq + static_cast<int64_t>(c->groups.size());
}

// First un-pruned sequence number (observability/test surface).
int64_t hvdtpu_ctl_base_seq(void* h) {
  auto* c = static_cast<Controller*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  return c->base_seq;
}

int hvdtpu_ctl_shutdown_flag(void* h) {
  auto* c = static_cast<Controller*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  return c->shutdown ? 1 : 0;
}

// Serialize all groups with seq >= after_seq into a ResponseList (the
// response-list Bcast, operations.cc:2282-2287). Also records the caller's
// ack (after_seq), pruning history once every rank has acked (a days-long
// job must not grow coordinator memory linearly). Returns bytes written,
// or -(needed) when the buffer is too small.
int64_t hvdtpu_ctl_fetch(void* h, int32_t rank, int64_t after_seq,
                         uint8_t* out, int64_t cap) {
  auto* c = static_cast<Controller*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  auto it = c->acked.find(rank);
  if (it == c->acked.end() || it->second < after_seq)
    c->acked[rank] = after_seq;
  if (static_cast<int>(c->acked.size()) == c->nproc) {
    int64_t floor = INT64_MAX;
    for (const auto& kv : c->acked) floor = std::min(floor, kv.second);
    if (floor > c->base_seq) {
      int64_t drop = std::min<int64_t>(floor - c->base_seq,
                                       static_cast<int64_t>(c->groups.size()));
      c->groups.erase(c->groups.begin(), c->groups.begin() + drop);
      c->base_seq += drop;
    }
  }
  ResponseList out_list;
  out_list.shutdown = c->shutdown;
  int64_t start = std::max<int64_t>(0, after_seq - c->base_seq);
  for (size_t i = static_cast<size_t>(start); i < c->groups.size(); ++i)
    out_list.responses.push_back(c->groups[i]);
  std::vector<uint8_t> buf;
  out_list.SerializeTo(&buf);
  if (static_cast<int64_t>(buf.size()) > cap)
    return -static_cast<int64_t>(buf.size());
  std::memcpy(out, buf.data(), buf.size());
  return static_cast<int64_t>(buf.size());
}

// Autotune tick — called once per coordinator-side engine cycle. Feeds the
// tuner the bytes planned since the last tick over the elapsed wall time
// (the reference scores bytes over the whole cycle interval,
// parameter_manager.cc:144-170). Applies a changed fusion threshold to the
// planner; scalar knobs are read back via hvdtpu_ctl_params.
void hvdtpu_ctl_tick(void* h) {
  auto* c = static_cast<Controller*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  auto now = Clock::now();
  double secs = std::chrono::duration<double>(now - c->last_tick).count();
  c->last_tick = now;
  int64_t bytes = c->bytes_since_tick;
  c->bytes_since_tick = 0;
  if (!c->pm.IsAutoTuning()) return;
  if (c->pm.Update(bytes, secs)) {
    c->fusion_threshold = c->pm.TensorFusionThresholdBytes();
    c->cycle_time_ms = c->pm.CycleTimeMs();
  }
}

// Global-autotuner fusion move: the coordinator-side arbiter accepted a
// new cap, so this planner must cut future groups with it (the Python
// fallback planner reads CoordinatorService.fusion_threshold directly;
// the native planner's copy lives behind this handle).
void hvdtpu_ctl_set_fusion_threshold(void* h, int64_t bytes) {
  auto* c = static_cast<Controller*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  c->fusion_threshold = bytes;
}

// Current (possibly tuned) knobs, served to workers in the fetch RPC so
// every process flips scalar knobs in lockstep (SyncParams,
// parameter_manager.cc:213-246).
void hvdtpu_ctl_params(void* h, int64_t* fusion_bytes, double* cycle_ms,
                       int32_t* flags, int32_t* autotune_active,
                       int32_t* autotune_done) {
  auto* c = static_cast<Controller*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  if (fusion_bytes) *fusion_bytes = c->fusion_threshold;
  if (cycle_ms) *cycle_ms = c->cycle_time_ms;
  if (flags) *flags = CurrentFlags(*c);
  if (autotune_active) *autotune_active = c->pm.IsAutoTuning() ? 1 : 0;
  if (autotune_done) *autotune_done = c->pm.IsDone() ? 1 : 0;
}

// Stall report: tensors announced by only a subset of ranks for longer
// than the warning window, naming ready and missing ranks — the
// coordinator's diagnostic (CheckForStalledTensors, operations.cc:
// 1625-1672). Lines are newline-joined; returns bytes written (0 if
// nothing stalled), or -(needed) if cap is too small.
int64_t hvdtpu_ctl_stalled(void* h, uint8_t* out, int64_t cap) {
  auto* c = static_cast<Controller*>(h);
  std::vector<std::string> lines;
  {
    std::lock_guard<std::mutex> lk(c->mu);
    if (c->stall_warning_sec <= 0) return 0;
    lines = c->table.StalledTensors(c->nproc, c->stall_warning_sec);
  }
  std::string joined;
  for (const auto& l : lines) {
    if (!joined.empty()) joined += "\n";
    joined += l;
  }
  if (static_cast<int64_t>(joined.size()) > cap)
    return -static_cast<int64_t>(joined.size());
  std::memcpy(out, joined.data(), joined.size());
  return static_cast<int64_t>(joined.size());
}

}  // extern "C"
