// Core runtime / background thread — TPU-native equivalent of
// horovod/common/operations.{h,cc} (N3), exposed as a C API for ctypes.
//
// Architecture: the reference's background thread owns negotiation, tensor
// fusion and the MPI/NCCL calls (operations.cc:1695-1999, 2030-2380). On
// TPU the data plane is XLA — collectives execute as jitted programs
// launched from Python — so the native runtime keeps everything *around*
// the collective: the tensor table with duplicate-name rejection
// (operations.cc:270-273, 2472-2509), the cycle timer, negotiation via
// MessageTable + ConstructResponse, fusion planning with look-ahead
// (operations.cc:2149-2265), the timeline, stall detection, and the
// autotuner. Execution requests flow to Python through a registered
// callback (the role the PerformOperation dispatch plays in the reference);
// Python reports completion back so the runtime can close timeline events,
// clear in-flight names, and feed the autotuner.
//
// Threading: one background thread per process (operations.cc:109-114); a
// single mutex guards queue+table (operations.cc:120-127); the execute
// callback is invoked WITHOUT the lock held (it re-enters Python, which
// takes the GIL).

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <unordered_map>

#include <algorithm>
#include <cstdio>
#include <cstdlib>

#include "common.h"
#include "coordinator.h"
#include "half.h"
#include "fusion_buffer.h"
#include "logging.h"
#include "message.h"
#include "parameter_manager.h"
#include "timeline.h"

namespace hvdtpu {
namespace {

using Clock = std::chrono::steady_clock;

typedef void (*ExecuteCallback)(void* user, int32_t op,
                                const int64_t* handles, int32_t count,
                                const char* error_message);

// Multi-process transport bridge (the MPI_Gatherv/Bcast legs of the
// reference cycle, operations.cc:2324-2345, carried by Python over the
// launcher's TCP control plane). The background thread hands Python this
// process's serialized RequestList; Python announces it to the rank-0
// controller and long-polls the agreed ResponseList, whose bytes it
// writes into resp_buf. Returns bytes written, 0 for "nothing yet", or
// -(needed) when resp_cap is too small (the cycle retries with a larger
// buffer).
// `complete` is 1 when the drained batch is a COMPLETE enqueue burst
// (drained after debounce-quiet or an explicit flush hint, not by the
// max-defer valve) — the coordinator may plan eagerly the moment every
// rank's complete announce has landed, skipping its own quiet window.
typedef int64_t (*TransportCallback)(void* user, const uint8_t* req_bytes,
                                     int64_t req_len, int32_t nreq,
                                     int32_t complete, int64_t pending,
                                     uint8_t* resp_buf, int64_t resp_cap);

// Delivery of one coordinator-agreed group to Python for XLA execution
// (the PerformOperation dispatch, operations.cc:768-791). `nnames` is the
// group's tensor count as planned; `count` the handles found locally —
// a mismatch means local/coordinator desync, which Python treats as fatal
// rather than skipping a collective its peers will enter. `sizes` carries
// the per-rank first dims for allgather (nnames * nproc entries in
// tensor_names order); `flags` the plan-time execution-mode bits.
typedef void (*GroupCallback)(void* user, int32_t op, const int64_t* handles,
                              int32_t count, int32_t nnames,
                              const int64_t* sizes, int32_t nsizes,
                              int32_t flags, const char* error_message);

struct PendingEntry {
  int64_t handle;
  Request request;
  int64_t nbytes;
  Clock::time_point enqueued;
  bool executing = false;  // negotiated & handed to the execute callback
};

struct HandleState {
  std::string name;
  int32_t status = -1;  // -1 in flight; else StatusType
  std::string reason;
};

struct GlobalState {
  std::mutex mu;
  std::atomic<bool> initialized{false};
  std::atomic<bool> shutdown_requested{false};
  bool background_done = false;
  std::condition_variable shutdown_cv;

  int rank = 0, size = 1, local_size = 1, virtual_size = 1;

  std::thread background;

  // Message queue + tensor table (operations.cc:120-143).
  std::deque<PendingEntry> message_queue;
  std::unordered_map<std::string, PendingEntry> tensor_table;  // in flight
  std::unordered_map<int64_t, HandleState> handles;
  int64_t next_handle = 1;

  MessageTable message_table;

  ExecuteCallback execute_cb = nullptr;
  void* execute_user = nullptr;
  TransportCallback transport_cb = nullptr;
  void* transport_user = nullptr;
  GroupCallback group_cb = nullptr;
  void* group_user = nullptr;

  // Knobs (operations.cc:1824-1909).
  std::atomic<int64_t> fusion_threshold{64LL * 1024 * 1024};
  std::atomic<int64_t> cycle_time_us{1000};
  double stall_warning_sec = 60.0;  // STALL_WARNING_TIME operations.cc:258
  Clock::time_point last_stall_check = Clock::now();

  Timeline timeline;
  FusionBufferManager fusion_buffers;
  ParameterManager param_manager;

  // Cycle stats for the autotuner.
  std::atomic<int64_t> cycle_bytes{0};

  // Enqueue-burst debounce: steady_clock nanos of the newest and oldest
  // queued request. A cycle defers draining while a burst is still
  // arriving (< kDrainDebounceNs since the last enqueue) so one training
  // step's requests always fuse into the same groups — every distinct
  // group composition is a distinct fused XLA program, and timing-
  // dependent chunking would mean a fresh compile per step instead of a
  // cache hit. kDrainMaxDeferNs bounds the wait so a continuous enqueue
  // stream cannot starve dispatch, and a queue that did not GROW since
  // the previous check drains immediately — a lone blocking caller's
  // single request must not pay the debounce (its submitter is stuck on
  // the handle; no burst can follow).
  std::atomic<int64_t> last_enqueue_ns{0};
  std::atomic<int64_t> oldest_enqueue_ns{0};
  size_t last_seen_qlen = 0;  // background thread only

  // Flush hint (hvdtpu_flush): a submitter about to block on a handle
  // declares its burst fully enqueued — the cycle drains NOW instead of
  // waiting out the debounce, and the cycle's pacing sleep is interrupted
  // via cycle_cv so the drain starts immediately.
  std::atomic<bool> flush_hint{false};
  // Explicit burst scope (hvdtpu_burst_begin/end): while a submitter has
  // a burst open, the drain defers REGARDLESS of queue growth. The
  // growth heuristic alone misfires on an oversubscribed host: the
  // enqueueing thread gets descheduled mid-burst for > the debounce
  // window, the cycle sees "stopped growing" and drains a PARTIAL burst
  // — a new fusion composition, hence a fresh XLA compile, every step.
  std::atomic<int32_t> burst_depth{0};
  // Burst-scope owner threads (per-thread open-scope count). A flush
  // hint from a thread that owns NO open scope — a foreign waiter
  // blocking on a handle while another thread's scope is open — must
  // cut the scope instead of being consumed, or the waiter stalls until
  // the 1 s burst valve fires (a per-op latency landmine).
  std::mutex burst_owner_mu;
  std::unordered_map<std::thread::id, int32_t> burst_owners;
  std::atomic<bool> foreign_flush{false};
  std::condition_variable cycle_cv;
  std::mutex cycle_mu;
};

constexpr int64_t kDrainDebounceNs = 2'000'000;    // 2 ms
constexpr int64_t kDrainMaxDeferNs = 20'000'000;   // 20 ms
// Explicit burst scopes get a much larger valve: the submitter's
// burst_end IS the drain boundary, and on an oversubscribed host a
// 50-leaf enqueue loop alone can take > 20 ms of wall time. Cutting it
// mid-scope makes the group composition (and the quantized fusion-buffer
// sizes) timing-dependent — a fresh XLA compile per step. The valve only
// guards against a submitter that hangs inside an open scope.
constexpr int64_t kBurstMaxDeferNs = 1'000'000'000;  // 1 s

int64_t NowNs() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             Clock::now().time_since_epoch())
      .count();
}

// True while an enqueue burst is still arriving (defer the drain). When
// returning false (drain now), *complete reports whether the drained
// batch is a COMPLETE burst: true for debounce-quiet / flush-hint /
// stopped-growing drains, false only for the max-defer valve (the burst
// may still be arriving).
bool DrainShouldDefer(GlobalState& st, bool* complete) {
  *complete = true;
  if (st.shutdown_requested.load()) return false;  // drain for teardown
  std::lock_guard<std::mutex> lk(st.mu);
  size_t qlen = st.message_queue.size();
  size_t last = st.last_seen_qlen;
  st.last_seen_qlen = qlen;
  if (st.burst_depth.load() > 0 && qlen > 0) {
    // Submitter declared a burst open: defer regardless of growth (the
    // growth heuristic misfires when the enqueuer is descheduled on a
    // busy host), bounded by the burst valve. The scope OWNER's flush
    // hint is consumed here — the open scope supersedes it (its own
    // burst_end will flush), and leaving it set would defeat
    // CycleSleep's pacing for the rest of the scope (a hot spin). A
    // FOREIGN waiter's hint (a thread with no open scope blocking on a
    // handle, hvdtpu_flush) cuts the scope instead: stalling that
    // waiter until the 1 s valve is a worse failure mode than one
    // timing-dependent group composition.
    st.flush_hint.store(false);
    if (st.foreign_flush.exchange(false)) {
      *complete = false;  // mid-scope cut: the burst may still be arriving
      return false;
    }
    if (NowNs() - st.oldest_enqueue_ns.load() >= kBurstMaxDeferNs) {
      *complete = false;
      return false;
    }
    return true;
  }
  // No open scope. Clear a foreign mark ONLY together with consuming
  // its paired flush hint — hvdtpu_flush stores foreign_flush first,
  // then flush_hint, and a cycle landing between the two stores must
  // not wipe the mark (the waiter hints only once; losing the mark and
  // then having a scope open re-creates the 1 s stall). A mark whose
  // hint has not landed yet survives to the next cycle.
  if (st.flush_hint.exchange(false)) {
    st.foreign_flush.store(false);
    return false;  // submitter says done
  }
  if (qlen == 0) return false;
  if (qlen <= last) return false;  // burst stopped growing: drain now
  int64_t now = NowNs();
  if (now - st.oldest_enqueue_ns.load() >= kDrainMaxDeferNs) {
    *complete = false;
    return false;
  }
  return now - st.last_enqueue_ns.load() < kDrainDebounceNs;
}

// Pace out the remainder of the cycle, interruptibly: a flush hint or
// shutdown wakes the sleep so a known-complete burst drains immediately
// instead of waiting out the cycle timer.
void CycleSleep(GlobalState& st, Clock::time_point cycle_start) {
  auto elapsed = Clock::now() - cycle_start;
  auto cycle = std::chrono::microseconds(st.cycle_time_us.load());
  if (elapsed >= cycle) return;
  std::unique_lock<std::mutex> lk(st.cycle_mu);
  st.cycle_cv.wait_for(lk, cycle - elapsed, [&] {
    return st.flush_hint.load() || st.shutdown_requested.load();
  });
}

GlobalState* g_state = nullptr;

void EmitTimelineStartGroup(GlobalState& st, const Response& resp) {
  static const char* kOpName[] = {"ALLREDUCE", "ALLGATHER", "BROADCAST"};
  if (!st.timeline.Initialized()) return;
  for (const auto& name : resp.tensor_names) {
    st.timeline.NegotiateEnd(name);
    if (resp.response_type != Response::ERROR) {
      st.timeline.Start(name, kOpName[resp.response_type]);
      st.timeline.ActivityStart(name, "QUEUE");
    }
  }
}

// Deliver the coordinator's agreed groups to Python (multi-process mode).
// Mirrors the worker half of the reference cycle after the response Bcast
// (operations.cc:2361-2377): every process executes the SAME groups in the
// SAME order — here as jitted SPMD programs launched by the group callback.
void HandleResponsesMP(GlobalState& st, ResponseList& list) {
  GroupCallback cb;
  void* user;
  {
    std::lock_guard<std::mutex> lk(st.mu);
    cb = st.group_cb;
    user = st.group_user;
  }
  if (list.shutdown) {
    // A peer announced shutdown — possibly from its teardown path, in
    // which case it will never enter the SPMD programs for the groups
    // delivered alongside the flag. Executing them could hang this rank
    // in an XLA collective, so fail EVERYTHING not yet executing with
    // SHUT_DOWN_ERROR (matching the reference's drain of queued tensors,
    // operations.cc:1942-1998, and the Python fallback's behavior —
    // mixed fleets must make the same call or they deadlock each other).
    std::vector<int64_t> hs;
    {
      std::lock_guard<std::mutex> lk(st.mu);
      for (const auto& kv : st.tensor_table)
        if (!kv.second.executing) hs.push_back(kv.second.handle);
      st.message_queue.clear();
    }
    if (!hs.empty() && cb)
      cb(user, static_cast<int32_t>(Response::ERROR), hs.data(),
         static_cast<int32_t>(hs.size()), static_cast<int32_t>(hs.size()),
         nullptr, 0, 0,
         "Horovod has been shut down. This was caused by an exception on "
         "one of the ranks or an attempt to run a collective after one of "
         "the ranks finished execution.");
    st.shutdown_requested.store(true);
    return;
  }
  for (auto& resp : list.responses) {
    EmitTimelineStartGroup(st, resp);
    std::vector<int64_t> hs;
    {
      std::lock_guard<std::mutex> lk(st.mu);
      for (const auto& name : resp.tensor_names) {
        auto it = st.tensor_table.find(name);
        if (it != st.tensor_table.end()) {
          it->second.executing = true;
          hs.push_back(it->second.handle);
        }
      }
    }
    if (cb)
      cb(user, static_cast<int32_t>(resp.response_type), hs.data(),
         static_cast<int32_t>(hs.size()),
         static_cast<int32_t>(resp.tensor_names.size()),
         resp.tensor_sizes.data(),
         static_cast<int32_t>(resp.tensor_sizes.size()), resp.flags,
         resp.error_message.c_str());
  }
}

// Multi-process cycle: serialize the drained batch, hand it to the Python
// transport (announce + long-poll fetch over TCP), parse the agreed
// ResponseList, dispatch groups. The reference's RunLoopOnce worker half
// (operations.cc:2323-2377) with message.cc's codec as the wire format.
bool RunLoopOnceMP(GlobalState& st) {
  auto cycle_start = Clock::now();
  st.timeline.MarkCycleStart();

  // Burst debounce, as in RunLoopOnce: announcing a partial burst would
  // chunk the coordinator's view and destabilize fusion groups. While
  // deferring, skip the transport leg entirely — its fetch long-poll
  // would hold the rest of the burst back for up to 50 ms.
  bool complete = true;
  if (DrainShouldDefer(st, &complete)) {
    CycleSleep(st, cycle_start);
    return true;  // next cycle drains (defer is max-defer bounded)
  }
  std::deque<PendingEntry> batch;
  {
    std::lock_guard<std::mutex> lk(st.mu);
    batch = std::move(st.message_queue);
    st.message_queue.clear();
    st.last_seen_qlen = 0;
  }
  RequestList rl;
  for (auto& pe : batch) rl.requests.push_back(pe.request);

  int64_t pending;
  TransportCallback cb;
  void* user;
  {
    std::lock_guard<std::mutex> lk(st.mu);
    pending = static_cast<int64_t>(st.tensor_table.size());
    cb = st.transport_cb;
    user = st.transport_user;
  }

  if (cb && (!rl.requests.empty() || pending > 0)) {
    std::vector<uint8_t> req_buf;
    rl.SerializeTo(&req_buf);
    static thread_local std::vector<uint8_t> resp_buf(1 << 20);
    int64_t n = cb(user, req_buf.data(),
                   static_cast<int64_t>(req_buf.size()),
                   static_cast<int32_t>(rl.requests.size()),
                   complete ? 1 : 0, pending,
                   resp_buf.data(), static_cast<int64_t>(resp_buf.size()));
    if (n < 0) {
      resp_buf.resize(static_cast<size_t>(-n));
      n = cb(user, req_buf.data(), static_cast<int64_t>(req_buf.size()),
             0 /*already announced*/, complete ? 1 : 0, pending,
             resp_buf.data(), static_cast<int64_t>(resp_buf.size()));
    }
    if (n > 0) {
      ResponseList list;
      if (ResponseList::ParseFrom(resp_buf.data(), static_cast<size_t>(n),
                                  &list)) {
        HandleResponsesMP(st, list);
      } else {
        HVD_LOG(WARNING) << "could not parse coordinator response list ("
                         << n << " bytes); skipping cycle";
      }
    }
  }

  // Local stall hint (names only): the coordinator's fetch responses carry
  // the authoritative missing-ranks report (hvdtpu_ctl_stalled), which
  // Python logs on every process.
  if (st.stall_warning_sec > 0) {
    auto now = Clock::now();
    if (std::chrono::duration<double>(now - st.last_stall_check).count() >
        st.stall_warning_sec) {
      st.last_stall_check = now;
      std::vector<std::string> stalled;
      {
        std::lock_guard<std::mutex> lk(st.mu);
        for (const auto& kv : st.tensor_table)
          if (!kv.second.executing) {
            double age =
                std::chrono::duration<double>(now - kv.second.enqueued)
                    .count();
            if (age > st.stall_warning_sec) stalled.push_back(kv.first);
          }
      }
      if (!stalled.empty()) {
        std::string names;
        for (const auto& n : stalled)
          names += (names.empty() ? "" : ", ") + n;
        HVD_LOG(WARNING)
            << "One or more tensors were submitted to be reduced, gathered "
            << "or broadcasted by subset of ranks and are waiting for "
            << "remainder of ranks for more than " << st.stall_warning_sec
            << " seconds. Stalled ops: " << names;
      }
    }
  }

  if (st.shutdown_requested.load()) {
    std::lock_guard<std::mutex> lk(st.mu);
    if (st.message_queue.empty()) return false;
  }

  CycleSleep(st, cycle_start);
  return true;
}

// One cycle of the background loop (RunLoopOnce, operations.cc:2030-2380).
// Returns false when shutdown was requested and the queue is drained.
bool RunLoopOnce(GlobalState& st) {
  auto cycle_start = Clock::now();
  st.timeline.MarkCycleStart();

  // Drain local queue under lock (operations.cc:2050-2058) — unless an
  // enqueue burst is still arriving (DrainShouldDefer): draining
  // mid-burst would cut timing-dependent fusion groups and recompile
  // their XLA programs every step.
  std::deque<PendingEntry> batch;
  bool complete = true;
  if (!DrainShouldDefer(st, &complete)) {
    std::lock_guard<std::mutex> lk(st.mu);
    batch = std::move(st.message_queue);
    st.message_queue.clear();
    st.last_seen_qlen = 0;
  }

  // Negotiation: every enqueue on the single-controller path announces the
  // tensor for ALL local virtual ranks at once, so readiness counting runs
  // at process granularity. With one process (size_procs == 1) tensors are
  // ready immediately; the multi-host controller feeds remote request
  // lists into the same MessageTable.
  std::deque<Response> ready;
  std::unordered_map<std::string, int64_t> sizes;
  std::unordered_map<std::string, DataType> dtypes;
  std::unordered_map<std::string, std::vector<int64_t>> handle_of;
  {
    std::lock_guard<std::mutex> lk(st.mu);
    for (auto& pe : batch) {
      bool all_ready = st.message_table.Increment(pe.request, /*size=*/1);
      sizes[pe.request.tensor_name] = pe.nbytes;
      dtypes[pe.request.tensor_name] = pe.request.tensor_type;
      handle_of[pe.request.tensor_name].push_back(pe.handle);
      if (all_ready) {
        auto reqs = st.message_table.Take(pe.request.tensor_name);
        ready.push_back(ConstructResponse(reqs, 1, st.virtual_size));
      }
    }
  }

  if (!ready.empty()) {
    // Fusion planning with look-ahead (operations.cc:2149-2265).
    auto plans = FuseResponses(std::move(ready), sizes, dtypes,
                               st.fusion_threshold.load());

    for (auto& resp : plans) {
      EmitTimelineStartGroup(st, resp);
      std::vector<int64_t> hs;
      int64_t bytes = 0;
      {
        std::lock_guard<std::mutex> lk(st.mu);
        for (const auto& name : resp.tensor_names) {
          auto it = st.tensor_table.find(name);
          if (it != st.tensor_table.end()) it->second.executing = true;
        }
      }
      for (const auto& name : resp.tensor_names) {
        for (int64_t h : handle_of[name]) hs.push_back(h);
        bytes += sizes.count(name) ? sizes[name] : 0;
      }
      st.cycle_bytes.fetch_add(bytes);
      ExecuteCallback cb;
      void* user;
      {
        std::lock_guard<std::mutex> lk(st.mu);
        cb = st.execute_cb;
        user = st.execute_user;
      }
      if (resp.response_type == Response::ERROR) {
        // Mismatch verdicts are delivered to the callback as errors so the
        // owner can fail the handles (operations.cc:1613-1620 semantics).
        if (cb) cb(user, static_cast<int32_t>(resp.response_type), hs.data(),
                   static_cast<int32_t>(hs.size()),
                   resp.error_message.c_str());
      } else if (cb) {
        cb(user, static_cast<int32_t>(resp.response_type), hs.data(),
           static_cast<int32_t>(hs.size()), "");
      }
    }
  }

  // Stall detection (CheckForStalledTensors, operations.cc:1625-1672).
  if (st.stall_warning_sec > 0) {
    auto now = Clock::now();
    if (std::chrono::duration<double>(now - st.last_stall_check).count() >
        st.stall_warning_sec) {
      st.last_stall_check = now;
      std::vector<std::string> stalled;
      {
        std::lock_guard<std::mutex> lk(st.mu);
        for (const auto& kv : st.tensor_table) {
          // Only un-negotiated tensors count — the reference scans its
          // MessageTable, not ops already executing
          // (CheckForStalledTensors, operations.cc:1625-1672).
          if (kv.second.executing) continue;
          double age = std::chrono::duration<double>(now - kv.second.enqueued)
                           .count();
          if (age > st.stall_warning_sec) stalled.push_back(kv.first);
        }
      }
      if (!stalled.empty()) {
        std::string names;
        for (const auto& n : stalled) names += (names.empty() ? "" : ", ") + n;
        HVD_LOG(WARNING)
            << "One or more tensors were submitted to be reduced, gathered "
            << "or broadcasted by subset of ranks and are waiting for "
            << "remainder of ranks for more than " << st.stall_warning_sec
            << " seconds. Stalled ops: " << names;
      }
    }
  }

  if (st.shutdown_requested.load()) {
    std::lock_guard<std::mutex> lk(st.mu);
    if (st.message_queue.empty()) return false;
  }

  // Sleep out the remainder of the cycle (operations.cc:2032-2040),
  // interruptibly (flush hint / shutdown).
  CycleSleep(st, cycle_start);

  // Autotuner: feed the FULL cycle wall time including the pacing sleep —
  // the reference scores bytes over the whole interval between samples
  // (parameter_manager.cc:144-170), which is what makes the cycle-time
  // knob observable to the optimizer.
  double secs =
      std::chrono::duration<double>(Clock::now() - cycle_start).count();
  if (st.param_manager.IsAutoTuning()) {
    if (st.param_manager.Update(st.cycle_bytes.exchange(0), secs)) {
      st.fusion_threshold.store(st.param_manager.TensorFusionThresholdBytes());
      st.cycle_time_us.store(
          static_cast<int64_t>(st.param_manager.CycleTimeMs() * 1000));
    }
  } else {
    st.cycle_bytes.store(0);
  }
  return true;
}

void BackgroundThreadLoop(GlobalState& st) {
  // (BackgroundThreadLoop, operations.cc:1695-1999 — minus MPI bring-up,
  // which jax.distributed handles before this thread starts.) With more
  // than one host process, the cycle negotiates through the rank-0
  // controller over the Python transport instead of planning locally.
  const bool mp = st.size > 1;
  while (mp ? RunLoopOnceMP(st) : RunLoopOnce(st)) {
  }
  {
    std::lock_guard<std::mutex> lk(st.mu);
    st.background_done = true;
  }
  st.shutdown_cv.notify_all();
}

}  // namespace
}  // namespace hvdtpu

// ---------------------------------------------------------------------------
// C API (ctypes surface) — parity with the reference's C init/rank API
// (operations.cc:2413-2468) plus the enqueue/callback bridge.
// ---------------------------------------------------------------------------

using namespace hvdtpu;

extern "C" {

namespace {

// Serializes init/shutdown transitions; never taken by the background
// thread, so joining under it cannot deadlock.
std::mutex g_init_mu;

const char* EnvOr(const char* primary, const char* fallback) {
  const char* v = std::getenv(primary);
  return v ? v : std::getenv(fallback);
}

// Knob parsing (operations.cc:1824-1909) — shared by fresh init and
// re-init after shutdown so env-derived config (timeline, autotune,
// fusion/cycle knobs, stall check) is honored on every bring-up. Every
// knob is reset to its default first so a re-init with a *changed*
// environment behaves exactly like a fresh init (no feature stays on
// because a previous session enabled it).
void ConfigureFromEnv(GlobalState& st) {
  st.fusion_threshold.store(64LL * 1024 * 1024);  // operations.cc:1838
  st.cycle_time_us.store(1000);  // TPU default 1 ms, see utils/env.py
  st.param_manager.SetAutoTuning(false);
  const char* v = EnvOr("HOROVOD_TPU_FUSION_THRESHOLD",
                        "HOROVOD_FUSION_THRESHOLD");
  if (v) st.fusion_threshold.store(std::atoll(v));
  v = EnvOr("HOROVOD_TPU_CYCLE_TIME", "HOROVOD_CYCLE_TIME");
  if (v) st.cycle_time_us.store(static_cast<int64_t>(std::atof(v) * 1000));
  v = EnvOr("HOROVOD_TPU_STALL_CHECK_DISABLE",
            "HOROVOD_STALL_CHECK_DISABLE");
  st.stall_warning_sec = (v && std::strcmp(v, "0") != 0) ? 0 : 60;

  v = EnvOr("HOROVOD_TPU_TIMELINE", "HOROVOD_TIMELINE");
  if (v && *v && st.rank == 0) {
    const char* mc = EnvOr("HOROVOD_TPU_TIMELINE_MARK_CYCLES",
                           "HOROVOD_TIMELINE_MARK_CYCLES");
    st.timeline.Initialize(v, mc && std::strcmp(mc, "0") != 0);
  }

  v = EnvOr("HOROVOD_TPU_AUTOTUNE", "HOROVOD_AUTOTUNE");
  if (v && std::strcmp(v, "0") != 0) {
    const char* lg = EnvOr("HOROVOD_TPU_AUTOTUNE_LOG",
                           "HOROVOD_AUTOTUNE_LOG");
    st.param_manager.Initialize(st.rank, lg ? lg : "");
    st.param_manager.SetCurrent(
        st.fusion_threshold.load() / (1024.0 * 1024.0),
        st.cycle_time_us.load() / 1000.0);
    st.param_manager.SetAutoTuning(true);
  }
}

}  // namespace

int hvdtpu_init(int rank, int size, int local_size, int virtual_size) {
  // InitializeHorovodOnce (operations.cc:2384-2402). `rank`/`size` are
  // host-process granular (the negotiation unit); `virtual_size` is the
  // total device count, bounding broadcast root ranks.
  std::lock_guard<std::mutex> init_lk(g_init_mu);
  if (g_state && g_state->initialized.load()) return 0;
  if (g_state) {
    // Re-init after shutdown (test hook): reset the retained state and
    // reconfigure from the environment exactly like a fresh init.
    GlobalState& st = *g_state;
    if (st.background.joinable()) st.background.join();
    {
      std::lock_guard<std::mutex> lk(st.mu);
      st.message_queue.clear();
      st.tensor_table.clear();
      st.handles.clear();
      st.shutdown_requested.store(false);
      st.background_done = false;
      st.flush_hint.store(false);
      st.burst_depth.store(0);
      st.foreign_flush.store(false);
      {
        std::lock_guard<std::mutex> olk(st.burst_owner_mu);
        st.burst_owners.clear();
      }
      st.rank = rank;
      st.size = size;
      st.local_size = local_size;
      st.virtual_size = virtual_size > 0 ? virtual_size
                                         : size * local_size;
    }
    ConfigureFromEnv(st);
    st.background = std::thread(BackgroundThreadLoop, std::ref(st));
    st.initialized.store(true);
    return 0;
  }
  auto* st = new GlobalState();
  st->rank = rank;
  st->size = size;
  st->local_size = local_size;
  st->virtual_size = virtual_size > 0 ? virtual_size : size * local_size;
  ConfigureFromEnv(*st);
  st->background = std::thread(BackgroundThreadLoop, std::ref(*st));
  st->initialized.store(true);
  g_state = st;
  HVD_LOG(DEBUG) << "hvdtpu core initialized (rank " << rank << "/" << size
                 << ")";
  return 0;
}

int hvdtpu_initialized() {
  return g_state && g_state->initialized.load() ? 1 : 0;
}

void hvdtpu_shutdown() {
  // Coordinated shutdown (operations.cc:1942-1998): drain, stop thread,
  // close the timeline. The GlobalState is intentionally NEVER freed —
  // other threads may be concurrently inside C-API calls that already
  // passed the g_state null-check (the reference keeps its global state
  // for the process lifetime for the same reason).
  std::lock_guard<std::mutex> init_lk(g_init_mu);
  if (!g_state) return;
  GlobalState& st = *g_state;
  st.shutdown_requested.store(true);
  {
    std::lock_guard<std::mutex> lk(st.cycle_mu);  // see hvdtpu_flush
  }
  st.cycle_cv.notify_all();  // interrupt the pacing sleep
  if (st.background.joinable()) st.background.join();
  st.timeline.Shutdown();
  {
    // Python drops its trampoline references after shutdown; a stale
    // pointer surviving into a re-init would be a use-after-free.
    std::lock_guard<std::mutex> lk(st.mu);
    st.execute_cb = nullptr;
    st.transport_cb = nullptr;
    st.group_cb = nullptr;
  }
  st.initialized.store(false);
}

void hvdtpu_set_execute_callback(void (*cb)(void*, int32_t, const int64_t*,
                                            int32_t, const char*),
                                 void* user) {
  if (!g_state) return;
  std::lock_guard<std::mutex> lk(g_state->mu);
  g_state->execute_cb = cb;
  g_state->execute_user = user;
}

void hvdtpu_set_transport_callback(
    int64_t (*cb)(void*, const uint8_t*, int64_t, int32_t, int32_t,
                  int64_t, uint8_t*, int64_t),
    void* user) {
  if (!g_state) return;
  std::lock_guard<std::mutex> lk(g_state->mu);
  g_state->transport_cb = cb;
  g_state->transport_user = user;
}

// Tuned execution-mode flags of the SINGLE-PROCESS autotuner
// (Response::Flags bits). In MP mode flags ride each planned Response
// (controller.cc CurrentFlags); in SP mode no response crosses a wire,
// so the execute callback reads them here and applies them to the
// executor — without this the tuner could explore hierarchical modes
// whose flag never reached execution (VERDICT r2 #4).
int32_t hvdtpu_current_flags() {
  if (!g_state) return 0;
  GlobalState& st = *g_state;
  if (!st.param_manager.IsAutoTuning()) return 0;
  int32_t f = 0;
  if (st.param_manager.HierarchicalAllreduce())
    f |= Response::HIERARCHICAL_ALLREDUCE;
  if (st.param_manager.HierarchicalAllgather())
    f |= Response::HIERARCHICAL_ALLGATHER;
  return f;
}

// Flush hint: a submitter about to block on a handle declares the current
// enqueue burst complete — the background cycle drains it NOW (skipping
// the drain debounce and interrupting the pacing sleep) instead of
// waiting for the burst-quiet window. Collapses 1-3 ms of per-step
// control latency in tight synchronous training loops.
void hvdtpu_flush() {
  if (!g_state || !g_state->initialized.load()) return;
  {
    // A waiter with no open scope of its own must not have its hint
    // consumed by a burst scope (see DrainShouldDefer) — mark it
    // foreign so the cycle cuts the scope instead of deferring. Marked
    // regardless of CURRENT depth: a hint landing just before another
    // thread's burst_begin would otherwise be consumed by that scope
    // (the cycle may not run in between). A stale mark with no scope
    // open is cleared by the cycle's no-scope branch. Scope exits set
    // flush_hint directly in hvdtpu_burst_end, never through here, so
    // the per-step exit flush is never mistaken for a foreign waiter.
    std::lock_guard<std::mutex> lk(g_state->burst_owner_mu);
    if (g_state->burst_owners.find(std::this_thread::get_id()) ==
        g_state->burst_owners.end()) {
      g_state->foreign_flush.store(true);
    }
  }
  {
    // Store under cycle_mu: CycleSleep checks the predicate under the
    // same lock, so an unserialized store+notify could land between its
    // check and its block — a lost wakeup that waits out the full cycle.
    std::lock_guard<std::mutex> lk(g_state->cycle_mu);
    g_state->flush_hint.store(true);
  }
  g_state->cycle_cv.notify_all();
}

// Explicit burst scope: between begin and end the cycle will not drain
// the queue (bounded by the max-defer valve), so a multi-tensor
// submission always lands as ONE fusion burst — deterministic group
// composition independent of scheduler timing. end() of the outermost
// scope flushes: the cycle drains immediately.
void hvdtpu_burst_begin() {
  if (!g_state || !g_state->initialized.load()) return;
  {
    std::lock_guard<std::mutex> lk(g_state->burst_owner_mu);
    g_state->burst_owners[std::this_thread::get_id()]++;
  }
  g_state->burst_depth.fetch_add(1);
}

void hvdtpu_burst_end() {
  if (!g_state || !g_state->initialized.load()) return;
  {
    std::lock_guard<std::mutex> lk(g_state->burst_owner_mu);
    auto it = g_state->burst_owners.find(std::this_thread::get_id());
    if (it != g_state->burst_owners.end() && --it->second <= 0) {
      g_state->burst_owners.erase(it);
    }
  }
  if (g_state->burst_depth.fetch_sub(1) <= 1) {
    {
      std::lock_guard<std::mutex> lk(g_state->cycle_mu);  // see hvdtpu_flush
      g_state->flush_hint.store(true);
    }
    g_state->cycle_cv.notify_all();
  }
}

void hvdtpu_set_group_callback(
    void (*cb)(void*, int32_t, const int64_t*, int32_t, int32_t,
               const int64_t*, int32_t, int32_t, const char*),
    void* user) {
  if (!g_state) return;
  std::lock_guard<std::mutex> lk(g_state->mu);
  g_state->group_cb = cb;
  g_state->group_user = user;
}

// Returns handle > 0, or -1 for duplicate name (DUPLICATE_NAME_ERROR,
// operations.cc:270-273), -2 if shut down (SHUT_DOWN_ERROR).
int64_t hvdtpu_enqueue(int32_t op, const char* name, int32_t dtype,
                       const int64_t* shape, int32_t ndims, int32_t root_rank,
                       int32_t device, int64_t nbytes) {
  if (!g_state || !g_state->initialized.load()) return -2;
  GlobalState& st = *g_state;
  if (st.shutdown_requested.load()) return -2;

  PendingEntry pe;
  pe.request.request_rank = st.rank;
  pe.request.request_type = static_cast<Request::Type>(op);
  pe.request.tensor_type = static_cast<DataType>(dtype);
  pe.request.tensor_name = name;
  pe.request.root_rank = root_rank;
  pe.request.device = device;
  std::vector<int64_t> dims(shape, shape + ndims);
  pe.request.tensor_shape = TensorShape(std::move(dims));
  pe.nbytes = nbytes;
  pe.enqueued = Clock::now();

  std::lock_guard<std::mutex> lk(st.mu);
  if (st.tensor_table.count(pe.request.tensor_name)) return -1;
  int64_t h = st.next_handle++;
  pe.handle = h;
  st.handles[h] = HandleState{pe.request.tensor_name, -1, ""};
  st.tensor_table.emplace(pe.request.tensor_name, pe);
  bool was_empty = st.message_queue.empty();
  st.message_queue.push_back(std::move(pe));
  int64_t now = NowNs();
  st.last_enqueue_ns.store(now);
  if (was_empty) st.oldest_enqueue_ns.store(now);
  if (st.timeline.Initialized()) {
    st.timeline.NegotiateStart(name, op);
    st.timeline.NegotiateRankReady(name, st.rank);
  }
  return h;
}

// Python reports group completion. status_type: StatusType values; reason
// used when != OK.
void hvdtpu_complete(const int64_t* handles, int32_t count,
                     int32_t status_type, const char* reason) {
  if (!g_state) return;
  GlobalState& st = *g_state;
  std::vector<std::string> names;
  {
    std::lock_guard<std::mutex> lk(st.mu);
    for (int i = 0; i < count; ++i) {
      auto it = st.handles.find(handles[i]);
      if (it == st.handles.end()) continue;
      it->second.status = status_type;
      it->second.reason = reason ? reason : "";
      names.push_back(it->second.name);
      st.tensor_table.erase(it->second.name);
    }
  }
  if (st.timeline.Initialized()) {
    for (const auto& n : names) {
      st.timeline.ActivityEnd(n);   // close QUEUE/XLA activity
      st.timeline.End(n, "");
    }
  }
}

// Poll handle: -1 in flight, else StatusType value (PollHandle,
// torch/handle_manager.cc:21-50).
int32_t hvdtpu_poll(int64_t handle) {
  if (!g_state) return static_cast<int32_t>(StatusType::ABORTED);
  std::lock_guard<std::mutex> lk(g_state->mu);
  auto it = g_state->handles.find(handle);
  if (it == g_state->handles.end())
    return static_cast<int32_t>(StatusType::INVALID_ARGUMENT);
  return it->second.status;
}

void hvdtpu_release_handle(int64_t handle) {
  if (!g_state) return;
  std::lock_guard<std::mutex> lk(g_state->mu);
  g_state->handles.erase(handle);
}

int hvdtpu_rank() { return g_state ? g_state->rank : -1; }
int hvdtpu_size() { return g_state ? g_state->size : -1; }
int hvdtpu_local_size() { return g_state ? g_state->local_size : -1; }

void hvdtpu_set_fusion_threshold(int64_t bytes) {
  if (g_state) g_state->fusion_threshold.store(bytes);
}
int64_t hvdtpu_get_fusion_threshold() {
  return g_state ? g_state->fusion_threshold.load() : -1;
}
void hvdtpu_set_cycle_time_ms(double ms) {
  if (g_state)
    g_state->cycle_time_us.store(static_cast<int64_t>(ms * 1000));
}
double hvdtpu_get_cycle_time_ms() {
  return g_state ? g_state->cycle_time_us.load() / 1000.0 : -1;
}

// Timeline bridge for Python-side activities (XLA launch/wait phases).
void hvdtpu_timeline_activity_start(const char* tensor,
                                    const char* activity) {
  if (g_state) g_state->timeline.ActivityStart(tensor, activity);
}
void hvdtpu_timeline_activity_end(const char* tensor) {
  if (g_state) g_state->timeline.ActivityEnd(tensor);
}
int hvdtpu_timeline_enabled() {
  return g_state && g_state->timeline.Initialized() ? 1 : 0;
}

// Autotune inspection (test / observability surface).
int hvdtpu_autotune_active() {
  return g_state && g_state->param_manager.IsAutoTuning() &&
                 !g_state->param_manager.IsDone()
             ? 1 : 0;
}
int hvdtpu_autotune_done() {
  return g_state && g_state->param_manager.IsDone() ? 1 : 0;
}

// Host staging arena (FusionBufferManager bridge).
uint8_t* hvdtpu_fusion_buffer(int device, int64_t threshold) {
  return g_state ? g_state->fusion_buffers.GetBuffer(device, threshold)
                 : nullptr;
}

// ---- wire protocol + negotiation test surface (used by pytest via ctypes
// and by the multi-host controller) ----------------------------------------

int64_t hvdtpu_wire_roundtrip_request_list(const uint8_t* in, int64_t in_len,
                                           uint8_t* out, int64_t out_cap) {
  RequestList rl;
  if (!RequestList::ParseFrom(in, static_cast<size_t>(in_len), &rl)) return -1;
  std::vector<uint8_t> buf;
  rl.SerializeTo(&buf);
  if (static_cast<int64_t>(buf.size()) > out_cap) return -1;
  std::memcpy(out, buf.data(), buf.size());
  return static_cast<int64_t>(buf.size());
}

// Build a serialized Request for tests / the controller client.
int64_t hvdtpu_wire_make_request(int32_t rank, int32_t op, int32_t dtype,
                                 const char* name, int32_t root_rank,
                                 int32_t device, const int64_t* shape,
                                 int32_t ndims, uint8_t* out,
                                 int64_t out_cap) {
  Request r;
  r.request_rank = rank;
  r.request_type = static_cast<Request::Type>(op);
  r.tensor_type = static_cast<DataType>(dtype);
  r.tensor_name = name;
  r.root_rank = root_rank;
  r.device = device;
  r.tensor_shape = TensorShape(std::vector<int64_t>(shape, shape + ndims));
  std::vector<uint8_t> buf;
  r.SerializeTo(&buf);
  if (static_cast<int64_t>(buf.size()) > out_cap) return -1;
  std::memcpy(out, buf.data(), buf.size());
  return static_cast<int64_t>(buf.size());
}

// Run coordinator validation over a batch of serialized Requests (size =
// world size). Writes the Response error message (or "") to err; returns
// the Response type.
int32_t hvdtpu_negotiate(const uint8_t* data, int64_t len, int32_t nreq,
                         int32_t world_size, char* err, int64_t err_cap,
                         int64_t* tensor_sizes_out, int32_t sizes_cap) {
  std::vector<Request> reqs;
  size_t off = 0;
  for (int i = 0; i < nreq; ++i) {
    Request r;
    size_t consumed;
    if (!Request::ParseFrom(data + off, static_cast<size_t>(len) - off,
                            &consumed, &r)) {
      std::snprintf(err, err_cap, "parse error at request %d", i);
      return static_cast<int32_t>(Response::ERROR);
    }
    off += consumed;
    reqs.push_back(std::move(r));
  }
  Response resp = ConstructResponse(reqs, world_size);
  std::snprintf(err, err_cap, "%s", resp.error_message.c_str());
  int32_t n = std::min<int32_t>(sizes_cap,
                                static_cast<int32_t>(resp.tensor_sizes.size()));
  for (int32_t i = 0; i < n; ++i) tensor_sizes_out[i] = resp.tensor_sizes[i];
  return static_cast<int32_t>(resp.response_type);
}

// half/bf16 conversion surface (N8 parity; exercised by tests).
void hvdtpu_half_to_float(const uint16_t* in, float* out, int64_t n) {
  for (int64_t i = 0; i < n; ++i) out[i] = HalfBits2Float(in[i]);
}
void hvdtpu_float_to_half(const float* in, uint16_t* out, int64_t n) {
  for (int64_t i = 0; i < n; ++i) out[i] = Float2HalfBits(in[i]);
}
void hvdtpu_halfsum(const uint16_t* src, uint16_t* dst, int64_t n) {
  HalfSum(src, dst, static_cast<size_t>(n));
}
void hvdtpu_bf16sum(const uint16_t* src, uint16_t* dst, int64_t n) {
  BF16Sum(src, dst, static_cast<size_t>(n));
}

}  // extern "C"
