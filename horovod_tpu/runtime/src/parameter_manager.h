// Parameter manager (autotuning) — equivalent of
// horovod/common/parameter_manager.{h,cc} (N5).
//
// Tunes the fusion-buffer threshold (MB) and cycle time (ms) jointly with
// Bayesian optimization, and BOTH hierarchical flags (allreduce AND
// allgather) categorically, to maximize throughput score = bytes /
// microsecond — the reference's knobs and score exactly
// (parameter_manager.cc:41-54, 144-170: CategoricalParameterManagers over
// {false,true} for hierarchical_allreduce and hierarchical_allgather,
// BayesianParameter for the scalars). Scoring
// protocol kept: samples are accumulated over a fixed number of cycles,
// several warmup samples are discarded, and the median of recent samples
// drives each tuning step (parameter_manager.h:211-213).
#ifndef HVD_TPU_PARAMETER_MANAGER_H
#define HVD_TPU_PARAMETER_MANAGER_H

#include <cstdint>
#include <cstdio>
#include <string>
#include <vector>

#include "bayesian_optimization.h"

namespace hvdtpu {

class ParameterManager {
 public:
  ParameterManager();

  void Initialize(int rank, const std::string& log_path);
  // Seed the tuner with the knobs the runtime is ACTUALLY running, so the
  // first observation is attributed to the right point.
  void SetCurrent(double fusion_mb, double cycle_ms) {
    fusion_mb_ = std::min(64.0, std::max(0.0, fusion_mb));
    cycle_ms_ = std::min(100.0, std::max(1.0, cycle_ms));
    best_fusion_mb_ = fusion_mb_;
    best_cycle_ms_ = cycle_ms_;
  }
  void SetAutoTuning(bool active) { active_ = active; }
  bool IsAutoTuning() const { return active_; }

  // Feed one completed-cycle observation (total payload bytes moved and
  // wall seconds). Returns true when parameters changed (reference
  // ParameterManager::Update, parameter_manager.cc:144-170).
  bool Update(int64_t bytes, double seconds);

  int64_t TensorFusionThresholdBytes() const;
  double CycleTimeMs() const;
  bool HierarchicalAllreduce() const;
  bool HierarchicalAllgather() const;

  // Freeze to best-seen values (reference convergence path,
  // parameter_manager.cc:173-209).
  void SetDone();
  bool IsDone() const { return done_; }

 private:
  void Tune(double score);
  // `combo` indexes the categorical pair: bit 1 = hierarchical
  // allreduce, bit 0 = hierarchical allgather.
  void ApplyPoint(const std::vector<double>& p, int combo);
  void LogSample(double score);
  int Combo() const {
    return (hier_allreduce_ ? 2 : 0) | (hier_allgather_ ? 1 : 0);
  }

  bool active_ = false;
  bool done_ = false;
  int rank_ = 0;

  // Current / best values.
  double fusion_mb_ = 64.0;   // default operations.cc:1838
  double cycle_ms_ = 5.0;     // default operations.cc:1846
  bool hier_allreduce_ = false;
  bool hier_allgather_ = false;
  double best_score_ = -1.0;
  double best_fusion_mb_ = 64.0;
  double best_cycle_ms_ = 5.0;
  int best_combo_ = 0;

  // Scoring accumulation (parameter_manager.cc:28-29: 10 cycles/sample,
  // median of 5 samples, 3 warmup discards).
  static constexpr int kCyclesPerSample = 10;
  static constexpr int kSamplesPerStep = 5;
  static constexpr int kWarmupSamples = 3;
  static constexpr int kMaxSteps = 30;

  int64_t acc_bytes_ = 0;
  double acc_seconds_ = 0.0;
  int acc_cycles_ = 0;
  std::vector<double> samples_;
  int warmups_left_ = kWarmupSamples;
  int steps_ = 0;

  // One BO instance per (hier_allreduce, hier_allgather) combination,
  // the reference's CategoricalParameter × BayesianParameter structure
  // with both categoricals (parameter_manager.cc:41-54).
  std::vector<BayesianOptimization> bo_;
  int category_ = 0;  // position in the categorical exploration schedule

  std::FILE* log_ = nullptr;
};

}  // namespace hvdtpu

#endif  // HVD_TPU_PARAMETER_MANAGER_H
