#include "half.h"

#include <cmath>
#include <cstring>

namespace hvdtpu {

float HalfBits2Float(uint16_t h) {
  // Bit-level conversion mirroring reference half.h:38-84.
  uint32_t sign = (h >> 15) & 1;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t mant = h & 0x3ff;
  uint32_t f;
  if (exp == 0) {
    if (mant == 0) {
      f = sign << 31;  // +-0
    } else {
      // Subnormal: normalize.
      int e = -1;
      uint32_t m = mant;
      do {
        ++e;
        m <<= 1;
      } while ((m & 0x400) == 0);
      f = (sign << 31) | ((127 - 15 - e) << 23) | ((m & 0x3ff) << 13);
    }
  } else if (exp == 0x1f) {
    f = (sign << 31) | 0x7f800000 | (mant << 13);  // inf/nan
  } else {
    f = (sign << 31) | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float out;
  std::memcpy(&out, &f, 4);
  return out;
}

uint16_t Float2HalfBits(float v) {
  // Mirrors reference half.h:86-130 (round-to-nearest-even).
  uint32_t f;
  std::memcpy(&f, &v, 4);
  uint32_t sign = (f >> 31) & 1;
  int32_t exp = static_cast<int32_t>((f >> 23) & 0xff) - 127 + 15;
  uint32_t mant = f & 0x7fffff;
  uint16_t h;
  if (((f >> 23) & 0xff) == 0xff) {
    h = static_cast<uint16_t>((sign << 15) | 0x7c00 |
                              (mant ? 0x200 | (mant >> 13) : 0));
  } else if (exp >= 0x1f) {
    h = static_cast<uint16_t>((sign << 15) | 0x7c00);  // overflow -> inf
  } else if (exp <= 0) {
    if (exp < -10) {
      h = static_cast<uint16_t>(sign << 15);  // underflow -> 0
    } else {
      // Subnormal half.
      mant |= 0x800000;
      int shift = 14 - exp;
      uint32_t m = mant >> shift;
      uint32_t rem = mant & ((1u << shift) - 1);
      uint32_t half = 1u << (shift - 1);
      if (rem > half || (rem == half && (m & 1))) ++m;
      h = static_cast<uint16_t>((sign << 15) | m);
    }
  } else {
    uint32_t m = mant >> 13;
    uint32_t rem = mant & 0x1fff;
    if (rem > 0x1000 || (rem == 0x1000 && (m & 1))) {
      ++m;
      if (m == 0x400) {
        m = 0;
        ++exp;
        if (exp >= 0x1f) {
          h = static_cast<uint16_t>((sign << 15) | 0x7c00);
          return h;
        }
      }
    }
    h = static_cast<uint16_t>((sign << 15) | (exp << 10) | m);
  }
  return h;
}

float BF16Bits2Float(uint16_t b) {
  uint32_t f = static_cast<uint32_t>(b) << 16;
  float out;
  std::memcpy(&out, &f, 4);
  return out;
}

uint16_t Float2BF16Bits(float v) {
  uint32_t f;
  std::memcpy(&f, &v, 4);
  // Round-to-nearest-even on the dropped 16 bits; NaN stays NaN.
  if ((f & 0x7f800000) == 0x7f800000 && (f & 0x7fffff)) {
    return static_cast<uint16_t>((f >> 16) | 0x0040);
  }
  uint32_t lsb = (f >> 16) & 1;
  f += 0x7fff + lsb;
  return static_cast<uint16_t>(f >> 16);
}

void HalfSum(const uint16_t* src, uint16_t* dst, size_t n) {
  // Scalar fallback of the reference's AVX/F16C loop (half.cc:42-90); the
  // compiler auto-vectorizes the conversions where F16C is available.
  for (size_t i = 0; i < n; ++i) {
    dst[i] = Float2HalfBits(HalfBits2Float(dst[i]) + HalfBits2Float(src[i]));
  }
}

void BF16Sum(const uint16_t* src, uint16_t* dst, size_t n) {
  for (size_t i = 0; i < n; ++i) {
    dst[i] = Float2BF16Bits(BF16Bits2Float(dst[i]) + BF16Bits2Float(src[i]));
  }
}

}  // namespace hvdtpu
