// Binary codec for the control-plane wire protocol. See message.h.
#include "message.h"

#include <cstring>

namespace hvdtpu {

namespace {

// Little-endian primitive writers/readers. All lengths are uint32.
void PutI32(std::vector<uint8_t>* out, int32_t v) {
  uint32_t u = static_cast<uint32_t>(v);
  out->push_back(u & 0xff);
  out->push_back((u >> 8) & 0xff);
  out->push_back((u >> 16) & 0xff);
  out->push_back((u >> 24) & 0xff);
}

void PutI64(std::vector<uint8_t>* out, int64_t v) {
  uint64_t u = static_cast<uint64_t>(v);
  for (int i = 0; i < 8; ++i) out->push_back((u >> (8 * i)) & 0xff);
}

void PutStr(std::vector<uint8_t>* out, const std::string& s) {
  PutI32(out, static_cast<int32_t>(s.size()));
  out->insert(out->end(), s.begin(), s.end());
}

struct Reader {
  const uint8_t* p;
  size_t len;
  size_t off = 0;

  bool I32(int32_t* v) {
    if (off + 4 > len) return false;
    uint32_t u = 0;
    for (int i = 0; i < 4; ++i) u |= static_cast<uint32_t>(p[off + i]) << (8 * i);
    *v = static_cast<int32_t>(u);
    off += 4;
    return true;
  }
  bool I64(int64_t* v) {
    if (off + 8 > len) return false;
    uint64_t u = 0;
    for (int i = 0; i < 8; ++i) u |= static_cast<uint64_t>(p[off + i]) << (8 * i);
    *v = static_cast<int64_t>(u);
    off += 8;
    return true;
  }
  bool Str(std::string* s) {
    int32_t n;
    if (!I32(&n) || n < 0 || off + static_cast<size_t>(n) > len) return false;
    s->assign(reinterpret_cast<const char*>(p + off), n);
    off += n;
    return true;
  }
};

}  // namespace

const char* DataTypeName(DataType t) {
  switch (t) {
    case DataType::HVD_UINT8: return "uint8";
    case DataType::HVD_INT8: return "int8";
    case DataType::HVD_UINT16: return "uint16";
    case DataType::HVD_INT16: return "int16";
    case DataType::HVD_INT32: return "int32";
    case DataType::HVD_INT64: return "int64";
    case DataType::HVD_FLOAT16: return "float16";
    case DataType::HVD_FLOAT32: return "float32";
    case DataType::HVD_FLOAT64: return "float64";
    case DataType::HVD_BOOL: return "bool";
    case DataType::HVD_BFLOAT16: return "bfloat16";
    case DataType::HVD_UINT32: return "uint32";
    case DataType::HVD_UINT64: return "uint64";
  }
  return "unknown";
}

int64_t DataTypeSize(DataType t) {
  switch (t) {
    case DataType::HVD_UINT8:
    case DataType::HVD_INT8:
    case DataType::HVD_BOOL:
      return 1;
    case DataType::HVD_UINT16:
    case DataType::HVD_INT16:
    case DataType::HVD_FLOAT16:
    case DataType::HVD_BFLOAT16:
      return 2;
    case DataType::HVD_INT32:
    case DataType::HVD_FLOAT32:
    case DataType::HVD_UINT32:
      return 4;
    case DataType::HVD_INT64:
    case DataType::HVD_FLOAT64:
    case DataType::HVD_UINT64:
      return 8;
  }
  return 0;
}

std::string TensorShape::DebugString() const {
  std::string s = "[";
  for (size_t i = 0; i < dims_.size(); ++i) {
    if (i) s += ", ";
    s += std::to_string(dims_[i]);
  }
  s += "]";
  return s;
}

const char* RequestTypeName(Request::Type t) {
  switch (t) {
    case Request::ALLREDUCE: return "allreduce";
    case Request::ALLGATHER: return "allgather";
    case Request::BROADCAST: return "broadcast";
  }
  return "unknown";
}

void Request::SerializeTo(std::vector<uint8_t>* out) const {
  PutI32(out, request_rank);
  PutI32(out, static_cast<int32_t>(request_type));
  PutI32(out, static_cast<int32_t>(tensor_type));
  PutStr(out, tensor_name);
  PutI32(out, root_rank);
  PutI32(out, device);
  PutI32(out, tensor_shape.ndims());
  for (auto d : tensor_shape.dims()) PutI64(out, d);
}

bool Request::ParseFrom(const uint8_t* data, size_t len, size_t* consumed,
                        Request* out) {
  Reader r{data, len};
  int32_t type, dtype, ndims;
  if (!r.I32(&out->request_rank)) return false;
  if (!r.I32(&type)) return false;
  if (!r.I32(&dtype)) return false;
  if (!r.Str(&out->tensor_name)) return false;
  if (!r.I32(&out->root_rank)) return false;
  if (!r.I32(&out->device)) return false;
  if (!r.I32(&ndims) || ndims < 0 || ndims > 255) return false;
  out->request_type = static_cast<Type>(type);
  out->tensor_type = static_cast<DataType>(dtype);
  std::vector<int64_t> dims(ndims);
  for (int i = 0; i < ndims; ++i)
    if (!r.I64(&dims[i])) return false;
  out->tensor_shape = TensorShape(std::move(dims));
  *consumed = r.off;
  return true;
}

void RequestList::SerializeTo(std::vector<uint8_t>* out) const {
  PutI32(out, shutdown ? 1 : 0);
  PutI32(out, static_cast<int32_t>(requests.size()));
  for (const auto& req : requests) req.SerializeTo(out);
}

bool RequestList::ParseFrom(const uint8_t* data, size_t len,
                            RequestList* out) {
  Reader r{data, len};
  int32_t sd, n;
  if (!r.I32(&sd) || !r.I32(&n) || n < 0) return false;
  out->shutdown = sd != 0;
  out->requests.clear();
  size_t off = r.off;
  for (int i = 0; i < n; ++i) {
    Request req;
    size_t consumed;
    if (!Request::ParseFrom(data + off, len - off, &consumed, &req))
      return false;
    off += consumed;
    out->requests.push_back(std::move(req));
  }
  return true;
}

void Response::SerializeTo(std::vector<uint8_t>* out) const {
  PutI32(out, static_cast<int32_t>(response_type));
  PutI32(out, static_cast<int32_t>(tensor_names.size()));
  for (const auto& n : tensor_names) PutStr(out, n);
  PutStr(out, error_message);
  PutI32(out, static_cast<int32_t>(devices.size()));
  for (auto d : devices) PutI32(out, d);
  PutI32(out, static_cast<int32_t>(tensor_sizes.size()));
  for (auto s : tensor_sizes) PutI64(out, s);
  PutI32(out, flags);
}

bool Response::ParseFrom(const uint8_t* data, size_t len, size_t* consumed,
                         Response* out) {
  Reader r{data, len};
  int32_t type, n;
  if (!r.I32(&type)) return false;
  out->response_type = static_cast<Type>(type);
  if (!r.I32(&n) || n < 0) return false;
  out->tensor_names.resize(n);
  for (int i = 0; i < n; ++i)
    if (!r.Str(&out->tensor_names[i])) return false;
  if (!r.Str(&out->error_message)) return false;
  if (!r.I32(&n) || n < 0) return false;
  out->devices.resize(n);
  for (int i = 0; i < n; ++i)
    if (!r.I32(&out->devices[i])) return false;
  if (!r.I32(&n) || n < 0) return false;
  out->tensor_sizes.resize(n);
  for (int i = 0; i < n; ++i)
    if (!r.I64(&out->tensor_sizes[i])) return false;
  if (!r.I32(&out->flags)) return false;
  *consumed = r.off;
  return true;
}

void ResponseList::SerializeTo(std::vector<uint8_t>* out) const {
  PutI32(out, shutdown ? 1 : 0);
  PutI32(out, static_cast<int32_t>(responses.size()));
  for (const auto& resp : responses) resp.SerializeTo(out);
}

bool ResponseList::ParseFrom(const uint8_t* data, size_t len,
                             ResponseList* out) {
  Reader r{data, len};
  int32_t sd, n;
  if (!r.I32(&sd) || !r.I32(&n) || n < 0) return false;
  out->shutdown = sd != 0;
  out->responses.clear();
  size_t off = r.off;
  for (int i = 0; i < n; ++i) {
    Response resp;
    size_t consumed;
    if (!Response::ParseFrom(data + off, len - off, &consumed, &resp))
      return false;
    off += consumed;
    out->responses.push_back(std::move(resp));
  }
  return true;
}

}  // namespace hvdtpu
