#include "fusion_buffer.h"

namespace hvdtpu {

uint8_t* FusionBufferManager::GetBuffer(int device, int64_t threshold_bytes) {
  std::lock_guard<std::mutex> lk(mu_);
  // Round up so every segment boundary can stay 64B-aligned
  // (FUSION_BUFFER_ATOMIC_UNIT rounding, operations.cc:742-764).
  int64_t want = (threshold_bytes + kFusionBufferAtomicUnit - 1) /
                 kFusionBufferAtomicUnit * kFusionBufferAtomicUnit;
  auto& buf = buffers_[device];
  if (buf.size < want) {
    buf.data = std::make_unique<uint8_t[]>(static_cast<size_t>(want));
    buf.size = want;
  }
  return buf.data.get();
}

int64_t FusionBufferManager::buffer_size(int device) const {
  std::lock_guard<std::mutex> lk(mu_);
  auto it = buffers_.find(device);
  return it == buffers_.end() ? 0 : it->second.size;
}

}  // namespace hvdtpu
