// Coordinator / negotiation logic — TPU-native equivalent of the
// coordinator half of horovod/common/operations.cc (N3):
//   - MessageTable + IncrementTensorCount (operations.cc:287-313)
//   - ConstructResponse validation with rich mismatch diagnostics
//     (operations.cc:321-523)
//   - fusion assembly with look-ahead over skipped responses
//     (operations.cc:2149-2265)
//   - stall detection (CheckForStalledTensors, operations.cc:1625-1672)
//
// Under XLA's SPMD model a *jitted* collective needs no negotiation (all
// ranks run one program). Negotiation still matters for the eager path
// across host processes: frameworks enqueue tensors in nondeterministic
// order, and a tensor may only be executed once EVERY process has announced
// it. The coordinator keeps the reference's rank-0 gather/verdict/broadcast
// design, riding the runner's TCP rendezvous instead of MPI.
#ifndef HVD_TPU_COORDINATOR_H
#define HVD_TPU_COORDINATOR_H

#include <chrono>
#include <deque>
#include <string>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "message.h"

namespace hvdtpu {

// Tracks which ranks have announced each tensor
// (MessageTable, operations.cc:128-143).
class MessageTable {
 public:
  using Clock = std::chrono::steady_clock;

  struct Entry {
    std::vector<Request> requests;   // one per reporting rank
    Clock::time_point first_seen;
  };

  // Returns true when all `size` ranks have now reported `name`
  // (IncrementTensorCount, operations.cc:287-313).
  bool Increment(const Request& msg, int size);

  // The ready request vector for a tensor; empties the entry.
  std::vector<Request> Take(const std::string& name);

  bool Contains(const std::string& name) const {
    return table_.count(name) != 0;
  }
  size_t size() const { return table_.size(); }

  // Tensors stuck longer than `warn_after` seconds, with the ranks that DID
  // report and the missing ranks (CheckForStalledTensors,
  // operations.cc:1625-1672). Returns human-readable report lines.
  std::vector<std::string> StalledTensors(int size, double warn_after) const;

 private:
  std::unordered_map<std::string, Entry> table_;
};

// Validates that all ranks agree and builds the verdict for one ready
// tensor (ConstructMPIResponse, operations.cc:321-523). Checks, in the
// reference's order: op type, dtype, shape (allreduce/broadcast: all dims;
// allgather: all dims but the first), root rank (broadcast), device list.
// `root_bound` bounds valid broadcast root ranks; the control plane runs at
// host-process granularity while root ranks are *virtual* (device) ranks,
// so the bound can exceed `size`. Defaults to `size`.
Response ConstructResponse(const std::vector<Request>& requests, int size,
                           int root_bound = -1);

// Greedy same-op/same-dtype fusion under a byte threshold with look-ahead
// over skipped responses (operations.cc:2149-2265). `sizes_bytes` maps
// tensor name -> payload bytes. Allgather responses are also fused when
// their non-first dims match, like the reference's fused allgather.
std::vector<Response> FuseResponses(std::deque<Response> responses,
                                    const std::unordered_map<std::string, int64_t>& sizes_bytes,
                                    const std::unordered_map<std::string, DataType>& dtypes,
                                    int64_t threshold_bytes);

}  // namespace hvdtpu

#endif  // HVD_TPU_COORDINATOR_H
