#include "gaussian_process.h"

#include <algorithm>
#include <cmath>

namespace hvdtpu {

void GaussianProcess::AddSample(const std::vector<double>& x, double y) {
  xs_.push_back(x);
  ys_.push_back(y);
  fitted_ = false;
}

double GaussianProcess::best_y() const {
  double best = -1e300;
  for (double y : ys_) best = std::max(best, y);
  return best;
}

double GaussianProcess::Kernel(const std::vector<double>& a,
                               const std::vector<double>& b) const {
  double d2 = 0;
  for (size_t i = 0; i < a.size(); ++i) {
    double d = a[i] - b[i];
    d2 += d * d;
  }
  return amp_ * std::exp(-d2 / (2.0 * length_ * length_));
}

bool GaussianProcess::Cholesky(const std::vector<double>& a, int n,
                               std::vector<double>* lout) const {
  // Dense lower-triangular Cholesky; n is small (≤ a few hundred samples).
  std::vector<double>& l = *lout;
  l.assign(n * n, 0.0);
  for (int i = 0; i < n; ++i) {
    for (int j = 0; j <= i; ++j) {
      double s = a[i * n + j];
      for (int k = 0; k < j; ++k) s -= l[i * n + k] * l[j * n + k];
      if (i == j) {
        if (s <= 0) return false;
        l[i * n + i] = std::sqrt(s);
      } else {
        l[i * n + j] = s / l[j * n + j];
      }
    }
  }
  return true;
}

std::vector<double> GaussianProcess::CholSolve(const std::vector<double>& l,
                                               int n,
                                               std::vector<double> b) const {
  for (int i = 0; i < n; ++i) {
    double s = b[i];
    for (int k = 0; k < i; ++k) s -= l[i * n + k] * b[k];
    b[i] = s / l[i * n + i];
  }
  for (int i = n - 1; i >= 0; --i) {
    double s = b[i];
    for (int k = i + 1; k < n; ++k) s -= l[k * n + i] * b[k];
    b[i] = s / l[i * n + i];
  }
  return b;
}

double GaussianProcess::LogMarginalLikelihood(double length,
                                              double amp) const {
  // -1/2 y^T K^-1 y - 1/2 log|K| - n/2 log 2π with centered y.
  int n = static_cast<int>(ys_.size());
  GaussianProcess tmp = *this;
  tmp.length_ = length;
  tmp.amp_ = amp;
  std::vector<double> k(n * n);
  for (int i = 0; i < n; ++i)
    for (int j = 0; j < n; ++j)
      k[i * n + j] = tmp.Kernel(xs_[i], xs_[j]) + (i == j ? noise_ : 0.0);
  std::vector<double> l;
  if (!tmp.Cholesky(k, n, &l)) return -1e300;
  std::vector<double> yc(n);
  for (int i = 0; i < n; ++i) yc[i] = ys_[i] - y_mean_;
  std::vector<double> alpha = tmp.CholSolve(l, n, yc);
  double quad = 0, logdet = 0;
  for (int i = 0; i < n; ++i) {
    quad += yc[i] * alpha[i];
    logdet += std::log(l[i * n + i]);
  }
  return -0.5 * quad - logdet - 0.5 * n * std::log(2 * M_PI);
}

bool GaussianProcess::Fit() {
  int n = static_cast<int>(ys_.size());
  if (n == 0) return false;
  y_mean_ = 0;
  for (double y : ys_) y_mean_ += y;
  y_mean_ /= n;

  // Hyperparameter fit: grid over length scales / amplitudes (stands in for
  // the reference's L-BFGS fit, gaussian_process.cc Fit()).
  if (n >= 3) {
    double best_ll = -1e301, best_len = length_, best_amp = amp_;
    double var = 0;
    for (double y : ys_) var += (y - y_mean_) * (y - y_mean_);
    var = var / n + 1e-12;
    for (double len : {0.05, 0.1, 0.2, 0.35, 0.5, 0.8, 1.2}) {
      for (double amp : {0.5 * var, var, 2.0 * var}) {
        double ll = LogMarginalLikelihood(len, amp);
        if (ll > best_ll) {
          best_ll = ll;
          best_len = len;
          best_amp = amp;
        }
      }
    }
    length_ = best_len;
    amp_ = best_amp;
  }

  std::vector<double> k(n * n);
  for (int i = 0; i < n; ++i)
    for (int j = 0; j < n; ++j)
      k[i * n + j] = Kernel(xs_[i], xs_[j]) + (i == j ? noise_ : 0.0);
  if (!Cholesky(k, n, &chol_)) return false;
  std::vector<double> yc(n);
  for (int i = 0; i < n; ++i) yc[i] = ys_[i] - y_mean_;
  alpha_ = CholSolve(chol_, n, yc);
  fitted_ = true;
  return true;
}

void GaussianProcess::Predict(const std::vector<double>& x, double* mean,
                              double* variance) const {
  int n = static_cast<int>(ys_.size());
  if (!fitted_ || n == 0) {
    *mean = y_mean_;
    *variance = amp_;
    return;
  }
  std::vector<double> kstar(n);
  for (int i = 0; i < n; ++i) kstar[i] = Kernel(x, xs_[i]);
  double m = y_mean_;
  for (int i = 0; i < n; ++i) m += kstar[i] * alpha_[i];
  // v = L^-1 k*; var = k(x,x) - v^T v
  std::vector<double> v(kstar);
  for (int i = 0; i < n; ++i) {
    double s = v[i];
    for (int k = 0; k < i; ++k) s -= chol_[i * n + k] * v[k];
    v[i] = s / chol_[i * n + i];
  }
  double var = Kernel(x, x);
  for (int i = 0; i < n; ++i) var -= v[i] * v[i];
  *mean = m;
  *variance = std::max(var, 1e-12);
}

}  // namespace hvdtpu
