// Logging — TPU-native equivalent of horovod/common/logging.{h,cc} (N9):
// stream-style LOG(severity) macros, levels TRACE..FATAL, controlled by
// HOROVOD_LOG_LEVEL / HOROVOD_LOG_HIDE_TIME (logging.cc:76-92).
#ifndef HVD_TPU_LOGGING_H
#define HVD_TPU_LOGGING_H

#include <sstream>
#include <string>

namespace hvdtpu {

enum class LogLevel : int { TRACE = 0, DEBUG = 1, INFO = 2, WARNING = 3,
                            ERROR = 4, FATAL = 5 };

LogLevel MinLogLevelFromEnv();
bool LogHideTimeFromEnv();

class LogMessage : public std::basic_ostringstream<char> {
 public:
  LogMessage(const char* file, int line, LogLevel level);
  ~LogMessage();

 private:
  const char* file_;
  int line_;
  LogLevel level_;
};

#define HVD_LOG_TRACE \
  ::hvdtpu::LogMessage(__FILE__, __LINE__, ::hvdtpu::LogLevel::TRACE)
#define HVD_LOG_DEBUG \
  ::hvdtpu::LogMessage(__FILE__, __LINE__, ::hvdtpu::LogLevel::DEBUG)
#define HVD_LOG_INFO \
  ::hvdtpu::LogMessage(__FILE__, __LINE__, ::hvdtpu::LogLevel::INFO)
#define HVD_LOG_WARNING \
  ::hvdtpu::LogMessage(__FILE__, __LINE__, ::hvdtpu::LogLevel::WARNING)
#define HVD_LOG_ERROR \
  ::hvdtpu::LogMessage(__FILE__, __LINE__, ::hvdtpu::LogLevel::ERROR)

// LOG(severity) in the reference (logging.h:21-67); prefixed here to stay
// symbol-clean in a shared object loaded next to other frameworks (the role
// of horovod.lds/exp, reference N15).
#define HVD_LOG(level) HVD_LOG_##level

}  // namespace hvdtpu

#endif  // HVD_TPU_LOGGING_H
