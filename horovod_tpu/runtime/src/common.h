// Core types — TPU-native equivalent of horovod/common/common.h (N1).
//
// The reference defines Status (common.h:33-53), TensorShape (55-75) and the
// framework-adapter interfaces (Tensor/OpContext/PersistentBuffer/ReadyEvent,
// 77-110). On the TPU rebuild the framework adapters collapse into JAX
// arrays, so the native core keeps Status/TensorShape/DataType and drops the
// per-framework ABI bridge; device readiness is XLA program order.
#ifndef HVD_TPU_COMMON_H
#define HVD_TPU_COMMON_H

#include <cstdint>
#include <string>
#include <vector>

namespace hvdtpu {

// Mirrors StatusType (reference common.h:33-38).
enum class StatusType : int32_t {
  OK = 0,
  UNKNOWN_ERROR = 1,
  PRECONDITION_ERROR = 2,
  ABORTED = 3,
  INVALID_ARGUMENT = 4,
};

class Status {
 public:
  Status() = default;
  static Status OK() { return Status(); }
  static Status UnknownError(std::string msg) {
    return Status(StatusType::UNKNOWN_ERROR, std::move(msg));
  }
  static Status PreconditionError(std::string msg) {
    return Status(StatusType::PRECONDITION_ERROR, std::move(msg));
  }
  static Status Aborted(std::string msg) {
    return Status(StatusType::ABORTED, std::move(msg));
  }
  static Status InvalidArgument(std::string msg) {
    return Status(StatusType::INVALID_ARGUMENT, std::move(msg));
  }

  bool ok() const { return type_ == StatusType::OK; }
  StatusType type() const { return type_; }
  const std::string& reason() const { return reason_; }

 private:
  Status(StatusType type, std::string reason)
      : type_(type), reason_(std::move(reason)) {}
  StatusType type_ = StatusType::OK;
  std::string reason_;
};

// Wire dtypes — reference mpi_message.h:26-37 (10 dtypes) plus BFLOAT16,
// the TPU-native 16-bit float.
enum class DataType : int32_t {
  HVD_UINT8 = 0,
  HVD_INT8 = 1,
  HVD_UINT16 = 2,
  HVD_INT16 = 3,
  HVD_INT32 = 4,
  HVD_INT64 = 5,
  HVD_FLOAT16 = 6,
  HVD_FLOAT32 = 7,
  HVD_FLOAT64 = 8,
  HVD_BOOL = 9,
  HVD_BFLOAT16 = 10,
  // Beyond the reference's 10 dtypes (mpi_message.h:26-37): jax PRNG
  // keys are uint32, so the TPU wire must carry unsigned 32/64-bit.
  HVD_UINT32 = 11,
  HVD_UINT64 = 12,
};

const char* DataTypeName(DataType t);
int64_t DataTypeSize(DataType t);

// Mirrors TensorShape (reference common.h:55-75).
class TensorShape {
 public:
  TensorShape() = default;
  explicit TensorShape(std::vector<int64_t> dims) : dims_(std::move(dims)) {}
  void AddDim(int64_t d) { dims_.push_back(d); }
  int ndims() const { return static_cast<int>(dims_.size()); }
  int64_t dim_size(int i) const { return dims_[i]; }
  const std::vector<int64_t>& dims() const { return dims_; }
  int64_t num_elements() const {
    int64_t n = 1;
    for (auto d : dims_) n *= d;
    return n;
  }
  std::string DebugString() const;
  bool operator==(const TensorShape& o) const { return dims_ == o.dims_; }
  bool operator!=(const TensorShape& o) const { return dims_ != o.dims_; }

 private:
  std::vector<int64_t> dims_;
};

// "Device" for fusion-buffer keying. On TPU every eager tensor stages
// through host memory before device_put; we keep the reference's convention
// of CPU_DEVICE_ID = -1 (common.h:28) with non-negative ids meaning a local
// chip ordinal.
constexpr int CPU_DEVICE_ID = -1;

}  // namespace hvdtpu

#endif  // HVD_TPU_COMMON_H
