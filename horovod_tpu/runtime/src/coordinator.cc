// See coordinator.h. Citations refer to /root/reference paths.
#include "coordinator.h"

#include <algorithm>
#include <sstream>

namespace hvdtpu {

bool MessageTable::Increment(const Request& msg, int size) {
  auto it = table_.find(msg.tensor_name);
  if (it == table_.end()) {
    Entry e;
    e.first_seen = Clock::now();
    e.requests.push_back(msg);
    table_.emplace(msg.tensor_name, std::move(e));
    return size == 1;
  }
  it->second.requests.push_back(msg);
  return static_cast<int>(it->second.requests.size()) == size;
}

std::vector<Request> MessageTable::Take(const std::string& name) {
  auto it = table_.find(name);
  if (it == table_.end()) return {};
  auto reqs = std::move(it->second.requests);
  table_.erase(it);
  return reqs;
}

std::vector<std::string> MessageTable::StalledTensors(
    int size, double warn_after) const {
  std::vector<std::string> out;
  auto now = Clock::now();
  for (const auto& kv : table_) {
    double age =
        std::chrono::duration<double>(now - kv.second.first_seen).count();
    if (age < warn_after) continue;
    std::vector<bool> seen(size, false);
    for (const auto& r : kv.second.requests)
      if (r.request_rank >= 0 && r.request_rank < size)
        seen[r.request_rank] = true;
    std::ostringstream os;
    // "<name>\t<display line>": the tab-separated name prefix is the
    // STRUCTURED key consumers (native.py stalled()) split on, so the
    // engine's missing-ranks merge never re-parses the display text.
    os << kv.first << "\t" << kv.first << " [ready ranks:";
    for (int i = 0; i < size; ++i)
      if (seen[i]) os << " " << i;
    os << "; missing ranks:";
    for (int i = 0; i < size; ++i)
      if (!seen[i]) os << " " << i;
    os << "]";
    out.push_back(os.str());
  }
  std::sort(out.begin(), out.end());
  return out;
}

namespace {

Response ErrorResponse(const std::string& name, const std::string& msg) {
  Response r;
  r.response_type = Response::ERROR;
  r.tensor_names = {name};
  r.error_message = msg;
  return r;
}

}  // namespace

Response ConstructResponse(const std::vector<Request>& requests, int size,
                           int root_bound) {
  if (root_bound < 0) root_bound = size;
  // Mirrors ConstructMPIResponse (operations.cc:321-523): every check
  // produces a response that names the offending ranks' values instead of
  // letting the collective deadlock or crash.
  if (requests.empty()) {
    return ErrorResponse("", "No requests submitted for negotiation.");
  }
  const Request& first = requests[0];
  const std::string& name = first.tensor_name;

  if (static_cast<int>(requests.size()) != size) {
    std::ostringstream os;
    os << "Only " << requests.size() << " out of " << size
       << " ranks submitted tensor " << name << ".";
    return ErrorResponse(name, os.str());
  }

  // Rank sanity: request_rank may come off the wire — bound it before it is
  // used as an index below.
  for (const auto& r : requests) {
    if (r.request_rank < 0 || r.request_rank >= size) {
      std::ostringstream os;
      os << "Invalid request rank " << r.request_rank << " for tensor "
         << name << " (world size " << size << ").";
      return ErrorResponse(name, os.str());
    }
  }

  // Op type consistency (operations.cc:341-358).
  for (const auto& r : requests) {
    if (r.request_type != first.request_type) {
      std::ostringstream os;
      os << "Mismatched collective operations: One rank did an "
         << RequestTypeName(first.request_type) << ", but another rank did an "
         << RequestTypeName(r.request_type) << ".";
      return ErrorResponse(name, os.str());
    }
  }

  // Dtype consistency (operations.cc:360-376).
  for (const auto& r : requests) {
    if (r.tensor_type != first.tensor_type) {
      std::ostringstream os;
      os << "Mismatched data types: One rank had type "
         << DataTypeName(first.tensor_type) << ", but another rank had type "
         << DataTypeName(r.tensor_type) << ".";
      return ErrorResponse(name, os.str());
    }
  }

  if (first.request_type == Request::ALLREDUCE ||
      first.request_type == Request::BROADCAST) {
    // Full-shape consistency (operations.cc:378-396).
    for (const auto& r : requests) {
      if (r.tensor_shape != first.tensor_shape) {
        std::ostringstream os;
        os << "Mismatched " << RequestTypeName(first.request_type)
           << " tensor shapes: One rank sent a tensor of shape "
           << first.tensor_shape.DebugString()
           << ", but another rank sent a tensor of shape "
           << r.tensor_shape.DebugString() << ".";
        return ErrorResponse(name, os.str());
      }
    }
  }

  std::vector<int64_t> tensor_sizes;
  if (first.request_type == Request::ALLGATHER) {
    // All dims but the first must match (operations.cc:398-446); collect
    // per-rank first dims in rank order for the fused gather.
    if (first.tensor_shape.ndims() == 0) {
      return ErrorResponse(name, "Rank zero tried to gather a rank-zero "
                                 "tensor.");
    }
    tensor_sizes.resize(size, 0);
    for (const auto& r : requests) {
      if (r.tensor_shape.ndims() != first.tensor_shape.ndims()) {
        std::ostringstream os;
        os << "Mismatched allgather tensor shapes: One rank sent a tensor "
           << "of rank " << first.tensor_shape.ndims()
           << ", but another rank sent a tensor of rank "
           << r.tensor_shape.ndims() << ".";
        return ErrorResponse(name, os.str());
      }
      for (int d = 1; d < first.tensor_shape.ndims(); ++d) {
        if (r.tensor_shape.dim_size(d) != first.tensor_shape.dim_size(d)) {
          std::ostringstream os;
          os << "Mismatched allgather tensor shapes: One rank sent a tensor "
             << "with dimension " << d << " equal to "
             << first.tensor_shape.dim_size(d)
             << ", but another rank sent a tensor with dimension " << d
             << " equal to " << r.tensor_shape.dim_size(d) << ".";
          return ErrorResponse(name, os.str());
        }
      }
      tensor_sizes[r.request_rank] = r.tensor_shape.dim_size(0);
    }
  }

  if (first.request_type == Request::BROADCAST) {
    // Root rank consistency + validity (operations.cc:448-478).
    for (const auto& r : requests) {
      if (r.root_rank != first.root_rank) {
        std::ostringstream os;
        os << "Mismatched root ranks: One rank specified root rank "
           << first.root_rank << ", but another rank specified root rank "
           << r.root_rank << ".";
        return ErrorResponse(name, os.str());
      }
    }
    if (first.root_rank < 0 || first.root_rank >= root_bound) {
      std::ostringstream os;
      os << "Invalid root rank: " << first.root_rank
         << " (world size " << root_bound << ").";
      return ErrorResponse(name, os.str());
    }
  }

  // Device consistency (operations.cc:480-497). On the TPU path the
  // device slot carries an execution-semantics fingerprint
  // (collective._semantics_fingerprint: average/prescale/postscale/
  // sharded) — ranks disagreeing would execute DIFFERENT programs for
  // one agreed group, so a mismatch is an error verdict, not a silent
  // local subdivision.
  for (const auto& r : requests) {
    if (r.device != first.device) {
      std::ostringstream os;
      os << "Mismatched execution attributes for tensor " << name
         << ": ranks passed different average/prescale/postscale/sharded "
         << "arguments (fingerprints " << first.device << " vs "
         << r.device << ").";
      return ErrorResponse(name, os.str());
    }
  }
  std::vector<int32_t> devices(size, CPU_DEVICE_ID);
  for (const auto& r : requests) devices[r.request_rank] = r.device;

  Response resp;
  switch (first.request_type) {
    case Request::ALLREDUCE: resp.response_type = Response::ALLREDUCE; break;
    case Request::ALLGATHER: resp.response_type = Response::ALLGATHER; break;
    case Request::BROADCAST: resp.response_type = Response::BROADCAST; break;
  }
  resp.tensor_names = {name};
  resp.devices = std::move(devices);
  resp.tensor_sizes = std::move(tensor_sizes);
  return resp;
}

std::vector<Response> FuseResponses(
    std::deque<Response> responses,
    const std::unordered_map<std::string, int64_t>& sizes_bytes,
    const std::unordered_map<std::string, DataType>& dtypes,
    int64_t threshold_bytes) {
  // Mirrors the fusion loop (operations.cc:2149-2265): take the head
  // response, then scan the remaining queue for joinable responses, keeping
  // skipped ones (mixed-dtype look-ahead) in order for the next pass.
  auto bytes_of = [&](const std::string& n) -> int64_t {
    auto it = sizes_bytes.find(n);
    return it == sizes_bytes.end() ? 0 : it->second;
  };
  auto dtype_of = [&](const std::string& n) -> DataType {
    auto it = dtypes.find(n);
    return it == dtypes.end() ? DataType::HVD_FLOAT32 : it->second;
  };

  std::vector<Response> out;
  while (!responses.empty()) {
    Response head = std::move(responses.front());
    responses.pop_front();
    if (head.response_type == Response::ERROR) {
      out.push_back(std::move(head));
      continue;
    }
    int64_t total = bytes_of(head.tensor_names[0]);
    DataType head_dtype = dtype_of(head.tensor_names[0]);

    std::deque<Response> skipped;
    while (!responses.empty()) {
      Response cand = std::move(responses.front());
      responses.pop_front();
      bool joinable =
          cand.response_type == head.response_type &&
          cand.response_type != Response::ERROR &&
          dtype_of(cand.tensor_names[0]) == head_dtype &&
          cand.devices == head.devices &&
          total + bytes_of(cand.tensor_names[0]) <= threshold_bytes;
      // Fused allgathers keep one first-dim-size vector per joined tensor
      // (head.tensor_sizes grows by world_size per join); the executor
      // gathers each tensor of the group separately, so no per-rank size
      // compatibility is needed at plan time.
      if (joinable) {
        total += bytes_of(cand.tensor_names[0]);
        for (auto& n : cand.tensor_names)
          head.tensor_names.push_back(std::move(n));
        for (auto s : cand.tensor_sizes) head.tensor_sizes.push_back(s);
      } else {
        skipped.push_back(std::move(cand));
      }
    }
    responses = std::move(skipped);
    out.push_back(std::move(head));
  }
  return out;
}

}  // namespace hvdtpu
