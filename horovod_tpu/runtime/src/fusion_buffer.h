// Fusion buffer manager — TPU-native equivalent of
// horovod/common/fusion_buffer_manager.{h,cc} (N4).
//
// The reference lazily allocates ONE persistent buffer of exactly the
// fusion-threshold bytes per (device, framework) key and reallocates when
// the autotuner changes the threshold (fusion_buffer_manager.cc:21-45). On
// TPU the *device-side* fused buffer is the XLA concat inside the jitted
// program; what remains native is the HOST staging arena used to assemble
// eager numpy payloads contiguously before a single device_put (and to
// stage fused results back). Alignment is kept at 64 bytes — the
// FUSION_BUFFER_ATOMIC_UNIT (reference operations.h:52-54) — so fused
// segment boundaries stay SIMD/DMA friendly.
#ifndef HVD_TPU_FUSION_BUFFER_H
#define HVD_TPU_FUSION_BUFFER_H

#include <cstdint>
#include <map>
#include <memory>
#include <mutex>

namespace hvdtpu {

constexpr int64_t kFusionBufferAtomicUnit = 64;  // operations.h:52-54

class FusionBufferManager {
 public:
  // Returns the persistent buffer for `device`, (re)allocating when the
  // requested threshold grew (InitializeBuffer + GetBuffer,
  // fusion_buffer_manager.cc:21-53). Thread-safe.
  uint8_t* GetBuffer(int device, int64_t threshold_bytes);

  int64_t buffer_size(int device) const;

 private:
  struct Buf {
    std::unique_ptr<uint8_t[]> data;
    int64_t size = 0;
  };
  mutable std::mutex mu_;
  std::map<int, Buf> buffers_;
};

}  // namespace hvdtpu

#endif  // HVD_TPU_FUSION_BUFFER_H
