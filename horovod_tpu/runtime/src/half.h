// Software float16/bfloat16 conversion — equivalent of
// horovod/common/half.{h,cc} (N8).
//
// The reference needs fp16 software emulation because MPI has no fp16
// reduction (half.cc:42-90 registers a custom MPI_Op with F16C fast path).
// On TPU the MXU handles bf16/fp16 natively inside XLA programs; the native
// conversion here serves the host-side paths that remain — wire compression
// of control/test payloads and host staging buffers — plus parity tests.
#ifndef HVD_TPU_HALF_H
#define HVD_TPU_HALF_H

#include <cstdint>
#include <cstddef>

namespace hvdtpu {

// Bit-exact fp16 <-> fp32 (reference half.h:38-130 HalfBits2Float /
// Float2HalfBits).
float HalfBits2Float(uint16_t h);
uint16_t Float2HalfBits(float f);

// bfloat16 <-> fp32 — truncation with round-to-nearest-even, the TPU-native
// 16-bit format (no reference equivalent; bf16 is this platform's dtype).
float BF16Bits2Float(uint16_t b);
uint16_t Float2BF16Bits(float f);

// Vectorizable array sum: dst[i] += src[i] over fp16 payloads — the
// float16_sum MPI op body (half.cc:42-90), used by host-side fused
// reductions in tests and the wire path.
void HalfSum(const uint16_t* src, uint16_t* dst, size_t n);
void BF16Sum(const uint16_t* src, uint16_t* dst, size_t n);

}  // namespace hvdtpu

#endif  // HVD_TPU_HALF_H
