// Timeline — TPU-native equivalent of horovod/common/timeline.{h,cc} (N7).
//
// Chrome-trace (catapult) JSON profiler written on the coordinator process
// only, enabled by HOROVOD_TIMELINE=<file> (operations.cc:1824-1829). Design
// kept from the reference: events are pushed into a lock-free single-
// producer/single-consumer ring buffer (the reference uses
// boost::lockfree::spsc_queue of capacity 2^20, timeline.h:66-68) drained by
// a dedicated writer thread, so the hot cycle never blocks on file IO. Each
// tensor is modeled as a Chrome "process" with an interned pid
// (timeline.cc:70-90). Phases: NEGOTIATE_<OP> with per-rank ready ticks,
// then the op with nested activities (WAIT_FOR_DATA, MEMCPY_IN_FUSION_
// BUFFER, XLA_ALLREDUCE, ... — reference operations.h:29-50).
#ifndef HVD_TPU_TIMELINE_H
#define HVD_TPU_TIMELINE_H

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <memory>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace hvdtpu {

enum class TimelineRecordType : int8_t {
  EVENT_BEGIN = 'B',
  EVENT_END = 'E',
  EVENT_INSTANT = 'i',
  META = 'M',
};

struct TimelineRecord {
  TimelineRecordType type;
  int64_t pid;
  int64_t ts_us;
  // Fixed-size payloads keep the ring buffer POD (no allocation on the
  // producer side once interned).
  char name[64];
  char args[64];
};

// Lock-free SPSC ring buffer (capacity must be a power of two) — stands in
// for boost::lockfree::spsc_queue (reference timeline.h:66-68).
class SpscRing {
 public:
  explicit SpscRing(size_t capacity_pow2);
  ~SpscRing();
  bool Push(const TimelineRecord& r);   // producer
  bool Pop(TimelineRecord* r);          // consumer
  size_t dropped() const { return dropped_.load(std::memory_order_relaxed); }

 private:
  std::vector<TimelineRecord> buf_;
  size_t mask_;
  std::atomic<size_t> head_{0};  // consumer position
  std::atomic<size_t> tail_{0};  // producer position
  std::atomic<size_t> dropped_{0};
};

class Timeline {
 public:
  Timeline() = default;
  ~Timeline() { Shutdown(); }

  void Initialize(const std::string& path, bool mark_cycles);
  bool Initialized() const { return initialized_; }
  void Shutdown();

  // Negotiation phase (reference timeline.h:42-50, operations.cc:298-311).
  void NegotiateStart(const std::string& tensor_name, int32_t request_type);
  void NegotiateRankReady(const std::string& tensor_name, int rank);
  void NegotiateEnd(const std::string& tensor_name);

  // Execution phase (timeline.h:52-60).
  void Start(const std::string& tensor_name, const std::string& op_name);
  void ActivityStart(const std::string& tensor_name,
                     const std::string& activity);
  void ActivityEnd(const std::string& tensor_name);
  void End(const std::string& tensor_name, const std::string& output_shape);

  // HOROVOD_TIMELINE_MARK_CYCLES (operations.cc:1831-1835).
  void MarkCycleStart();

 private:
  int64_t TensorPid(const std::string& tensor_name);
  void Emit(TimelineRecordType type, int64_t pid, const char* name,
            const char* args);
  void WriterLoop();

  bool initialized_ = false;
  bool mark_cycles_ = false;
  std::string path_;
  std::FILE* file_ = nullptr;  // opened in Initialize, closed by writer
  std::unique_ptr<SpscRing> ring_;
  std::thread writer_;
  std::atomic<bool> stop_{false};
  std::unordered_map<std::string, int64_t> tensor_pids_;
  std::vector<std::string> pending_meta_;
  int64_t start_us_ = 0;
};

}  // namespace hvdtpu

#endif  // HVD_TPU_TIMELINE_H
