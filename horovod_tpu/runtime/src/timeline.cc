// See timeline.h. Chrome-trace JSON format (catapult), one record per line.
#include "timeline.h"

#include <chrono>
#include <cstdio>
#include <cstring>
#include <memory>
#include <mutex>

#include "logging.h"

namespace hvdtpu {

namespace {

int64_t NowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Producer-side mutex: the reference guards Timeline with a recursive mutex
// (timeline.h:112-113) because enqueue threads and the background thread
// both emit; the ring itself stays single-consumer.
std::mutex& ProducerMutex() {
  static std::mutex m;
  return m;
}

void CopyStr(char* dst, size_t cap, const std::string& s) {
  size_t n = s.size() < cap - 1 ? s.size() : cap - 1;
  std::memcpy(dst, s.data(), n);
  dst[n] = '\0';
}

}  // namespace

SpscRing::SpscRing(size_t capacity_pow2)
    : buf_(capacity_pow2), mask_(capacity_pow2 - 1) {}

SpscRing::~SpscRing() = default;

bool SpscRing::Push(const TimelineRecord& r) {
  size_t tail = tail_.load(std::memory_order_relaxed);
  size_t head = head_.load(std::memory_order_acquire);
  if (tail - head >= buf_.size()) {
    dropped_.fetch_add(1, std::memory_order_relaxed);
    return false;  // full: drop instead of blocking the hot path
  }
  buf_[tail & mask_] = r;
  tail_.store(tail + 1, std::memory_order_release);
  return true;
}

bool SpscRing::Pop(TimelineRecord* r) {
  size_t head = head_.load(std::memory_order_relaxed);
  size_t tail = tail_.load(std::memory_order_acquire);
  if (head == tail) return false;
  *r = buf_[head & mask_];
  head_.store(head + 1, std::memory_order_release);
  return true;
}

void Timeline::Initialize(const std::string& path, bool mark_cycles) {
  std::lock_guard<std::mutex> lk(ProducerMutex());
  if (initialized_ || path.empty()) return;
  // Open up front so an unwritable path disables the timeline instead of
  // filling a ring nobody drains.
  file_ = std::fopen(path.c_str(), "w");
  if (!file_) {
    HVD_LOG(ERROR) << "Failed to open timeline file " << path
                   << "; timeline disabled";
    return;
  }
  path_ = path;
  mark_cycles_ = mark_cycles;
  ring_ = std::make_unique<SpscRing>(1 << 20);  // 2^20, timeline.h:66-68
  start_us_ = NowUs();
  // A fresh trace file needs fresh pid interning: cached pids would skip
  // the process_name META records in the new file.
  tensor_pids_.clear();
  stop_.store(false);
  writer_ = std::thread([this] { WriterLoop(); });
  initialized_ = true;
}

void Timeline::Shutdown() {
  {
    std::lock_guard<std::mutex> lk(ProducerMutex());
    if (!initialized_) return;
    initialized_ = false;
  }
  stop_.store(true);
  if (writer_.joinable()) writer_.join();
}

int64_t Timeline::TensorPid(const std::string& tensor_name) {
  // Interned pid per tensor; emit Chrome process_name metadata on first
  // sight (reference timeline.cc:70-90).
  auto it = tensor_pids_.find(tensor_name);
  if (it != tensor_pids_.end()) return it->second;
  int64_t pid = static_cast<int64_t>(tensor_pids_.size()) + 1;
  tensor_pids_.emplace(tensor_name, pid);
  TimelineRecord r{};
  r.type = TimelineRecordType::META;
  r.pid = pid;
  r.ts_us = NowUs() - start_us_;
  CopyStr(r.name, sizeof(r.name), tensor_name);
  ring_->Push(r);
  return pid;
}

void Timeline::Emit(TimelineRecordType type, int64_t pid, const char* name,
                    const char* args) {
  TimelineRecord r{};
  r.type = type;
  r.pid = pid;
  r.ts_us = NowUs() - start_us_;
  if (name) CopyStr(r.name, sizeof(r.name), name);
  if (args) CopyStr(r.args, sizeof(r.args), args);
  ring_->Push(r);
}

void Timeline::NegotiateStart(const std::string& tensor_name,
                              int32_t request_type) {
  std::lock_guard<std::mutex> lk(ProducerMutex());
  if (!initialized_) return;
  static const char* kOps[] = {"NEGOTIATE_ALLREDUCE", "NEGOTIATE_ALLGATHER",
                               "NEGOTIATE_BROADCAST"};
  const char* op = (request_type >= 0 && request_type < 3)
                       ? kOps[request_type] : "NEGOTIATE";
  Emit(TimelineRecordType::EVENT_BEGIN, TensorPid(tensor_name), op, nullptr);
}

void Timeline::NegotiateRankReady(const std::string& tensor_name, int rank) {
  std::lock_guard<std::mutex> lk(ProducerMutex());
  if (!initialized_) return;
  char name[32];
  std::snprintf(name, sizeof(name), "%d", rank);
  Emit(TimelineRecordType::EVENT_INSTANT, TensorPid(tensor_name), name,
       nullptr);
}

void Timeline::NegotiateEnd(const std::string& tensor_name) {
  std::lock_guard<std::mutex> lk(ProducerMutex());
  if (!initialized_) return;
  Emit(TimelineRecordType::EVENT_END, TensorPid(tensor_name), nullptr,
       nullptr);
}

void Timeline::Start(const std::string& tensor_name,
                     const std::string& op_name) {
  std::lock_guard<std::mutex> lk(ProducerMutex());
  if (!initialized_) return;
  Emit(TimelineRecordType::EVENT_BEGIN, TensorPid(tensor_name),
       op_name.c_str(), nullptr);
}

void Timeline::ActivityStart(const std::string& tensor_name,
                             const std::string& activity) {
  std::lock_guard<std::mutex> lk(ProducerMutex());
  if (!initialized_) return;
  Emit(TimelineRecordType::EVENT_BEGIN, TensorPid(tensor_name),
       activity.c_str(), nullptr);
}

void Timeline::ActivityEnd(const std::string& tensor_name) {
  std::lock_guard<std::mutex> lk(ProducerMutex());
  if (!initialized_) return;
  Emit(TimelineRecordType::EVENT_END, TensorPid(tensor_name), nullptr,
       nullptr);
}

void Timeline::End(const std::string& tensor_name,
                   const std::string& output_shape) {
  std::lock_guard<std::mutex> lk(ProducerMutex());
  if (!initialized_) return;
  // Close the activity (if any) and the op event; log shape as args
  // (reference timeline.cc End section).
  Emit(TimelineRecordType::EVENT_END, TensorPid(tensor_name), nullptr,
       output_shape.empty() ? nullptr : output_shape.c_str());
}

void Timeline::MarkCycleStart() {
  std::lock_guard<std::mutex> lk(ProducerMutex());
  if (!initialized_ || !mark_cycles_) return;
  Emit(TimelineRecordType::EVENT_INSTANT, 0, "CYCLE_START", nullptr);
}

void Timeline::WriterLoop() {
  std::FILE* f = file_;
  std::fputs("[\n", f);
  TimelineRecord r;
  bool first = true;
  auto write_one = [&](const TimelineRecord& rec) {
    if (!first) std::fputs(",\n", f);
    first = false;
    switch (rec.type) {
      case TimelineRecordType::META:
        std::fprintf(f,
                     "{\"name\": \"process_name\", \"ph\": \"M\", \"pid\": "
                     "%lld, \"args\": {\"name\": \"%s\"}}",
                     (long long)rec.pid, rec.name);
        break;
      case TimelineRecordType::EVENT_BEGIN:
        std::fprintf(f,
                     "{\"name\": \"%s\", \"ph\": \"B\", \"pid\": %lld, "
                     "\"tid\": 0, \"ts\": %lld}",
                     rec.name, (long long)rec.pid, (long long)rec.ts_us);
        break;
      case TimelineRecordType::EVENT_END:
        if (rec.args[0]) {
          std::fprintf(f,
                       "{\"ph\": \"E\", \"pid\": %lld, \"tid\": 0, \"ts\": "
                       "%lld, \"args\": {\"shape\": \"%s\"}}",
                       (long long)rec.pid, (long long)rec.ts_us, rec.args);
        } else {
          std::fprintf(f,
                       "{\"ph\": \"E\", \"pid\": %lld, \"tid\": 0, \"ts\": "
                       "%lld}",
                       (long long)rec.pid, (long long)rec.ts_us);
        }
        break;
      case TimelineRecordType::EVENT_INSTANT:
        std::fprintf(f,
                     "{\"name\": \"%s\", \"ph\": \"i\", \"pid\": %lld, "
                     "\"tid\": 0, \"ts\": %lld, \"s\": \"g\"}",
                     rec.name, (long long)rec.pid, (long long)rec.ts_us);
        break;
    }
  };
  while (!stop_.load(std::memory_order_acquire)) {
    bool any = false;
    while (ring_->Pop(&r)) {
      write_one(r);
      any = true;
    }
    if (any) {
      std::fflush(f);
    } else {
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  }
  while (ring_->Pop(&r)) write_one(r);
  // Leave the JSON array unterminated-but-valid-enough for catapult, the
  // same trailing behavior as the reference writer (chrome://tracing
  // accepts a missing closing bracket).
  std::fputs("\n", f);
  std::fclose(f);
}

}  // namespace hvdtpu
