// Bayesian optimization — equivalent of
// horovod/common/optim/bayesian_optimization.{h,cc} (N6): expected-
// improvement acquisition over a GP surrogate with random restarts
// (bayesian_optimization.h:45-110). The reference refines EI maxima with
// L-BFGS; here EI is maximized by dense random sampling plus local
// coordinate refinement — equivalent behavior for the 2-D (fusion MB,
// cycle ms) space.
#ifndef HVD_TPU_BAYESIAN_OPTIMIZATION_H
#define HVD_TPU_BAYESIAN_OPTIMIZATION_H

#include <cstdint>
#include <random>
#include <utility>
#include <vector>

#include "gaussian_process.h"

namespace hvdtpu {

class BayesianOptimization {
 public:
  // bounds: per-dimension [lo, hi].
  explicit BayesianOptimization(
      std::vector<std::pair<double, double>> bounds, double xi = 0.01,
      uint64_t seed = 42)
      : bounds_(std::move(bounds)), xi_(xi), rng_(seed) {}

  void AddSample(const std::vector<double>& x, double y);

  // Next point to try: argmax of expected improvement.
  std::vector<double> NextSample();

  // Best observed point so far.
  std::vector<double> BestSample() const;

  size_t num_samples() const { return gp_.num_samples(); }

 private:
  std::vector<double> Normalize(const std::vector<double>& x) const;
  std::vector<double> Denormalize(const std::vector<double>& x) const;
  double ExpectedImprovement(const std::vector<double>& xn) const;

  std::vector<std::pair<double, double>> bounds_;
  double xi_;
  std::mt19937_64 rng_;
  GaussianProcess gp_;
  std::vector<std::vector<double>> raw_xs_;
  std::vector<double> raw_ys_;
};

}  // namespace hvdtpu

#endif  // HVD_TPU_BAYESIAN_OPTIMIZATION_H
