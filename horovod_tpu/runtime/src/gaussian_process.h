// Gaussian-process regression — equivalent of
// horovod/common/optim/gaussian_process.{h,cc} (N6).
//
// RBF kernel + Cholesky posterior, as the reference (gaussian_process.h:
// 45-111). The reference fits kernel hyperparameters with L-BFGS over
// Eigen; this rebuild has no Eigen/lbfgs dependency, so the kernel length
// scale/amplitude are fit by maximizing the log marginal likelihood over a
// small log-spaced grid — same objective, simpler optimizer, adequate for
// the 2-D knob space the autotuner explores.
#ifndef HVD_TPU_GAUSSIAN_PROCESS_H
#define HVD_TPU_GAUSSIAN_PROCESS_H

#include <cstddef>
#include <vector>

namespace hvdtpu {

class GaussianProcess {
 public:
  GaussianProcess(double length_scale = 0.5, double noise = 1e-6)
      : length_(length_scale), noise_(noise) {}

  // Add observation x (d-dim, normalized to [0,1]) with value y.
  void AddSample(const std::vector<double>& x, double y);

  // Re-fit hyperparameters (grid-search marginal likelihood) and refresh the
  // Cholesky factorization. Returns false if the kernel matrix is singular.
  bool Fit();

  // Posterior mean and variance at x.
  void Predict(const std::vector<double>& x, double* mean,
               double* variance) const;

  size_t num_samples() const { return ys_.size(); }
  double best_y() const;

 private:
  double Kernel(const std::vector<double>& a,
                const std::vector<double>& b) const;
  bool Cholesky(const std::vector<double>& a, int n,
                std::vector<double>* l) const;
  // Solve L y = b then L^T x = y.
  std::vector<double> CholSolve(const std::vector<double>& l, int n,
                                std::vector<double> b) const;
  double LogMarginalLikelihood(double length, double amp) const;

  double length_;
  double amp_ = 1.0;
  double noise_;
  std::vector<std::vector<double>> xs_;
  std::vector<double> ys_;
  double y_mean_ = 0.0;
  // Cached factorization.
  std::vector<double> chol_;
  std::vector<double> alpha_;   // K^-1 (y - mean)
  bool fitted_ = false;
};

}  // namespace hvdtpu

#endif  // HVD_TPU_GAUSSIAN_PROCESS_H
