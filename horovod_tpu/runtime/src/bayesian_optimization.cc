#include "bayesian_optimization.h"

#include <algorithm>
#include <cmath>

namespace hvdtpu {

namespace {
// Standard normal pdf/cdf for EI.
double Pdf(double z) { return std::exp(-0.5 * z * z) / std::sqrt(2 * M_PI); }
double Cdf(double z) { return 0.5 * std::erfc(-z / std::sqrt(2.0)); }
}  // namespace

std::vector<double> BayesianOptimization::Normalize(
    const std::vector<double>& x) const {
  std::vector<double> out(x.size());
  for (size_t i = 0; i < x.size(); ++i) {
    double lo = bounds_[i].first, hi = bounds_[i].second;
    out[i] = hi > lo ? (x[i] - lo) / (hi - lo) : 0.0;
  }
  return out;
}

std::vector<double> BayesianOptimization::Denormalize(
    const std::vector<double>& x) const {
  std::vector<double> out(x.size());
  for (size_t i = 0; i < x.size(); ++i) {
    double lo = bounds_[i].first, hi = bounds_[i].second;
    out[i] = lo + x[i] * (hi - lo);
  }
  return out;
}

void BayesianOptimization::AddSample(const std::vector<double>& x, double y) {
  raw_xs_.push_back(x);
  raw_ys_.push_back(y);
  gp_.AddSample(Normalize(x), y);
  gp_.Fit();
}

double BayesianOptimization::ExpectedImprovement(
    const std::vector<double>& xn) const {
  // EI(x) = (mu - best - xi) Phi(z) + sigma phi(z)
  // (reference bayesian_optimization.cc ExpectedImprovement).
  double mu, var;
  gp_.Predict(xn, &mu, &var);
  double sigma = std::sqrt(var);
  double best = gp_.best_y();
  double imp = mu - best - xi_;
  if (sigma < 1e-12) return std::max(imp, 0.0);
  double z = imp / sigma;
  return imp * Cdf(z) + sigma * Pdf(z);
}

std::vector<double> BayesianOptimization::NextSample() {
  size_t d = bounds_.size();
  if (gp_.num_samples() == 0) {
    // No data: center of the space.
    std::vector<double> mid(d, 0.5);
    return Denormalize(mid);
  }
  std::uniform_real_distribution<double> u(0.0, 1.0);
  std::vector<double> best_x(d, 0.5);
  double best_ei = -1.0;
  // Random restarts (reference uses n_iter random restarts + L-BFGS).
  for (int it = 0; it < 512; ++it) {
    std::vector<double> x(d);
    for (size_t i = 0; i < d; ++i) x[i] = u(rng_);
    double ei = ExpectedImprovement(x);
    if (ei > best_ei) {
      best_ei = ei;
      best_x = x;
    }
  }
  // Local coordinate refinement around the incumbent.
  double step = 0.05;
  for (int round = 0; round < 3; ++round, step *= 0.5) {
    for (size_t i = 0; i < d; ++i) {
      for (double delta : {-step, step}) {
        std::vector<double> x = best_x;
        x[i] = std::min(1.0, std::max(0.0, x[i] + delta));
        double ei = ExpectedImprovement(x);
        if (ei > best_ei) {
          best_ei = ei;
          best_x = x;
        }
      }
    }
  }
  return Denormalize(best_x);
}

std::vector<double> BayesianOptimization::BestSample() const {
  if (raw_ys_.empty()) return {};
  size_t bi = 0;
  for (size_t i = 1; i < raw_ys_.size(); ++i)
    if (raw_ys_[i] > raw_ys_[bi]) bi = i;
  return raw_xs_[bi];
}

}  // namespace hvdtpu
