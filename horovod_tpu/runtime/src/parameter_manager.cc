#include "parameter_manager.h"

#include <algorithm>
#include <cmath>

#include "logging.h"

namespace hvdtpu {

namespace {
// Knob bounds — fusion threshold 0..64 MB, cycle time 1..100 ms
// (reference parameter_manager.cc:41-54).
std::vector<std::pair<double, double>> KnobBounds() {
  return {{0.0, 64.0}, {1.0, 100.0}};
}
}  // namespace

ParameterManager::ParameterManager()
    : bo_flat_(KnobBounds(), 0.01, 41), bo_hier_(KnobBounds(), 0.01, 43) {}

void ParameterManager::Initialize(int rank, const std::string& log_path) {
  rank_ = rank;
  // Re-initialization (init after shutdown) restarts tuning from scratch:
  // drop converged/accumulated state and any previous log handle.
  done_ = false;
  active_ = false;
  warmups_left_ = kWarmupSamples;
  acc_bytes_ = 0;
  acc_seconds_ = 0.0;
  acc_cycles_ = 0;
  samples_.clear();
  steps_ = 0;
  best_score_ = -1.0;
  if (log_) {
    std::fclose(log_);
    log_ = nullptr;
  }
  if (rank == 0 && !log_path.empty()) {
    log_ = std::fopen(log_path.c_str(), "w");
    if (log_) std::fputs("fusion_mb,cycle_ms,hierarchical,score\n", log_);
  }
}

int64_t ParameterManager::TensorFusionThresholdBytes() const {
  return static_cast<int64_t>(fusion_mb_ * 1024.0 * 1024.0);
}

double ParameterManager::CycleTimeMs() const { return cycle_ms_; }

bool ParameterManager::HierarchicalAllreduce() const { return hierarchical_; }

bool ParameterManager::Update(int64_t bytes, double seconds) {
  if (!active_ || done_) return false;
  acc_bytes_ += bytes;
  acc_seconds_ += seconds;
  if (++acc_cycles_ < kCyclesPerSample) return false;

  // Score = bytes per microsecond (parameter_manager.cc:144-170).
  double score =
      acc_seconds_ > 0 ? (acc_bytes_ / (acc_seconds_ * 1e6)) : 0.0;
  acc_bytes_ = 0;
  acc_seconds_ = 0;
  acc_cycles_ = 0;

  if (warmups_left_ > 0) {
    --warmups_left_;
    return false;
  }
  samples_.push_back(score);
  if (static_cast<int>(samples_.size()) < kSamplesPerStep) return false;

  std::vector<double> s = samples_;
  samples_.clear();
  std::nth_element(s.begin(), s.begin() + s.size() / 2, s.end());
  double median = s[s.size() / 2];
  LogSample(median);
  Tune(median);
  return true;
}

void ParameterManager::Tune(double median_score) {
  // Record the observation for the active category.
  std::vector<double> point = {fusion_mb_, cycle_ms_};
  (hierarchical_ ? bo_hier_ : bo_flat_).AddSample(point, median_score);
  if (median_score > best_score_) {
    best_score_ = median_score;
    best_fusion_mb_ = fusion_mb_;
    best_cycle_ms_ = cycle_ms_;
    best_hierarchical_ = hierarchical_;
  }

  if (++steps_ >= kMaxSteps) {
    SetDone();
    return;
  }

  // Alternate the categorical flag (CategoricalParameter sweep) and ask the
  // corresponding BO for its next point.
  category_ = (category_ + 1) % 4;           // explore hierarchical 1 in 4
  bool next_hier = category_ == 3;
  auto next = (next_hier ? bo_hier_ : bo_flat_).NextSample();
  ApplyPoint(next, next_hier);
  HVD_LOG(DEBUG) << "autotune step " << steps_ << ": fusion_mb=" << fusion_mb_
                 << " cycle_ms=" << cycle_ms_ << " hier=" << hierarchical_
                 << " (median score " << median_score << ")";
}

void ParameterManager::ApplyPoint(const std::vector<double>& p,
                                  bool hierarchical) {
  fusion_mb_ = std::min(64.0, std::max(0.0, p[0]));
  cycle_ms_ = std::min(100.0, std::max(1.0, p[1]));
  hierarchical_ = hierarchical;
}

void ParameterManager::SetDone() {
  // Freeze to best (parameter_manager.cc:173-209).
  fusion_mb_ = best_fusion_mb_;
  cycle_ms_ = best_cycle_ms_;
  hierarchical_ = best_hierarchical_;
  done_ = true;
  if (rank_ == 0) {
    HVD_LOG(INFO) << "autotune converged: fusion_mb=" << fusion_mb_
                  << " cycle_ms=" << cycle_ms_
                  << " hierarchical=" << hierarchical_
                  << " score=" << best_score_;
  }
  if (log_) {
    std::fflush(log_);
    std::fclose(log_);
    log_ = nullptr;
  }
}

void ParameterManager::LogSample(double score) {
  if (log_) {
    std::fprintf(log_, "%.3f,%.3f,%d,%.6f\n", fusion_mb_, cycle_ms_,
                 hierarchical_ ? 1 : 0, score);
    std::fflush(log_);
  }
}

}  // namespace hvdtpu
