#include "parameter_manager.h"

#include <algorithm>
#include <cmath>

#include "logging.h"

namespace hvdtpu {

namespace {
// Knob bounds — fusion threshold 0..64 MB, cycle time 1..100 ms
// (reference parameter_manager.cc:41-54).
std::vector<std::pair<double, double>> KnobBounds() {
  return {{0.0, 64.0}, {1.0, 100.0}};
}
}  // namespace

ParameterManager::ParameterManager() {
  // One BO per (hier_allreduce, hier_allgather) combination, distinct
  // seeds so exploration differs across categories.
  for (int c = 0; c < 4; ++c)
    bo_.emplace_back(KnobBounds(), 0.01, 41 + 2 * c);
}

void ParameterManager::Initialize(int rank, const std::string& log_path) {
  rank_ = rank;
  // Re-initialization (init after shutdown) restarts tuning from scratch:
  // drop converged/accumulated state and any previous log handle.
  done_ = false;
  active_ = false;
  warmups_left_ = kWarmupSamples;
  acc_bytes_ = 0;
  acc_seconds_ = 0.0;
  acc_cycles_ = 0;
  samples_.clear();
  steps_ = 0;
  best_score_ = -1.0;
  if (log_) {
    std::fclose(log_);
    log_ = nullptr;
  }
  if (rank == 0 && !log_path.empty()) {
    log_ = std::fopen(log_path.c_str(), "w");
    if (log_)
      std::fputs("fusion_mb,cycle_ms,hier_allreduce,hier_allgather,score\n",
                 log_);
  }
}

int64_t ParameterManager::TensorFusionThresholdBytes() const {
  return static_cast<int64_t>(fusion_mb_ * 1024.0 * 1024.0);
}

double ParameterManager::CycleTimeMs() const { return cycle_ms_; }

bool ParameterManager::HierarchicalAllreduce() const {
  return hier_allreduce_;
}

bool ParameterManager::HierarchicalAllgather() const {
  return hier_allgather_;
}

bool ParameterManager::Update(int64_t bytes, double seconds) {
  if (!active_ || done_) return false;
  acc_bytes_ += bytes;
  acc_seconds_ += seconds;
  if (++acc_cycles_ < kCyclesPerSample) return false;

  // Score = bytes per microsecond (parameter_manager.cc:144-170).
  double score =
      acc_seconds_ > 0 ? (acc_bytes_ / (acc_seconds_ * 1e6)) : 0.0;
  acc_bytes_ = 0;
  acc_seconds_ = 0;
  acc_cycles_ = 0;

  if (warmups_left_ > 0) {
    --warmups_left_;
    return false;
  }
  samples_.push_back(score);
  if (static_cast<int>(samples_.size()) < kSamplesPerStep) return false;

  std::vector<double> s = samples_;
  samples_.clear();
  std::nth_element(s.begin(), s.begin() + s.size() / 2, s.end());
  double median = s[s.size() / 2];
  LogSample(median);
  Tune(median);
  return true;
}

void ParameterManager::Tune(double median_score) {
  // Record the observation for the active categorical combination.
  std::vector<double> point = {fusion_mb_, cycle_ms_};
  bo_[Combo()].AddSample(point, median_score);
  if (median_score > best_score_) {
    best_score_ = median_score;
    best_fusion_mb_ = fusion_mb_;
    best_cycle_ms_ = cycle_ms_;
    best_combo_ = Combo();
  }

  if (++steps_ >= kMaxSteps) {
    SetDone();
    return;
  }

  // Sweep both categoricals (the reference's two CategoricalParameter
  // managers, parameter_manager.cc:41-54): mostly-flat schedule with
  // each non-flat combination explored once per period.
  static const int kSchedule[8] = {0, 2, 0, 1, 0, 3, 0, 0};
  category_ = (category_ + 1) % 8;
  int next_combo = kSchedule[category_];
  auto next = bo_[next_combo].NextSample();
  ApplyPoint(next, next_combo);
  HVD_LOG(DEBUG) << "autotune step " << steps_ << ": fusion_mb=" << fusion_mb_
                 << " cycle_ms=" << cycle_ms_
                 << " hier_ar=" << hier_allreduce_
                 << " hier_ag=" << hier_allgather_
                 << " (median score " << median_score << ")";
}

void ParameterManager::ApplyPoint(const std::vector<double>& p, int combo) {
  fusion_mb_ = std::min(64.0, std::max(0.0, p[0]));
  cycle_ms_ = std::min(100.0, std::max(1.0, p[1]));
  hier_allreduce_ = (combo & 2) != 0;
  hier_allgather_ = (combo & 1) != 0;
}

void ParameterManager::SetDone() {
  // Freeze to best (parameter_manager.cc:173-209).
  fusion_mb_ = best_fusion_mb_;
  cycle_ms_ = best_cycle_ms_;
  hier_allreduce_ = (best_combo_ & 2) != 0;
  hier_allgather_ = (best_combo_ & 1) != 0;
  done_ = true;
  if (rank_ == 0) {
    HVD_LOG(INFO) << "autotune converged: fusion_mb=" << fusion_mb_
                  << " cycle_ms=" << cycle_ms_
                  << " hier_allreduce=" << hier_allreduce_
                  << " hier_allgather=" << hier_allgather_
                  << " score=" << best_score_;
  }
  if (log_) {
    std::fflush(log_);
    std::fclose(log_);
    log_ = nullptr;
  }
}

void ParameterManager::LogSample(double score) {
  if (log_) {
    std::fprintf(log_, "%.3f,%.3f,%d,%d,%.6f\n", fusion_mb_, cycle_ms_,
                 hier_allreduce_ ? 1 : 0, hier_allgather_ ? 1 : 0, score);
    std::fflush(log_);
  }
}

}  // namespace hvdtpu
