#include "logging.h"

#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>

namespace hvdtpu {

LogLevel MinLogLevelFromEnv() {
  // HOROVOD_LOG_LEVEL (reference logging.cc:76-84); HOROVOD_TPU_ overrides.
  const char* v = std::getenv("HOROVOD_TPU_LOG_LEVEL");
  if (!v) v = std::getenv("HOROVOD_LOG_LEVEL");
  if (!v) return LogLevel::WARNING;
  std::string s(v);
  if (s == "trace") return LogLevel::TRACE;
  if (s == "debug") return LogLevel::DEBUG;
  if (s == "info") return LogLevel::INFO;
  if (s == "warning") return LogLevel::WARNING;
  if (s == "error") return LogLevel::ERROR;
  if (s == "fatal") return LogLevel::FATAL;
  return LogLevel::WARNING;
}

bool LogHideTimeFromEnv() {
  const char* v = std::getenv("HOROVOD_TPU_LOG_HIDE_TIME");
  if (!v) v = std::getenv("HOROVOD_LOG_HIDE_TIME");
  return v != nullptr && std::strcmp(v, "1") == 0;
}

namespace {
const char* LevelName(LogLevel l) {
  switch (l) {
    case LogLevel::TRACE: return "trace";
    case LogLevel::DEBUG: return "debug";
    case LogLevel::INFO: return "info";
    case LogLevel::WARNING: return "warning";
    case LogLevel::ERROR: return "error";
    case LogLevel::FATAL: return "fatal";
  }
  return "?";
}
}  // namespace

LogMessage::LogMessage(const char* file, int line, LogLevel level)
    : file_(file), line_(line), level_(level) {}

LogMessage::~LogMessage() {
  static LogLevel min_level = MinLogLevelFromEnv();
  static bool hide_time = LogHideTimeFromEnv();
  if (level_ < min_level) return;
  if (!hide_time) {
    auto now = std::chrono::system_clock::now();
    std::time_t t = std::chrono::system_clock::to_time_t(now);
    auto us = std::chrono::duration_cast<std::chrono::microseconds>(
                  now.time_since_epoch()).count() % 1000000;
    char buf[32];
    std::tm tm_buf;
    localtime_r(&t, &tm_buf);
    std::strftime(buf, sizeof(buf), "%Y-%m-%d %H:%M:%S", &tm_buf);
    std::fprintf(stderr, "[%s.%06ld: %s %s:%d] %s\n", buf, (long)us,
                 LevelName(level_), file_, line_, str().c_str());
  } else {
    std::fprintf(stderr, "[%s %s:%d] %s\n", LevelName(level_), file_, line_,
                 str().c_str());
  }
  if (level_ == LogLevel::FATAL) std::abort();
}

}  // namespace hvdtpu
