// Wire protocol (control plane) — TPU-native equivalent of
// horovod/common/mpi_message.{h,cc} + wire/mpi_message.fbs (N2).
//
// The reference serializes negotiation messages with FlatBuffers. We use a
// dependency-free little-endian binary format (length-prefixed strings,
// fixed-width ints): the control plane rides a TCP rendezvous between host
// processes instead of MPI_Gatherv/Bcast, and messages are small (names +
// shapes), so a compact hand-rolled codec is simpler and faster than
// vendoring a serialization library.
#ifndef HVD_TPU_MESSAGE_H
#define HVD_TPU_MESSAGE_H

#include <cstdint>
#include <string>
#include <vector>

#include "common.h"

namespace hvdtpu {

// Mirrors MPIRequest (reference mpi_message.h:44-86): one rank announcing a
// tensor is ready for a collective.
struct Request {
  enum Type : int32_t { ALLREDUCE = 0, ALLGATHER = 1, BROADCAST = 2 };

  int32_t request_rank = 0;
  Type request_type = ALLREDUCE;
  DataType tensor_type = DataType::HVD_FLOAT32;
  std::string tensor_name;
  int32_t root_rank = -1;
  int32_t device = CPU_DEVICE_ID;
  TensorShape tensor_shape;

  void SerializeTo(std::vector<uint8_t>* out) const;
  static bool ParseFrom(const uint8_t* data, size_t len, size_t* consumed,
                        Request* out);
};

const char* RequestTypeName(Request::Type t);

// Mirrors MPIRequestList{requests, shutdown} (mpi_message.h:88-105).
struct RequestList {
  std::vector<Request> requests;
  bool shutdown = false;

  void SerializeTo(std::vector<uint8_t>* out) const;
  static bool ParseFrom(const uint8_t* data, size_t len, RequestList* out);
};

// Mirrors MPIResponse (mpi_message.h:112-155): the coordinator's verdict for
// one fused group — op to run, fused tensor names, error text, devices, and
// per-rank first-dim sizes for allgather.
struct Response {
  enum Type : int32_t {
    ALLREDUCE = 0,
    ALLGATHER = 1,
    BROADCAST = 2,
    ERROR = 3,
  };

  // Execution-mode flags stamped by the coordinator at plan time. SPMD
  // execution requires every process to run the SAME program for a group,
  // so knobs that change the program (hierarchical modes — autotuned or
  // env-set) travel WITH the group instead of being applied independently
  // per process (the synchronization the reference gets from SyncParams
  // inside its lockstep cycle, parameter_manager.cc:213-246).
  enum Flags : int32_t {
    HIERARCHICAL_ALLREDUCE = 1 << 0,
    HIERARCHICAL_ALLGATHER = 1 << 1,
  };

  Type response_type = ALLREDUCE;
  std::vector<std::string> tensor_names;
  std::string error_message;
  std::vector<int32_t> devices;
  // Allgather: first-dimension size contributed by each rank
  // (mpi_message.h:147-152 tensor_sizes).
  std::vector<int64_t> tensor_sizes;
  int32_t flags = 0;

  void SerializeTo(std::vector<uint8_t>* out) const;
  static bool ParseFrom(const uint8_t* data, size_t len, size_t* consumed,
                        Response* out);
};

// Mirrors MPIResponseList (mpi_message.h:157-174).
struct ResponseList {
  std::vector<Response> responses;
  bool shutdown = false;

  void SerializeTo(std::vector<uint8_t>* out) const;
  static bool ParseFrom(const uint8_t* data, size_t len, ResponseList* out);
};

}  // namespace hvdtpu

#endif  // HVD_TPU_MESSAGE_H
