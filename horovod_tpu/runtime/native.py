"""ctypes binding to the native runtime core — the equivalent of the
reference's ``HorovodBasics`` ctypes loader (horovod/common/__init__.py:
23-154), which loads the framework .so and exposes the C init/rank API.

Loads (building on demand) ``libhorovod_tpu_core.so`` and exposes a typed
wrapper. The native core owns the background cycle, tensor table, fusion
planning, timeline, stall detection and autotuner; Python registers an
execute callback that runs the planned groups as XLA programs.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..utils.logging import get_logger

_log = get_logger("native")

# Wire dtype enum — runtime/src/common.h DataType.
DTYPE_TO_ENUM = {
    np.dtype(np.uint8): 0,
    np.dtype(np.int8): 1,
    np.dtype(np.uint16): 2,
    np.dtype(np.int16): 3,
    np.dtype(np.int32): 4,
    np.dtype(np.int64): 5,
    np.dtype(np.float16): 6,
    np.dtype(np.float32): 7,
    np.dtype(np.float64): 8,
    np.dtype(bool): 9,
    np.dtype(np.uint32): 11,
    np.dtype(np.uint64): 12,
}
BFLOAT16_ENUM = 10

EXECUTE_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_int32,
                              ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
                              ctypes.c_char_p)

# Multi-process transport bridge: (user, req_bytes, req_len, nreq,
# complete, pending, resp_buf, resp_cap) -> resp_len (see core.cc
# TransportCallback). `complete` marks the batch a complete enqueue burst
# (eager-plannable by the coordinator).
TRANSPORT_CB = ctypes.CFUNCTYPE(
    ctypes.c_int64, ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64)

# Group delivery: (user, op, handles, count, nnames, sizes, nsizes, flags,
# error) (see core.cc GroupCallback).
GROUP_CB = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
    ctypes.c_int32, ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
    ctypes.c_int32, ctypes.c_int32, ctypes.c_char_p)


class NativeCore:
    """Typed wrapper over the hvdtpu_* C API."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        self._cb_refs = {}  # keep callbacks alive (ctypes requirement)
        self._configure()

    def _configure(self):
        lib = self._lib
        lib.hvdtpu_init.argtypes = [ctypes.c_int] * 4
        lib.hvdtpu_init.restype = ctypes.c_int
        lib.hvdtpu_initialized.restype = ctypes.c_int
        lib.hvdtpu_shutdown.restype = None
        lib.hvdtpu_enqueue.argtypes = [
            ctypes.c_int32, ctypes.c_char_p, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int64]
        lib.hvdtpu_enqueue.restype = ctypes.c_int64
        lib.hvdtpu_complete.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32, ctypes.c_int32,
            ctypes.c_char_p]
        lib.hvdtpu_poll.argtypes = [ctypes.c_int64]
        lib.hvdtpu_poll.restype = ctypes.c_int32
        lib.hvdtpu_release_handle.argtypes = [ctypes.c_int64]
        lib.hvdtpu_set_execute_callback.argtypes = [EXECUTE_CB,
                                                    ctypes.c_void_p]
        lib.hvdtpu_set_transport_callback.argtypes = [TRANSPORT_CB,
                                                      ctypes.c_void_p]
        lib.hvdtpu_set_group_callback.argtypes = [GROUP_CB, ctypes.c_void_p]
        lib.hvdtpu_ctl_create.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int64, ctypes.c_double,
            ctypes.c_double, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_char_p]
        lib.hvdtpu_ctl_create.restype = ctypes.c_void_p
        lib.hvdtpu_ctl_destroy.argtypes = [ctypes.c_void_p]
        lib.hvdtpu_ctl_announce.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64]
        lib.hvdtpu_ctl_announce.restype = ctypes.c_int64
        lib.hvdtpu_ctl_group_count.argtypes = [ctypes.c_void_p]
        lib.hvdtpu_ctl_group_count.restype = ctypes.c_int64
        lib.hvdtpu_ctl_base_seq.argtypes = [ctypes.c_void_p]
        lib.hvdtpu_ctl_base_seq.restype = ctypes.c_int64
        lib.hvdtpu_ctl_shutdown_flag.argtypes = [ctypes.c_void_p]
        lib.hvdtpu_ctl_shutdown_flag.restype = ctypes.c_int
        lib.hvdtpu_ctl_fetch.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64]
        lib.hvdtpu_ctl_fetch.restype = ctypes.c_int64
        lib.hvdtpu_ctl_tick.argtypes = [ctypes.c_void_p]
        lib.hvdtpu_ctl_plan.argtypes = [ctypes.c_void_p]
        lib.hvdtpu_ctl_plan.restype = ctypes.c_int64
        lib.hvdtpu_ctl_plan_ready.argtypes = [ctypes.c_void_p]
        lib.hvdtpu_ctl_plan_ready.restype = ctypes.c_int64
        lib.hvdtpu_flush.restype = None
        lib.hvdtpu_burst_begin.restype = None
        lib.hvdtpu_burst_end.restype = None
        lib.hvdtpu_current_flags.restype = ctypes.c_int32
        lib.hvdtpu_ctl_maybe_plan.argtypes = [ctypes.c_void_p]
        lib.hvdtpu_ctl_maybe_plan.restype = ctypes.c_int64
        lib.hvdtpu_ctl_params.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32)]
        lib.hvdtpu_ctl_stalled.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64]
        lib.hvdtpu_ctl_stalled.restype = ctypes.c_int64
        lib.hvdtpu_ctl_set_fusion_threshold.argtypes = [
            ctypes.c_void_p, ctypes.c_int64]
        lib.hvdtpu_set_fusion_threshold.argtypes = [ctypes.c_int64]
        lib.hvdtpu_get_fusion_threshold.restype = ctypes.c_int64
        lib.hvdtpu_set_cycle_time_ms.argtypes = [ctypes.c_double]
        lib.hvdtpu_get_cycle_time_ms.restype = ctypes.c_double
        lib.hvdtpu_timeline_activity_start.argtypes = [ctypes.c_char_p,
                                                       ctypes.c_char_p]
        lib.hvdtpu_timeline_activity_end.argtypes = [ctypes.c_char_p]
        lib.hvdtpu_timeline_enabled.restype = ctypes.c_int
        lib.hvdtpu_autotune_active.restype = ctypes.c_int
        lib.hvdtpu_autotune_done.restype = ctypes.c_int
        lib.hvdtpu_wire_make_request.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_char_p,
            ctypes.c_int32, ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64]
        lib.hvdtpu_wire_make_request.restype = ctypes.c_int64
        lib.hvdtpu_wire_roundtrip_request_list.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64]
        lib.hvdtpu_wire_roundtrip_request_list.restype = ctypes.c_int64
        lib.hvdtpu_negotiate.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32]
        lib.hvdtpu_negotiate.restype = ctypes.c_int32
        for name in ("hvdtpu_half_to_float", "hvdtpu_float_to_half",
                     "hvdtpu_halfsum", "hvdtpu_bf16sum"):
            getattr(lib, name).restype = None
        lib.hvdtpu_half_to_float.argtypes = [
            ctypes.POINTER(ctypes.c_uint16), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64]
        lib.hvdtpu_float_to_half.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_uint16),
            ctypes.c_int64]
        lib.hvdtpu_halfsum.argtypes = [
            ctypes.POINTER(ctypes.c_uint16), ctypes.POINTER(ctypes.c_uint16),
            ctypes.c_int64]
        lib.hvdtpu_bf16sum.argtypes = lib.hvdtpu_halfsum.argtypes

    # ------------------------------------------------------------------ api

    def init(self, rank: int, size: int, local_size: int,
             virtual_size: int = 0) -> None:
        self._lib.hvdtpu_init(rank, size, local_size, virtual_size)

    def initialized(self) -> bool:
        return bool(self._lib.hvdtpu_initialized())

    def shutdown(self) -> None:
        self._lib.hvdtpu_shutdown()
        self._cb_refs.clear()

    def set_execute_callback(
            self, fn: Callable[[int, list, str], None]) -> None:
        """``fn(op, handle_ids, error_message)`` — called from the native
        background thread (ctypes re-acquires the GIL)."""

        @EXECUTE_CB
        def trampoline(_user, op, handles_ptr, count, err):
            ids = [handles_ptr[i] for i in range(count)]
            try:
                fn(int(op), ids, err.decode() if err else "")
            except BaseException as e:  # never let exceptions cross into C
                _log.error("execute callback raised: %s", e)

        self._cb_refs["execute"] = trampoline
        self._lib.hvdtpu_set_execute_callback(trampoline, None)

    def set_transport_callback(
            self, fn: Callable[[bytes, int, int, int],
                               Optional[bytes]]) -> None:
        """``fn(request_list_bytes, nreq, complete, pending) ->
        response_list_bytes`` — the MP cycle's announce+fetch leg, called
        from the native background thread. ``nreq == 0`` means the batch
        was already announced (retry after a short response buffer);
        ``complete`` marks the batch a complete enqueue burst; return
        b"" (or None) for "nothing to deliver"."""

        # Overflow cache: when a fetched ResponseList exceeds the native
        # cycle's buffer, the payload must survive until the C++ retry —
        # the client's fetch cursor has already advanced past these
        # groups, so dropping them would lose agreed collectives and
        # deadlock the SPMD fleet.
        state = {"pending": None}

        @TRANSPORT_CB
        def trampoline(_user, req_ptr, req_len, nreq, complete, pending,
                       resp_buf, resp_cap):
            try:
                if state["pending"] is not None:
                    resp = state["pending"]
                    state["pending"] = None
                else:
                    data = (ctypes.string_at(req_ptr, req_len)
                            if req_len > 0 else b"")
                    resp = fn(data, int(nreq), int(complete), int(pending))
                if not resp:
                    return 0
                if len(resp) > resp_cap:
                    state["pending"] = resp
                    return -len(resp)
                ctypes.memmove(resp_buf, resp, len(resp))
                return len(resp)
            except BaseException as e:  # never let exceptions cross into C
                _log.error("transport callback raised: %s", e)
                return 0

        self._cb_refs["transport"] = trampoline
        self._lib.hvdtpu_set_transport_callback(trampoline, None)

    def set_group_callback(
            self, fn: Callable[[int, list, int, list, int, str], None]
    ) -> None:
        """``fn(op, handle_ids, nnames, sizes, flags, error)`` — delivery
        of one coordinator-agreed group for XLA execution (core.cc
        GroupCallback)."""

        @GROUP_CB
        def trampoline(_user, op, handles_ptr, count, nnames, sizes_ptr,
                       nsizes, flags, err):
            ids = [handles_ptr[i] for i in range(count)]
            sizes = [sizes_ptr[i] for i in range(nsizes)] if nsizes else []
            try:
                fn(int(op), ids, int(nnames), sizes, int(flags),
                   err.decode() if err else "")
            except BaseException as e:  # never let exceptions cross into C
                _log.error("group callback raised: %s", e)

        self._cb_refs["group"] = trampoline
        self._lib.hvdtpu_set_group_callback(trampoline, None)

    def enqueue(self, op: int, name: str, dtype, shape: Sequence[int],
                root_rank: int = -1, device: int = -1,
                nbytes: int = 0) -> int:
        if str(dtype) == "bfloat16":
            enum = BFLOAT16_ENUM
        elif str(dtype).startswith("float8"):
            # The native planner only needs a size-consistent dtype key for
            # fusion grouping and cross-rank validation; fp8 plans under
            # the 1-byte uint8 slot and the executor dispatches on the
            # real jax dtype.
            enum = DTYPE_TO_ENUM[np.dtype(np.uint8)]
        else:
            try:
                enum = DTYPE_TO_ENUM[np.dtype(dtype)]
            except KeyError:
                raise ValueError(
                    f"dtype {dtype!r} is not supported on the collective "
                    f"wire (supported: "
                    f"{sorted(str(d) for d in DTYPE_TO_ENUM)} + bfloat16/"
                    "float8)") from None
        arr = (ctypes.c_int64 * max(len(shape), 1))(*shape)
        return int(self._lib.hvdtpu_enqueue(
            op, name.encode(), enum, arr, len(shape), root_rank, device,
            nbytes))

    def flush(self) -> None:
        """Declare the current enqueue burst complete (a submitter is
        about to block on a handle): the background cycle drains and
        announces it immediately instead of waiting out the drain
        debounce."""
        self._lib.hvdtpu_flush()

    def burst_begin(self) -> None:
        """Open an explicit burst scope: the cycle defers draining until
        the matching burst_end (bounded by the max-defer valve), so the
        whole submission fuses as ONE deterministic group."""
        self._lib.hvdtpu_burst_begin()

    def burst_end(self) -> None:
        self._lib.hvdtpu_burst_end()

    def complete(self, handles: Sequence[int], status: int = 0,
                 reason: str = "") -> None:
        arr = (ctypes.c_int64 * max(len(handles), 1))(*handles)
        self._lib.hvdtpu_complete(arr, len(handles), status, reason.encode())

    def poll(self, handle: int) -> int:
        return int(self._lib.hvdtpu_poll(handle))

    def release(self, handle: int) -> None:
        self._lib.hvdtpu_release_handle(handle)

    # knobs ----------------------------------------------------------------

    @property
    def fusion_threshold(self) -> int:
        return int(self._lib.hvdtpu_get_fusion_threshold())

    @fusion_threshold.setter
    def fusion_threshold(self, v: int) -> None:
        self._lib.hvdtpu_set_fusion_threshold(v)

    @property
    def cycle_time_ms(self) -> float:
        return float(self._lib.hvdtpu_get_cycle_time_ms())

    @cycle_time_ms.setter
    def cycle_time_ms(self, v: float) -> None:
        self._lib.hvdtpu_set_cycle_time_ms(v)

    # timeline -------------------------------------------------------------

    def timeline_enabled(self) -> bool:
        return bool(self._lib.hvdtpu_timeline_enabled())

    def timeline_activity_start(self, tensor: str, activity: str) -> None:
        self._lib.hvdtpu_timeline_activity_start(tensor.encode(),
                                                 activity.encode())

    def timeline_activity_end(self, tensor: str) -> None:
        self._lib.hvdtpu_timeline_activity_end(tensor.encode())

    def autotune_active(self) -> bool:
        return bool(self._lib.hvdtpu_autotune_active())

    def current_flags(self) -> int:
        """Single-process tuner's execution-mode flags (Response::Flags
        bits) — applied by the execute callback so a tuned hierarchical
        mode actually switches the executor's path."""
        return int(self._lib.hvdtpu_current_flags())

    def autotune_done(self) -> bool:
        """True once the tuner converged and froze to its best point
        (parameter_manager.cc:173-209 semantics)."""
        return bool(self._lib.hvdtpu_autotune_done())

    # wire/test surface ----------------------------------------------------

    def wire_make_request(self, rank: int, op: int, dtype_enum: int,
                          name: str, root_rank: int, device: int,
                          shape: Sequence[int]) -> bytes:
        cap = 1024 + len(name)
        buf = (ctypes.c_uint8 * cap)()
        arr = (ctypes.c_int64 * max(len(shape), 1))(*shape)
        n = self._lib.hvdtpu_wire_make_request(
            rank, op, dtype_enum, name.encode(), root_rank, device, arr,
            len(shape), buf, cap)
        if n < 0:
            raise RuntimeError("wire_make_request failed")
        return bytes(buf[:n])

    def wire_roundtrip_request_list(self, payload: bytes) -> bytes:
        src = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
        cap = len(payload) + 64
        dst = (ctypes.c_uint8 * cap)()
        n = self._lib.hvdtpu_wire_roundtrip_request_list(
            src, len(payload), dst, cap)
        if n < 0:
            raise RuntimeError("request list did not round-trip")
        return bytes(dst[:n])

    def negotiate(self, serialized_requests: bytes, nreq: int,
                  world_size: int):
        """Run ConstructResponse over serialized requests; returns
        (response_type, error_message, tensor_sizes)."""
        src = (ctypes.c_uint8 * len(serialized_requests)).from_buffer_copy(
            serialized_requests)
        err = ctypes.create_string_buffer(2048)
        sizes = (ctypes.c_int64 * world_size)()
        rtype = self._lib.hvdtpu_negotiate(
            src, len(serialized_requests), nreq, world_size, err, 2048,
            sizes, world_size)
        return int(rtype), err.value.decode(), list(sizes)

    # half -----------------------------------------------------------------

    def half_to_float(self, bits: np.ndarray) -> np.ndarray:
        bits = np.ascontiguousarray(bits, dtype=np.uint16)
        out = np.empty(bits.shape, np.float32)
        self._lib.hvdtpu_half_to_float(
            bits.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), bits.size)
        return out

    def float_to_half(self, vals: np.ndarray) -> np.ndarray:
        vals = np.ascontiguousarray(vals, dtype=np.float32)
        out = np.empty(vals.shape, np.uint16)
        self._lib.hvdtpu_float_to_half(
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)), vals.size)
        return out

    def halfsum(self, src_bits: np.ndarray, dst_bits: np.ndarray) -> None:
        self._lib.hvdtpu_halfsum(
            src_bits.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
            dst_bits.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
            src_bits.size)


class NativeController:
    """The rank-0 multi-process controller (runtime/src/controller.cc):
    MessageTable + ConstructResponse + FuseResponses + ParameterManager
    behind a C handle, fed/drained by the Python TCP service with
    message.cc-codec payloads. ONE planner and ONE wire for cross-process
    negotiation (the reference's coordinator half of RunLoopOnce)."""

    def __init__(self, core: NativeCore, nproc: int, virtual_size: int,
                 fusion_threshold: int, cycle_time_ms: float,
                 stall_warning_sec: float, hier_allreduce: bool,
                 hier_allgather: bool, autotune: bool,
                 autotune_log: str = ""):
        self._lib = core._lib
        self._h = self._lib.hvdtpu_ctl_create(
            nproc, virtual_size, fusion_threshold, cycle_time_ms,
            stall_warning_sec, int(hier_allreduce), int(hier_allgather),
            int(autotune), autotune_log.encode())
        self.nproc = nproc

    def close(self) -> None:
        if self._h:
            self._lib.hvdtpu_ctl_destroy(self._h)
            self._h = None

    def announce(self, payload: bytes) -> int:
        """Feed one serialized RequestList; returns total group count."""
        buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
        n = int(self._lib.hvdtpu_ctl_announce(self._h, buf, len(payload)))
        if n < 0:
            raise ValueError("controller could not parse announce payload")
        return n

    def group_count(self) -> int:
        return int(self._lib.hvdtpu_ctl_group_count(self._h))

    def base_seq(self) -> int:
        return int(self._lib.hvdtpu_ctl_base_seq(self._h))

    def shutdown_flag(self) -> bool:
        return bool(self._lib.hvdtpu_ctl_shutdown_flag(self._h))

    def fetch(self, rank: int, after_seq: int) -> bytes:
        """Serialized ResponseList of groups with seq >= after_seq."""
        cap = 1 << 16
        while True:
            buf = (ctypes.c_uint8 * cap)()
            n = int(self._lib.hvdtpu_ctl_fetch(self._h, rank, after_seq,
                                               buf, cap))
            if n >= 0:
                return bytes(buf[:n])
            cap = -n

    def tick(self) -> None:
        self._lib.hvdtpu_ctl_tick(self._h)

    def plan(self) -> int:
        """Fetch-timeout valve: cut groups from whatever is fully
        announced even while some tensor is still partial. Returns the
        new total group count."""
        return int(self._lib.hvdtpu_ctl_plan(self._h))

    def maybe_plan(self) -> int:
        """Quiescence planner: cut groups once the announce stream has
        been quiet for the debounce window and no tensor is partial.
        Returns the total group count."""
        return int(self._lib.hvdtpu_ctl_maybe_plan(self._h))

    def plan_ready(self) -> int:
        """Eager planner for burst-complete announces: plan iff no
        tensor is partially announced (no quiet-window wait). Returns
        the total group count."""
        return int(self._lib.hvdtpu_ctl_plan_ready(self._h))

    def set_fusion_threshold(self, nbytes: int) -> None:
        """Push a tuner-arbitrated fusion cap into the native planner
        (docs/autotune.md) — future groups are cut with the new cap."""
        self._lib.hvdtpu_ctl_set_fusion_threshold(self._h, int(nbytes))

    def params(self) -> dict:
        fusion = ctypes.c_int64()
        cycle = ctypes.c_double()
        flags = ctypes.c_int32()
        active = ctypes.c_int32()
        done = ctypes.c_int32()
        self._lib.hvdtpu_ctl_params(self._h, ctypes.byref(fusion),
                                    ctypes.byref(cycle), ctypes.byref(flags),
                                    ctypes.byref(active), ctypes.byref(done))
        return {"fusion_threshold": fusion.value,
                "cycle_time_ms": cycle.value, "flags": flags.value,
                "autotune_active": bool(active.value),
                "autotune_done": bool(done.value)}

    def stalled(self) -> List[tuple]:
        """(tensor_name, display_line) pairs — the native wire is one
        "name\\tdisplay" line per stalled tensor (coordinator.cc
        StalledTensors), split here so consumers never parse display
        text."""
        cap = 1 << 16
        while True:
            buf = (ctypes.c_uint8 * cap)()
            n = int(self._lib.hvdtpu_ctl_stalled(self._h, buf, cap))
            if n >= 0:
                text = bytes(buf[:n]).decode()
                if not text:
                    return []
                out = []
                for raw in text.split("\n"):
                    name, _, line = raw.partition("\t")
                    out.append((name, line or raw))
                return out
            cap = -n


_core: Optional[NativeCore] = None
_load_failed = False
_lock = threading.Lock()


def load(required: bool = False) -> Optional[NativeCore]:
    """Load (building if needed) the native core; returns None when the
    toolchain is unavailable unless ``required``."""
    global _core, _load_failed
    with _lock:
        if _core is not None:
            return _core
        if _load_failed and not required:
            return None
        try:
            from . import build as _build
            path = _build.build()
            _core = NativeCore(ctypes.CDLL(path))
            return _core
        except Exception as e:
            _load_failed = True
            if required:
                raise
            _log.warning("native core unavailable, using Python control "
                         "plane: %s", e)
            return None
