"""Multi-axis mesh construction.

The reference's topology is world/local/cross MPI communicators
(operations.cc:1760-1797). The TPU-native generalization is an N-D named
mesh; each parallelism strategy binds to an axis name:

  'dp' data, 'fsdp' sharded-data, 'tp' tensor, 'pp' pipeline,
  'sp' sequence/context, 'ep' expert.

``create_mesh`` builds the mesh with axis sizes that must multiply to the
device count; leading axes span hosts (DCN) and trailing axes stay inside a
host (ICI), following the scaling-book recipe of keeping high-traffic axes
(tp/sp) on ICI.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Axis-name → size spec. size -1 means "absorb remaining devices"."""

    axes: Tuple[Tuple[str, int], ...]

    @classmethod
    def of(cls, **sizes: int) -> "MeshSpec":
        return cls(tuple(sizes.items()))

    def resolve(self, n_devices: int) -> Dict[str, int]:
        fixed = math.prod(s for _, s in self.axes if s > 0)
        wild = [a for a, s in self.axes if s <= 0]
        if len(wild) > 1:
            raise ValueError("at most one axis may have size -1")
        out = dict(self.axes)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"{fixed}")
            out[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh axes {dict(self.axes)} multiply to {fixed}, but "
                f"{n_devices} devices are available")
        return out


def create_mesh(spec: Optional[MeshSpec] = None,
                devices: Optional[Sequence] = None,
                **axis_sizes: int) -> Mesh:
    """Create a named mesh over ``devices`` (default: all).

    ``create_mesh(dp=-1)`` — flat data parallel.
    ``create_mesh(dp=2, tp=2, sp=2)`` — 3-axis hybrid on 8 chips.
    """
    if spec is None:
        spec = MeshSpec.of(**(axis_sizes or {"dp": -1}))
    devs = list(devices) if devices is not None else list(jax.devices())
    sizes = spec.resolve(len(devs))
    names = tuple(sizes.keys())
    shape = tuple(sizes.values())
    try:
        arr = mesh_utils.create_device_mesh(shape, devices=devs)
    except Exception:
        # CPU-emulation / exotic topologies: plain reshape keeps axis order.
        arr = np.asarray(devs, dtype=object).reshape(shape)
    return Mesh(arr, names)
