"""Multi-axis mesh construction.

The reference's topology is world/local/cross MPI communicators
(operations.cc:1760-1797). The TPU-native generalization is an N-D named
mesh; each parallelism strategy binds to an axis name:

  'dp' data, 'fsdp' sharded-data, 'tp' tensor, 'pp' pipeline,
  'sp' sequence/context, 'ep' expert.

``create_mesh`` builds the mesh with axis sizes that must multiply to the
device count; leading axes span hosts (DCN) and trailing axes stay inside a
host (ICI), following the scaling-book recipe of keeping high-traffic axes
(tp/sp) on ICI.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Axis-name → size spec. size -1 means "absorb remaining devices"."""

    axes: Tuple[Tuple[str, int], ...]

    @classmethod
    def of(cls, **sizes: int) -> "MeshSpec":
        return cls(tuple(sizes.items()))

    def resolve(self, n_devices: int) -> Dict[str, int]:
        fixed = math.prod(s for _, s in self.axes if s > 0)
        wild = [a for a, s in self.axes if s <= 0]
        if len(wild) > 1:
            raise ValueError("at most one axis may have size -1")
        out = dict(self.axes)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"{fixed}")
            out[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh axes {dict(self.axes)} multiply to {fixed}, but "
                f"{n_devices} devices are available")
        return out


def axis_kinds(mesh: Mesh) -> Dict[str, str]:
    """Classify every mesh axis as ``"ici"`` (stays inside one
    slice/host — chip-to-chip interconnect) or ``"dcn"`` (crosses slice
    or host boundaries — data-center network), by walking the device
    grid: an axis is DCN iff stepping along it ever changes the device's
    ``slice_index`` (TPU multislice) or, failing that attribute,
    ``process_index`` (multi-host).

    The CPU-emulation mesh has a single process, so every axis reads as
    ICI there; ``HOROVOD_TPU_DCN_AXES`` (comma-separated axis names)
    overrides the detection for tests, benches, and exotic fabrics —
    the same simulated-multihost lever as the checkpoint engine's
    ``process_fn``."""
    import os
    forced = {a.strip()
              for a in os.environ.get("HOROVOD_TPU_DCN_AXES", "").split(",")
              if a.strip()}
    devs = mesh.devices
    kinds: Dict[str, str] = {}
    for k, name in enumerate(mesh.axis_names):
        if name in forced:
            kinds[name] = "dcn"
            continue
        crosses = False
        if devs.shape[k] > 1:
            rolled = np.roll(devs, -1, axis=k)
            for a, b in zip(devs.ravel(), rolled.ravel()):
                sa = getattr(a, "slice_index", None)
                sb = getattr(b, "slice_index", None)
                if sa is not None and sb is not None:
                    if sa != sb:
                        crosses = True
                        break
                elif getattr(a, "process_index", 0) != \
                        getattr(b, "process_index", 0):
                    crosses = True
                    break
        kinds[name] = "dcn" if crosses else "ici"
    return kinds


def dcn_axes(mesh: Mesh) -> Tuple[str, ...]:
    """Mesh axes that cross slice/host boundaries (see
    :func:`axis_kinds`)."""
    return tuple(a for a, k in axis_kinds(mesh).items() if k == "dcn")


def ici_axes(mesh: Mesh) -> Tuple[str, ...]:
    """Mesh axes that stay on the chip interconnect."""
    return tuple(a for a, k in axis_kinds(mesh).items() if k == "ici")


def create_mesh(spec: Optional[MeshSpec] = None,
                devices: Optional[Sequence] = None,
                **axis_sizes: int) -> Mesh:
    """Create a named mesh over ``devices`` (default: all).

    ``create_mesh(dp=-1)`` — flat data parallel.
    ``create_mesh(dp=2, tp=2, sp=2)`` — 3-axis hybrid on 8 chips.
    """
    if spec is None:
        spec = MeshSpec.of(**(axis_sizes or {"dp": -1}))
    devs = list(devices) if devices is not None else list(jax.devices())
    sizes = spec.resolve(len(devs))
    names = tuple(sizes.keys())
    shape = tuple(sizes.values())
    try:
        arr = mesh_utils.create_device_mesh(shape, devices=devs)
    except Exception:
        # CPU-emulation / exotic topologies: plain reshape keeps axis order.
        arr = np.asarray(devs, dtype=object).reshape(shape)
    return Mesh(arr, names)
