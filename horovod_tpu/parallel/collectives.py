"""In-jit collectives over named mesh axes.

The TPU-native replacement for the reference's L0 transport (MPI/NCCL calls,
operations.cc:1117-1612): inside a jitted SPMD program, XLA schedules these
over ICI/DCN — fusion, overlap, and stream management all belong to the
compiler (SURVEY.md §5.8). These wrappers exist so higher layers (tensor/
sequence/pipeline/expert parallel) read as communication patterns, and so
the eager layer and in-jit layer share vocabulary.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def axis_size(axis: str) -> int:
    return lax.axis_size(axis)


def axis_index(axis: str):
    return lax.axis_index(axis)


def psum(x, axis: str):
    """MPI_Allreduce / ncclAllReduce equivalent (operations.cc:1437-1446)."""
    return lax.psum(x, axis)


def pmean(x, axis: str):
    return lax.pmean(x, axis)


def psum_scatter(x, axis: str, *, scatter_dimension: int = 0,
                 tiled: bool = True):
    """ReduceScatter (the intra-node half of hierarchical allreduce,
    operations.cc:1284-1436)."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dimension,
                            tiled=tiled)


def all_gather(x, axis: str, *, gather_dimension: int = 0,
               tiled: bool = True):
    """MPI_Allgatherv equivalent (operations.cc:843-1113)."""
    return lax.all_gather(x, axis, axis=gather_dimension, tiled=tiled)


def ppermute(x, axis: str, perm: Sequence[Tuple[int, int]]):
    """Point-to-point permutation over the axis ring (no reference
    equivalent — MPI send/recv patterns are absent there; this is the
    primitive behind ring attention and pipeline shifts)."""
    return lax.ppermute(x, axis, perm)


def ring_shift(x, axis: str, *, offset: int = 1):
    """Shift each shard's value to the next rank around the ring
    (rank i -> rank (i+offset) % n). The building block of ring attention
    and the pipeline activation hand-off."""
    n = lax.axis_size(axis)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def all_to_all(x, axis: str, *, split_axis: int, concat_axis: int,
               tiled: bool = True):
    """All-to-all (the expert-parallel dispatch primitive; also the
    DeepSpeed-Ulysses sequence<->head exchange)."""
    return lax.all_to_all(x, axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


# ---------------------------------------------------------------------------
# Hierarchical (ICI-then-DCN) reduction — the 2D topology-aware summation
# of "Scale MLPerf-0.6 models on Google TPU-v3 Pods" (arXiv 1909.09756),
# docs/pipeline.md. On a pp×dp multislice mesh the data-parallel gradient
# reduction would otherwise push the FULL gradient vector over the
# cross-slice DCN links; reducing in-slice first (reduce-scatter on ICI)
# shrinks the DCN leg to 1/ici_size of the bytes, and the PR 2 wire specs
# quantize that leg further where bytes are most expensive.
# ---------------------------------------------------------------------------


def hierarchical_psum(x, ici_axis: str, dcn_axis: str, *,
                      wire=None, average: bool = False):
    """Sum (or mean) ``x`` over BOTH axes via the two-stage reduction:

      1. ``psum_scatter`` over ``ici_axis`` — each in-slice rank ends up
         owning the in-slice sum of a 1/ici_size span,
      2. ``psum`` of the span over ``dcn_axis`` — the only cross-slice
         traffic, 1/ici_size of the flat-allreduce bytes; with ``wire``
         (a :mod:`horovod_tpu.quantization` spec name like
         ``"int8x256"``) the span crosses block-quantized,
      3. ``all_gather`` over ``ici_axis`` to rebuild the full tensor.

    Mathematically equal to ``psum(x, (ici_axis, dcn_axis))`` up to fp
    summation order (and, with ``wire``, quantization error on the DCN
    leg). Arbitrary shapes are handled by flattening and zero-padding to
    a multiple of the ici axis size."""
    n_ici = lax.axis_size(ici_axis)
    shape, dtype = x.shape, x.dtype
    flat = jnp.ravel(x).astype(jnp.float32)
    n = flat.size
    if n == 0:
        return x
    pad = (-n) % n_ici
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    span = lax.psum_scatter(flat, ici_axis, scatter_dimension=0,
                            tiled=True)
    if wire is not None:
        from .. import quantization as _quant
        span = _quant.quantized_psum(span, dcn_axis, wire)
    else:
        span = lax.psum(span, dcn_axis)
    out = lax.all_gather(span, ici_axis, axis=0, tiled=True)[:n]
    if average:
        out = out / (n_ici * lax.axis_size(dcn_axis))
    return out.reshape(shape).astype(dtype)


def hierarchical_psum_tree(tree, ici_axis: str, dcn_axis: str, *,
                           wire=None, average: bool = False):
    """Leaf-wise :func:`hierarchical_psum` over a pytree (gradients)."""
    return jax.tree_util.tree_map(
        lambda g: hierarchical_psum(g, ici_axis, dcn_axis, wire=wire,
                                    average=average), tree)


def cross_slice_bytes(n_elements: int, ici_size: int, *,
                      hierarchical: bool = True, wire=None,
                      dtype_bytes: int = 4) -> int:
    """Static bytes one rank contributes to the CROSS-SLICE (DCN) leg
    per reduction of ``n_elements``: the flat allreduce moves the full
    tensor over the combined axis, the hierarchical reduction only its
    1/ici_size span — block-quantized when ``wire`` is set. Used by
    ``bench_engine.py --pipeline`` and the docs' sizing math; the
    measured counterpart is the engine's wire-byte accounting."""
    if not hierarchical:
        return int(n_elements) * dtype_bytes
    span = -(-int(n_elements) // int(ici_size))
    if wire is not None:
        from .. import quantization as _quant
        return _quant.wire_nbytes(wire, span)
    return span * dtype_bytes
