"""In-jit collectives over named mesh axes.

The TPU-native replacement for the reference's L0 transport (MPI/NCCL calls,
operations.cc:1117-1612): inside a jitted SPMD program, XLA schedules these
over ICI/DCN — fusion, overlap, and stream management all belong to the
compiler (SURVEY.md §5.8). These wrappers exist so higher layers (tensor/
sequence/pipeline/expert parallel) read as communication patterns, and so
the eager layer and in-jit layer share vocabulary.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
from jax import lax


def axis_size(axis: str) -> int:
    return lax.axis_size(axis)


def axis_index(axis: str):
    return lax.axis_index(axis)


def psum(x, axis: str):
    """MPI_Allreduce / ncclAllReduce equivalent (operations.cc:1437-1446)."""
    return lax.psum(x, axis)


def pmean(x, axis: str):
    return lax.pmean(x, axis)


def psum_scatter(x, axis: str, *, scatter_dimension: int = 0,
                 tiled: bool = True):
    """ReduceScatter (the intra-node half of hierarchical allreduce,
    operations.cc:1284-1436)."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dimension,
                            tiled=tiled)


def all_gather(x, axis: str, *, gather_dimension: int = 0,
               tiled: bool = True):
    """MPI_Allgatherv equivalent (operations.cc:843-1113)."""
    return lax.all_gather(x, axis, axis=gather_dimension, tiled=tiled)


def ppermute(x, axis: str, perm: Sequence[Tuple[int, int]]):
    """Point-to-point permutation over the axis ring (no reference
    equivalent — MPI send/recv patterns are absent there; this is the
    primitive behind ring attention and pipeline shifts)."""
    return lax.ppermute(x, axis, perm)


def ring_shift(x, axis: str, *, offset: int = 1):
    """Shift each shard's value to the next rank around the ring
    (rank i -> rank (i+offset) % n). The building block of ring attention
    and the pipeline activation hand-off."""
    n = lax.axis_size(axis)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def all_to_all(x, axis: str, *, split_axis: int, concat_axis: int,
               tiled: bool = True):
    """All-to-all (the expert-parallel dispatch primitive; also the
    DeepSpeed-Ulysses sequence<->head exchange)."""
    return lax.all_to_all(x, axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)
