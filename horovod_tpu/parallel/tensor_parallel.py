"""Tensor parallelism — Megatron-style column/row-parallel layers.

No reference equivalent (SURVEY.md §2.1: TP absent); built on the mesh
collective layer. The classic pairing keeps activations local between the
two halves of an MLP / attention block:

  ColumnParallelDense: Y_k = X @ W_k       (weights split on OUTPUT dim;
                                            no comm going in)
  RowParallelDense:    Y   = psum_k(X_k @ W_k)  (weights split on INPUT
                                            dim; ONE psum coming out)

so an MLP (column → gelu → row) or attention (column QKV → heads local →
row out-proj) costs exactly one psum per block, riding ICI.

These are shard_map-level modules: they expect to run *inside* a
``shard_map`` where ``axis_name`` is bound, with per-shard parameter
slices. Parameter sharding specs for jit-level use are provided by
``param_specs`` in models/transformer.py.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


class ColumnParallelDense(nn.Module):
    """Dense with output features split over ``axis_name``.

    ``features`` is the GLOBAL output dim; this shard holds
    features / axis_size columns.
    """

    features: int
    axis_name: str = "tp"
    use_bias: bool = True
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        n = lax.axis_size(self.axis_name)
        if self.features % n:
            raise ValueError(
                f"features {self.features} not divisible by "
                f"{self.axis_name} size {n}")
        local = self.features // n
        w = self.param("kernel", nn.initializers.lecun_normal(),
                       (x.shape[-1], local), jnp.float32)
        y = jnp.dot(x.astype(self.dtype), w.astype(self.dtype))
        if self.use_bias:
            b = self.param("bias", nn.initializers.zeros, (local,),
                           jnp.float32)
            y = y + b.astype(self.dtype)
        return y


class RowParallelDense(nn.Module):
    """Dense with input features split over ``axis_name``; output psum'd.

    ``features`` is the GLOBAL output dim; the input x is the local shard
    of the hidden (produced by a ColumnParallelDense).
    """

    features: int
    axis_name: str = "tp"
    use_bias: bool = True
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        w = self.param("kernel", nn.initializers.lecun_normal(),
                       (x.shape[-1], self.features), jnp.float32)
        y = jnp.dot(x.astype(self.dtype), w.astype(self.dtype))
        # The single communication point of the block.
        y = lax.psum(y, self.axis_name)
        if self.use_bias:
            b = self.param("bias", nn.initializers.zeros, (self.features,),
                           jnp.float32)
            y = y + b.astype(self.dtype)
        return y


class ParallelMLP(nn.Module):
    """column → activation → row: one psum per MLP (Megatron fig. 3)."""

    hidden: int           # global intermediate dim
    features: int         # model dim
    axis_name: str = "tp"
    act: Callable = nn.gelu
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        h = ColumnParallelDense(self.hidden, self.axis_name,
                                dtype=self.dtype, name="wi")(x)
        h = self.act(h)
        return RowParallelDense(self.features, self.axis_name,
                                dtype=self.dtype, name="wo")(h)
