"""Parallelism strategies over the device mesh.

The reference implements exactly one strategy — synchronous data parallelism
via allreduce (SURVEY.md §2.1: "TP / PP / SP / EP / CP / ring-attention:
ABSENT") — so everything here beyond :mod:`data_parallel` is an extension
built on the same mesh-axis collective layer, designed TPU-first:

- :mod:`mesh`       — multi-axis mesh construction ('dp','tp','pp','sp','ep')
- :mod:`collectives` — named-axis collective wrappers for in-jit use
- :mod:`data_parallel` — batch sharding + gradient psum (the reference's
  core capability, recast as shardings)
- :mod:`tensor_parallel` — column/row-parallel Dense + attention heads
- :mod:`ring_attention` — sequence/context parallelism for long sequences
  (ppermute ring with online-softmax accumulation)
- :mod:`ulysses`    — all-to-all sequence parallelism (DeepSpeed-Ulysses:
  reshard seq->heads, local attention, reshard back)
- :mod:`pipeline`   — schedule-driven microbatch pipeline over 'pp'
  (gpipe / 1f1b / interleaved virtual stages, forward AND backward,
  docs/pipeline.md)
- :mod:`expert`     — mixture-of-experts dispatch over 'ep' (all_to_all)
- :mod:`zero`       — ZeRO-1 optimizer-state sharding over 'dp'
  (psum_scatter grads, shard moments 1/N, all_gather updates)
"""

from .mesh import (MeshSpec, axis_kinds, create_mesh, dcn_axes,
                   ici_axes)
from .collectives import (all_gather, all_to_all, axis_index, axis_size,
                          cross_slice_bytes, hierarchical_psum,
                          hierarchical_psum_tree, ppermute, psum,
                          psum_scatter, ring_shift)
from .data_parallel import shard_batch, allreduce_gradients_in_jit
from .pipeline import (PipelineSchedule, pipeline_apply,
                       pipeline_value_and_grad, schedule_info)
from .zero import (Zero1State, zero1_init, zero1_state_specs,
                   zero1_update)

__all__ = [
    "MeshSpec", "create_mesh", "axis_kinds", "dcn_axes", "ici_axes",
    "psum", "all_gather", "ppermute", "all_to_all", "psum_scatter",
    "axis_index", "axis_size", "ring_shift",
    "hierarchical_psum", "hierarchical_psum_tree", "cross_slice_bytes",
    "shard_batch", "allreduce_gradients_in_jit",
    "PipelineSchedule", "pipeline_apply", "pipeline_value_and_grad",
    "schedule_info",
    "Zero1State", "zero1_init", "zero1_state_specs", "zero1_update",
]
