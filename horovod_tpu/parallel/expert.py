"""Expert parallelism — mixture-of-experts dispatch over the 'ep' axis.

No reference equivalent (SURVEY.md §2.1: EP absent). TPU-first design
following the Switch/GShard pattern with static shapes throughout:

  1. A router scores tokens against experts (one small matmul).
  2. Tokens are dispatched to their top-1 expert with a fixed per-expert
     capacity C (static shape — XLA requirement; overflow tokens drop, the
     standard TPU MoE trade-off).
  3. ``all_to_all`` over 'ep' exchanges the per-expert buckets so each rank
     holds the tokens routed to ITS experts.
  4. The local expert MLP runs as one batched matmul (MXU-friendly).
  5. A second ``all_to_all`` returns outputs; combine weights scatter them
     back into sequence order.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


def top1_dispatch(router_logits, capacity: int):
    """Top-1 routing with fixed capacity.

    Args:
      router_logits: [tokens, num_experts]
      capacity: max tokens kept per expert (static).
    Returns:
      dispatch: [tokens, num_experts, capacity] one-hot dispatch mask
      combine:  [tokens, num_experts, capacity] combine weights (gate prob)
    """
    n_tokens, n_experts = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)                     # [tokens]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]

    onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.int32)
    # Position of each token within its expert's bucket (running count).
    position = jnp.cumsum(onehot, axis=0) * onehot - 1      # [tokens, E]
    keep = position < capacity
    pos_onehot = jax.nn.one_hot(
        jnp.where(keep, position, -1), capacity, dtype=jnp.float32)
    dispatch = onehot[..., None] * pos_onehot               # [T, E, C]
    combine = dispatch * gate[:, None, None]
    return dispatch.astype(jnp.float32), combine


def moe_apply(params, x, *, num_experts: int, capacity_factor: float,
              axis_name: str, act: Callable, dtype=jnp.bfloat16):
    """Functional top-1 MoE (used by the flagship model and tests).

    params: dict with
      router: [F, E_global]
      wi:     [E_local, F, H]
      wo:     [E_local, H, F]
    x: [tokens_local, F] inside shard_map over ``axis_name``.
    """
    n_shards = lax.axis_size(axis_name)
    e_local = num_experts // n_shards
    t, f = x.shape
    capacity = max(1, int(capacity_factor * t / num_experts))

    logits = jnp.dot(x.astype(jnp.float32), params["router"])
    dispatch, combine = top1_dispatch(logits, capacity)   # [T, E, C]

    # Per-global-expert buckets of this rank's tokens: [E, C, F].
    buckets = jnp.einsum("tec,tf->ecf", dispatch, x.astype(jnp.float32))

    # Exchange so rank r receives bucket groups for ITS experts from every
    # rank: reshape [E, C, F] -> [n_shards, e_local*C, F]; all_to_all
    # scatters dim 0 and concatenates arrivals on dim 1.
    buckets = buckets.reshape(n_shards, e_local * capacity, f)
    buckets = lax.all_to_all(buckets, axis_name, split_axis=0,
                             concat_axis=1, tiled=True)
    # -> [n_shards * e_local * C? ] with tiled=True: [n_shards,
    #    n_shards * e_local * capacity / n_shards ...]; net effect:
    # [n_shards, e_local * capacity, f] where dim 0 now indexes SOURCE rank.
    buckets = buckets.reshape(n_shards, e_local, capacity, f)
    buckets = buckets.transpose(1, 0, 2, 3).reshape(
        e_local, n_shards * capacity, f)                  # [E_l, N*C, F]

    # Local expert MLPs, batched on the expert dim (one big MXU matmul).
    h = jnp.einsum("ecf,efh->ech", buckets.astype(dtype),
                   params["wi"].astype(dtype))
    h = act(h)
    y = jnp.einsum("ech,ehf->ecf", h, params["wo"].astype(dtype))
    y = y.astype(jnp.float32)

    # Return trip: invert the exchange.
    y = y.reshape(e_local, n_shards, capacity, f).transpose(1, 0, 2, 3)
    y = y.reshape(n_shards, e_local * capacity, f)
    y = lax.all_to_all(y, axis_name, split_axis=0, concat_axis=1,
                       tiled=True)
    y = y.reshape(num_experts, capacity, f)               # [E, C, F]

    # Combine back to token order.
    out = jnp.einsum("tec,ecf->tf", combine, y)
    return out.astype(x.dtype)


def moe_init(rng, *, num_experts: int, experts_per_shard: int, features: int,
             hidden: int):
    """Initialize per-shard MoE params (router replicated, experts local)."""
    k1, k2, k3 = jax.random.split(rng, 3)
    scale_in = (1.0 / features) ** 0.5
    scale_hid = (1.0 / hidden) ** 0.5
    return {
        "router": jax.random.normal(k1, (features, num_experts),
                                    jnp.float32) * scale_in,
        "wi": jax.random.normal(k2, (experts_per_shard, features, hidden),
                                jnp.float32) * scale_in,
        "wo": jax.random.normal(k3, (experts_per_shard, hidden, features),
                                jnp.float32) * scale_hid,
    }
